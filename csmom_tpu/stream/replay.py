"""Event-time replay: drive a tick log through ingest -> update -> serve.

The replay harness is the live workload's rehearsal stage: a recorded or
synthetic bar-tick log is driven through the watermark ingestor, the
incremental updaters, and the signal service — deterministically (one
seed reproduces the exact stream), on the event-time clock (ordering
and lateness decisions come from tick stamps, never the wall clock; the
wall is read only to report throughput), and chaos-injectable (late /
out-of-order / duplicate / gap ticks and an ingest-serve version skew
are fault-plan actions interpreted at the ``stream.tick`` /
``stream.serve`` checkpoints).

The run lands as ``REPLAY_<run>.json`` with two closed books the schema
(:mod:`csmom_tpu.chaos.invariants`, kind ``replay``) refuses to bend:

- tick accounting: ``applied + merged_late + quarantined + deduped ==
  offered`` and ``offered == generated + duplicated - dropped_gap`` —
  every tick the feed emitted is in exactly one bucket;
- version reconciliation: every served response's ``panel_version`` is
  one the ingestor actually issued (``serve_version_max <=
  ingest_version_final``), and a request whose snapshot version skews
  beyond the allowed window is REFUSED and counted
  (``skew_refusals``), mirroring the serving pool's AOT-version gate.

Zero-compile windows: the serve leg dispatches only warmed bucket
shapes (the serve manifest profile), and the periodic on-device
reconciliation dispatches only the ``stream`` manifest profile's shapes
(the jitted ``signals`` engines at the canonical replay panel) — so the
whole replay window reports ``in_window_fresh_compiles == 0`` when the
warmup held, measured via ``profiling.compile_stats`` exactly like the
serve artifact.
"""

from __future__ import annotations

import bisect
import dataclasses
import itertools
import random

import numpy as np

from csmom_tpu.serve.loadgen import _percentiles, write_artifact
from csmom_tpu.stream.incremental import (
    IncrementalMomentum,
    IncrementalTurnover,
)
from csmom_tpu.stream.ingest import StreamIngestor, Tick, WatermarkPolicy
from csmom_tpu.stream.ring import LiveRing
from csmom_tpu.utils.deadline import mono_now_s

__all__ = ["ReplayConfig", "REPLAY_BARS", "REPLAY_SMOKE_BARS",
           "builtin_fault_plan", "run_replay", "synth_tick_log",
           "write_artifact"]

SCHEMA_VERSION = 1

# canonical replay panel lengths — the compile/manifest.py `stream` /
# `stream-smoke` profiles enumerate the jitted reconcile entries at
# exactly these time axes, so an on-device reconciliation pass inside a
# replay window dispatches only warmed shapes
REPLAY_BARS = 96
REPLAY_SMOKE_BARS = 32


@dataclasses.dataclass(frozen=True)
class ReplayConfig:
    """One replay run (everything the artifact needs to be replayed)."""

    run_id: str = "smoke"
    seed: int = 0
    n_assets: int = 8
    bars: int = REPLAY_SMOKE_BARS
    # ring capacity in bars.  None = the default 3/4 of the log (floored
    # at the serve window), so every replay WRAPS the ring and exercises
    # the window-slide reconcile path by default — the r12 harness
    # pinned capacity == bars, which masked the reconcile false-drift
    # defect (ROADMAP item 4 (a)); set capacity == bars explicitly to
    # get the old no-eviction behavior.
    capacity: int | None = None
    bar_period_ns: int = 60_000_000_000        # one-minute bars
    t0_ns: int = 1_700_000_000_000_000_000     # event-time origin
    allowed_lateness_bars: int = 3
    max_delay_bars: int = 6                    # chaos tick_late delays
    engine: str = "stub"                       # serve + reconcile backend
    profile: str = "serve-smoke"               # serve bucket profile
    serve_every_bars: int = 4
    requests_per_probe: int = 2
    deadline_s: float = 3.0
    reconcile_every_bars: int = 8
    lookback: int = 12
    skip: int = 1
    turn_lookback: int = 3
    dtype: str = "float32"
    max_version_skew: int = 0                  # the feed is synchronous

    def resolved_capacity(self) -> int:
        """The ring capacity this run uses (see ``capacity``)."""
        if self.capacity is not None:
            return int(self.capacity)
        from csmom_tpu.serve.buckets import bucket_spec

        months = bucket_spec(self.profile).months
        return max(months, (3 * self.bars) // 4)

    def validate(self) -> None:
        from csmom_tpu.serve.buckets import bucket_spec

        spec = bucket_spec(self.profile)
        if self.bars < spec.months:
            raise ValueError(
                f"bars={self.bars} < serve months {spec.months} "
                f"(profile {self.profile!r}): the serve leg could never "
                "slice a scoring window")
        if self.resolved_capacity() < spec.months:
            raise ValueError(
                f"capacity={self.resolved_capacity()} < serve months "
                f"{spec.months}: a snapshot window could never carry a "
                "full scoring history")
        if self.n_assets > spec.max_assets:
            raise ValueError(
                f"n_assets={self.n_assets} exceeds the largest serve "
                f"bucket ({spec.max_assets})")
        if self.bars < self.lookback + self.skip + 1:
            raise ValueError("bars too short for the momentum window")


def synth_tick_log(cfg: ReplayConfig) -> list:
    """Deterministic bar-ordered tick log: one (price, volume) tick per
    asset per bar, asset order seeded-shuffled within each bar.  Faults
    (not this generator) create the disorder a real feed would."""
    rng = random.Random(cfg.seed)
    r = np.random.default_rng(cfg.seed)
    A, B = cfg.n_assets, cfg.bars
    steps = r.normal(0.0, 0.01, size=(A, B))
    prices = 100.0 * np.exp(np.cumsum(steps, axis=1))
    volumes = r.lognormal(mean=10.0, sigma=0.4, size=(A, B))
    tickers = [f"S{i:03d}" for i in range(A)]
    out = []
    seq = 0
    for b in range(B):
        order = list(range(A))
        rng.shuffle(order)
        bar_time = cfg.t0_ns + b * cfg.bar_period_ns
        for a in order:
            out.append(Tick(asset=tickers[a], bar_time=bar_time,
                            price=float(prices[a, b]),
                            volume=float(volumes[a, b]), seq=seq))
            seq += 1
    return out


def builtin_fault_plan(cfg: ReplayConfig):
    """The canonical replay fault plan: late + out-of-order ticks (a
    deterministic delay cycle straddling the lateness allowance, so both
    merge AND quarantine outcomes occur), duplicates, one whole-bar gap,
    and exactly one ingest-serve version-skew event."""
    from csmom_tpu.chaos.plan import Fault, FaultPlan

    A, B = cfg.n_assets, cfg.bars
    total = A * B
    gap_bar = max(cfg.lookback + cfg.skip + 2, int(B * 0.7))
    return FaultPlan(
        name="replay-builtin-faults", seed=cfg.seed + 12, faults=(
            Fault(point="stream.tick", action="tick_late",
                  after=int(total * 0.35), max_fires=6),
            Fault(point="stream.tick", action="tick_late",
                  after=int(total * 0.55), max_fires=5),
            Fault(point="stream.tick", action="tick_dup",
                  after=int(total * 0.45), max_fires=4),
            Fault(point="stream.tick", action="tick_drop",
                  after=gap_bar * A, max_fires=A),
            Fault(point="stream.serve", action="version_skew",
                  after=2, max_fires=1),
        ))


# ------------------------------------------------------------------- run ---

def _delay_cycle(lateness: int, max_delay: int):
    """Deterministic tick_late delays straddling the watermark: delays
    <= lateness merge late, delays > lateness quarantine — a fault plan
    that fires tick_late more than twice provably exercises BOTH paths."""
    lo = max(1, lateness)
    hi = max(lateness + 2, min(max_delay, lateness + 3))
    return itertools.cycle([lo, hi, max(1, lateness - 1), hi + 1])


def _pad_for_engine(values, mask, a_bucket: int, bars: int, dtype):
    """Left-pad the time axis and bottom-pad the asset axis up to the
    warmed (a_bucket, bars) manifest shape.  Padding is masked, so the
    padded engines' LAST column equals the unpadded recompute for the
    real rows (row-independent signals; leading masked columns shift
    indices, never trailing-window values)."""
    A, T = values.shape
    out_v = np.full((a_bucket, bars), np.nan, dtype)
    out_m = np.zeros((a_bucket, bars), bool)
    out_v[:A, bars - T:] = values
    out_m[:A, bars - T:] = mask
    return out_v, out_m


class _EngineReconciler:
    """On-device reconciliation against the REAL jitted signals engines
    (the ``stream`` manifest profile's entries) — the equivalence check
    the tentpole promises, dispatched only at warmed shapes."""

    def __init__(self, cfg: ReplayConfig, a_bucket: int):
        self.cfg = cfg
        self.a_bucket = a_bucket
        self.checks = 0
        self.max_abs_diff = 0.0

    def warm(self) -> None:
        z = np.zeros((self.a_bucket, self.cfg.bars),
                     np.dtype(self.cfg.dtype))
        m = np.zeros((self.a_bucket, self.cfg.bars), bool)
        self._mom(z, m)
        self._turn(z, m)

    def _mom(self, v, m):
        import jax

        from csmom_tpu.signals.momentum import momentum

        out, ok = momentum(v, m, lookback=self.cfg.lookback,
                           skip=self.cfg.skip)
        jax.block_until_ready(out)
        return np.asarray(out), np.asarray(ok)

    def _turn(self, v, m):
        import jax

        from csmom_tpu.signals.turnover import turnover_features

        shares = np.ones(self.a_bucket, v.dtype)
        (out, ok) = turnover_features(
            v, m, shares, lookback=self.cfg.turn_lookback)["turn_avg"]
        jax.block_until_ready(out)
        return np.asarray(out), np.asarray(ok)

    def check(self, snapshot, mom_cur, turn_cur) -> None:
        dt = np.dtype(self.cfg.dtype)
        A = snapshot.n_assets
        pv, pm = _pad_for_engine(
            np.asarray(snapshot.values["price"], dt),
            snapshot.mask["price"], self.a_bucket, self.cfg.bars, dt)
        mom, _ = self._mom(pv, pm)
        vv, vm = _pad_for_engine(
            np.asarray(snapshot.values["volume"], dt),
            snapshot.mask["volume"], self.a_bucket, self.cfg.bars, dt)
        turn, _ = self._turn(vv, vm)
        for ref, cur in ((mom[:A, -1], mom_cur), (turn[:A, -1], turn_cur)):
            both = np.isfinite(ref) & np.isfinite(cur)
            if both.any():
                d = float(np.max(np.abs(ref[both] - cur[both])))
                self.max_abs_diff = max(self.max_abs_diff, d)
        self.checks += 1


def run_replay(cfg: ReplayConfig) -> dict:
    """Drive the full loop; returns the REPLAY artifact object."""
    from csmom_tpu.chaos.inject import checkpoint
    from csmom_tpu.obs import metrics, span
    from csmom_tpu.serve.service import ServeConfig, SignalService

    cfg.validate()
    dt = np.dtype(cfg.dtype)
    log = synth_tick_log(cfg)
    tickers = sorted({t.asset for t in log})
    ring = LiveRing(tickers, capacity=cfg.resolved_capacity(),
                    fields=("price", "volume"), dtype=dt)
    ing = StreamIngestor(ring, WatermarkPolicy(
        bar_period_ns=cfg.bar_period_ns,
        allowed_lateness_bars=cfg.allowed_lateness_bars))
    mom_upd = IncrementalMomentum(len(tickers), lookback=cfg.lookback,
                                  skip=cfg.skip, dtype=dt)
    turn_upd = IncrementalTurnover(len(tickers),
                                   shares=np.ones(len(tickers)),
                                   lookback=cfg.turn_lookback, dtype=dt)

    svc = SignalService(ServeConfig(
        profile=cfg.profile, engine=cfg.engine,
        default_deadline_s=cfg.deadline_s))
    svc.attach_live_version(lambda: ring.version,
                            max_skew=cfg.max_version_skew)
    svc.start()
    spec = svc.spec
    a_bucket = spec.asset_bucket_for(len(tickers))

    engine_rec = None
    compile_stats0 = None
    if cfg.engine == "jax":
        from csmom_tpu.utils.profiling import compile_stats

        engine_rec = _EngineReconciler(cfg, a_bucket)
        engine_rec.warm()  # after this, the replay window must not compile
        compile_stats0 = compile_stats()

    delays = _delay_cycle(cfg.allowed_lateness_bars, cfg.max_delay_bars)
    held: list = []               # (release_bar, tick) — late/ooo buffer
    dropped_gap = 0
    duplicated = 0
    requests: list = []           # (request, snapshot_last_bar_time)
    bar_clock: list = []          # (mono wall, ingest frontier bar time)
    held_snapshot = None          # the stale snapshot a skew event serves
    skew_events = 0               # probes that served from a stale snapshot
    skew_attempts = 0             # stale-version REQUESTS submitted

    by_bar: dict = {}
    for t in log:
        by_bar.setdefault(t.bar_time, []).append(t)
    bar_times = sorted(by_bar)

    def _on_merge_or_outcome(outcome: str) -> None:
        if outcome == "merged_late":
            mom_upd.mark_dirty()
            turn_upd.mark_dirty()
        metrics.counter(f"replay.{outcome}").inc()

    def _release(upto_bar_idx: int) -> None:
        still = []
        for rel, tick in held:
            if rel <= upto_bar_idx:
                _on_merge_or_outcome(ing.offer(tick))
            else:
                still.append((rel, tick))
        held[:] = still

    def _probe(bar_idx: int) -> None:
        nonlocal held_snapshot, skew_events, skew_attempts
        snap = ring.snapshot()
        mom_upd.sync(snap)
        turn_upd.sync(snap)
        if snap.n_bars < spec.months:
            return
        if held_snapshot is None:
            held_snapshot = snap
        fired = checkpoint("stream.serve", bar=bar_idx,
                           version=snap.version)
        use = snap
        if fired == "version_skew" and held_snapshot.version < snap.version:
            use = held_snapshot      # serve from a stale panel: must refuse
            skew_events += 1
        for k in range(cfg.requests_per_probe):
            kind = "momentum" if k % 2 == 0 else "turnover"
            field = "price" if kind == "momentum" else "volume"
            v, m = use.window(field, spec.months)
            if use is held_snapshot and use is not snap:
                skew_attempts += 1
            requests.append((svc.submit(
                kind, np.asarray(v, np.dtype(spec.dtype)), m,
                deadline_s=cfg.deadline_s, panel_version=use.version),
                use.last_bar_time))

    def _reconcile(bar_idx: int) -> None:
        snap = ring.snapshot()
        mom_upd.reconcile(snap)
        turn_upd.reconcile(snap)
        if engine_rec is not None:
            engine_rec.check(snap, mom_upd.current()[0],
                             turn_upd.current()[0])

    t_start = mono_now_s()
    with span("replay.run", root=True, run=cfg.run_id, bars=cfg.bars):
        for b, bt in enumerate(bar_times):
            for tick in by_bar[bt]:
                fired = checkpoint("stream.tick", seq=tick.seq, bar=b)
                if fired == "tick_drop":
                    dropped_gap += 1
                    continue
                if fired == "tick_late":
                    held.append((b + next(delays), tick))
                    continue
                outcome = ing.offer(tick)
                _on_merge_or_outcome(outcome)
                if fired == "tick_dup":
                    duplicated += 1
                    _on_merge_or_outcome(ing.offer(tick))
            _release(b)
            bar_clock.append((mono_now_s(), ring.last_bar_time))
            # consume the bar(s) just closed into the running updaters
            snap_needed = mom_upd.dirty or turn_upd.dirty
            if not snap_needed:
                for g in range(mom_upd.consumed, ring.next_bar_index):
                    pv, pm = ring.column("price", g)
                    vv, vm = ring.column("volume", g)
                    mom_upd.update(pv, pm)
                    turn_upd.update(vv, vm)
            else:
                snap = ring.snapshot()
                mom_upd.sync(snap)
                turn_upd.sync(snap)
            if (b + 1) % cfg.serve_every_bars == 0:
                _probe(b)
            if b and (b + 1) % cfg.reconcile_every_bars == 0:
                _reconcile(b)
        # end of log: flush the late buffer, close the books.  A flushed
        # tick for the FINAL bar lands as 'applied' — but that bar was
        # already consumed, so it dirties the updaters exactly like a
        # merge (the final reconcile would otherwise read it as drift)
        for rel, tick in held:
            _on_merge_or_outcome(ing.offer(tick))
            mom_upd.mark_dirty()
            turn_upd.mark_dirty()
        held.clear()
        _reconcile(len(bar_times))
        give_up = mono_now_s() + 30.0
        for r, _ in requests:
            r.wait(timeout=max(0.0, give_up - mono_now_s()))
        svc.stop(drain=True)
    wall_s = mono_now_s() - t_start

    # served-response staleness: how far ingest had moved past each
    # response's snapshot by the time the response completed — measured
    # against the per-bar ingest clock, served requests only (a refused
    # skew probe's lag is the injected fault, not serving staleness)
    walls = [w for w, _ in bar_clock]
    staleness_ms: list = []
    for r, snap_last in requests:
        if r.state != "served" or r.t_done_s is None:
            continue
        i = bisect.bisect_right(walls, r.t_done_s) - 1
        frontier = bar_clock[i][1] if i >= 0 else snap_last
        staleness_ms.append(max(0, frontier - snap_last) / 1e6)

    fresh = 0 if cfg.engine != "jax" else None
    if compile_stats0 is not None:
        from csmom_tpu.utils.profiling import compile_stats

        fresh = compile_stats().delta(compile_stats0).backend_compiles
    return build_artifact(
        cfg, ing, ring, svc, [r for r, _ in requests], wall_s,
        generated=len(log), dropped_gap=dropped_gap, duplicated=duplicated,
        staleness_ms=staleness_ms, skew_events=skew_events,
        skew_attempts=skew_attempts,
        mom_upd=mom_upd, turn_upd=turn_upd, engine_rec=engine_rec,
        fresh_compiles=(fresh if fresh is not None
                        else "not measurable: compile stats unavailable"),
    )


def build_artifact(cfg, ing, ring, svc, requests, wall_s, *, generated,
                   dropped_gap, duplicated, staleness_ms, skew_events,
                   skew_attempts, mom_upd, turn_upd, engine_rec,
                   fresh_compiles) -> dict:
    """The REPLAY artifact: closed tick books, version reconciliation,
    serve books, reconcile evidence — everything the ``replay`` schema
    kind enforces."""
    acct = ing.accounting()
    sacct = svc.accounting()
    served = [r for r in requests if r.state == "served"]
    versions = [r.panel_version for r in served
                if r.panel_version is not None]
    ring_stats = ring.stats()
    tps = round(acct["offered"] / wall_s, 3) if wall_s > 0 else 0.0
    workload = (
        f"replay {cfg.bars}x{cfg.n_assets} {cfg.bar_period_ns // 10**9}s-"
        f"bars seed {cfg.seed}, lateness {cfg.allowed_lateness_bars} bars, "
        f"serve profile {cfg.profile} ({cfg.dtype}, {cfg.engine} engine)"
    )
    extra = {
        "platform": _platform(svc),
        "engine": cfg.engine,
        "workload": workload,
        "warm_report": svc.warm_report,
    }
    if cfg.profile == "serve-smoke":
        extra["smoke"] = ("smoke-bucket replay: pipeline-shaped, workload "
                          "reduced — NOT a performance capture")
    reconcile = {
        "count": mom_upd.reconciliations + turn_upd.reconciliations,
        "drift_events": mom_upd.drift_events + turn_upd.drift_events,
        "rebuilds": mom_upd.rebuilds + turn_upd.rebuilds,
        # window-slide re-anchors (ring wrapped past the prefix anchor):
        # expected whenever bars > capacity, and NOT drift — the defect
        # (a) fix made this a counted, first-class event
        "reanchors": mom_upd.reanchors + turn_upd.reanchors,
        "engine_checks": 0 if engine_rec is None else engine_rec.checks,
        "engine_max_abs_diff": (
            0.0 if engine_rec is None
            else round(engine_rec.max_abs_diff, 12)),
    }
    return {
        "kind": "replay",
        "schema_version": SCHEMA_VERSION,
        "run_id": cfg.run_id,
        "metric": "replay_ticks_per_s",
        "value": tps,
        "unit": "ticks/s",
        "vs_baseline": 1.0,
        "wall_s": round(wall_s, 4),
        "ticks": {
            "generated": generated,
            "offered": acct["offered"],
            "applied": acct["applied"],
            "merged_late": acct["merged_late"],
            "quarantined": acct["quarantined"],
            "deduped": acct["deduped"],
            "dropped_gap": dropped_gap,
            "duplicated": duplicated,
        },
        "panel": {
            "version_final": ring_stats["version"],
            "bars_appended": ring_stats["bars_appended"],
            "bars_in_window": ring_stats["bars_in_window"],
            "capacity": ring_stats["capacity"],
            "evictions": ring_stats["evictions"],
            "gap_bars": acct["gap_bars"],
            "stale_bars": ring_stats["stale_bars"],
            "unfilled_cells": ring_stats["unfilled_cells"],
            "merge_version_bumps": acct["merge_version_bumps"],
        },
        "versions": {
            "ingest_final": ring_stats["version"],
            "serve_min": min(versions) if versions else None,
            "serve_max": max(versions) if versions else None,
            "skew_events": skew_events,        # stale-snapshot probes
            "skew_attempts": skew_attempts,    # stale-version requests
            "skew_refusals": sacct.get("rejected_version_skew", 0),
        },
        "serve": {
            "requests": sacct,
            "latency_ms": {"total": _percentiles(
                [r.total_s for r in served if r.total_s is not None])},
        },
        "staleness_ms": dict(
            _percentiles([s / 1e3 for s in staleness_ms]),
            max=round(max(staleness_ms), 3) if staleness_ms else None,
            n=len(staleness_ms),
        ),
        "reconcile": reconcile,
        "compile": {
            "in_window_fresh_compiles": fresh_compiles,
            "note": "backend_compiles delta since the post-warm snapshot "
                    "(serve buckets + stream reconcile entries): 0 = the "
                    "whole replay window dispatched warmed shapes only",
        },
        "offered": {
            "seed": cfg.seed,
            "n_assets": cfg.n_assets,
            "bars": cfg.bars,
            "bar_period_ms": cfg.bar_period_ns / 1e6,
            "allowed_lateness_bars": cfg.allowed_lateness_bars,
            "serve_every_bars": cfg.serve_every_bars,
            "reconcile_every_bars": cfg.reconcile_every_bars,
            "deadline_ms": round(1e3 * cfg.deadline_s, 3),
        },
        "extra": extra,
    }


def _platform(svc) -> str:
    if svc.engine.name == "stub":
        return "stub"
    import jax

    return jax.default_backend()
