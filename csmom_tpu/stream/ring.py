"""Ring-buffered live panel: a fixed-capacity time axis that absorbs ticks.

The batch world's :class:`csmom_tpu.panel.panel.Panel` is built once and
frozen; the live world appends a bar at a time.  This ring is the bridge:
a dense ``[A, capacity]`` array family (one per field) whose columns are
a circular window over a *global* monotone bar index, so appending bar
``n`` costs one column write — no reallocation, no shifting, and the
arrays backing a long-running session never move (the buffers are
allocated once and donated to every update in place, which is what lets
a jitted on-device mirror reuse its HBM block instead of reallocating
per bar).

Versioning is the consistency contract with the serving side:

- every mutation (bar append, tick write, late merge) bumps a
  monotonically increasing ``version`` — there is no "modified in place
  without anyone knowing" state;
- :meth:`snapshot` captures an IMMUTABLE copy (read-only numpy arrays)
  stamped with the version at capture time.  A consumer holding a
  snapshot can be audited: a response stamped ``panel_version=v`` was
  computed from exactly the data version ``v`` described, and the
  replay artifact's ingest-vs-serve version reconciliation is checkable
  arithmetic, not trust.

Staleness is explicit, never synthesized: a bar the stream skipped is
materialized as a masked, NaN, ``stale``-flagged column — the ring
NEVER carries the last price forward into a gap.  Downstream signal
engines apply their own documented pad semantics (``signals.momentum``
forward-fills by design); the point is that the *data layer* records
"missing", and the ``stale`` plane lets a server measure and refuse
staleness instead of discovering it in a P&L.

Time discipline: this module reads NO clock.  Bar times are event time
from the tick log (int64 epoch-ns), versions are counters; wall-clock
throughput is the replay harness's business.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["LiveRing", "RingSnapshot", "T_MIN"]

# sentinel bar time of a never-written column (int64 epoch-ns domain)
T_MIN = np.iinfo(np.int64).min


@dataclasses.dataclass(frozen=True)
class RingSnapshot:
    """An immutable, versioned view of the live window (oldest -> newest).

    Arrays are copies with ``writeable=False``: a snapshot taken at
    version ``v`` still describes version ``v`` after a million more
    ticks have landed in the ring.
    """

    version: int
    first_bar_index: int          # global index of column 0
    bar_times: np.ndarray         # int64[W] event-time ns, ascending
    values: dict                  # field -> f[A, W]
    mask: dict                    # field -> bool[A, W]
    stale: np.ndarray             # bool[W] gap-materialized bars
    tickers: tuple

    @property
    def n_bars(self) -> int:
        return int(self.bar_times.shape[0])

    @property
    def n_assets(self) -> int:
        return len(self.tickers)

    @property
    def last_bar_time(self) -> int:
        return int(self.bar_times[-1]) if self.n_bars else T_MIN

    def window(self, field: str, bars: int | None = None) -> tuple:
        """``(values, mask)`` of the trailing ``bars`` columns (all when
        None).  Views into the snapshot's read-only arrays — zero-copy,
        still immutable."""
        v = self.values[field]
        m = self.mask[field]
        if bars is None or bars >= v.shape[1]:
            return v, m
        return v[:, -bars:], m[:, -bars:]


class LiveRing:
    """Fixed-capacity multi-field ring over the time axis.

    Bars are identified by a GLOBAL monotone index (bar 0 is the first
    ever appended); column ``i % capacity`` holds bar ``i``.  The live
    window is ``[next_bar - min(next_bar, capacity), next_bar)``.
    """

    def __init__(self, tickers, capacity: int, fields=("price", "volume"),
                 dtype=np.float64):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        if not tickers:
            raise ValueError("need at least one ticker")
        self.tickers = tuple(tickers)
        self.capacity = int(capacity)
        self.fields = tuple(fields)
        self.dtype = np.dtype(dtype)
        A = len(self.tickers)
        self._values = {f: np.full((A, self.capacity), np.nan, self.dtype)
                        for f in self.fields}
        self._mask = {f: np.zeros((A, self.capacity), bool)
                      for f in self.fields}
        self._bar_times = np.full(self.capacity, T_MIN, np.int64)
        self._stale = np.zeros(self.capacity, bool)
        self._next_bar = 0            # global index the NEXT append gets
        self._version = 0
        self._evictions = 0           # bars overwritten by ring wrap
        self._asset_index = {t: i for i, t in enumerate(self.tickers)}

    # ------------------------------------------------------------ queries --

    @property
    def version(self) -> int:
        return self._version

    @property
    def n_bars(self) -> int:
        """Bars currently IN the window (<= capacity)."""
        return min(self._next_bar, self.capacity)

    @property
    def next_bar_index(self) -> int:
        return self._next_bar

    @property
    def first_bar_index(self) -> int:
        return self._next_bar - self.n_bars

    @property
    def evictions(self) -> int:
        return self._evictions

    @property
    def last_bar_time(self) -> int:
        if self._next_bar == 0:
            return T_MIN
        return int(self._bar_times[self._col(self._next_bar - 1)])

    def asset_index(self, ticker: str) -> int:
        return self._asset_index[ticker]

    def in_window(self, bar_index: int) -> bool:
        return self.first_bar_index <= bar_index < self._next_bar

    def bar_time(self, bar_index: int) -> int:
        if not self.in_window(bar_index):
            raise IndexError(
                f"bar {bar_index} outside the live window "
                f"[{self.first_bar_index}, {self._next_bar})")
        return int(self._bar_times[self._col(bar_index)])

    def _col(self, bar_index: int) -> int:
        return bar_index % self.capacity

    # ---------------------------------------------------------- mutations --

    def append_bar(self, bar_time: int, stale: bool = False) -> int:
        """Open a new bar column at event time ``bar_time``; returns its
        global index.  ``stale=True`` marks a gap-materialized bar (the
        stream skipped it; no data, no carry).  Bar times must be
        strictly ascending — out-of-order bars are the INGESTOR's
        business (it merges or quarantines them), never the ring's."""
        bar_time = int(bar_time)
        if self._next_bar and bar_time <= self.last_bar_time:
            raise ValueError(
                f"append_bar({bar_time}) not after the latest bar "
                f"({self.last_bar_time}); late data merges via write()")
        idx = self._next_bar
        col = self._col(idx)
        if idx >= self.capacity:
            self._evictions += 1
        for f in self.fields:
            self._values[f][:, col] = np.nan
            self._mask[f][:, col] = False
        self._bar_times[col] = bar_time
        self._stale[col] = stale
        self._next_bar = idx + 1
        self._version += 1
        return idx

    def write(self, field: str, asset: int | str, bar_index: int,
              value: float) -> None:
        """Set one (asset, bar) cell; bumps the version.  Writing into a
        past in-window bar IS the late-merge path — the cell's bar loses
        its stale flag only if every field stays NaN-consistent (a bar
        with any real observation is no longer a pure gap)."""
        if isinstance(asset, str):
            asset = self._asset_index[asset]
        if not self.in_window(bar_index):
            raise IndexError(
                f"bar {bar_index} outside the live window "
                f"[{self.first_bar_index}, {self._next_bar})")
        col = self._col(bar_index)
        self._values[field][asset, col] = value
        self._mask[field][asset, col] = np.isfinite(value)
        if np.isfinite(value):
            self._stale[col] = False
        self._version += 1

    def column(self, field: str, bar_index: int) -> tuple:
        """``(values[A], mask[A])`` copies of one in-window bar — the
        O(A) read the incremental updaters consume at bar close."""
        if not self.in_window(bar_index):
            raise IndexError(
                f"bar {bar_index} outside the live window "
                f"[{self.first_bar_index}, {self._next_bar})")
        col = self._col(bar_index)
        return (self._values[field][:, col].copy(),
                self._mask[field][:, col].copy())

    def cell_written(self, field: str, asset: int | str,
                     bar_index: int) -> bool:
        if isinstance(asset, str):
            asset = self._asset_index[asset]
        if not self.in_window(bar_index):
            return False
        return bool(self._mask[field][asset, self._col(bar_index)])

    # ----------------------------------------------------------- snapshot --

    def snapshot(self) -> RingSnapshot:
        """Immutable versioned copy of the live window, time-ordered."""
        n = self.n_bars
        first = self.first_bar_index
        cols = np.array([self._col(first + i) for i in range(n)], int)
        values = {}
        mask = {}
        for f in self.fields:
            v = self._values[f][:, cols].copy()
            m = self._mask[f][:, cols].copy()
            v.flags.writeable = False
            m.flags.writeable = False
            values[f] = v
            mask[f] = m
        bt = self._bar_times[cols].copy()
        st = self._stale[cols].copy()
        bt.flags.writeable = False
        st.flags.writeable = False
        return RingSnapshot(
            version=self._version, first_bar_index=first, bar_times=bt,
            values=values, mask=mask, stale=st, tickers=self.tickers,
        )

    def stats(self) -> dict:
        n = self.n_bars
        cells = n * len(self.tickers)
        unfilled = 0
        stale_bars = 0
        if n:
            first = self.first_bar_index
            cols = np.array([self._col(first + i) for i in range(n)], int)
            unfilled = int((~self._mask[self.fields[0]][:, cols]).sum())
            stale_bars = int(self._stale[cols].sum())
        return {
            "version": self._version,
            "bars_appended": self._next_bar,
            "bars_in_window": n,
            "capacity": self.capacity,
            "evictions": self._evictions,
            "stale_bars": stale_bars,
            "unfilled_cells": unfilled,
            "cells": cells,
        }
