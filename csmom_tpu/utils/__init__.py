"""Cross-cutting utilities: logging, profiling, numerical guards."""

from csmom_tpu.utils.logging import get_logger
from csmom_tpu.utils.profiling import fetch, measure_rtt, wall, trace
from csmom_tpu.utils.guards import validate_panel, checked

__all__ = ["get_logger", "fetch", "measure_rtt", "wall", "trace",
           "validate_panel", "checked"]
