"""Cross-cutting utilities: logging, profiling, numerical guards."""

from csmom_tpu.utils.logging import get_logger

__all__ = ["get_logger"]
