"""Deadline guard for externally-timeout'd measurement processes.

The capture processes (bench.py children, benchmarks/tpu_scaling.py,
benchmarks/grid_phases.py) run under a hard external ``timeout`` because
the tunneled TPU backend can hang at any point.  A SIGKILL at that
timeout must never discard what the process already measured — r4/r5
lost complete on-chip headlines exactly this way.  This guard arms a
timer that prints a caller-built partial summary and exits 0 just before
the external deadline, under a lock so exactly one summary line ever
reaches stdout.

The deadline is anchored at ``t0`` — the CALLER's module-import time,
not guard-arm time: tunneled jax startup (import, device init, RTT
probe) can eat 60-120 s before the guard is armed, and an unanchored
timer would fire after the external SIGKILL, which is the bug this
module exists to prevent.

The reference has no analogue (no benchmarks, no timeouts —
``/root/reference/README.md`` is a bare title); this is capture-harness
plumbing for the TPU rebuild's evidence discipline.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

__all__ = ["deadline_guard"]


def _emit(line: str, *, flush_first: bool) -> None:
    """Write the summary as ONE ``os.write`` syscall, preceded by a newline.

    The driver parses the process's TRAILING JSON line, and callers print
    per-row progress concurrently with the watchdog thread — two buffered
    ``print``s can interleave at the stream-buffer level and corrupt that
    line.  A single ``os.write`` to fd 1 is one syscall (atomic for pipe
    writes up to PIPE_BUF-sized chunks and never interleaved mid-call by
    the kernel for regular files), and the leading newline terminates any
    half-flushed progress row so the JSON always starts at column 0.

    ``flush_first`` orders any buffered progress output BEFORE the summary
    — safe only on the caller's own thread.  The watchdog must NOT flush:
    the main thread may be blocked mid-write holding the stream's internal
    lock (a full pipe on a hung tunnel), and the watchdog taking that lock
    would deadlock the very dump that exists to beat the SIGKILL.  Its
    half-buffered rows die with ``os._exit``, which is the safe outcome.

    On the watchdog path there is one more race: between this write and
    the ``os._exit`` that follows it, the main thread can fill its stream
    buffer and flush a progress fragment AFTER the summary, displacing the
    trailing line.  So the watchdog first points fd 1 at ``/dev/null``
    (late flushes vanish) and emits on a private dup of the real stream.
    """
    fd = 1
    if flush_first:
        try:
            sys.stdout.flush()
        except Exception:
            pass
    else:
        try:
            fd = os.dup(1)
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, 1)
        except OSError:
            fd = 1  # quarantine unavailable: emit on the raw fd anyway
    os.write(fd, ("\n" + line + "\n").encode())


def deadline_guard(
    env_var: str,
    partial_line: Callable[[], Optional[str]],
    t0: float,
    margin_s: float = 45.0,
    min_delay_s: float = 30.0,
) -> Callable[[str], None]:
    """Arm a partial-dump watchdog; returns ``finish(line)`` for the caller.

    ``env_var`` names the wall-budget env (seconds since ``t0``); unset or
    0 arms nothing.  When the budget (minus ``margin_s``) expires,
    ``partial_line()`` is called: a string is printed and the process
    exits 0 (an explicitly-partial but parseable record); ``None`` means
    nothing worth a line was measured yet and the process exits 3.  The
    caller's normal path ends with ``finish(full_line)``, which wins the
    lock, cancels the timer, and prints — whichever of the two prints
    first is the process's single stdout summary line.
    """
    budget = float(os.environ.get(env_var, "0") or 0)
    lock = threading.Lock()
    done = threading.Event()

    def _fire():
        with lock:
            if done.is_set():
                return  # full line already printed (or printing won race)
            line = partial_line()
            if line is None:
                os._exit(3)  # nothing measured: no artifact-worthy line
            _emit(line, flush_first=False)  # no flush: see _emit
            os._exit(0)

    timer = None
    if budget:
        # min_delay_s floors the fuse so a guard armed late (or a tiny
        # budget) still gives the measurement a beat to land its first
        # result; tests shrink it to exercise the firing path quickly
        delay = max(min_delay_s, budget - (time.monotonic() - t0) - margin_s)
        timer = threading.Timer(delay, _fire)
        timer.daemon = True
        timer.start()

    def finish(line: str) -> None:
        with lock:
            done.set()
            if timer is not None:
                timer.cancel()
            # caller's thread: progress rows it printed flush first, then
            # the summary lands as one uninterleavable write
            _emit(line, flush_first=True)

    return finish
