"""Deadline guard for externally-timeout'd measurement processes.

The capture processes (bench.py children, benchmarks/tpu_scaling.py,
benchmarks/grid_phases.py) run under a hard external ``timeout`` because
the tunneled TPU backend can hang at any point.  A SIGKILL at that
timeout must never discard what the process already measured — r4/r5
lost complete on-chip headlines exactly this way.  This guard arms a
timer that prints a caller-built partial summary and exits 0 just before
the external deadline, under a lock so exactly one summary line ever
reaches stdout.

The deadline is anchored at ``t0`` — the CALLER's module-import time,
not guard-arm time: tunneled jax startup (import, device init, RTT
probe) can eat 60-120 s before the guard is armed, and an unanchored
timer would fire after the external SIGKILL, which is the bug this
module exists to prevent.

Clock discipline: every anchor and every elapsed computation here is
``time.monotonic()``.  The wall clock (``time.time``) is NEVER consulted
— an NTP step or a deliberate skew (the chaos ``clock_skew`` fault,
:mod:`csmom_tpu.chaos`) during a capture would otherwise shorten or
stretch the fuse and either lose the window to the external SIGKILL or
dump a partial while time remained.  ``t0`` MUST therefore come from
``time.monotonic()``; a wall-clock anchor (epoch seconds) is detected at
arm time and re-anchored to "now" with a stderr note, because a silently
never-firing guard is the precise failure this module exists to prevent.

Code that legitimately needs the wall clock (file-mtime TTLs, identity
stamps) must go through :func:`wall_now_s` / :func:`file_age_s` /
:func:`marker_fresh` below — the skew-resistant CLOCK_REALTIME readers —
rather than ``time.time``; the ``clock-discipline`` rule of ``csmom
lint`` (csmom_tpu/analysis/rules.py, tier-1) enforces exactly that,
alias-aware, so rebinding the clock under another name does not dodge
it.

The reference has no analogue (no benchmarks, no timeouts —
``/root/reference/README.md`` is a bare title); this is capture-harness
plumbing for the TPU rebuild's evidence discipline.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

__all__ = ["deadline_guard", "file_age_s", "marker_fresh", "mono_now_s",
           "trip_active_guard", "wall_now_s"]


def mono_now_s() -> float:
    """Current monotonic seconds — THE clock for durations and deadlines.

    The serve/ queue and batcher (and any future timing path) read time
    through this helper instead of calling ``time.monotonic()`` inline,
    so the time-discipline lint can pin whole modules to "all timing goes
    through utils.deadline" the same way it pins the wall clock: one
    documented home, grep-enforceable, skew-proof by construction (a
    chaos ``clock_skew`` fault perturbs ``time.time`` only).
    """
    return time.monotonic()


# -- skew-resistant wall-clock helpers ---------------------------------------
#
# Some checks genuinely need the wall clock: a file-mtime TTL ("is this
# probe-success marker recent?") compares against st_mtime, which IS wall
# time — no monotonic clock can age a file written by another process.
# But ``time.time`` is exactly what the chaos ``clock_skew`` fault (and a
# real NTP step, partially) perturbs, and the r7 skew-proofing banned it
# from this module.  These helpers are the one documented home for such
# checks: they read CLOCK_REALTIME through ``time.clock_gettime``, which
# the skew fault's monkeypatch cannot touch, and they clamp the
# pathological cases (negative ages from a backwards step) toward the
# SAFE side — "stale", never "fresh forever".

def wall_now_s() -> float:
    """Current wall-clock seconds (CLOCK_REALTIME), immune to the chaos
    ``clock_skew`` monkeypatch of ``time.time``.  For identity stamps and
    file-age comparisons only — NEVER for durations (use monotonic)."""
    return time.clock_gettime(time.CLOCK_REALTIME)


def file_age_s(path: str) -> float:
    """Age of ``path`` in seconds (>= 0) per its mtime.  A negative raw
    age (mtime in the future: a backwards clock step, a copied file)
    clamps to +inf — an unknowable age must read as stale, not fresh.
    Raises ``OSError`` when the file is absent/unstatable."""
    age = wall_now_s() - os.path.getmtime(path)
    return age if age >= 0 else float("inf")


def marker_fresh(path: str, ttl_s: float) -> bool:
    """True iff ``path`` exists and is younger than ``ttl_s`` — the
    skew-safe form of the wall-clock-minus-getmtime TTL idiom.
    ``ttl_s <= 0`` means "never fresh" (TTL disabled); a missing or
    unstatable marker is simply not fresh."""
    if ttl_s <= 0:
        return False
    try:
        return file_age_s(path) < ttl_s
    except OSError:
        return False

# the most recently armed guard's fire callable, for the chaos
# ``trip_deadline`` fault (one guard per capture process by construction)
_ACTIVE_FIRE: Optional[Callable[[], None]] = None


def trip_active_guard() -> bool:
    """Fire the armed deadline guard NOW (chaos hook).

    Behaves exactly as if the budget expired at this instant: the partial
    line (if any) is emitted through the quarantined path and the process
    exits.  Returns False when no guard is armed in this process (the
    caller logs; a rehearsal asserting on guard behavior treats that as a
    wiring failure, not a pass).
    """
    fire = _ACTIVE_FIRE
    if fire is None:
        return False
    fire()
    return True  # pragma: no cover - fire() exits the process


def _emit(line: str, *, flush_first: bool) -> None:
    """Write the summary as ONE ``os.write`` syscall, preceded by a newline.

    The driver parses the process's TRAILING JSON line, and callers print
    per-row progress concurrently with the watchdog thread — two buffered
    ``print``s can interleave at the stream-buffer level and corrupt that
    line.  A single ``os.write`` to fd 1 is one syscall (atomic for pipe
    writes up to PIPE_BUF-sized chunks and never interleaved mid-call by
    the kernel for regular files), and the leading newline terminates any
    half-flushed progress row so the JSON always starts at column 0.

    ``flush_first`` orders any buffered progress output BEFORE the summary
    — safe only on the caller's own thread.  The watchdog must NOT flush:
    the main thread may be blocked mid-write holding the stream's internal
    lock (a full pipe on a hung tunnel), and the watchdog taking that lock
    would deadlock the very dump that exists to beat the SIGKILL.  Its
    half-buffered rows die with ``os._exit``, which is the safe outcome.

    On the watchdog path there is one more race: between this write and
    the ``os._exit`` that follows it, the main thread can fill its stream
    buffer and flush a progress fragment AFTER the summary, displacing the
    trailing line.  So the watchdog first points fd 1 at ``/dev/null``
    (late flushes vanish) and emits on a private dup of the real stream.
    """
    fd = 1
    if flush_first:
        try:
            sys.stdout.flush()
        except Exception:
            pass
    else:
        try:
            fd = os.dup(1)
            devnull = os.open(os.devnull, os.O_WRONLY)
            os.dup2(devnull, 1)
        except OSError:
            fd = 1  # quarantine unavailable: emit on the raw fd anyway
    os.write(fd, ("\n" + line + "\n").encode())


def deadline_guard(
    env_var: str,
    partial_line: Callable[[], Optional[str]],
    t0: float,
    margin_s: float = 45.0,
    min_delay_s: float = 30.0,
) -> Callable[[str], None]:
    """Arm a partial-dump watchdog; returns ``finish(line)`` for the caller.

    ``env_var`` names the wall-budget env (seconds since ``t0``); unset or
    0 arms nothing.  When the budget (minus ``margin_s``) expires,
    ``partial_line()`` is called: a string is printed and the process
    exits 0 (an explicitly-partial but parseable record); ``None`` means
    nothing worth a line was measured yet and the process exits 3.  The
    caller's normal path ends with ``finish(full_line)``, which wins the
    lock, cancels the timer, and prints — whichever of the two prints
    first is the process's single stdout summary line.
    """
    global _ACTIVE_FIRE
    budget = float(os.environ.get(env_var, "0") or 0)
    lock = threading.Lock()
    done = threading.Event()

    # a wall-clock anchor (epoch seconds from time.time, ~1.7e9) instead of
    # a monotonic one would push the fuse past any real budget and the
    # guard would silently never fire — re-anchor and say so, loudly
    if abs(time.monotonic() - t0) > 2 * 86400:
        print(
            "deadline_guard: t0 does not look like a time.monotonic() "
            "anchor (wall-clock seconds?); re-anchoring to now — pass "
            "t0=time.monotonic() captured at process start",
            file=sys.stderr, flush=True,
        )
        t0 = time.monotonic()

    def _fire():
        with lock:
            if done.is_set():
                return  # full line already printed (or printing won race)
            # partial_line() serializes live progress state the main thread
            # is still mutating (bench's _PROG/_LEGS dicts); a mid-mutation
            # snapshot can raise ("dictionary changed size during
            # iteration") and an unguarded raise here would kill the timer
            # thread with NO line and NO exit — the exact lost-window
            # failure this guard exists to prevent.  Retry a few times
            # (each attempt re-snapshots), then fall through to exit 3.
            line = None
            for _ in range(5):
                try:
                    line = partial_line()
                    break
                except Exception:
                    # lint: allow[lock-discipline] dying process: the dump
                    time.sleep(0.02)  # beat retries under the emit lock on
                    # purpose — once the guard fires, no waiter may print
            if line is None:
                os._exit(3)  # nothing measured: no artifact-worthy line
            _emit(line, flush_first=False)  # no flush: see _emit
            os._exit(0)

    timer = None
    if budget:
        # min_delay_s floors the fuse so a guard armed late (or a tiny
        # budget) still gives the measurement a beat to land its first
        # result; tests shrink it to exercise the firing path quickly
        delay = max(min_delay_s, budget - (time.monotonic() - t0) - margin_s)
        timer = threading.Timer(delay, _fire)
        timer.daemon = True
        timer.start()
        _ACTIVE_FIRE = _fire

    def finish(line: str) -> None:
        global _ACTIVE_FIRE
        with lock:
            done.set()
            _ACTIVE_FIRE = None
            if timer is not None:
                timer.cancel()
            # caller's thread: progress rows it printed flush first, then
            # the summary lands as one uninterleavable write
            _emit(line, flush_first=True)

    return finish
