"""Numerical guards (SURVEY §5: the TPU-native stand-in for sanitizers).

The reference's failure mode is silent row-dropping; a dense-panel engine's
failure mode is silent NaN/Inf propagation.  Two tools:

- ``validate_panel`` — host-side ingest gate: mask/value consistency, no
  Inf, monotone time axis.  Bad *assets* are maskable (fault isolation at
  universe level); a malformed panel raises.
- ``checked(fn)`` — ``jax.experimental.checkify`` wrapper adding float
  (NaN/Inf) and index OOB checks inside a jitted kernel; returns
  ``(err, out)`` with ``err.throw()`` re-raising on the host.  Used in
  tests and debug runs; production paths run the unchecked kernel (checkify
  inserts real ops, so it is opt-in by construction).
"""

from __future__ import annotations

import numpy as np

from csmom_tpu.utils.logging import get_logger

log = get_logger("guards")


def validate_panel(values, mask, times=None, name: str = "panel") -> None:
    """Raise ValueError on structural problems; warn on maskable ones.

    Checks: shapes match; no +-Inf anywhere; no non-finite value where
    mask=True (NaN under mask is the convention, NaN *over* mask poisons
    reductions); times (if given) strictly increasing and length-matched.
    """
    values = np.asarray(values)
    mask = np.asarray(mask)
    if values.shape != mask.shape:
        raise ValueError(f"{name}: values{values.shape} vs mask{mask.shape}")
    if np.isinf(values).any():
        raise ValueError(f"{name}: contains Inf (corrupt ingest?)")
    bad = mask & ~np.isfinite(values)
    if bad.any():
        a_bad = np.unique(np.nonzero(bad)[0])
        raise ValueError(
            f"{name}: {int(bad.sum())} masked-valid slots hold non-finite "
            f"values (asset rows {a_bad[:10].tolist()}...)"
        )
    if times is not None:
        times = np.asarray(times)
        if len(times) != values.shape[-1]:
            raise ValueError(f"{name}: {len(times)} times vs T={values.shape[-1]}")
        if len(times) > 1 and not (times[1:] > times[:-1]).all():
            raise ValueError(f"{name}: time axis not strictly increasing")
    dead = ~mask.any(axis=-1)
    if dead.any():
        log.warning("%s: %d asset(s) fully masked (dead lanes)", name, int(dead.sum()))


def checked(fn, errors=None):
    """Wrap ``fn`` with checkify float+index error tracking.

    Returns a function ``g(*args) -> (err, out)``; call ``err.throw()`` to
    surface the first failed check as a Python exception.
    """
    from jax.experimental import checkify

    if errors is None:
        errors = checkify.float_checks | checkify.index_checks
    return checkify.checkify(fn, errors=errors)
