"""Persistent XLA compile cache, one call to turn on.

On this image's tunneled 'axon' TPU backend a fresh jit compile costs
~30 s+ per computation shape, and the tunnel is only up in short windows
— recompiling shapes that were already compiled in an earlier process is
the single largest waste of a window.  The serialized-executable cache
keys on (HLO, backend), so it is correct across processes and survives
restarts; CPU runs benefit too (the test tier's warm wall dropped from
6m34 to 2m55 with the same mechanism — tests/conftest.py).

The reference has no analogue (single process, no compilation —
`/root/reference/run_demo.py` is plain pandas); this is TPU-runtime
plumbing the rebuild needs and the reference never did.

Callers: the CLI (device-using subcommands re-jit the same shapes between
invocations), the test tier (tests/conftest.py, "jit" dir), and — sharing
one "bench" dir so no tunnel window recompiles what a previous attempt
paid for — bench.py children, benchmarks/tpu_scaling.py, and
benchmarks/grid_phases.py.
"""

from __future__ import annotations

import os
import tempfile

__all__ = ["enable_persistent_cache"]


def enable_persistent_cache(subdir: str = "cli") -> str | None:
    """Point jax at a uid-suffixed on-disk compile cache; returns its path.

    ``CSMOM_JIT_CACHE=0`` disables (same contract as the test tier's
    conftest); any other non-empty value overrides the directory.  Must be
    called after ``import jax`` and before the first compilation; calling
    it later is harmless (already-live executables just aren't cached).
    Never raises — the cache is an optimization, not a dependency.
    """
    configured = os.environ.get("CSMOM_JIT_CACHE", "")
    if configured == "0":
        return None
    if configured:
        path = configured
    else:
        # uid-suffixed: a fixed path in world-writable /tmp would collide
        # across users (and let one user feed another serialized executables)
        path = os.path.join(
            tempfile.gettempdir(), f"csmom_{subdir}_cache-{os.getuid()}"
        )
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception:
        return None
