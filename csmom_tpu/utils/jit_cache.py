"""Persistent XLA compile cache, one call to turn on.

On this image's tunneled 'axon' TPU backend a fresh jit compile costs
~30 s+ per computation shape, and the tunnel is only up in short windows
— recompiling shapes that were already compiled in an earlier process is
the single largest waste of a window.  The serialized-executable cache
keys on (HLO, backend), so it is correct across processes and survives
restarts; CPU runs benefit too (the test tier's warm wall dropped from
6m34 to 2m55 with the same mechanism — tests/conftest.py).

The reference has no analogue (single process, no compilation —
`/root/reference/run_demo.py` is plain pandas); this is TPU-runtime
plumbing the rebuild needs and the reference never did.

Callers: the CLI (device-using subcommands re-jit the same shapes between
invocations), the test tier (tests/conftest.py, "jit" dir), and — sharing
one "bench" dir so no tunnel window recompiles what a previous attempt
paid for — bench.py children, benchmarks/tpu_scaling.py, and
benchmarks/grid_phases.py.
"""

from __future__ import annotations

import os
import tempfile

__all__ = ["cache_dir", "enable_persistent_cache"]


def cache_dir(subdir: str = "cli") -> str | None:
    """The cache directory ``enable_persistent_cache(subdir)`` would use,
    without touching jax (pure path derivation — safe from any process).

    ``CSMOM_JIT_CACHE=0`` -> None (disabled); any other non-empty value
    overrides the directory.  Single source of the path scheme: readers
    like the warmup report loader resolve through here so a scheme change
    cannot strand them looking in the wrong directory.
    """
    configured = os.environ.get("CSMOM_JIT_CACHE", "")
    if configured == "0":
        return None
    if configured:
        return configured
    # uid-suffixed: a fixed path in world-writable /tmp would collide
    # across users (and let one user feed another serialized executables)
    return os.path.join(
        tempfile.gettempdir(), f"csmom_{subdir}_cache-{os.getuid()}"
    )


def enable_persistent_cache(subdir: str = "cli",
                            min_compile_s: float = 0.5) -> str | None:
    """Point jax at a uid-suffixed on-disk compile cache; returns its path.

    ``CSMOM_JIT_CACHE=0`` disables (same contract as the test tier's
    conftest); any other non-empty value overrides the directory.  Must be
    called after ``import jax`` and before the first compilation; calling
    it later is harmless (already-live executables just aren't cached).
    Never raises — the cache is an optimization, not a dependency.

    ``min_compile_s`` is the persistence floor: compiles faster than this
    are not written (the steady-state default keeps sub-second noise out
    of the cache).  The AOT warmup passes 0.0 — its contract is that EVERY
    manifest shape lands on disk, so a later process can assert
    hit-count == manifest size instead of "most shapes were slow enough".
    """
    path = cache_dir(subdir)
    if path is None:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", min_compile_s
        )
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        return path
    except Exception:
        return None
