"""Structured logging.

The reference's observability is ~20 bare ``print()`` call sites
(e.g. ``/root/reference/run_demo.py:43,72-73``, ``data_io.py:156,171``).
Here every module logs through a namespaced stdlib logger with one shared
format, switchable via ``CSMOM_LOG_LEVEL``.
"""

from __future__ import annotations

import logging
import os

_CONFIGURED = False


def _configure_root() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("CSMOM_LOG_LEVEL", "INFO").upper()
    # logging.getLevelNamesMapping() is 3.11+; this must import on 3.10.
    # getLevelName(name) round-trips a KNOWN level name to its int and
    # returns the "Level %s" string for anything else, on every supported
    # interpreter — so "is it an int" is the version-portable validity test.
    if not isinstance(logging.getLevelName(level), int):
        level = "INFO"
    handler = logging.StreamHandler()
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)-7s %(name)s: %(message)s", "%H:%M:%S")
    )
    root = logging.getLogger("csmom_tpu")
    root.setLevel(level)
    if not root.handlers:
        root.addHandler(handler)
    root.propagate = False
    _CONFIGURED = True


def get_logger(name: str) -> logging.Logger:
    _configure_root()
    if not name.startswith("csmom_tpu"):
        name = f"csmom_tpu.{name}"
    return logging.getLogger(name)
