"""Tracing / profiling utilities (SURVEY §5: absent in the reference).

Two layers, both zero-cost when unused:

- ``wall(fn, *args)`` — wall-clock a compiled call correctly: JAX dispatch
  is async, so a naive ``time.time()`` pair measures only the enqueue;
  every timing here closes over ``block_until_ready``.
- ``fetch(y)`` / ``measure_rtt()`` — the stricter discipline for
  remote/tunneled backends (this image's 'axon' TPU), where
  ``block_until_ready`` has been observed to return in ~60 us without a
  device round trip, flat across a 32x spread of problem sizes: a timed
  rep must ``device_get`` a (small) result to host to provably include
  execution, and the tiny-op RTT is the floor such walls cannot go under.
  This is the timing discipline behind every number in bench.py and
  benchmarks/.
- ``trace(label, out_dir=...)`` — a context manager that wraps
  ``jax.profiler.trace`` (Perfetto/XPlane dump viewable in Perfetto or
  TensorBoard) when given a directory, and always logs the wall time of the
  block under its label.
"""

from __future__ import annotations

import contextlib
import time

import jax

from csmom_tpu.utils.logging import get_logger

log = get_logger("profiling")


def wall(fn, *args, warmup: int = 0, **kwargs):
    """Execute ``fn(*args, **kwargs)``, blocking on all outputs; return
    ``(result, seconds)``.  ``warmup`` extra untimed calls first (compile +
    cache effects)."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def fetch(y):
    """Materialize ``y`` on the host and return it as a numpy array.

    Use inside timed loops instead of ``block_until_ready``: the host
    transfer forces real execution even on tunneled backends whose ready
    signal is unreliable.  Reduce to a scalar inside the jit first so the
    transfer itself is negligible."""
    import numpy as np

    return np.asarray(jax.device_get(y))


# peak HBM bandwidth by jax device_kind, GB/s — the roofline denominator.
# Single source of truth for bench.py / benchmarks/grid_phases.py: achieved
# GB/s only means something as a fraction of the chip it ran on.
PEAK_HBM_GBPS = {
    "TPU v4": 1228.0, "TPU v5 lite": 819.0, "TPU v5e": 819.0,
    "TPU v5p": 2765.0, "TPU v6 lite": 1640.0, "TPU v6e": 1640.0,
}


def measure_rtt(dtype=None, reps: int = 10) -> float:
    """Per-call floor of ``fetch``-timed walls: dispatch + device round
    trip for a trivial op, in seconds (mean over ``reps``)."""
    import jax.numpy as jnp

    tiny = jax.jit(lambda a: a + 1.0)
    s = jax.device_put(jnp.asarray(0, dtype) if dtype else jnp.float32(0))
    fetch(tiny(s))  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        fetch(tiny(s))
    return (time.perf_counter() - t0) / reps


@contextlib.contextmanager
def trace(label: str, out_dir: str | None = None):
    """Time (and optionally profile) a block.

    With ``out_dir``, wraps the block in ``jax.profiler.trace`` producing a
    Perfetto-compatible dump; without, it is just a labelled wall timer.
    NOTE: ops dispatched inside the block are only awaited if the caller
    blocks; for exact kernel walls use :func:`wall`.
    """
    ctx = (
        jax.profiler.trace(out_dir, create_perfetto_trace=True)
        if out_dir
        else contextlib.nullcontext()
    )
    t0 = time.perf_counter()
    with ctx:
        yield
    dt = time.perf_counter() - t0
    log.info("%s: %.4fs%s", label, dt, f" (trace -> {out_dir})" if out_dir else "")
