"""Tracing / profiling utilities (SURVEY §5: absent in the reference).

Two layers, both zero-cost when unused:

- ``wall(fn, *args)`` — wall-clock a compiled call correctly
  (lint: allow[clock-discipline] the warning against the idiom, not a use):
  JAX dispatch is async, so a naive ``time.time()`` pair measures only the
  enqueue; every timing here closes over ``block_until_ready``.
- ``fetch(y)`` / ``measure_rtt()`` — the stricter discipline for
  remote/tunneled backends (this image's 'axon' TPU), where
  ``block_until_ready`` has been observed to return in ~60 us without a
  device round trip, flat across a 32x spread of problem sizes: a timed
  rep must ``device_get`` a (small) result to host to provably include
  execution, and the tiny-op RTT is the floor such walls cannot go under.
  This is the timing discipline behind every number in bench.py and
  benchmarks/.
- ``trace(label, out_dir=...)`` — a context manager that wraps
  ``jax.profiler.trace`` (Perfetto/XPlane dump viewable in Perfetto or
  TensorBoard) when given a directory, and always logs the wall time of the
  block under its label.
- ``compile_stats()`` / ``count_dispatches()`` — the AOT warm-start
  pipeline's accounting: persistent-compile-cache hit/miss counters (the
  number bench records so "0 in-window compiles" is a measured claim, not
  a hope) and a per-call dispatch counter that pins the one-dispatch
  property of the grid/event hot paths.
"""

from __future__ import annotations

import contextlib
import dataclasses
import sys
import time

import jax

from csmom_tpu.utils.logging import get_logger

log = get_logger("profiling")


def wall(fn, *args, warmup: int = 0, **kwargs):
    """Execute ``fn(*args, **kwargs)``, blocking on all outputs; return
    ``(result, seconds)``.  ``warmup`` extra untimed calls first (compile +
    cache effects)."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


def fetch(y):
    """Materialize ``y`` on the host and return it as a numpy array.

    Use inside timed loops instead of ``block_until_ready``: the host
    transfer forces real execution even on tunneled backends whose ready
    signal is unreliable.  Reduce to a scalar inside the jit first so the
    transfer itself is negligible."""
    import numpy as np

    return np.asarray(jax.device_get(y))


# peak HBM bandwidth by jax device_kind, GB/s — the roofline denominator.
# Single source of truth for bench.py / benchmarks/grid_phases.py: achieved
# GB/s only means something as a fraction of the chip it ran on.
PEAK_HBM_GBPS = {
    "TPU v4": 1228.0, "TPU v5 lite": 819.0, "TPU v5e": 819.0,
    "TPU v5p": 2765.0, "TPU v6 lite": 1640.0, "TPU v6e": 1640.0,
}


def measure_rtt(dtype=None, reps: int = 10) -> float:
    """Per-call floor of ``fetch``-timed walls: dispatch + device round
    trip for a trivial op, in seconds (mean over ``reps``)."""
    import jax.numpy as jnp

    tiny = jax.jit(lambda a: a + 1.0)
    s = jax.device_put(jnp.asarray(0, dtype) if dtype else jnp.float32(0))
    fetch(tiny(s))  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        fetch(tiny(s))
    return (time.perf_counter() - t0) / reps


# -- compile-cache / dispatch accounting -------------------------------------
#
# jax.monitoring is the only stable-ish signal for what the runtime compiled
# vs served from the serialized-executable cache.  Verified semantics on the
# 0.4.x line this image ships (jax._src.{compiler,compilation_cache}):
#
#   compile_requests_use_cache  one per top-level compile request, only when
#                               a cache dir is configured;
#   cache_hits                  serialized executable successfully READ from
#                               the cache (no backend compile happened);
#   cache_misses                executable compiled AND WRITTEN to the cache
#                               — a compile under the persistence thresholds
#                               (min compile time / entry size) records
#                               NEITHER hit nor miss, which is why the
#                               warmup path zeroes those thresholds;
#   backend_compile_duration    wraps compile_or_get_cached, so it fires on
#                               every top-level compile request, cache hit,
#                               write, or cache-disabled alike;
#   jaxpr_trace_duration        one per traced computation, including inner
#                               jits traced during an outer trace that never
#                               dispatch on their own.
#
# Counters are process-global and monotone; callers diff snapshots.

_COUNTERS = {
    "cache_hits": 0,        # persistent-cache reads (serialized executable load)
    "cache_misses": 0,      # persistent-cache writes (fresh compile, persisted)
    "cache_requests": 0,    # compile requests that consulted the cache
    "traces": 0,            # computations traced+lowered this process
    "backend_compiles": 0,  # top-level compile requests (cache load OR compile)
}

# Idempotency across RE-IMPORT, not just re-call: a module-level boolean
# resets when this module is reloaded (importlib.reload, a second import
# path, an embedder re-exec'ing site code) while the listeners registered
# with jax.monitoring live on — the old closures keep counting into the
# old dict and a fresh registration double-counts every event.  So the
# installed marker AND the live counter dict are stashed on jax's own
# monitoring module (one per process, reload-proof); a reloaded copy of
# this module ADOPTS the existing dict instead of re-registering.  Fork
# needs nothing: the child inherits both the registered listeners and the
# counter values, which stay correct (they are process-global monotone
# counts, and the fork point is their shared baseline).
_LISTENER_TAG = "_csmom_profiling_counters"


def _install_listeners() -> None:
    global _COUNTERS
    from jax._src import monitoring

    existing = getattr(monitoring, _LISTENER_TAG, None)
    if existing is not None:
        _COUNTERS = existing  # adopt, never re-register (see _LISTENER_TAG)
        return
    c = _COUNTERS  # bind the dict, not the module global: reload-proof

    def _on_event(event, **kw):
        if event == "/jax/compilation_cache/cache_hits":
            c["cache_hits"] += 1
        elif event == "/jax/compilation_cache/cache_misses":
            c["cache_misses"] += 1
        elif event == "/jax/compilation_cache/compile_requests_use_cache":
            c["cache_requests"] += 1

    def _on_duration(event, duration, **kw):
        if event == "/jax/core/compile/jaxpr_trace_duration":
            c["traces"] += 1
        elif event == "/jax/core/compile/backend_compile_duration":
            c["backend_compiles"] += 1

    monitoring.register_event_listener(_on_event)
    monitoring.register_event_duration_secs_listener(_on_duration)
    setattr(monitoring, _LISTENER_TAG, c)


def listeners_installed() -> bool:
    """Whether this process's compile/dispatch listeners are registered.

    Read from the reload-proof marker on jax's monitoring module (NOT a
    module global here, which a reload would zero); surfaced in every
    ``obs.metrics.snapshot()`` so a record whose compile counters read 0
    shows whether that means "nothing compiled" or "nobody was counting".
    """
    if "jax" not in sys.modules:
        return False
    try:
        from jax._src import monitoring
    except ImportError:  # pragma: no cover - jax layout drift
        return False
    return getattr(monitoring, _LISTENER_TAG, None) is not None


@dataclasses.dataclass(frozen=True)
class CompileStats:
    """Snapshot of the process-global compile counters (monotone)."""

    cache_hits: int
    cache_misses: int
    cache_requests: int
    traces: int
    backend_compiles: int

    def delta(self, since: "CompileStats") -> "CompileStats":
        return CompileStats(*(getattr(self, f.name) - getattr(since, f.name)
                              for f in dataclasses.fields(self)))

    @property
    def hit_rate(self) -> float | None:
        """Fraction of cache-consulting compile requests served from the
        serialized-executable cache; None when the cache saw no traffic
        (disabled, or nothing compiled since the snapshot base)."""
        if not self.cache_requests:
            return None
        return self.cache_hits / self.cache_requests

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        hr = self.hit_rate
        d["cache_hit_rate"] = (round(hr, 4) if hr is not None else
                               "not applicable: no cache-consulting compiles "
                               "in this window (cache disabled or all shapes "
                               "already live in-process)")
        return d


def compile_stats() -> CompileStats:
    """Current counters.  The persistent-cache fields only move when a
    compilation cache directory is configured (utils.jit_cache)."""
    _install_listeners()
    return CompileStats(**_COUNTERS)


@contextlib.contextmanager
def count_dispatches(clear_caches: bool = True):
    """Count distinct TOP-LEVEL XLA computations dispatched in the block.

    Every computation a block launches must first be compiled in-process,
    so with the in-process executable caches cleared on entry, the number
    of top-level compile requests during the block equals the number of
    DISTINCT computations it dispatched — one jit call that stays
    on-device scores exactly 1, and any host round-trip between stages
    (an eager op, a second jit, an implicit recommit) scores >= 2.  This
    is the test hook behind the grid hot path's one-dispatch-per-call pin.

    The counted signal is the top-level backend-compile counter, which on
    this jax line wraps ``compile_or_get_cached`` and therefore fires once
    per top-level computation whether the executable was compiled fresh or
    loaded from the persistent cache, cache configured or not.  NOT the
    jaxpr-trace counter: nested inner jits trace during outer tracing
    without ever dispatching.

    Yields a dict whose ``"dispatches"`` key is filled on exit.  Repeat
    launches of one already-counted computation are not re-counted — so
    ``== 1`` is a sound single-dispatch pin, while larger values are a
    lower bound on launches.
    """
    _install_listeners()
    if clear_caches:
        jax.clear_caches()
    before = dict(_COUNTERS)
    box: dict = {}
    try:
        yield box
    finally:
        box["dispatches"] = (
            _COUNTERS["backend_compiles"] - before["backend_compiles"]
        )


@contextlib.contextmanager
def trace(label: str, out_dir: str | None = None):
    """Time (and optionally profile) a block.

    With ``out_dir``, wraps the block in ``jax.profiler.trace`` producing a
    Perfetto-compatible dump; without, it is just a labelled wall timer.
    NOTE: ops dispatched inside the block are only awaited if the caller
    blocks; for exact kernel walls use :func:`wall`.
    """
    ctx = (
        jax.profiler.trace(out_dir, create_perfetto_trace=True)
        if out_dir
        else contextlib.nullcontext()
    )
    t0 = time.perf_counter()
    with ctx:
        yield
    dt = time.perf_counter() - t0
    log.info("%s: %.4fs%s", label, dt, f" (trace -> {out_dir})" if out_dir else "")
