"""Tracing / profiling utilities (SURVEY §5: absent in the reference).

Two layers, both zero-cost when unused:

- ``wall(fn, *args)`` — wall-clock a compiled call correctly: JAX dispatch
  is async, so a naive ``time.time()`` pair measures only the enqueue;
  every timing here closes over ``block_until_ready``.  This is the timing
  discipline behind every number in BASELINE.md / bench.py.
- ``trace(label, out_dir=...)`` — a context manager that wraps
  ``jax.profiler.trace`` (Perfetto/XPlane dump viewable in Perfetto or
  TensorBoard) when given a directory, and always logs the wall time of the
  block under its label.
"""

from __future__ import annotations

import contextlib
import time

import jax

from csmom_tpu.utils.logging import get_logger

log = get_logger("profiling")


def wall(fn, *args, warmup: int = 0, **kwargs):
    """Execute ``fn(*args, **kwargs)``, blocking on all outputs; return
    ``(result, seconds)``.  ``warmup`` extra untimed calls first (compile +
    cache effects)."""
    for _ in range(warmup):
        jax.block_until_ready(fn(*args, **kwargs))
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    return out, time.perf_counter() - t0


@contextlib.contextmanager
def trace(label: str, out_dir: str | None = None):
    """Time (and optionally profile) a block.

    With ``out_dir``, wraps the block in ``jax.profiler.trace`` producing a
    Perfetto-compatible dump; without, it is just a labelled wall timer.
    NOTE: ops dispatched inside the block are only awaited if the caller
    blocks; for exact kernel walls use :func:`wall`.
    """
    ctx = (
        jax.profiler.trace(out_dir, create_perfetto_trace=True)
        if out_dir
        else contextlib.nullcontext()
    )
    t0 = time.perf_counter()
    with ctx:
        yield
    dt = time.perf_counter() - t0
    log.info("%s: %.4fs%s", label, dt, f" (trace -> {out_dir})" if out_dir else "")
