"""In-sample vs walk-forward scoring: how much of the demo PnL is leak?

The reference's intraday demo trains on the first 70% of minute rows and
then scores the ENTIRE history — its own training rows included
(``/root/reference/run_demo.py:139-147``; SURVEY §2.1.4) — and books
+$765k on the shipped data.  This example runs the same pipeline twice
through this framework:

1. ``model='ridge'``        — the reference's scaffold, replicated
   (leaky by design, kept for parity), and
2. ``model='online_ridge'`` — the strictly-causal walk-forward scan
   (every score out-of-sample by construction —
   ``csmom_tpu/models/online_ridge.py``),

and prints both PnLs side by side.  The sign flip IS the finding: the
in-sample profit does not survive causal scoring on this universe, which
is the honest answer a researcher needs before believing the demo.

Run:  python examples/causal_scoring.py [--data-dir DIR] [--platform cpu]

Precision note: this example enables f64 (like the golden-parity tests);
``csmom intraday --model online_ridge`` runs the default f32 path and
books a different trade COUNT (28.5k vs 37.6k) because the causal
scores sit near the 1e-5 entry threshold, where f32 rounding flips
thousands of marginal crossings.  The conclusion is identical in both
precisions: the out-of-sample PnL is negative.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default="/root/reference/data")
    ap.add_argument("--platform", default="cpu")
    args = ap.parse_args(argv)

    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.config import DEFAULT_TICKERS
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    minute_df = load_intraday(args.data_dir, list(DEFAULT_TICKERS))
    daily_df = load_daily(
        args.data_dir, [t for t in DEFAULT_TICKERS if t != "AAPL"]
    )
    if len(minute_df) == 0:
        raise SystemExit(f"no intraday caches under {args.data_dir}")

    rows = []
    for model in ("ridge", "online_ridge"):
        res, fit, *_ = intraday_pipeline(minute_df, daily_df, model=model)
        rows.append((
            model,
            int(res.n_trades),
            float(res.total_pnl),
            [float(x) for x in np.asarray(fit.cv_mse)],
        ))

    mse_label = {"ridge": "fold MSEs (in-sample folds)",
                 "online_ridge": "prequential MSEs (all OOS)"}
    print(f"{'model':<14} {'trades':>8} {'total PnL':>16}   quality")
    for model, n, pnl, mses in rows:
        ms = ", ".join(f"{m:.2e}" for m in mses)
        print(f"{model:<14} {n:>8} {pnl:>16,.2f}   {mse_label[model]}: [{ms}]")

    leak = rows[0][2] - rows[1][2]
    print(
        f"\nscoring the training span (the reference's scaffold) is worth "
        f"${leak:,.0f} of the in-sample PnL on this universe — the causal "
        f"number is the one a live strategy would have seen"
    )


if __name__ == "__main__":
    main()
