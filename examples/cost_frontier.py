"""Cost frontier: hysteresis band width x transaction-cost level, one table.

The practical question every momentum desk asks — "at what cost level does
the strategy die, and how much does trading less buy back?" — answered with
the framework's two cost tools composed:

- the hysteresis band (``backtest/banded.py``) cuts turnover by holding
  names inside a stay-zone instead of re-forming the book monthly;
- linear cost netting (``net = gross - hs * turnover``) is exact per band,
  so every (band, cost-level) cell prices from ONE banded run per band —
  formation itself ranks exactly once for the whole table
  (``banded_from_labels`` reuses the plain run's labels).

The reference has no cost model at all (its trade log stores the impact
leg but nothing consumes it — ``run_demo.py:188-189``).

Run:  python examples/cost_frontier.py [--data-dir DIR] [--platform cpu]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default="/root/reference/data")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--bands", default="0,1,2")
    ap.add_argument("--tc-bps", default="0,5,10,25,50")
    args = ap.parse_args(argv)

    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)
    jax.config.update("jax_enable_x64", True)

    import jax.numpy as jnp
    import numpy as np

    from csmom_tpu.api import monthly_price_panel
    from csmom_tpu.backtest import monthly_spread_backtest
    from csmom_tpu.backtest.banded import banded_from_labels
    from csmom_tpu.config import DEFAULT_TICKERS
    from csmom_tpu.signals.momentum import monthly_returns

    bands = [int(b) for b in args.bands.split(",")]
    levels = [float(x) for x in args.tc_bps.split(",")]

    panel, _ = monthly_price_panel(args.data_dir, list(DEFAULT_TICKERS))
    v, m = panel.device()
    plain = monthly_spread_backtest(v, m, lookback=12, skip=1)
    mret, mret_valid = monthly_returns(v, m)

    print(f"universe: {panel.n_assets} tickers x {panel.n_times} months; "
          "net mean spread per (band, half-spread bps):")
    hdr = f"{'band':>4}  {'turnover':>8}  " + "  ".join(
        f"{f'{x:g}bps':>10}" for x in levels
    )
    print(hdr)
    rows = {}
    for b in bands:
        r = banded_from_labels(plain.labels, mret, mret_valid,
                               n_bins=10, band=b)
        rv = np.asarray(r.spread_valid)
        turn = np.asarray(r.turnover)
        spread = np.asarray(r.spread)
        mt = float(turn[rv].mean())
        nets = [float(np.nanmean(np.where(rv, spread - hs / 1e4 * turn,
                                          np.nan)))
                for hs in levels]
        rows[b] = (mt, nets)
        print(f"{b:>4}  {mt:>8.3f}  " + "  ".join(
            f"{n:>+10.6f}" for n in nets))

    # golden sanity: turnover must fall with the band, and at a high-enough
    # cost level the wider band must dominate (its whole economic point)
    mts = [rows[b][0] for b in bands]
    assert all(a > b for a, b in zip(mts, mts[1:])), "turnover not falling"
    worst = [rows[b][1][-1] for b in bands]
    assert worst[-1] > worst[0], (
        "widest band should win at the highest cost level"
    )
    print("frontier sanity checks passed")
    return 0


if __name__ == "__main__":
    main()
