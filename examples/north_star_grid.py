"""North-star scale: the full J x K grid on a synthetic 3000 x 60yr panel.

One compiled call evaluates all 16 Jegadeesh-Titman cells (overlapping
1/K cohort holding) over a 3,000-stock, 60-year monthly panel with
staggered listings; a second fused call walk-forwards the grid for an
out-of-sample selection path.  On a TPU v5e chip the 16-cell grid runs in
~0.1 s; the CPU default below is scaled down so the demo finishes in
seconds (pass --assets 3000 --years 60 for the real thing).

Run:  python examples/north_star_grid.py [--assets N] [--years Y]
      [--impl xla|matmul|matmul_bf16|pallas] [--platform cpu]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--assets", type=int, default=512)
    ap.add_argument("--years", type=int, default=15)
    ap.add_argument("--impl", default="matmul",
                    choices=["xla", "matmul", "matmul_bf16", "pallas"])
    ap.add_argument("--platform", default="cpu")
    args = ap.parse_args(argv)

    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)

    import time

    import numpy as np

    from csmom_tpu.backtest.grid import jk_grid_backtest
    from csmom_tpu.panel.calendar import month_end_aggregate, month_end_segments
    from csmom_tpu.panel.synthetic import synthetic_daily_panel
    from csmom_tpu.utils.profiling import fetch

    T = args.years * 252
    panel = synthetic_daily_panel(args.assets, T, seed=7, listing_gaps=True)
    seg, ends = month_end_segments(panel.times)
    v, m = panel.device(np.float32)
    pm, mm = month_end_aggregate(v, m, seg, len(ends))

    Js = np.array([3, 6, 9, 12])
    Ks = np.array([3, 6, 9, 12])

    # one jitted function, one compile: the timed rep fetches only the
    # [nJ, nK] means; the same executable's full result feeds the report
    # and the walk-forward selection below
    g = jax.jit(lambda p, q: jk_grid_backtest(
        p, q, Js, Ks, skip=1, mode="rank", impl=args.impl
    ))
    res = g(pm, mm)
    fetch(res.mean_spread)  # compile + materialize
    t0 = time.perf_counter()
    fetch(g(pm, mm).mean_spread)
    wall = time.perf_counter() - t0
    print(f"{args.assets} assets x {args.years} yr "
          f"({len(ends)} months), impl={args.impl}: "
          f"16-cell grid in {wall:.3f}s")
    print("\nmean spread (%/mo):")
    ms = np.asarray(res.mean_spread) * 100
    print("      " + "  ".join(f"K={k:<4d}" for k in Ks))
    for i, j in enumerate(Js):
        print(f"J={j:<3d} " + "  ".join(f"{ms[i, k]:+.3f}" for k in range(len(Ks))))

    from csmom_tpu.backtest.walkforward import walk_forward_select

    wf = walk_forward_select(res.spreads, res.spread_valid)
    picked = np.asarray(wf.choice)
    live = picked >= 0
    if live.any():
        uniq, cnt = np.unique(picked[live], return_counts=True)
        top = uniq[np.argmax(cnt)]
        print(f"\nwalk-forward: Sharpe {float(wf.ann_sharpe):.3f} "
              f"(NW t {float(wf.tstat_nw):+.2f}); most-picked cell "
              f"J={Js[top // len(Ks)]}, K={Ks[top % len(Ks)]} "
              f"({cnt.max()}/{live.sum()} months)")


if __name__ == "__main__":
    main()
