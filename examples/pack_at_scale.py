"""At-scale data path: packed binary panels feeding the compiled grid.

The reference re-parses per-ticker CSV text on every run
(``/root/reference/src/data_io.py:131-159``) — fine at 20 tickers,
hopeless at the north star.  This demo is the scale workflow:

1. build a universe once (synthetic here; ``csmom fetch --pack`` for real
   caches) and write it as a packed directory — dense ``[A, T]`` ``.npy``
   per field + manifest (:mod:`csmom_tpu.panel.pack`);
2. re-open it memory-mapped (O(metadata) open; pages stream to HBM on
   first touch) and run the 16-cell J x K grid from it;
3. assert the packed path is bit-identical to the in-memory panel —
   the pack is a cache, never a different answer.

Run:  python examples/pack_at_scale.py [--assets N] [--years Y]
      [--platform cpu] [--keep DIR]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--assets", type=int, default=256)
    ap.add_argument("--years", type=int, default=10)
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--keep", metavar="DIR",
                    help="write the pack here and keep it (default: tmp)")
    args = ap.parse_args(argv)

    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)

    import dataclasses

    import numpy as np

    from csmom_tpu.backtest.grid import jk_grid_backtest
    from csmom_tpu.panel.calendar import month_end_aggregate, month_end_segments
    from csmom_tpu.panel.pack import load_packed, save_packed
    from csmom_tpu.panel.panel import PanelBundle
    from csmom_tpu.panel.synthetic import synthetic_daily_panel
    from csmom_tpu.utils.profiling import fetch

    T = args.years * 252
    t0 = time.perf_counter()
    px = synthetic_daily_panel(args.assets, T, seed=11, listing_gaps=True)
    # pack the full daily bundle the monthly pipeline expects (adj_close +
    # volume) so the kept pack really is a drop-in --data-dir
    panel = dataclasses.replace(px, name="adj_close")
    vol_rng = np.random.default_rng(12)
    vol_vals = np.where(
        panel.mask, np.exp(vol_rng.normal(13.0, 1.0, size=panel.shape)), np.nan
    )
    volume = dataclasses.replace(panel, values=vol_vals, name="volume")
    bundle = PanelBundle(
        panels={"adj_close": panel, "volume": volume},
        tickers=panel.tickers, times=panel.times,
    )
    synth_s = time.perf_counter() - t0

    tmp_root = None if args.keep else tempfile.mkdtemp(prefix="csmom_pack_demo_")
    pack_dir = args.keep or os.path.join(tmp_root, "pack")
    t0 = time.perf_counter()
    save_packed(bundle, pack_dir)
    write_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    packed = load_packed(pack_dir)["adj_close"]  # memmap: no bulk read yet
    open_s = time.perf_counter() - t0

    Js = np.array([3, 6, 9, 12])
    Ks = np.array([3, 6, 9, 12])

    def run(p):
        seg, ends = month_end_segments(p.times)
        v, m = p.device(np.float32)
        pm, mm = month_end_aggregate(v, m, seg, len(ends))
        res = jk_grid_backtest(pm, mm, Js, Ks, skip=1, mode="rank",
                               impl="matmul")
        fetch(res.mean_spread)
        return res

    t0 = time.perf_counter()
    res_packed = run(packed)                # pages fault in here
    grid_s = time.perf_counter() - t0
    res_mem = run(panel)

    np.testing.assert_array_equal(
        np.asarray(res_packed.mean_spread), np.asarray(res_mem.mean_spread)
    )
    np.testing.assert_array_equal(
        np.asarray(res_packed.spread_valid), np.asarray(res_mem.spread_valid)
    )

    a, t = panel.shape
    size_mb = sum(
        os.path.getsize(os.path.join(pack_dir, f))
        for f in os.listdir(pack_dir)
    ) / 1e6
    print(f"{a} assets x {t} days: pack {size_mb:.1f} MB "
          f"(synth {synth_s:.2f}s, write {write_s:.2f}s, "
          f"open {open_s * 1e3:.1f}ms, grid-from-pack {grid_s:.2f}s)")
    print("packed == in-memory: bit-identical 16-cell grid "
          f"(best cell mean {float(np.nanmax(np.asarray(res_packed.mean_spread))) * 100:+.3f}%/mo)")
    if args.keep:
        print(f"pack kept at {pack_dir} — any monthly subcommand accepts it "
              "as --data-dir")
    else:
        import shutil

        shutil.rmtree(tmp_root, ignore_errors=True)


if __name__ == "__main__":
    main()
