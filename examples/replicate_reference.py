"""End-to-end reference parity: the whole demo pipeline in a few calls.

Reproduces what ``/root/reference/run_demo.py`` does (monthly momentum
replication + intraday ridge pipeline + event backtest) through this
framework's public API, and checks the golden numbers the reference's own
data pins down (BASELINE.md measured values).

Run:  python examples/replicate_reference.py [--data-dir DIR] [--platform cpu]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default="/root/reference/data")
    ap.add_argument("--platform", default="cpu",
                    help="jax platform to pin before first device use")
    args = ap.parse_args(argv)

    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from csmom_tpu.api import intraday_pipeline, monthly_price_panel
    from csmom_tpu.backtest.monthly import monthly_spread_backtest
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    from csmom_tpu.config import DEFAULT_TICKERS

    # the reference's 20-ticker universe; its own loader silently loses AAPL
    # to the dialect-B cache bug (SURVEY 2.1.1), so parity mode drops it too
    tickers = [t for t in DEFAULT_TICKERS if t != "AAPL"]

    # -- monthly leg (run_demo.py:31-79) ------------------------------------
    daily = load_daily(args.data_dir, tickers)
    panel, volume = monthly_price_panel(args.data_dir, tickers, daily_df=daily)
    v, m = panel.device(np.float64)
    res = monthly_spread_backtest(v, m, lookback=12, skip=1)
    print(f"monthly mean spread {float(res.mean_spread):+.6f}  "
          f"Sharpe {float(res.ann_sharpe):.4f}  "
          f"NW t {float(res.tstat_nw):+.3f}")
    assert abs(float(res.mean_spread) - 0.003674) < 5e-6, "golden mean drifted"
    assert abs(float(res.ann_sharpe) - 0.1002) < 5e-4, "golden Sharpe drifted"

    # -- intraday leg (run_demo.py:81-191) ----------------------------------
    minute = load_intraday(args.data_dir, tickers + ["AAPL"])
    ev, fit, compact, *_ = intraday_pipeline(minute, daily)
    print(f"intraday trades {int(ev.n_trades)}  "
          f"PnL ${float(ev.total_pnl):,.2f}  "
          f"CV MSEs {[f'{x:.3g}' for x in np.asarray(fit.cv_mse)]}")
    assert int(ev.n_trades) == 28_020, "golden trade fingerprint drifted"

    print("parity OK: measured baseline reproduced")


if __name__ == "__main__":
    main()
