"""Strategy zoo: every registered signal over one panel, one comparison table.

Demonstrates the Strategy plugin boundary (the engines never change as the
signal does) and the batched tearsheet: each strategy's monthly spread
series gets the full risk summary, printed as one table.

Run:  python examples/strategy_zoo.py [--data-dir DIR] [--platform cpu]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data-dir", default="/root/reference/data")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--n-bins", type=int, default=10)
    args = ap.parse_args(argv)

    import jax

    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)
    jax.config.update("jax_enable_x64", True)

    import numpy as np

    from csmom_tpu.analytics import tearsheet
    from csmom_tpu.api import monthly_price_panel
    from csmom_tpu.strategy import make_strategy, strategy_backtest

    from csmom_tpu.config import DEFAULT_TICKERS

    # parity universe: the reference's 20 names minus AAPL (SURVEY 2.1.1)
    tickers = [t for t in DEFAULT_TICKERS if t != "AAPL"]
    panel, volume = monthly_price_panel(args.data_dir, tickers)
    v, m = panel.device(np.float64)

    zoo = [
        ("momentum J=12",        make_strategy("momentum"), {}),
        ("momentum J=6",         make_strategy("momentum", lookback=6), {}),
        # Novy-Marx (2012) intermediate momentum: months t-12..t-7 only —
        # registered under its own name (strategy/builtin.py)
        ("intermediate mom",     make_strategy("intermediate_momentum"), {}),
        ("reversal 1m",          make_strategy("reversal"), {}),
        ("residual mom",         make_strategy("residual_momentum"), {}),
        # Blitz-van Vliet (2007) volatility effect: the one risk-sorted
        # zoo member, at the paper's 36m window — the 84-month demo panel
        # still yields ~4 years of scored months (min_obs=12 starts it
        # earlier than a strict 36-of-36 would)
        ("low vol (36m)",        make_strategy("low_volatility"), {}),
        # rank mode: the 52w-high score has an atom at exactly 1.0, and
        # qcut's duplicate-edge dropping would empty the top bin on
        # strong-market months (see the strategy's docstring); GH rank on
        # ordinals, so this row does too
        ("52w high (rank)",      make_strategy("high_52w"),
         {"mode": "rank"}),
        ("volume-z mom",         make_strategy("volume_z_momentum"),
         {"volumes": volume.values, "volumes_mask": volume.mask}),
    ]

    rows = []
    for label, strat, panels in zoo:
        mode = panels.pop("mode", "qcut")
        res = strategy_backtest(v, m, strat, n_bins=args.n_bins, mode=mode,
                                **panels)
        spread = np.asarray(res.spread)
        valid = np.asarray(res.spread_valid)
        ts = tearsheet(np.nan_to_num(spread), valid, freq_per_year=12)
        rows.append((
            label,
            float(res.mean_spread),
            float(res.ann_sharpe),
            float(res.tstat_nw),
            float(ts.max_drawdown),
            float(ts.hit_rate),
            int(ts.n_periods),
        ))

    hdr = f"{'strategy':<16} {'mean/mo':>9} {'sharpe':>7} {'t(NW)':>6} " \
          f"{'maxDD':>7} {'hit':>6} {'months':>7}"
    print(hdr)
    print("-" * len(hdr))
    for label, mu, sh, t, dd, hit, n in rows:
        print(f"{label:<16} {mu:>+9.4f} {sh:>7.3f} {t:>+6.2f} "
              f"{dd:>6.1%} {hit:>6.1%} {n:>7d}")


if __name__ == "__main__":
    main()
