"""Test harness config.

Multi-device code is tested on a virtual 8-device CPU mesh — the standard way
to exercise shard_map/collective code without a TPU pod.  The env vars must be
set before jax initializes, hence this module-level block.

float64 is enabled so kernel<->pandas oracle comparisons are tight; production
TPU paths run f32/bf16 (kernels are dtype-polymorphic).
"""

import os

# NOTE: this image pins JAX_PLATFORMS=axon in the environment and a
# sitecustomize imports jax at interpreter start, so env vars are captured
# before conftest runs; jax.config.update is the only override that works.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: the fast tier's wall is ~75% jit
# compiles (census: 1,286 compiles / ~370 s XLA on this image), and they
# repeat identically run over run.  Caching every compile over 0.5 s makes
# re-runs mostly load-bound (the common case while iterating); the first
# run on a machine still pays full compile.  CSMOM_JIT_CACHE=0 disables,
# any other value overrides the directory.
from csmom_tpu.utils.jit_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache("jit")  # -> csmom_jit_cache-{uid}, the tier's dir

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# overridable so a bare checkout can be simulated (point it at a nonexistent
# dir to prove `-m "not reference_data"` needs nothing outside the repo)
REFERENCE_DATA = os.environ.get("CSMOM_REFERENCE_DATA", "/root/reference/data")

# the reference demo's hardcoded universe (run_demo.py:15-16)
DEMO_TICKERS = [
    "AAPL", "MSFT", "AMZN", "GOOGL", "NVDA", "TSLA", "META", "JPM", "BAC", "WMT",
    "PG", "KO", "DIS", "CSCO", "ORCL", "INTC", "AMD", "NFLX", "C", "GS",
]
# the panel the BASELINE measured numbers were produced on: AAPL is dropped by
# the reference's dialect-B cache bug (SURVEY §2.1.1), leaving 19 names
MEASURED_TICKERS = [t for t in DEMO_TICKERS if t != "AAPL"]


# golden/parity tests that read the mount carry the `reference_data` marker
# (deselectable tier) and skip automatically when the mount is absent
requires_reference = pytest.mark.reference_data


def pytest_collection_modifyitems(config, items):
    if os.path.isdir(REFERENCE_DATA):
        return
    skip = pytest.mark.skip(reason=f"reference data not mounted at {REFERENCE_DATA}")
    for item in items:
        if "reference_data" in item.keywords:
            item.add_marker(skip)


def _n_memory_maps() -> int:
    try:
        with open("/proc/self/maps") as f:
            return sum(1 for _ in f)
    except OSError:  # non-Linux: no budget to watch
        return 0


def _map_budget() -> int:
    try:
        with open("/proc/sys/vm/max_map_count") as f:
            limit = int(f.read())
    except (OSError, ValueError):
        limit = 65530
    return int(limit * 0.6)


_MAP_BUDGET = _map_budget()
_MAP_STATS = {"max_maps": 0, "clears": 0}


@pytest.fixture(autouse=True)
def _bound_live_executables():
    """Drop jax's compiled-program caches when memory maps near the limit.

    The full tier compiles hundreds of distinct shapes; every live XLA CPU
    executable holds memory mappings, and past the vm.max_map_count budget
    (65530 default) the NEXT compile segfaults inside
    backend_compile_and_load (observed twice, at different tests, once the
    suite grew past ~380 compiles). Clearing after *every* module fixes
    that but costs ~2x wall in recompiles of cross-module shared helpers;
    instead the map count is checked directly — per TEST, since one
    compile-heavy module could cross the budget between module-scoped
    checks — and caches are dropped only past 60% of the limit.  The read
    is one /proc line-count (~50 us); the clear fires a handful of times
    per full run and never in a small one.
    """
    yield
    n = _n_memory_maps()
    _MAP_STATS["max_maps"] = max(_MAP_STATS["max_maps"], n)
    if n > _MAP_BUDGET:
        _MAP_STATS["clears"] += 1
        jax.clear_caches()


# -- compile census (CSMOM_COUNT_COMPILES=1) --------------------------------
# The fast tier's wall is almost entirely jit compiles (VERDICT r4 weak #2),
# and the full tier lives near the XLA-CPU live-executable limit, so the
# number of DISTINCT compiles is the quantity to engineer down.  With
# CSMOM_COUNT_COMPILES=1 every "Compiling <fn>" log line is attributed to
# the currently running test and a per-test census prints at session end —
# the map that says which tests to shape-dedupe or demote to slow.
_COMPILE_COUNTS: dict = {}
_CURRENT_TEST = [None]

if os.environ.get("CSMOM_COUNT_COMPILES"):
    import logging

    jax.config.update("jax_log_compiles", True)

    class _CompileCounter(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            key = _CURRENT_TEST[0] or "<collection/session>"
            entry = _COMPILE_COUNTS.setdefault(key, [0, 0.0])
            if msg.startswith("Compiling "):
                entry[0] += 1
            elif msg.startswith("Finished XLA compilation"):
                try:
                    entry[1] += float(msg.rsplit(" in ", 1)[1].split()[0])
                except (IndexError, ValueError):
                    pass

    # "Compiling jit(...)" comes from pxla; "Finished XLA compilation of
    # ... in N sec" from dispatch (verified on this image's jax 0.9.0)
    for _name in ("jax._src.interpreters.pxla", "jax._src.dispatch"):
        logging.getLogger(_name).addHandler(_CompileCounter())

    @pytest.fixture(autouse=True)
    def _attribute_compiles(request):
        _CURRENT_TEST[0] = request.node.nodeid
        yield
        _CURRENT_TEST[0] = None

    def pytest_terminal_summary(terminalreporter):
        items = sorted(_COMPILE_COUNTS.items(), key=lambda kv: -kv[1][1])
        total = sum(v[0] for v in _COMPILE_COUNTS.values())
        total_s = sum(v[1] for v in _COMPILE_COUNTS.values())
        terminalreporter.write_line(
            f"\n== jit compile census: {total} compiles, {total_s:.0f}s "
            f"XLA wall, {len(items)} attribution keys (top 40 by wall) =="
        )
        for k, (n, s) in items[:40]:
            terminalreporter.write_line(f"{n:5d}  {s:7.1f}s  {k}")
        terminalreporter.write_line(
            f"memory maps: peak {_MAP_STATS['max_maps']} of budget "
            f"{_MAP_BUDGET}; emergency cache clears: {_MAP_STATS['clears']}"
        )


@pytest.fixture()
def rng(request):
    """Function-scoped, seeded from the test's nodeid: every test draws the
    same stream regardless of which other tests ran or in what order, so a
    failure reproduces under ``pytest path::test`` in isolation (a
    session-scoped shared generator made outcomes depend on execution
    subset — VERDICT r2 weak #2)."""
    import zlib

    return np.random.default_rng(zlib.crc32(request.node.nodeid.encode()))
