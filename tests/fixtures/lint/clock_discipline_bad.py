"""Known-bad fixture: the historical offenders plus every alias hole the
regex lint missed (ISSUE 11 satellite).  Never imported — lint-read only."""

import time
import time as tt
from datetime import datetime
from time import time as _t


def wall_reads():
    a = time.time()               # the historical bare form (regex-visible)
    b = _t()                      # from-import alias: regex-blind
    c = tt.time()                 # module alias: regex-blind
    d = getattr(time, "time")()   # getattr dodge: regex-blind
    indirect = time.time
    e = indirect()                # attribute-aliased rebind: regex-blind
    f = datetime.now()            # argless now: wall clock in disguise
    return a + b + c + d + e, f
