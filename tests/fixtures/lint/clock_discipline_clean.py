"""Clean twin: every clock need routed through the documented helpers."""

from datetime import datetime, timezone

from csmom_tpu.utils.deadline import mono_now_s, wall_now_s


def timed(fn):
    t0 = mono_now_s()
    fn()
    return mono_now_s() - t0


def stamp():
    # identity stamps take an explicit timezone (argful: allowed)
    return datetime.now(timezone.utc).isoformat(), wall_now_s()
