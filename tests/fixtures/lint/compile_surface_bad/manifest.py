"""The toy warmed manifest: serve.turnover.b4@8x24 is missing — the
fresh-in-window-compile-by-construction hole the rule exists to catch."""

LINT_SURFACE = {
    "warmed": [
        "serve.momentum.b1@8x24",
        "serve.momentum.b4@8x24",
        "serve.turnover.b1@8x24",
    ],
}
