"""Known-bad fixture: a toy serving surface (the LINT_SURFACE literal
form the compile-surface rule checks on non-package trees).  The
endpoint x bucket world declared here is larger than the warmed
manifest in manifest.py — one dispatchable shape has no warm entry."""

LINT_SURFACE = {
    "endpoints": ["momentum", "turnover"],
    "months": 24,
    "asset_buckets": [8],
    "batch_buckets": [1, 4],
}
