"""Every dispatchable (endpoint, bucket) shape has a warm entry."""

LINT_SURFACE = {
    "warmed": [
        "serve.momentum.b1@8x24",
        "serve.momentum.b4@8x24",
        "serve.turnover.b1@8x24",
        "serve.turnover.b4@8x24",
    ],
}
