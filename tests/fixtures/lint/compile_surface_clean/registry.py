"""Clean twin: the same toy surface declaration, fully covered by
manifest.py."""

LINT_SURFACE = {
    "endpoints": ["momentum", "turnover"],
    "months": 24,
    "asset_buckets": [8],
    "batch_buckets": [1, 4],
}
