"""Known-bad fixture for the dial-discipline rule: one-shot dials on
request hot paths — the connection-per-request design the r19 pooled
transport replaced.  Every call here must be flagged."""

from csmom_tpu.serve import proto
from csmom_tpu.serve.proto import request_once as one_shot


def _attempt(worker, header, values, mask, timeout):
    # a dispatch attempt dialing per call: the r18 tail, reintroduced
    return proto.request(worker.socket_path, header,
                         arrays={"values": values, "mask": mask},
                         timeout_s=timeout)


def drive_request(router, header, arrays):
    # the fabric client's hot path on the one-shot API (aliased import)
    return one_shot(router.socket_path, header, arrays, timeout_s=5.0)


def dispatch_loop(workers, header, arrays):
    out = []
    for w in workers:
        obj, _ = proto.request_once(w.socket_path, header, arrays)
        out.append(obj)
    return out
