"""Clean twin for the dial-discipline rule: probes and one-shot admin
ops keep the one-shot API (a fresh connection is the POINT there), and
hot paths dispatch over the pooled channels.  Nothing here may be
flagged."""

from csmom_tpu.serve import proto

_POOL = proto.ChannelPool()


def probe_worker(worker):
    # a probe measures the peer's ability to ACCEPT — one-shot is right
    return proto.request_once(worker.socket_path, {"op": "ping"},
                              timeout_s=2.0)


def collect_stats(handles):
    out = []
    for h in handles:
        obj, _ = proto.request(h.socket_path, {"op": "stats"},
                               timeout_s=5.0)
        out.append(obj)
    return out


def drain_stop(handle):
    return proto.request_once(handle.socket_path, {"op": "stop"},
                              timeout_s=10.0)


def _attempt(worker, header, values, mask, timeout):
    # the hot path on the pooled multiplexed transport
    return _POOL.request(worker.socket_path, header,
                         arrays={"values": values, "mask": mask},
                         timeout_s=timeout)
