"""Known-bad fixture: buffers read after being donated to XLA."""

import jax
import jax.numpy as jnp


def run(values, mask):
    fn = jax.jit(lambda v, m: jnp.where(m, v, 0.0), donate_argnums=(0,))
    out = fn(values, mask)
    return out + values          # `values` was surrendered at the call


def run_named_donated(values, mask, entry_donated):
    out = entry_donated(values, mask)
    checksum = values.sum()      # read-after-donate via a *_donated entry
    return out, checksum
