"""Clean twin: donated buffers are never touched again in scope."""

import jax
import jax.numpy as jnp


def run(values, mask):
    fn = jax.jit(lambda v, m: jnp.where(m, v, 0.0), donate_argnums=(0,))
    out = fn(values, mask)
    return out + mask.sum()      # mask (argnum 1) was not donated


def run_rebound(values, mask):
    fn = jax.jit(lambda v, m: v * 1.0, donate_argnums=(0, 1))
    values = fn(values, mask)    # rebind: the old buffer is gone by name
    return values
