"""Known-bad fixture: a parallel enumeration and an undeclared point."""

from csmom_tpu.chaos.inject import checkpoint

# the pre-ISSUE-9 buckets.py line: a module-level endpoint table outside
# csmom_tpu/registry/ forks the registry back into parallel lists
ENDPOINTS = ("momentum", "turnover", "backtest")


def probe():
    checkpoint("serve.not_a_point")   # absent from chaos.plan.KNOWN_POINTS
