"""Clean twin: registry queries and a declared checkpoint point."""

from csmom_tpu.chaos.inject import checkpoint
from csmom_tpu.registry import serve_endpoints


def probe():
    for kind in serve_endpoints():    # the registry IS the table
        checkpoint("serve.dispatch", kind=kind)
