"""A donated-buffer entry (name-marked, like the real *_donated jits)."""


def grid_step_donated(state):
    return state
