"""Known-bad fixture: traced functions whose host-sync escapes hide one
call hop away — the per-file tracer-hygiene rule stays silent on THIS
file (every escape lives in util.py/donated.py)."""

import jax

from .util import log_panel, refresh_state


@jax.jit
def score(panel):
    log_panel(panel)
    return panel * 2.0


@jax.jit
def step(state):
    return refresh_state(state)
