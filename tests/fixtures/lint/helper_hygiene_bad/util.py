"""The helpers hiding the escapes: host I/O, a clock read, and a
donated-buffer entry call — none of them traced-looking on their own."""

import time

from .donated import grid_step_donated


def log_panel(panel):
    print("panel", panel)
    return time.monotonic()


def refresh_state(state):
    return grid_step_donated(state)
