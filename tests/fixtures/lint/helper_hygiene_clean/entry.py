"""Clean twin: the traced functions call a pure helper."""

import jax

from .util import scale_panel


@jax.jit
def score(panel):
    return scale_panel(panel)


@jax.jit
def step(state):
    return scale_panel(state)
