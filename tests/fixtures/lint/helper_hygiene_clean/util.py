"""A pure helper: no host I/O, no clocks, no donation — safe to reach
from a traced body."""


def scale_panel(panel):
    return panel * 2.0
