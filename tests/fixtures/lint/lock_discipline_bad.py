"""Known-bad fixture: locks leaving scope bare, blocking under a lock."""

import threading
import time

_lock = threading.Lock()
_state = {"n": 0}


def leaky_update():
    _lock.acquire()              # no with, no try/finally: a raise between
    _state["n"] += 1             # acquire and release deadlocks every
    _lock.release()              # later waiter


def slow_path(sock, payload):
    with _lock:
        time.sleep(0.05)         # blocking call with the lock held
        sock.sendall(payload)    # socket write serializes every waiter
