"""Clean twin: with-blocks and try-finally, blocking work outside."""

import threading
import time

_lock = threading.Lock()
_state = {"n": 0}


def safe_update():
    with _lock:
        _state["n"] += 1


def safe_manual():
    _lock.acquire()
    try:
        _state["n"] += 1
    finally:
        _lock.release()


def slow_path(sock, payload):
    with _lock:
        n = _state["n"]
    time.sleep(0.05)             # the wait happens lock-free
    sock.sendall(payload + bytes([n % 256]))


def try_lock_then_release():
    # the canonical non-blocking acquire: the if-test acquire whose
    # body opens with a try releasing in its finally (the r19 baton)
    if _lock.acquire(blocking=False):
        try:
            _state["n"] += 1
        finally:
            _lock.release()
