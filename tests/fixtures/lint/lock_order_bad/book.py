"""Known-bad fixture: lock-order defects the r16 PER-FILE rule cannot
see (each function is locally disciplined — with-blocks only, no
lexical blocking call under a lock)."""

import threading

from .helpers import slow_push


class Book:
    def __init__(self):
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()

    def credit(self):
        # order: _lock -> _state_lock
        with self._lock:
            with self._state_lock:
                return 1

    def debit(self):
        # order: _state_lock -> _lock, but only THROUGH _flush — the
        # opposite order is invisible to any single-function view
        with self._state_lock:
            return self._flush()

    def _flush(self):
        with self._lock:
            return 2

    def publish(self):
        # helper-hidden blocking call: slow_push sleeps, one hop away
        with self._lock:
            return slow_push(self)
