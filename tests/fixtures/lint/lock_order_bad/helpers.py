"""The helper that hides the blocking call one hop from the lock."""

import time


def slow_push(book):
    time.sleep(0.01)
    return book
