"""Clean twin: same two locks, ONE global acquisition order
(_lock before _state_lock, everywhere), blocking work outside the
critical section."""

import threading

from .helpers import slow_push


class Book:
    def __init__(self):
        self._lock = threading.Lock()
        self._state_lock = threading.Lock()

    def credit(self):
        with self._lock:
            with self._state_lock:
                return 1

    def debit(self):
        with self._lock:
            return self._flush()

    def _flush(self):
        with self._state_lock:
            return 2

    def publish(self):
        with self._lock:
            payload = 3
        return slow_push(payload)
