"""The same helper — legal when called with no lock held."""

import time


def slow_push(payload):
    time.sleep(0.01)
    return payload
