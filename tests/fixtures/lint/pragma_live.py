"""Fixture: a live pragma suppresses exactly its finding, nothing else."""

import time


def wall():
    # lint: allow[clock-discipline] fixture demonstrating a live suppression
    return time.time()
