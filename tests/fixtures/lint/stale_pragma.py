"""Known-bad fixture: an unused suppression is itself a finding."""


def fine():
    # lint: allow[clock-discipline] nothing below actually reads a clock
    return 42
