"""Known-bad fixture: host-sync escapes inside traced functions."""

import time

import jax
import numpy as np

_CALLS = 0


def score(values, mask):
    global _CALLS
    _CALLS = _CALLS + 1          # mutable-global write under tracing
    print("scoring batch")       # host I/O inside the trace
    t0 = time.monotonic()        # clock read inside the trace
    host = np.asarray(values)    # host materialization of a traced value
    lead = float(mask)           # concretization of a traced value
    tail = values.item()         # device->host sync
    return host.sum() + lead + tail + t0


scorer = jax.jit(score)
