"""Clean twin: a pure traced scorer (static args may concretize)."""

from functools import partial

import jax
import jax.numpy as jnp


@partial(jax.jit, static_argnames=("n_bins",))
def score(values, mask, n_bins):
    bins = float(n_bins)         # static arg: concretizing it is fine
    return jnp.where(mask, values, jnp.nan).sum() / bins


batched = jax.jit(jax.vmap(lambda v, m: jnp.where(m, v, 0.0).sum()))
