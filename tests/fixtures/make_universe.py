"""Regenerate the committed synthetic CSV universe (tests/fixtures/universe).

The reference vendors its data assets in-repo (20 tickers of cached
yfinance CSVs — SURVEY §2 row 16); licensing keeps real price data out of
this repo, so the committed universe is SYNTHETIC: 8 tickers of daily bars
from the seeded generator, written in the two real cache dialects (6 in
dialect A, 2 in dialect B) so a bare checkout exercises the entire
CSV-ingest path — dialect detection, preamble stripping, pivot — at
universe scale, not just on the two single-file dialect fixtures.

Deterministic: re-running reproduces the committed files byte-for-byte
(PCG64 + fixed formatting).  If the generator's stream ever changes
(numpy NEP 19), re-run this and re-pin the golden constants in
tests/test_synthetic_golden.py::test_csv_universe_golden.
"""

import os

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "universe")
TICKERS = ["SYNAA", "SYNBB", "SYNCC", "SYNDD", "SYNEE", "SYNFF",
           "SYNGG", "SYNHH"]
N_DAYS = 500
SEED = 2026


def main() -> None:
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))
    from csmom_tpu.panel.synthetic import synthetic_daily_panel

    panel = synthetic_daily_panel(len(TICKERS), N_DAYS, seed=SEED,
                                  listing_gaps=True)
    rng = np.random.default_rng(SEED + 1)
    os.makedirs(OUT, exist_ok=True)
    dates = np.datetime_as_string(panel.times.astype("datetime64[D]"))
    for i, t in enumerate(TICKERS):
        close = panel.values[i]
        m = panel.mask[i]
        vol = rng.integers(2e5, 5e6, size=N_DAYS)
        # OHLC around the close path, plausibly ordered
        spread = np.abs(rng.normal(0, 0.01, size=N_DAYS)) * close
        o = close * (1 + rng.normal(0, 0.005, size=N_DAYS))
        hi = np.maximum(o, close) + spread
        lo = np.minimum(o, close) - spread
        rows = [
            f"{dates[d]},{close[d]:.6f},{close[d]:.6f},{hi[d]:.6f},"
            f"{lo[d]:.6f},{o[d]:.6f},{vol[d]}"
            for d in range(N_DAYS) if m[d]
        ]
        if i < 6:  # dialect A: Date header + junk ticker row
            text = (
                "Date,Adj Close,Close,High,Low,Open,Volume\n"
                + f",{t},{t},{t},{t},{t},{t}\n"
                + "\n".join(rows) + "\n"
            )
        else:      # dialect B: Price/Ticker/Date 3-row preamble, no Adj Close
            rows_b = [
                f"{dates[d]},{close[d]:.6f},{hi[d]:.6f},{lo[d]:.6f},"
                f"{o[d]:.6f},{vol[d]}"
                for d in range(N_DAYS) if m[d]
            ]
            text = (
                "Price,Close,High,Low,Open,Volume\n"
                + f"Ticker,{t},{t},{t},{t},{t}\n"
                + "Date,,,,,\n"
                + "\n".join(rows_b) + "\n"
            )
        with open(os.path.join(OUT, f"{t}_daily.csv"), "w") as f:
            f.write(text)
    print(f"wrote {len(TICKERS)} daily CSVs to {OUT}")


if __name__ == "__main__":
    main()
