"""Regression tests for the round-1 advisor findings (ADVICE.md)."""

import argparse

import numpy as np
import pytest

from csmom_tpu.backends import run_monthly
from csmom_tpu.panel.panel import Panel
from csmom_tpu.strategy import (
    Momentum,
    Reversal,
    VolumeZMomentum,
    ZScoreCombo,
    consumed_panels,
)


def _toy_panel(rng, a=20, m=36):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(a, m)), axis=1))
    times = np.array([np.datetime64("2000-01-31") + 31 * i for i in range(m)])
    return Panel.from_dense(prices, [f"T{i:03d}" for i in range(a)], times)


def _vol_panels(rng, a=20, m=36):
    vols = rng.integers(1_000, 9_000, size=(a, m)).astype(float)
    return vols, np.ones((a, m), bool)


# --- ADVICE #3: stray panel kwargs must not be swallowed by **panels ------

def test_misspelled_panel_kwarg_raises(rng):
    panel = _toy_panel(rng)
    vols, vmask = _vol_panels(rng)
    with pytest.raises(TypeError, match="volumes_maks"):
        run_monthly(panel, n_bins=5, strategy=VolumeZMomentum(),
                    volumes=vols, volumes_maks=vmask)


def test_declared_panels_accepted(rng):
    panel = _toy_panel(rng)
    vols, vmask = _vol_panels(rng)
    rep = run_monthly(panel, n_bins=5, strategy=VolumeZMomentum(),
                      volumes=vols, volumes_mask=vmask)
    assert np.isfinite(rep.spread).any()


def test_combo_inherits_component_panels(rng):
    combo = ZScoreCombo(((Momentum(), 0.5), (VolumeZMomentum(), 0.5)))
    assert {"volumes", "volumes_mask"} <= set(consumed_panels(combo))
    panel = _toy_panel(rng)
    vols, vmask = _vol_panels(rng)
    rep = run_monthly(panel, n_bins=5, strategy=combo,
                      volumes=vols, volumes_mask=vmask)
    assert np.isfinite(rep.spread).any()


def test_momentum_does_not_consume_volumes(rng):
    assert "volumes" not in consumed_panels(Momentum())
    with pytest.raises(TypeError, match="volumes"):
        run_monthly(_toy_panel(rng), n_bins=5, strategy=Momentum(),
                    volumes=_vol_panels(rng)[0])


# --- ADVICE #1: CLI must not inject momentum defaults into other
#     strategies' own defaults ---------------------------------------------

def _cli_args(**kv):
    ns = argparse.Namespace(strategy=None, strategy_arg=None, lookback=None,
                            skip=None, config=None, backend=None, out=None,
                            data_dir=None)
    for k, v in kv.items():
        setattr(ns, k, v)
    return ns


def test_reversal_keeps_its_own_defaults():
    from csmom_tpu.cli.main import _parse_strategy
    from csmom_tpu.config import RunConfig

    strat = _parse_strategy(_cli_args(strategy="reversal"), RunConfig())
    assert isinstance(strat, Reversal)
    # the documented 1-month Jegadeesh reversal, not a 12-month skip-1 one
    assert (strat.lookback, strat.skip) == (Reversal().lookback, Reversal().skip)


def test_explicit_lookback_still_flows_through():
    from csmom_tpu.cli.main import _load_cfg, _parse_strategy

    args = _cli_args(strategy="momentum", lookback=6)
    strat = _parse_strategy(args, _load_cfg(args))
    assert strat.lookback == 6


def test_config_file_momentum_keys_flow_through(tmp_path):
    from csmom_tpu.cli.main import _parse_strategy
    from csmom_tpu.config import load_config

    cfg_file = tmp_path / "cfg.toml"
    cfg_file.write_text("[momentum]\nlookback = 9\n")
    cfg = load_config(str(cfg_file))
    strat = _parse_strategy(_cli_args(strategy="momentum",
                                      config=str(cfg_file)), cfg)
    assert strat.lookback == 9
    # but skip (not in the file) stays the strategy's own default
    assert strat.skip == Momentum().skip


# --- ADVICE #4: model-dependent alpha default in the API layer ------------

@pytest.mark.slow
def test_intraday_alpha_default_resolves_per_model(rng, monkeypatch):
    import pandas as pd

    import csmom_tpu.models as models
    from csmom_tpu.api import intraday_pipeline, synthetic_minute_frame

    days = pd.date_range("2024-01-01", periods=3, freq="B")
    daily_df = pd.DataFrame({
        "date": np.repeat(days, 2),
        "ticker": ["AA", "BB"] * len(days),
        "open": 100.0,
        "close": 101.0,
        "adj_close": 101.0,
        "volume": 1e6,
    })
    minute_df = synthetic_minute_frame(daily_df, seed=0)

    seen = {}
    real = models.elastic_net_time_series_cv

    def spy(*a, **kw):
        seen["alpha"] = kw.get("alpha")
        return real(*a, **kw)

    monkeypatch.setattr(models, "elastic_net_time_series_cv", spy)
    intraday_pipeline(minute_df, daily_df, model="lasso")
    # the scale-appropriate default (docstring: useful l1 penalties are
    # ~1e-9..1e-7), not ridge's 1.0 which zeroes every coefficient
    assert seen["alpha"] == pytest.approx(1e-8)

    real_r = models.ridge_time_series_cv

    def spy_r(*a, **kw):
        seen["ridge_alpha"] = kw.get("alpha")
        return real_r(*a, **kw)

    monkeypatch.setattr(models, "ridge_time_series_cv", spy_r)
    intraday_pipeline(minute_df, daily_df, model="ridge")
    assert seen["ridge_alpha"] == pytest.approx(1.0)
