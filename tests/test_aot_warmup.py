"""AOT warm-start pipeline: manifest integrity, serialized-executable
cache round-trips across process restarts, dispatch hygiene, and buffer
donation (csmom_tpu.compile + utils.profiling counters).

The cross-process tests run real subprocesses: the pipeline's whole point
is that process A's compiles become process B's cache loads, which cannot
be tested inside one process (the in-process executable cache would
satisfy the second call without ever touching the disk cache).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from csmom_tpu.compile.manifest import PROFILES, ManifestEntry, build_manifest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ manifest ----

def test_smoke_manifest_validates_with_unique_names():
    entries = build_manifest("smoke")
    assert len(entries) >= 8  # every entry kind is represented
    names = [e.name for e in entries]
    assert len(set(names)) == len(names)
    for e in entries:
        e.validate()  # binds the abstract args against the live signature
        assert e.shape_summary()  # digest renders for every entry


def test_manifest_binds_against_live_signatures_so_drift_raises():
    # a stale entry — a kwarg the function does not have — must fail at
    # validate() time, not compile silently against the wrong call
    def engine(price, mask, *, n_bins=10):
        return price

    stale = ManifestEntry(
        name="drifted",
        fn=engine,
        args=(jax.ShapeDtypeStruct((4, 8), np.float32),
              jax.ShapeDtypeStruct((4, 8), bool)),
        kwargs={"renamed_param": 3},
    )
    with pytest.raises(TypeError):
        stale.validate()


def test_unknown_profile_rejected():
    with pytest.raises(ValueError, match="unknown warmup profile"):
        build_manifest("no-such-profile")
    assert "smoke" in PROFILES


# ------------------------------------- cross-process cache round-trip ----

_AOT_CHILD = """
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
from csmom_tpu.utils.jit_cache import enable_persistent_cache
from csmom_tpu.compile.aot import aot_compile
from csmom_tpu.compile.manifest import build_manifest

enable_persistent_cache("aot-test", min_compile_s=0.0)
entry = next(e for e in build_manifest("smoke")
             if e.name.startswith("monthly.net_of_costs"))
print(json.dumps(aot_compile(entry)))
"""


def _run_aot_child(cache_dir):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "CSMOM_JIT_CACHE": str(cache_dir),
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    p = subprocess.run(
        [sys.executable, "-c", _AOT_CHILD],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    line = [ln for ln in p.stdout.splitlines() if ln.startswith("{")][-1]
    return json.loads(line)


def test_aot_compile_hits_cache_after_process_restart(tmp_path):
    cache = tmp_path / "cache"
    cold = _run_aot_child(cache)
    assert cold["cache_hit"] is False
    assert cold["cache_writes"] >= 1  # executable serialized to disk
    assert os.listdir(cache)  # the artifact actually landed

    warm = _run_aot_child(cache)  # fresh interpreter, same cache dir
    assert warm["cache_hit"] is True, warm
    assert warm["cache_hits"] >= 1
    assert warm["cache_writes"] == 0  # no recompile — served from disk


def test_import_clean_on_running_interpreter():
    # the seed died at collection on this interpreter (a 3.11-only logging
    # call); pin that the package imports everywhere it is entered from,
    # even with a bogus log-level env (the code path that used the
    # 3.11-only API)
    env = dict(os.environ)
    env.update({
        "PYTHONPATH": _REPO + os.pathsep + env.get("PYTHONPATH", ""),
        "CSMOM_LOG_LEVEL": "NOT_A_LEVEL",
    })
    p = subprocess.run(
        [sys.executable, "-c",
         "import csmom_tpu, csmom_tpu.compile, csmom_tpu.cli.main, "
         "csmom_tpu.utils.logging as l; l.get_logger('t').info('ok'); "
         "print('imported')"],
        capture_output=True, text=True, timeout=240, env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    assert "imported" in p.stdout


# ------------------------------------------------- dispatch hygiene ----

def _grid_inputs(rng, A=16, T=48):
    p = jnp.asarray(
        50.0 * np.exp(np.cumsum(rng.normal(0, 0.02, (A, T)), axis=1))
    )
    return p, jnp.ones((A, T), bool)


def test_grid_hot_path_is_one_dispatch_per_call(rng):
    from csmom_tpu.compile.entries import grid_scalar_fn
    from csmom_tpu.utils.profiling import count_dispatches

    fn = grid_scalar_fn((3, 6), (3, 6), 1, "rank", "xla")
    p, m = _grid_inputs(rng)
    with count_dispatches() as box:
        np.asarray(fn(p, m))  # formation + label + cohort + reduce, fused
    assert box["dispatches"] == 1


def test_event_hot_path_is_one_dispatch_per_call(rng):
    from csmom_tpu.backtest.event import event_backtest
    from csmom_tpu.utils.profiling import count_dispatches

    A, T = 4, 32
    p, v = _grid_inputs(rng, A, T)
    s = jnp.asarray(rng.normal(0, 1e-4, (A, T)))
    adv = jnp.full((A,), 1e6)
    vol = jnp.full((A,), 0.02)
    with count_dispatches() as box:
        np.asarray(event_backtest(p, v, s, adv, vol).total_pnl)
    assert box["dispatches"] == 1


def test_dispatch_counter_sees_extra_computations(rng):
    # the counter must be able to FAIL: two distinct computations (a host
    # round-trip between stages) score >= 2, which is what the ==1 pins
    # above would catch if the hot path ever regressed
    from csmom_tpu.utils.profiling import count_dispatches

    p, _ = _grid_inputs(rng)
    f1 = jax.jit(lambda x: x + 1.0)
    f2 = jax.jit(lambda x: (x * 2.0).sum())
    with count_dispatches() as box:
        np.asarray(f2(f1(p)))
    assert box["dispatches"] >= 2


# --------------------------------------------------- buffer donation ----

def test_grid_donated_variant_matches_and_declares_donation(rng):
    import warnings

    from csmom_tpu.backtest.grid import jk_grid_backtest

    Js, Ks = np.array([3, 6]), np.array([3, 6])
    p0, m0 = _grid_inputs(rng)
    keep = jk_grid_backtest(p0, m0, Js, Ks)
    p1 = jnp.array(p0)
    m1 = jnp.array(m0)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        gave = jk_grid_backtest(p1, m1, Js, Ks, donate_panels=True)
    np.testing.assert_allclose(np.asarray(keep.mean_spread),
                               np.asarray(gave.mean_spread))
    # the donation must be REAL: either the backend consumed a panel
    # buffer (aliasing accepted) or it explicitly declined a declared
    # donation — a variant that never declared one shows neither
    declined = any("donated" in str(w.message).lower() for w in caught)
    assert p1.is_deleted() or m1.is_deleted() or declined


def test_event_donated_variant_matches_and_consumes_a_panel(rng):
    import warnings

    from csmom_tpu.backtest.event import event_backtest, event_backtest_donated

    A, T = 4, 32
    p0, v0 = _grid_inputs(rng, A, T)
    s0 = jnp.asarray(rng.normal(0, 1e-4, (A, T)))
    adv = jnp.full((A,), 1e6)
    vol = jnp.full((A,), 0.02)
    keep = event_backtest(p0, v0, s0, adv, vol)
    assert not p0.is_deleted()  # the plain engine never consumes inputs

    p1, v1, s1 = jnp.array(p0), jnp.array(v0), jnp.array(s0)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        gave = event_backtest_donated(p1, v1, s1, adv, vol)
    assert float(keep.total_pnl) == float(gave.total_pnl)
    declined = any("donated" in str(w.message).lower() for w in caught)
    assert p1.is_deleted() or v1.is_deleted() or s1.is_deleted() or declined


# ------------------------------------------- device-memory observability ----
# (the perf ledger's memory axis: aot_compile reads compiled.memory_
# analysis() — the one place a Compiled handle exists per hot shape —
# and the bytes flow into the entry record, the metrics snapshot, and a
# schema-valid TELEMETRY sidecar.  Same code path on TPU; pinned on CPU.)

def test_aot_compile_record_carries_memory_bytes(tmp_path, monkeypatch):
    from csmom_tpu import obs
    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.compile.aot import aot_compile
    from csmom_tpu.obs import memstats
    from csmom_tpu.obs import metrics as obs_metrics
    from csmom_tpu.obs import timeline as tl

    monkeypatch.delenv("CSMOM_TELEMETRY", raising=False)
    memstats.reset()
    entry = ManifestEntry(
        name="memtest.tiny@8x8",
        fn=jax.jit(lambda x: x.sum()),
        args=(jax.ShapeDtypeStruct((8, 8), np.float32),),
    )
    rec = aot_compile(entry)
    mem = rec["memory"]
    assert isinstance(mem, dict), mem
    # the comparable scalar + at least one measured byte field, all ints
    assert isinstance(mem["peak_bytes"], int)
    assert mem["platform"] == "cpu"
    assert any(k.endswith("_in_bytes") and isinstance(v, int)
               for k, v in mem.items())
    assert mem["argument_size_in_bytes"] == 8 * 8 * 4

    # registry -> metrics snapshot -> sidecar, schema-validated like any
    # committed artifact (the acceptance path for the TPU round too)
    assert memstats.snapshot()["memtest.tiny@8x8"] == mem
    obs.arm(run_id="memtest")
    try:
        snap = obs_metrics.snapshot()
        assert snap["memory"]["memtest.tiny@8x8"]["peak_bytes"] == \
            mem["peak_bytes"]
        name = tl.finish_and_write(str(tmp_path), fallback_metrics=snap)
    finally:
        obs.disarm()
    assert name == "TELEMETRY_memtest.json"
    assert inv.validate_file(os.path.join(str(tmp_path), name)) == []
    memstats.reset()


def test_warmup_report_carries_per_shape_memory(tmp_path, monkeypatch):
    """The manifest report's memory digest: every smoke entry measured,
    the binding (max-peak) shape named."""
    from csmom_tpu.compile.aot import warmup
    from csmom_tpu.obs import memstats

    monkeypatch.setenv("CSMOM_JIT_CACHE", "0")
    memstats.reset()
    rep = warmup(profiles=("smoke",), write_report=False,
                 include_golden_event=False)
    assert rep["n_errors"] == 0
    assert rep["memory"]["n_shapes_measured"] == rep["n_entries"]
    assert rep["memory"]["max_peak_bytes"] > 0
    assert rep["memory"]["max_peak_entry"]
    for row in rep["entries"]:
        assert isinstance(row["memory"], dict), row
        assert isinstance(row["memory"]["peak_bytes"], int)
    memstats.reset()
