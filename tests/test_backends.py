"""Cross-backend parity: the pandas engine and the TPU engine must agree.

This pins the north-star constraint — two engines behind one API — with the
TPU engine's golden-parity test (test_monthly_backtest.py) anchoring both to
the reference's measured numbers.
"""

import numpy as np
import pytest

from csmom_tpu.backends import run_monthly, monthly_spread_backtest_pandas
from csmom_tpu.panel.panel import Panel

from tests.conftest import MEASURED_TICKERS, REFERENCE_DATA, requires_reference


def _toy_panel(rng, a=30, m=48, gap_rate=0.0):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(a, m)), axis=1))
    if gap_rate:
        prices[rng.random((a, m)) < gap_rate] = np.nan
    # leading missing history for some assets (late listings)
    prices[: a // 5, : m // 4] = np.nan
    times = np.array([np.datetime64("2000-01-31") + 31 * i for i in range(m)])
    return Panel.from_dense(prices, [f"T{i:03d}" for i in range(a)], times)


def test_backends_agree_gap_free(rng):
    panel = _toy_panel(rng)
    tpu = run_monthly(panel, lookback=6, skip=1, n_bins=5, backend="tpu")
    pdr = run_monthly(panel, lookback=6, skip=1, n_bins=5, backend="pandas")
    assert tpu.backend == "tpu" and pdr.backend == "pandas"
    np.testing.assert_array_equal(np.isnan(tpu.spread), np.isnan(pdr.spread))
    np.testing.assert_allclose(tpu.spread, pdr.spread, rtol=1e-9, equal_nan=True)
    np.testing.assert_array_equal(tpu.labels, pdr.labels)
    np.testing.assert_allclose(tpu.mean_spread, pdr.mean_spread, rtol=1e-9)
    np.testing.assert_allclose(tpu.ann_sharpe, pdr.ann_sharpe, rtol=1e-9)
    np.testing.assert_allclose(tpu.tstat, pdr.tstat, rtol=1e-9)
    # NW t-stat: jax kernel vs the pandas engine's independent numpy oracle
    np.testing.assert_allclose(tpu.tstat_nw, pdr.tstat_nw, rtol=1e-9)


@pytest.mark.slow
def test_backends_agree_with_leading_gaps(rng):
    """Late listings (leading NaN runs) — warmup must match month for month."""
    panel = _toy_panel(rng, a=25, m=40)
    for lookback, skip in ((12, 1), (3, 0), (6, 2)):
        tpu = run_monthly(panel, lookback=lookback, skip=skip, n_bins=5, backend="tpu")
        pdr = run_monthly(panel, lookback=lookback, skip=skip, n_bins=5, backend="pandas")
        np.testing.assert_allclose(tpu.spread, pdr.spread, rtol=1e-9, equal_nan=True)
        np.testing.assert_array_equal(tpu.labels, pdr.labels)


@requires_reference
def test_pandas_engine_reproduces_measured_baseline():
    """The pandas engine hits the same measured numbers as the TPU engine
    (BASELINE.md: mean 0.003674, Sharpe 0.1002 on the 19-ticker panel)."""
    from csmom_tpu.api import monthly_price_panel

    prices, _ = monthly_price_panel(REFERENCE_DATA, MEASURED_TICKERS)
    rep = run_monthly(prices, lookback=12, skip=1, backend="pandas")
    assert abs(rep.mean_spread - 0.003674) < 5e-7
    assert abs(rep.ann_sharpe - 0.1002) < 5e-5
    # and both engines agree month-for-month on the real panel
    tpu = run_monthly(prices, lookback=12, skip=1, backend="tpu")
    np.testing.assert_allclose(rep.spread, tpu.spread, rtol=1e-6, atol=1e-9, equal_nan=True)


def test_unknown_backend_raises(rng):
    with pytest.raises(ValueError, match="unknown backend"):
        run_monthly(_toy_panel(rng), backend="torch")


def test_spread_series_roundtrip(rng):
    rep = run_monthly(_toy_panel(rng), lookback=3, n_bins=5, backend="pandas")
    s = rep.spread_series()
    assert len(s) == np.isfinite(rep.spread).sum()
