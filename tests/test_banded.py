"""Hysteresis-banded rebalancing vs the plain engine and a loop oracle."""

import numpy as np
import pytest

from csmom_tpu.backtest import banded_monthly_backtest, monthly_spread_backtest
from csmom_tpu.backtest.banded import banded_books
from csmom_tpu.costs.impact import long_short_weights, turnover_cost
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.signals.momentum import momentum


def _panel(rng, A=40, M=90):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, M)), axis=1))
    mask = np.ones((A, M), bool)
    mask[: A // 8, : M // 4] = False  # late entrants
    prices = np.where(mask, prices, np.nan)
    return prices, mask


def _books_loop(labels, n_bins, band):
    """Independent python-loop oracle of the hysteresis rule."""
    A, M = labels.shape
    long_b = np.zeros((A, M), bool)
    short_b = np.zeros((A, M), bool)
    lp = np.zeros(A, bool)
    sp = np.zeros(A, bool)
    top = n_bins - 1
    for t in range(M):
        lab = labels[:, t]
        lv = lab >= 0
        lnow = (lv & (lab == top)) | (lp & lv & (lab >= top - band))
        snow = (lv & (lab == 0)) | (sp & lv & (lab <= band))
        long_b[:, t], short_b[:, t] = lnow, snow
        lp, sp = lnow, snow
    return long_b, short_b


def test_band_zero_equals_plain_engine(rng):
    """band=0 IS the plain engine: same spread series, same validity, same
    stats — the invariant that pins the banded engine's conventions."""
    prices, mask = _panel(rng)
    plain = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    banded = banded_monthly_backtest(prices, mask, lookback=6, skip=1,
                                     n_bins=5, band=0)
    np.testing.assert_array_equal(np.asarray(banded.spread_valid),
                                  np.asarray(plain.spread_valid))
    np.testing.assert_allclose(np.asarray(banded.spread),
                               np.asarray(plain.spread),
                               rtol=1e-12, equal_nan=True)
    np.testing.assert_allclose(float(banded.mean_spread),
                               float(plain.mean_spread), rtol=1e-12)
    np.testing.assert_allclose(float(banded.ann_sharpe),
                               float(plain.ann_sharpe), rtol=1e-12)


def test_band_zero_equals_plain_engine_with_delistings(rng):
    """Same invariant on a panel with delistings: both engines must apply
    the same formation_listed_mask drop rule, not just agree on the
    late-entrant fixtures."""
    prices, mask = _panel(rng)
    prices = np.asarray(prices).copy()
    prices[-3:, 28:] = np.nan
    mask = np.isfinite(prices)
    plain = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    banded = banded_monthly_backtest(prices, mask, lookback=6, skip=1,
                                     n_bins=5, band=0)
    np.testing.assert_array_equal(np.asarray(banded.spread_valid),
                                  np.asarray(plain.spread_valid))
    np.testing.assert_allclose(np.asarray(banded.spread),
                               np.asarray(plain.spread),
                               rtol=1e-12, equal_nan=True)


def test_books_match_loop_oracle(rng):
    prices, mask = _panel(rng)
    mom, momv = momentum(np.asarray(prices), np.asarray(mask), lookback=6, skip=1)
    labels, _ = decile_assign_panel(mom, momv, n_bins=5, mode="qcut")
    labels = np.asarray(labels)
    for band in (0, 1):
        long_b, short_b = banded_books(labels, 5, band)
        wl, ws = _books_loop(labels, 5, band)
        np.testing.assert_array_equal(np.asarray(long_b), wl)
        np.testing.assert_array_equal(np.asarray(short_b), ws)


def test_membership_properties(rng):
    """Every member either entered at the extreme this month or persisted
    from last month inside the stay zone; books never overlap."""
    prices, mask = _panel(rng)
    mom, momv = momentum(np.asarray(prices), np.asarray(mask), lookback=6, skip=1)
    labels, _ = decile_assign_panel(mom, momv, n_bins=5, mode="qcut")
    labels = np.asarray(labels)
    long_b, short_b = map(np.asarray, banded_books(labels, 5, band=1))
    assert not (long_b & short_b).any()
    A, M = labels.shape
    for t in range(1, M):
        new = long_b[:, t] & ~long_b[:, t - 1]
        assert (labels[new, t] == 4).all()          # entries only at the top
        held = long_b[:, t] & long_b[:, t - 1]
        assert (labels[held, t] >= 3).all()         # stays only inside band
        exited = long_b[:, t - 1] & ~long_b[:, t]
        assert ((labels[exited, t] < 3)).all()      # exits only below band


def test_turnover_falls_with_band_and_costs_reprice(rng):
    """The band exists to cut turnover: mean L1 turnover must fall
    monotonically with band width on a noisy panel, and the banded
    turnover plugs into the same linear cost charge as the plain path."""
    prices, mask = _panel(rng, A=60, M=120)
    plain = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    w_plain = long_short_weights(plain.labels, plain.decile_counts, 5)
    plain_cost = np.asarray(turnover_cost(w_plain, half_spread=1.0))

    means = []
    for band in (0, 1):
        res = banded_monthly_backtest(prices, mask, lookback=6, skip=1,
                                      n_bins=5, band=band)
        means.append(float(np.asarray(res.turnover).mean()))
    # band=0 turnover == the plain cost path's unit-cost charge
    res0 = banded_monthly_backtest(prices, mask, lookback=6, skip=1,
                                   n_bins=5, band=0)
    np.testing.assert_allclose(np.asarray(res0.turnover), plain_cost,
                               rtol=1e-9, atol=1e-12)
    assert means[1] < means[0]


def test_band_bounds_validated():
    prices = np.full((4, 10), 50.0)
    mask = np.ones((4, 10), bool)
    with pytest.raises(ValueError, match="stay-zones"):
        banded_monthly_backtest(prices, mask, n_bins=5, band=2)
    with pytest.raises(ValueError, match="stay-zones"):
        banded_monthly_backtest(prices, mask, n_bins=5, band=-1)
