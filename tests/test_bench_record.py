"""The round record must survive the driver's 2,000-char stdout tail.

Round 4's official record was lost to exactly this: bench's single JSON
line outgrew the capture window and ``BENCH_r04.json`` landed with
``"parsed": null``.  These tests pin the fix: the headline line bench
prints is hard-capped (`bench.HEADLINE_MAX_CHARS`, itself well under
2,000), always parseable, and always points at the committed full record
— including on the worst day, when every probe burns out and the budget
hits zero (VERDICT r4 items #1 and #8).

bench.py is a repo-root script, not a package module; it is imported here
by file path.  Importing it must not initialize jax (the supervisor only
imports jax inside children), so the import itself is part of the test.
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(_REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _bloated_record():
    """A record strictly larger than anything a real run has produced:
    r4's truncated line was ~2k chars; this synthesizes ~40k."""
    probes = [
        {"utc": f"2026-07-30T21:{i:02d}:00+00:00", "stage": "loop",
         "ok": False,
         "info": "probe timeout after 120s (backend hung at init) " + "x" * 200}
        for i in range(60)
    ]
    return {
        "metric": "intraday_event_backtest_bar_groups_per_sec",
        "value": 12345.6,
        "unit": "bar_groups/s",
        "vs_baseline": 83.2,
        "extra": {
            "platform": "cpu",
            "device_kind": "cpu",
            "north_star_met": False,
            "grid16_rank_s": 1.2345,
            "grid_workload": "16 cells, 512 stocks x 3780 days (180 months)",
            "golden_ok": True,
            "event_backtest_wall_s": 0.0123,
            "tpu_probes": probes,
            "attempt_errors": ["default child: " + "e" * 500] * 10,
            "histrank_vs_allgather": {"note": "n" * 800},
            "tpu_last_verified": {
                "captured_utc": "2026-07-16T01:02:03+00:00 (r3 session)",
                "value": 999.9,
                "unit": "bar_groups/s",
                "provenance": "session-cached (originally: live …)" + "p" * 300,
                "extra": {"huge": "z" * 5000},
            },
        },
    }


def test_headline_is_capped_and_parseable(bench):
    rec = _bloated_record()
    assert len(json.dumps(rec)) > 10_000  # the input really is oversized
    line = bench._headline(rec, "BENCH_FULL_r05.json")
    assert len(line) <= bench.HEADLINE_MAX_CHARS
    assert bench.HEADLINE_MAX_CHARS <= 1800  # comfortably inside the window
    obj = json.loads(line)
    # the four driver-required fields survive verbatim
    assert obj["metric"] == rec["metric"]
    assert obj["value"] == rec["value"]
    assert obj["unit"] == rec["unit"]
    assert obj["vs_baseline"] == rec["vs_baseline"]
    # and the pointer to the committed full record is present
    assert obj["extra"]["full_record"] == "BENCH_FULL_r05.json"
    # probe spam is digested, not embedded
    assert "tpu_probes" not in obj["extra"]
    assert obj["extra"]["tpu_probes_summary"] == "0/60 ok"


def test_headline_degrade_path_still_capped(bench, monkeypatch):
    """Even if the digest itself somehow exceeds the cap, the degrade line
    (four fields + pointer) is what goes out — never a long line."""
    monkeypatch.setattr(bench, "HEADLINE_MAX_CHARS", 300)
    line = bench._headline(_bloated_record(), "BENCH_FULL_r05.json")
    assert len(line) <= 400  # four bounded fields + the tiny pointer extra
    obj = json.loads(line)
    assert obj["extra"]["full_record"] == "BENCH_FULL_r05.json"
    assert obj["value"] == 12345.6


def test_partial_capture_never_clobbers_full_tpu_cache(bench, tmp_path, monkeypatch):
    """A watchdog partial (headline-only) on-chip record must not replace a
    complete cached capture: future outage rounds would then surface the
    grid-less partial as 'most recent verified' forever.  Fresh FULL
    captures do replace, and partials do refresh other partials."""
    cache = tmp_path / "BENCH_TPU_LAST.json"
    monkeypatch.setattr(bench, "LAST_TPU_PATH", str(cache))

    def rec(value, partial=False):
        extra = {"platform": "tpu", "grid16_rank_s": 0.1}
        if partial:
            extra = {"platform": "tpu", "partial": "child deadline hit …"}
        return {"metric": "m", "value": value, "unit": "u",
                "vs_baseline": 1.0, "extra": extra}

    bench._save_last_tpu(rec(1.0), "t1")                     # full: saved
    assert json.loads(cache.read_text())["record"]["value"] == 1.0
    bench._save_last_tpu(rec(2.0, partial=True), "t2")       # partial: refused
    assert json.loads(cache.read_text())["record"]["value"] == 1.0
    bench._save_last_tpu(rec(3.0), "t3")                     # newer full: saved
    assert json.loads(cache.read_text())["record"]["value"] == 3.0
    cache.write_text(json.dumps(
        {"captured_utc": "t3", "provenance": "live",
         "record": rec(4.0, partial=True)}
    ))
    bench._save_last_tpu(rec(5.0, partial=True), "t4")       # partial-over-partial: refreshed
    assert json.loads(cache.read_text())["record"]["value"] == 5.0


@pytest.mark.slow
def test_child_deadline_dumps_partial_record():
    """r5: a child whose tunnel hangs mid-run must still print one
    parseable on-platform line before its budget expires (the r4 failure
    lost a fully-measured headline to SIGKILL).  The stall hook simulates
    the hang right after the headline; the deadline watchdog must dump an
    explicitly-partial record and exit 0 well before the 600s stall ends."""
    env = dict(os.environ)
    env.update({
        "CSMOM_BENCH_CHILD": "1",
        "CSMOM_BENCH_FORCE_CPU": "1",
        # watchdog fires ~105s in — the headline leg is ~15-25s warm but
        # has been seen >45s on a contended box; the margin must absorb that
        "CSMOM_BENCH_CHILD_BUDGET": "150",
        "CSMOM_BENCH_STALL_S": "600",       # hang far past the budget
    })
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True, text=True, timeout=170, env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1  # exactly one JSON line, even from the watchdog
    obj = json.loads(lines[0])
    assert obj["extra"]["partial"].startswith("child deadline hit")
    # the headline measured before the hang is intact and on-platform
    assert obj["value"] > 0
    assert obj["extra"]["platform"] == "cpu"
    assert obj["extra"]["golden_ok"] is True
    # legs the hang prevented are simply absent, not fabricated
    assert "grid16_rank_s" not in obj["extra"]


def test_total_failure_never_clobbers_a_measured_round_record(bench, tmp_path, monkeypatch):
    """An all-attempts-failed run (dead tunnel, tiny budget) must not erase
    the round's measured full record: the failure lands under a _failed
    sibling and the headline points there.  With no measured record to
    protect, the failure claims the main name (the round still gets a
    record)."""
    monkeypatch.setenv("CSMOM_BENCH_FULL_DIR", str(tmp_path))
    good = {"metric": "m", "value": 123.4, "unit": "u", "vs_baseline": 1.0,
            "extra": {"platform": "cpu"}}
    failed = {"metric": "m", "value": 0.0, "unit": "u", "vs_baseline": 0.0,
              "extra": {"error": "all benchmark attempts failed"}}

    # no existing record: the failure claims the main name
    ref = bench._write_full_record(dict(failed))
    assert ref == bench.FULL_RECORD_NAME

    # measured record present: the failure is diverted to the sibling
    (tmp_path / bench.FULL_RECORD_NAME).write_text(json.dumps(good))
    ref = bench._write_full_record(dict(failed))
    assert ref == bench.FULL_RECORD_NAME.replace(".json", "_failed.json")
    kept = json.loads((tmp_path / bench.FULL_RECORD_NAME).read_text())
    assert kept["value"] == 123.4
    diverted = json.loads((tmp_path / ref).read_text())
    assert diverted["value"] == 0.0

    # a measured result always claims the main name
    ref = bench._write_full_record(dict(good, value=555.5))
    assert ref == bench.FULL_RECORD_NAME
    assert json.loads(
        (tmp_path / bench.FULL_RECORD_NAME).read_text())["value"] == 555.5


def test_exhausted_budget_still_prints_valid_headline(tmp_path):
    """VERDICT r4 #8: a run whose probes/children all hit the budget
    ceiling must still emit one parseable, capped headline line AND write
    the full record file.  Budget=1s forces every stage into its
    'no budget left' branch, so this exercises the reporting path end to
    end in a few seconds (no jax child is ever launched)."""
    env = dict(os.environ)
    env.update({
        "CSMOM_BENCH_BUDGET": "1",
        "CSMOM_ROUND": "rtest",
        "CSMOM_BENCH_FULL_DIR": str(tmp_path),
    })
    p = subprocess.run(
        [sys.executable, os.path.join(_REPO, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert p.returncode == 0, p.stderr[-2000:]
    lines = [ln for ln in p.stdout.strip().splitlines() if ln.startswith("{")]
    assert len(lines) == 1  # exactly one JSON line on stdout
    assert len(lines[0]) <= 1800
    obj = json.loads(lines[0])
    for key in ("metric", "value", "unit", "vs_baseline"):
        assert key in obj
    assert obj["extra"]["full_record"] == "BENCH_FULL_rtest.json"
    full = json.loads((tmp_path / "BENCH_FULL_rtest.json").read_text())
    # the full record keeps what the headline digests away
    assert full["metric"] == obj["metric"]
    assert "tpu_probes" in full["extra"]


def test_headline_provenance_round_trips_complete(bench):
    """ISSUE 4 satellite: BENCH_r05.json landed with tpu_last_verified.
    provenance lossily cut mid-parenthesis ('…').  The headline now
    carries the complete provenance CLASS (the leading token), never a
    truncation; the full composed string stays only in the FULL record.
    Round-trip: headline -> parse -> provenance must be a complete
    prefix of the record's, with no loss marker."""
    rec = _bloated_record()
    long_prov = ("session-cached (originally: live (r3; "
                 "block_until_ready-timed — treat walls as "
                 "dispatch-inclusive upper bounds))")
    rec["extra"]["tpu_last_verified"]["provenance"] = long_prov
    line = bench._headline(rec, "BENCH_FULL_r05.json")
    assert len(line) <= bench.HEADLINE_MAX_CHARS
    got = json.loads(line)["extra"]["tpu_last_verified"]["provenance"]
    assert got == "session-cached"
    assert "…" not in got
    # complete-prefix property: nothing was cut mid-word — the headline
    # value plus the full-record pointer reconstructs the whole string
    assert long_prov.startswith(got)
    # the FULL record (what the headline points at) keeps it verbatim
    assert rec["extra"]["tpu_last_verified"]["provenance"] == long_prov
    # a short provenance ('live') survives whole too
    rec["extra"]["tpu_last_verified"]["provenance"] = "live"
    got = json.loads(bench._headline(rec, "BENCH_FULL_r05.json"))
    assert got["extra"]["tpu_last_verified"]["provenance"] == "live"
