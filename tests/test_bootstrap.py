"""Block bootstrap vs a numpy loop oracle + statistical sanity checks."""

import numpy as np
import jax
import jax.numpy as jnp

from csmom_tpu.analytics import block_bootstrap, block_bootstrap_grid, circular_block_indices


def np_masked_mean(x, v):
    return x[v].mean() if v.any() else np.nan


def np_sharpe(x, v, freq=12):
    xv = x[v]
    if len(xv) == 0:
        return np.nan
    sd = xv.std(ddof=1) if len(xv) > 1 else 0.0
    if not np.isfinite(sd) or sd == 0:
        return np.nan
    return xv.mean() * freq / (sd * np.sqrt(freq))


def test_indices_shape_and_blocks():
    key = jax.random.PRNGKey(0)
    idx = np.asarray(circular_block_indices(key, 50, 37, 6))
    assert idx.shape == (50, 37)
    assert idx.min() >= 0 and idx.max() < 37
    # consecutive entries inside a block step by exactly 1 mod T
    steps = (idx[:, 1:] - idx[:, :-1]) % 37
    # at least the within-block positions must be +1 steps
    within = np.ones(36, dtype=bool)
    within[5::6] = False  # block boundaries every 6 entries
    assert (steps[:, within] == 1).all()


def test_bootstrap_matches_numpy_oracle(rng):
    T = 60
    x = rng.normal(0.01, 0.05, size=T)
    v = rng.random(T) > 0.1
    x = np.where(v, x, np.nan)
    key = jax.random.PRNGKey(7)
    res = block_bootstrap(jnp.asarray(x), jnp.asarray(v), key, n_samples=64, block_len=5)
    idx = np.asarray(circular_block_indices(key, 64, T, 5))
    want_means = np.array([np_masked_mean(x[i], v[i]) for i in idx])
    want_sharpes = np.array([np_sharpe(x[i], v[i]) for i in idx])
    np.testing.assert_allclose(np.asarray(res.mean_samples), want_means, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(res.sharpe_samples), want_sharpes, rtol=1e-8)
    np.testing.assert_allclose(float(res.mean_point), np_masked_mean(x, v), rtol=1e-12)
    lo, hi = np.asarray(res.mean_ci)
    assert lo <= np.nanmedian(want_means) <= hi


def test_ci_covers_truth_mostly(rng):
    """Coverage sanity: the 95% CI of the mean should contain the true mean
    for a clean iid series."""
    T = 240
    mu = 0.01
    x = rng.normal(mu, 0.04, size=T)
    v = np.ones(T, dtype=bool)
    res = block_bootstrap(jnp.asarray(x), jnp.asarray(v), jax.random.PRNGKey(1),
                          n_samples=500, block_len=3)
    lo, hi = np.asarray(res.mean_ci)
    assert lo < mu < hi
    assert hi - lo < 0.03  # sane width at T=240, sigma=0.04


def test_grid_bootstrap_broadcasts(rng):
    G1, G2, T = 2, 3, 48
    x = rng.normal(0.0, 0.05, size=(G1, G2, T))
    v = rng.random((G1, G2, T)) > 0.15
    key = jax.random.PRNGKey(3)
    res = block_bootstrap_grid(jnp.asarray(x), jnp.asarray(v), key,
                               n_samples=32, block_len=4)
    assert res.mean_samples.shape == (32, G1, G2)
    assert res.mean_ci.shape == (2, G1, G2)
    # per-cell equality with the 1-D bootstrap under the same key
    one = block_bootstrap(jnp.asarray(x[1, 2]), jnp.asarray(v[1, 2]), key,
                          n_samples=32, block_len=4)
    np.testing.assert_allclose(
        np.asarray(res.mean_samples)[:, 1, 2], np.asarray(one.mean_samples), rtol=1e-12
    )


def test_block_len_one_is_iid(rng):
    x = rng.normal(size=24)
    v = np.ones(24, dtype=bool)
    res = block_bootstrap(jnp.asarray(x), jnp.asarray(v), jax.random.PRNGKey(5),
                          n_samples=16, block_len=1)
    assert np.isfinite(np.asarray(res.mean_samples)).all()
