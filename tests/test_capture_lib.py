"""The watcher's artifact-landing rules (benchmarks/capture_lib.sh).

These shell functions decide what the round's committed TPU evidence is;
their partial-vs-full rules mirror bench.py's BENCH_TPU_LAST cache
policy (pinned in test_bench_record.py), so they get the same pinning:
a partial never blocks its own upgrade, a full capture is never
displaced, and a partial sweep never claims the done-marker the watcher
loop re-checks.
"""

import json
import os
import subprocess

_LIB = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks", "capture_lib.sh",
)

FULL = json.dumps({"metric": "grid16_scaling", "rows": [1, 2, 3]})
PARTIAL = json.dumps({"metric": "grid16_scaling", "rows": [1],
                      "partial": "deadline hit"})


def _sh(cwd, body):
    return subprocess.run(
        ["bash", "-c", f'log() {{ :; }}; . "{_LIB}"; {body}'],
        cwd=cwd, capture_output=True, text=True, timeout=30,
    )


def _write(path, *lines):
    path.write_text("".join(f"{ln}\n" for ln in lines))


def test_land_artifact_extracts_last_json_line(tmp_path):
    raw = tmp_path / "raw.log"
    _write(raw, "noise", '{"point": 1}', FULL)
    r = _sh(tmp_path, f'land_artifact "{raw}" "{tmp_path}/art.json"')
    assert r.returncode == 0, r.stderr
    art = json.loads((tmp_path / "art.json").read_text())
    assert art["rows"] == [1, 2, 3]


def test_land_artifact_never_overwrites_full_with_anything(tmp_path):
    art = tmp_path / "art.json"
    art.write_text(FULL)
    raw = tmp_path / "raw.log"
    for newer in (PARTIAL, json.dumps({"metric": "x", "rows": []})):
        _write(raw, newer)
        _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
        assert json.loads(art.read_text())["rows"] == [1, 2, 3]


def test_land_artifact_upgrades_partial_with_full(tmp_path):
    art = tmp_path / "art.json"
    art.write_text(json.dumps(json.loads(PARTIAL), indent=1))
    raw = tmp_path / "raw.log"
    _write(raw, FULL)
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    got = json.loads(art.read_text())
    assert "partial" not in got and got["rows"] == [1, 2, 3]


def test_land_artifact_partial_does_not_refresh_equal_partial(tmp_path):
    """A newer partial with NO MORE measured rows never churns the
    committed artifact: the watcher retries via the absent done-marker.
    (A strictly richer partial is the exception — next test.)"""
    art = tmp_path / "art.json"
    art.write_text(json.dumps(json.loads(PARTIAL), indent=1))
    raw = tmp_path / "raw.log"
    newer_partial = json.dumps({"metric": "grid16_scaling",
                                "rows": [9], "partial": "deadline hit"})
    _write(raw, newer_partial)
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    assert json.loads(art.read_text())["rows"] == [1]


def test_land_artifact_richer_partial_upgrades_thinner_partial(tmp_path):
    """ADVICE r5 #3: a deadline-hit capture that measured strictly MORE
    rows than the committed partial is an upgrade, not churn — a later
    window that got further must not be discarded for having also hit
    its deadline."""
    art = tmp_path / "art.json"
    art.write_text(json.dumps(json.loads(PARTIAL), indent=1))  # 1 row
    raw = tmp_path / "raw.log"
    richer = json.dumps({"metric": "grid16_scaling", "rows": [9, 10],
                         "partial": "deadline hit"})
    _write(raw, richer)
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    assert json.loads(art.read_text())["rows"] == [9, 10]
    # and the reverse direction (thinner over richer) still refuses
    _write(raw, PARTIAL)
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    assert json.loads(art.read_text())["rows"] == [9, 10]
    # "phases" records (grid_phases.py) count the same way
    art2 = tmp_path / "art2.json"
    art2.write_text(json.dumps({"metric": "grid_phases", "phases": [1],
                                "partial": "deadline hit"}))
    _write(raw, json.dumps({"metric": "grid_phases", "phases": [1, 2, 3],
                            "partial": "deadline hit"}))
    _sh(tmp_path, f'land_artifact "{raw}" "{art2}"')
    assert json.loads(art2.read_text())["phases"] == [1, 2, 3]


def test_land_artifact_counts_rows_nested_under_extra(tmp_path):
    """bench-child and minibench partials carry their measurement list
    under extra.rows; the shell row counter must size them exactly like
    chaos.invariants.measured_rows or a richer partial is refused its
    upgrade."""
    art = tmp_path / "art.json"
    art.write_text(json.dumps({
        "metric": "m", "value": 1.0,
        "extra": {"rows": [{"r": 0}], "partial": "deadline hit"},
    }))
    raw = tmp_path / "raw.log"
    richer = json.dumps({
        "metric": "m", "value": 2.0,
        "extra": {"rows": [{"r": 0}, {"r": 1}], "partial": "deadline hit"},
    })
    _write(raw, richer)
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    assert len(json.loads(art.read_text())["extra"]["rows"]) == 2
    # thinner-over-richer still refuses through the extra-nested path
    _write(raw, json.dumps({
        "metric": "m", "value": 1.0,
        "extra": {"rows": [{"r": 9}], "partial": "deadline hit"},
    }))
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    assert len(json.loads(art.read_text())["extra"]["rows"]) == 2


def test_land_artifact_refuses_truncated_post_write(tmp_path):
    """The chaos land-short-write contract: a tmp file truncated between
    the formatter and the rename (ENOSPC) must never land, and an
    existing artifact stays untouched."""
    art = tmp_path / "art.json"
    art.write_text(json.dumps(json.loads(PARTIAL), indent=1))
    raw = tmp_path / "raw.log"
    _write(raw, FULL)
    r = _sh(tmp_path,
            f'CSMOM_FAULT_LAND_TRUNCATE_BYTES=15 land_artifact "{raw}" "{art}"')
    assert r.returncode == 0
    assert json.loads(art.read_text())["rows"] == [1]  # prior intact
    assert not (tmp_path / "art.json.tmp").exists()
    # fault cleared: the upgrade lands
    _sh(tmp_path, f'land_artifact "{raw}" "{art}"')
    assert json.loads(art.read_text())["rows"] == [1, 2, 3]


def test_promote_capture_full_claims_done_marker(tmp_path):
    raw = tmp_path / "scaling_raw.log"
    _write(tmp_path / "scaling_raw.log.tmp", '{"point": 1}', FULL)
    r = _sh(tmp_path,
            f'promote_capture sc "{raw}" "{tmp_path}/art.json"')
    assert r.returncode == 0, r.stderr
    assert raw.exists() and not (tmp_path / "scaling_raw.log.tmp").exists()
    assert json.loads((tmp_path / "art.json").read_text())["rows"] == [1, 2, 3]


def test_promote_capture_partial_keeps_done_marker_absent(tmp_path):
    raw = tmp_path / "scaling_raw.log"
    _write(tmp_path / "scaling_raw.log.tmp", PARTIAL)
    _sh(tmp_path, f'promote_capture sc "{raw}" "{tmp_path}/art.json"')
    # done-marker absent -> the watcher loop will re-run this capture
    assert not raw.exists()
    assert (tmp_path / "scaling_raw.log.partial").exists()
    # but the partial still lands provisionally for end-of-round evidence
    assert json.loads((tmp_path / "art.json").read_text())["rows"] == [1]
    # and a later full window upgrades the artifact and claims the marker
    _write(tmp_path / "scaling_raw.log.tmp", FULL)
    _sh(tmp_path, f'promote_capture sc "{raw}" "{tmp_path}/art.json"')
    assert raw.exists()
    got = json.loads((tmp_path / "art.json").read_text())
    assert "partial" not in got and got["rows"] == [1, 2, 3]
