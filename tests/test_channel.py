"""ISSUE 15: the persistent multiplexed transport's framing edges.

What the connection-per-request protocol never had to survive, pinned:

- **interleaved out-of-order replies** — two requests multiplex on ONE
  channel; the slow one's reply arrives second and each settles its own
  waiter by ``_mux`` id (never swapped, never lost);
- **a trickling peer mid-frame with other requests in flight** — the
  reader's frame deadline kills the channel and EVERY in-flight request
  reason-closes (bounded wall, no wedged dispatcher threads);
- **oversized-frame rejection before allocation** — a hostile length
  prefix is refused at read time, the channel dies with the pointed
  reason, and no gigabyte buffer is ever allocated;
- **pool mechanics** — reuse (one dial, many requests), the transparent
  stale-channel redial after a peer restart, idle reaping, reconnect
  backoff fast-fail, and the chaos ``partition`` severing in-flight
  requests (not just refusing new dials);
- **socket tuning** — TCP_NODELAY + SO_KEEPALIVE on both the connect
  and the accept side of every tcp stream (the satellite: Nagle was
  sitting on small framed replies).
"""

import math
import socket
import struct
import threading
import time

import numpy as np
import pytest

from csmom_tpu.serve import proto


def _panel(n=4, months=12):
    v = np.linspace(1.0, 2.0, n * months, dtype=np.float32)
    return v.reshape(n, months)


class _LoopServer:
    """A serve_connection-speaking peer with a controllable handler."""

    def __init__(self, handler=None):
        self.handler = handler or self._default
        self._srv = proto.listen("tcp:127.0.0.1:0")
        self.port = self._srv.getsockname()[1]
        self.address = f"tcp:127.0.0.1:{self.port}"
        self._stop = threading.Event()
        self.accepted = 0
        self._srv.settimeout(0.1)
        threading.Thread(target=self._loop, daemon=True).start()

    def _default(self, obj, arrays):
        time.sleep(obj.get("delay", 0.0))
        return {"state": "served", "tag": obj.get("tag")}, None

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            self.accepted += 1
            threading.Thread(target=proto.serve_connection,
                             args=(conn, self.handler),
                             daemon=True).start()

    def close(self):
        self._stop.set()
        self._srv.close()


# ------------------------------------------------------ mux correctness ---

def test_out_of_order_replies_settle_their_own_waiters():
    """Two in-flight requests on ONE channel; the fast one's reply
    overtakes the slow one's — each lands on its own dispatcher."""
    srv = _LoopServer()
    pool = proto.ChannelPool()
    try:
        out = {}

        def go(tag, delay):
            obj, _ = pool.request(
                srv.address, {"op": "score", "tag": tag, "delay": delay},
                timeout_s=5.0, fire_chaos=False)
            out[tag] = (obj["tag"], time.monotonic())

        ts = [threading.Thread(target=go, args=("slow", 0.4)),
              threading.Thread(target=go, args=("fast", 0.0))]
        ts[0].start()
        time.sleep(0.05)
        ts[1].start()
        for t in ts:
            t.join(5.0)
        assert out["slow"][0] == "slow" and out["fast"][0] == "fast"
        assert out["fast"][1] < out["slow"][1], (
            "the fast reply must not queue behind the slow request")
        stats = pool.stats()
        assert stats["dials"] == 1 and stats["reuses"] == 1, (
            "both requests must share one persistent channel")
    finally:
        pool.close()
        srv.close()


def test_arrays_round_trip_on_the_channel():
    v = _panel()
    srv = _LoopServer(lambda obj, arrays: (
        {"state": "served"}, {"result": arrays["values"] * 2.0}))
    pool = proto.ChannelPool()
    try:
        obj, arrays = pool.request(srv.address, {"op": "score"},
                                   {"values": v}, timeout_s=5.0,
                                   fire_chaos=False)
        assert obj["state"] == "served"
        np.testing.assert_array_equal(arrays["result"], v * 2.0)
        # the receive scratch buffer is reused: a second round trip
        # must not alias the first reply's memory
        first = arrays["result"]
        obj2, arrays2 = pool.request(srv.address, {"op": "score"},
                                     {"values": v + 1.0}, timeout_s=5.0,
                                     fire_chaos=False)
        np.testing.assert_array_equal(arrays2["result"], (v + 1.0) * 2.0)
        np.testing.assert_array_equal(first, v * 2.0)
    finally:
        pool.close()
        srv.close()


# -------------------------------------------------------- framing edges ---

def _raw_listener():
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    return srv, f"tcp:127.0.0.1:{srv.getsockname()[1]}"


def test_trickling_peer_mid_frame_fails_every_in_flight_request():
    """The peer starts a reply frame then trickles and stalls: the
    reader's frame deadline kills the channel within its budget and
    BOTH in-flight requests reason-close — not just the one whose
    reply was being trickled."""
    srv, address = _raw_listener()
    conns = []

    def trickle():
        conn, _ = srv.accept()
        conns.append(conn)
        # swallow both request frames, then start ONE reply frame that
        # promises 1000 bytes and delivers a dribble
        conn.settimeout(5.0)
        body = bytearray()
        while body.count(b'"op"') < 2:
            body += conn.recv(65536)
        conn.sendall(struct.pack("!I", 1000))
        for _ in range(3):
            conn.sendall(b"x")
            time.sleep(0.05)
        # then silence: the deadline must fire, not a forever-wait

    threading.Thread(target=trickle, daemon=True).start()
    # drive the CHANNEL directly: the pin here is the channel-level
    # contract (every in-flight request reason-closes when the frame
    # deadline kills the stream); the pool's retry-once-on-a-fresh-dial
    # rides ABOVE this and is pinned by the stale-channel test
    ch = proto.Channel(address, proto.connect(address, 2.0),
                       frame_deadline_s=0.6)
    try:
        errs = {}

        def go(tag):
            try:
                ch.request({"op": "score", "tag": tag}, None,
                           timeout_s=10.0)
                errs[tag] = None
            except (ConnectionError, proto.ProtocolError) as e:
                errs[tag] = str(e)

        t0 = time.monotonic()
        ts = [threading.Thread(target=go, args=(tag,))
              for tag in ("a", "b")]
        for t in ts:
            t.start()
        for t in ts:
            t.join(8.0)
        wall = time.monotonic() - t0
        assert errs.get("a") and errs.get("b"), (
            "both in-flight requests must fail when the channel dies, "
            f"got {errs}")
        assert all("deadline expired mid-frame" in e
                   for e in errs.values()), errs
        assert not ch.alive and "deadline" in (ch.close_reason or "")
        assert wall < 5.0, "the frame deadline did not bound the stall"
    finally:
        ch.close()
        srv.close()
        for c in conns:
            c.close()


def test_oversized_frame_refused_before_allocation():
    """A hostile length prefix (4 GB) is refused AT READ TIME with the
    pointed message — the channel dies, the buffer is never built."""
    srv, address = _raw_listener()

    def hostile():
        conn, _ = srv.accept()
        conn.settimeout(5.0)
        body = bytearray()
        while b'"op"' not in body:
            body += conn.recv(65536)
        conn.sendall(struct.pack("!I", 0xFFFFFFF0))

    threading.Thread(target=hostile, daemon=True).start()
    pool = proto.ChannelPool()
    try:
        with pytest.raises((ConnectionError, proto.ProtocolError)) as ei:
            pool.request(address, {"op": "score"}, timeout_s=5.0,
                         fire_chaos=False)
        assert "exceeds MAX_FRAME_BYTES" in str(ei.value)
        assert "Refusing" in str(ei.value)
    finally:
        pool.close()
        srv.close()


def test_reply_timeout_leaves_the_channel_healthy():
    """A waiter giving up is an ATTEMPT failure, not a channel death:
    the late reply is dropped by the demux (counted), and the next
    request reuses the same channel."""
    srv = _LoopServer()
    pool = proto.ChannelPool()
    try:
        with pytest.raises(proto.ReplyTimeout):
            pool.request(srv.address,
                         {"op": "score", "tag": "late", "delay": 0.6},
                         timeout_s=0.1, fire_chaos=False)
        obj, _ = pool.request(srv.address,
                              {"op": "score", "tag": "ok", "delay": 0.0},
                              timeout_s=5.0, fire_chaos=False)
        assert obj["tag"] == "ok"
        stats = pool.stats()
        assert stats["dials"] == 1, "a reply timeout must not redial"
        # the late reply lands in the channel buffer; the NEXT leader
        # (an idle channel parks no reader) drains it as an orphan
        # before reaching its own reply
        time.sleep(0.8)
        obj, _ = pool.request(srv.address,
                              {"op": "score", "tag": "after",
                               "delay": 0.0},
                              timeout_s=5.0, fire_chaos=False)
        assert obj["tag"] == "after"
        assert pool.stats()["orphan_replies"] == 1
        assert pool.stats()["dials"] == 1
    finally:
        pool.close()
        srv.close()


def test_legacy_untagged_reply_settles_the_oldest_pending():
    """A reply with no ``_mux`` echo (a legacy in-order peer) settles
    the oldest pending dispatch."""
    srv, address = _raw_listener()

    def legacy():
        conn, _ = srv.accept()
        conn.settimeout(5.0)
        # read exactly one frame (prefix + payload), reply untagged
        raw = b""
        while len(raw) < 4:
            raw += conn.recv(4 - len(raw))
        (total,) = struct.unpack("!I", raw)
        got = b""
        while len(got) < total:
            got += conn.recv(total - len(got))
        proto.send_msg(conn, {"state": "served", "legacy": True})
        conn.close()

    threading.Thread(target=legacy, daemon=True).start()
    pool = proto.ChannelPool()
    try:
        obj, _ = pool.request(address, {"op": "score"}, timeout_s=5.0,
                              fire_chaos=False)
        assert obj.get("legacy") is True
    finally:
        pool.close()
        srv.close()


# ------------------------------------------------------- pool mechanics ---

def test_stale_pooled_channel_redials_instead_of_failing():
    """The peer restarts between requests: the pooled channel's next
    use fails at the socket — the pool retries ONCE on a fresh dial
    and the request succeeds (a redial, not a failover)."""
    served = []

    class _OneShotServer(_LoopServer):
        # closes every connection after a single reply, like a peer
        # that restarted between our requests
        def _loop(self):
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    return
                self.accepted += 1
                threading.Thread(target=self._one, args=(conn,),
                                 daemon=True).start()

        def _one(self, conn):
            try:
                obj, arrays = proto.recv_msg(conn)
                mux = obj.pop("_mux", None)
                served.append(obj["tag"])
                reply = {"state": "served", "tag": obj["tag"]}
                if mux is not None:
                    reply["_mux"] = mux
                proto.send_msg(conn, reply)
            finally:
                conn.close()

    srv = _OneShotServer()
    pool = proto.ChannelPool()
    try:
        for i in range(3):
            obj, _ = pool.request(srv.address,
                                  {"op": "score", "tag": f"t{i}"},
                                  timeout_s=5.0, fire_chaos=False)
            assert obj["tag"] == f"t{i}"
        stats = pool.stats()
        assert stats["stale_retries"] >= 1 or stats["dials"] >= 2, stats
    finally:
        pool.close()
        srv.close()


def test_dial_backoff_fails_fast_then_recovers():
    """A refusing peer costs one connect timeout, then fails FAST until
    the backoff expires; a successful dial clears the backoff."""
    srv, address = _raw_listener()
    srv.close()  # nothing listens: dials fail
    pool = proto.ChannelPool(connect_timeout_s=0.5, backoff_base_s=0.2,
                             backoff_cap_s=0.2)
    with pytest.raises(OSError):
        pool.request(address, {"op": "score"}, timeout_s=1.0,
                     fire_chaos=False)
    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError) as ei:
        pool.request(address, {"op": "score"}, timeout_s=1.0,
                     fire_chaos=False)
    assert time.monotonic() - t0 < 0.15, "backoff must fail fast"
    assert "reconnect backoff" in str(ei.value)
    assert pool.stats()["dial_failures"] >= 1
    time.sleep(0.25)  # backoff expires; a live peer now accepts
    srv2 = _LoopServer()
    try:
        # same port is gone — this just proves a healthy peer clears
        # its own backoff entry on the first good dial
        obj, _ = pool.request(srv2.address, {"op": "score", "tag": "x"},
                              timeout_s=5.0, fire_chaos=False)
        assert obj["tag"] == "x"
    finally:
        pool.close()
        srv2.close()


def test_idle_channels_are_reaped_lazily():
    srv = _LoopServer()
    pool = proto.ChannelPool(idle_reap_s=0.1)
    try:
        pool.request(srv.address, {"op": "score", "tag": "a"},
                     timeout_s=5.0, fire_chaos=False)
        time.sleep(0.25)
        pool.request(srv.address, {"op": "score", "tag": "b"},
                     timeout_s=5.0, fire_chaos=False)
        stats = pool.stats()
        assert stats["reaped_idle"] == 1 and stats["dials"] == 2, stats
        assert stats["live_channels"] == 1
    finally:
        pool.close()
        srv.close()


def test_chaos_partition_severs_in_flight_requests(monkeypatch):
    """The ISSUE 15 chaos contract: a ``partition`` firing at
    serve.transport mid-stream reason-closes every in-flight request
    on the severed channel — not just future dials — and dials to the
    peer fail instantly until the partition heals."""
    from csmom_tpu.chaos import inject

    srv = _LoopServer()
    pool = proto.ChannelPool()
    plan = (
        'name = "partition-mid-stream"\n'
        "seed = 0\n\n"
        "[[fault]]\n"
        'point = "serve.transport"\n'
        'action = "partition"\n'
        "after = 1\n"
        "max_fires = 1\n"
    )
    monkeypatch.setenv("CSMOM_FAULT_PLAN", plan)
    monkeypatch.setenv(proto.PARTITION_ENV, "0.5")
    inject.reset()
    try:
        errs = {}

        def slow():
            try:
                pool.request(srv.address,
                             {"op": "score", "tag": "s", "delay": 2.0},
                             timeout_s=10.0)  # visit 1: no fault fires
                errs["slow"] = None
            except ConnectionError as e:
                errs["slow"] = str(e)

        t = threading.Thread(target=slow)
        t.start()
        time.sleep(0.2)  # the slow request is in flight on the channel
        with pytest.raises(ConnectionRefusedError):
            pool.request(srv.address, {"op": "score", "tag": "x"},
                         timeout_s=5.0)  # visit 2: partition fires
        t.join(5.0)
        assert errs["slow"] and "partition" in errs["slow"], (
            "the in-flight request must be severed with the partition "
            f"as its reason, got {errs}")
        # dials keep failing instantly while partitioned...
        with pytest.raises(ConnectionRefusedError):
            pool.request(srv.address, {"op": "score"}, timeout_s=5.0)
        # ...and heal after the window
        time.sleep(0.6)
        obj, _ = pool.request(srv.address,
                              {"op": "score", "tag": "healed"},
                              timeout_s=5.0)
        assert obj["tag"] == "healed"
    finally:
        inject.reset()
        pool.close()
        srv.close()


# --------------------------------------------------------- socket tuning ---

def test_tcp_sockets_are_tuned_on_both_sides():
    """The satellite: TCP_NODELAY (Nagle was delaying small framed
    replies) and SO_KEEPALIVE on every tcp stream, connect AND accept
    side."""
    captured = {}

    def handler(obj, arrays):
        return {"ok": True}, None

    srv = proto.listen("tcp:127.0.0.1:0")
    addr = f"tcp:127.0.0.1:{srv.getsockname()[1]}"
    srv.settimeout(2.0)

    def accept_once():
        conn, _ = srv.accept()
        proto.tune_stream_socket(conn)
        captured["nodelay"] = conn.getsockopt(socket.IPPROTO_TCP,
                                              socket.TCP_NODELAY)
        captured["keepalive"] = conn.getsockopt(socket.SOL_SOCKET,
                                                socket.SO_KEEPALIVE)
        threading.Thread(target=proto.serve_connection,
                         args=(conn, handler), daemon=True).start()

    threading.Thread(target=accept_once, daemon=True).start()
    client = proto.connect(addr, timeout_s=2.0)
    try:
        assert client.getsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY) == 1
        assert client.getsockopt(socket.SOL_SOCKET,
                                 socket.SO_KEEPALIVE) == 1
        deadline = time.monotonic() + 2.0
        while "nodelay" not in captured and time.monotonic() < deadline:
            time.sleep(0.01)
        assert captured.get("nodelay") == 1
        assert captured.get("keepalive") == 1
    finally:
        client.close()
        srv.close()

    # unix sockets have neither knob and must be left alone
    u = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        proto.tune_stream_socket(u)  # must not raise
    finally:
        u.close()
