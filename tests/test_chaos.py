"""Chaos subsystem: fault plans, checkpoints, artifact invariants.

The rehearsal driver itself is covered by test_rehearse.py; these are the
unit-level contracts the driver builds on.
"""

import dataclasses
import json
import os
import subprocess
import sys

import pytest

from csmom_tpu.chaos import inject
from csmom_tpu.chaos import invariants as inv
from csmom_tpu.chaos.plan import Fault, FaultPlan

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- plans ----

def test_plan_toml_roundtrip():
    plan = FaultPlan(name="t", seed=9, faults=(
        Fault(point="mini.row", action="sleep", seconds=0.25, after=2),
        Fault(point="bench.*", action="fail", role="supervisor",
              max_fires=0),
        Fault(point="bench.land", action="raise_oserror", errno_=28),
        Fault(point="bench.compile", action="kill", role="child",
              global_once=True),
    ))
    assert FaultPlan.from_toml(plan.to_toml()) == plan


def test_plan_rejects_unknown_action_and_keys():
    with pytest.raises(ValueError, match="unknown fault action"):
        FaultPlan.from_toml(
            'name = "x"\n[[fault]]\npoint = "p"\naction = "explode"\n'
        )
    with pytest.raises(ValueError, match="unknown keys"):
        FaultPlan.from_toml(
            'name = "x"\n[[fault]]\npoint = "p"\naction = "kill"\n'
            'tpyo = 1\n'
        )


def test_plan_env_value_inline_vs_path(tmp_path):
    toml = 'name = "n"\nseed = 1\n\n[[fault]]\npoint = "p"\naction = "fail"\n'
    assert FaultPlan.from_env_value(toml).name == "n"  # inline (newlines)
    p = tmp_path / "plan.toml"
    p.write_text(toml)
    assert FaultPlan.from_env_value(str(p)).name == "n"  # path


def test_fault_hit_windows_and_roles():
    f = Fault(point="a.*", action="fail", after=2, max_fires=2, role="child")
    assert not f.matches("a.x", 1, "child")     # before the window
    assert f.matches("a.x", 2, "child")
    assert f.matches("a.y", 3, "child")         # fnmatch pattern
    assert not f.matches("a.x", 4, "child")     # window exhausted
    assert not f.matches("a.x", 2, "supervisor")  # wrong role
    assert not f.matches("b.x", 2, "child")     # wrong point
    every = dataclasses.replace(f, max_fires=0)
    assert every.matches("a.x", 1000, "child")  # 0 = unbounded


# -------------------------------------------------------- checkpoints ----

def test_checkpoint_noop_without_plan(monkeypatch):
    monkeypatch.delenv("CSMOM_FAULT_PLAN", raising=False)
    inject.reset()
    assert inject.checkpoint("anything") is None


def test_checkpoint_fires_fail_action(monkeypatch, tmp_path):
    plan = FaultPlan(name="t", faults=(
        Fault(point="probe", action="fail", after=1, max_fires=1),
    ))
    p = tmp_path / "p.toml"
    p.write_text(plan.to_toml())
    monkeypatch.setenv("CSMOM_FAULT_PLAN", str(p))
    inject.reset()
    try:
        assert inject.checkpoint("probe") is None        # hit 0: before after
        assert inject.checkpoint("probe") == "fail"      # hit 1: fires
        assert inject.checkpoint("probe") is None        # hit 2: exhausted
    finally:
        inject.reset()


def test_checkpoint_global_once_claims_across_processes(monkeypatch, tmp_path):
    """Two processes sharing a state dir: exactly one firing."""
    plan = FaultPlan(name="g", faults=(
        Fault(point="p", action="fail", global_once=True),
    ))
    planfile = tmp_path / "p.toml"
    planfile.write_text(plan.to_toml())
    state = tmp_path / "state"
    code = (
        "from csmom_tpu.chaos.inject import checkpoint;"
        "print(checkpoint('p'))"
    )
    env = dict(os.environ, CSMOM_FAULT_PLAN=str(planfile),
               CSMOM_FAULT_STATE=str(state),
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    outs = [
        subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=120).stdout.strip()
        for _ in range(2)
    ]
    assert sorted(outs) == ["None", "fail"]


def test_corrupt_file_action_is_seeded_deterministic(monkeypatch, tmp_path):
    payload = bytes(range(256)) * 8
    outs = []
    for trial in range(2):
        target = tmp_path / f"f{trial}.bin"
        target.write_bytes(payload)
        plan = FaultPlan(name="c", seed=5, faults=(
            Fault(point="x", action="corrupt_file", path=str(target)),
        ))
        pf = tmp_path / f"plan{trial}.toml"
        pf.write_text(plan.to_toml())
        monkeypatch.setenv("CSMOM_FAULT_PLAN", str(pf))
        inject.reset()
        try:
            inject.checkpoint("x")
        finally:
            inject.reset()
        data = target.read_bytes()
        assert data != payload  # damage happened
        outs.append([i for i, (a, b) in enumerate(zip(payload, data))
                     if a != b])
    assert outs[0] == outs[1]  # same seed -> same flipped offsets


# --------------------------------------------------------- invariants ----

def _record(**over):
    rec = {"metric": "m", "value": 1.0, "unit": "u", "vs_baseline": 1.0,
           "extra": {"platform": "cpu"}}
    rec.update(over)
    return rec


def test_invariants_accept_valid_record():
    assert inv.validate(_record()) == []


def test_invariants_reject_broken_records():
    assert inv.validate({"metric": "m"})  # missing fields
    assert inv.validate(_record(value="fast"))  # non-numeric value
    bad_partial = _record(extra={"partial": ""})
    assert any("partial" in v for v in inv.validate(bad_partial))


def test_invariants_detect_r4_failure_shape():
    """rc == 0 with parsed null is the r4 lost-record signature."""
    cap = {"rc": 0, "tail": "garbage", "parsed": None, "cmd": "x", "n": 1}
    assert any("r4" in v for v in inv.validate(cap))
    cap_failed = {"rc": 1, "tail": "traceback", "parsed": None}
    assert inv.validate(cap_failed) == []  # a failed round may have no parse


def test_invariants_driver_capture_tail_must_agree():
    tail = json.dumps(_record(value=2.0))
    cap = {"rc": 0, "tail": tail + "\n", "parsed": _record(value=3.0)}
    assert any("disagrees" in v for v in inv.validate(cap))
    cap_ok = {"rc": 0, "tail": tail + "\n", "parsed": _record(value=2.0)}
    assert inv.validate(cap_ok) == []


def test_invariants_headline_text():
    good = "noise\n" + json.dumps(_record()) + "\n"
    assert inv.validate_headline_text(good) == []
    assert inv.validate_headline_text("no json here at all\n")
    too_long = json.dumps(_record(extra={"pad": "x" * 3000}))
    assert any("tail window" in v
               for v in inv.validate_headline_text(too_long))


def test_invariants_upgrade_monotone():
    full = _record()
    p1 = _record(extra={"partial": "p", "rows": [{"r": 0}]})
    p2 = _record(extra={"partial": "p", "rows": [{"r": 0}, {"r": 1}]})
    assert inv.upgrade_ok(None, p1) == []          # empty slot: anything
    assert inv.upgrade_ok(p1, p2) == []            # richer partial: ok
    assert inv.upgrade_ok(p2, p1)                  # downgrade: refused
    assert inv.upgrade_ok(p1, full) == []          # full over partial: ok
    assert inv.upgrade_ok(full, p2)                # partial over full: never
    assert inv.upgrade_ok(full, full)              # full never overwritten


def test_measured_rows_mirrors_capture_lib():
    assert inv.measured_rows({"rows": [1, 2, 3]}) == 3
    assert inv.measured_rows({"phases": [{}]}) == 1
    assert inv.measured_rows({"extra": {"rows": [1]}}) == 1
    assert inv.measured_rows(_record()) == 0


# ---------------------------------------------------- aot self-heal ----

class _FlakyLowered:
    """compile() raises once (a corrupt cache deserialization), then works."""

    def __init__(self):
        self.calls = 0

    def compile(self):
        self.calls += 1
        if self.calls == 1:
            raise RuntimeError("Error deserializing executable (corrupt)")
        return object()


def test_compile_self_heal_evicts_and_retries(tmp_path, monkeypatch):
    import jax

    from csmom_tpu.compile.aot import _compile_with_self_heal

    # a live cache dir with poisoned entries the heal must sweep
    cache = tmp_path / "cache"
    cache.mkdir()
    for i in range(3):
        (cache / f"entry{i}").write_bytes(b"\x00garbage\x00")
    (cache / "warmup_report.json").write_text("{}")  # report survives
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(cache))
    try:
        lowered = _FlakyLowered()
        _, healed = _compile_with_self_heal(lowered, "flaky-entry")
        assert healed is True
        assert lowered.calls == 2  # evict happened BETWEEN the attempts
        left = sorted(p.name for p in cache.iterdir())
        assert left == ["warmup_report.json"]  # entries evicted, report kept
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_compile_self_heal_leaves_cache_alone_for_real_errors(tmp_path):
    """A non-corruption compile failure (OOM, unsupported op) must
    propagate WITHOUT evicting the warmed cache: eviction cannot fix it,
    and destroying every already-warmed shape would cost the next window
    the exact compiles the warm-start pipeline exists to avoid."""
    import jax

    from csmom_tpu.compile.aot import _compile_with_self_heal

    cache = tmp_path / "cache"
    cache.mkdir()
    (cache / "warm_entry").write_bytes(b"precious warmed executable")
    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(cache))

    class _Broken:
        def compile(self):
            raise RuntimeError("RESOURCE_EXHAUSTED: out of memory")

    try:
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            _compile_with_self_heal(_Broken(), "broken-entry")
        assert (cache / "warm_entry").exists()  # nothing evicted
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


def test_compile_self_heal_propagates_double_corruption_failure():
    from csmom_tpu.compile.aot import _compile_with_self_heal

    class _Broken:
        def compile(self):
            raise RuntimeError("error deserializing executable, always")

    with pytest.raises(RuntimeError, match="deserializing"):
        _compile_with_self_heal(_Broken(), "broken-entry")


# ------------------------------------------------- deadline anchoring ----

def test_trip_active_guard_without_guard_is_false():
    from csmom_tpu.utils import deadline

    assert deadline._ACTIVE_FIRE is None
    assert deadline.trip_active_guard() is False


def test_deadline_reanchors_wall_clock_t0(monkeypatch, capsys):
    """A t0 taken from time.time() (epoch seconds) would push the fuse past
    any budget and the guard would never fire; the guard must detect the
    mis-anchor, re-anchor to now, and say so."""
    import time

    from csmom_tpu.utils.deadline import deadline_guard

    monkeypatch.setenv("CSMOM_TEST_DEADLINE_BUDGET", "3600")
    finish = deadline_guard(
        "CSMOM_TEST_DEADLINE_BUDGET", lambda: None, t0=time.time()
    )
    err = capsys.readouterr().err
    assert "re-anchoring" in err
    # disarm without printing a summary to this test's stdout
    from csmom_tpu.utils import deadline as dl

    dl._ACTIVE_FIRE = None
    del finish


def test_deadline_module_never_reads_the_wall_clock():
    """The clock-skew fault holds only if nothing here calls time.time()."""
    import inspect

    from csmom_tpu.utils import deadline

    src = inspect.getsource(deadline)
    assert "time.time()" not in src


# --------------------------------------- committed artifacts (satellite) ----

# BENCH_r04.json is the round-4 casualty this subsystem exists to prevent:
# rc 0 with a truncated tail and parsed: null.  It stays committed as
# evidence, and the checker must keep DETECTING it rather than excusing it.
_KNOWN_BAD = {"BENCH_r04.json": "r4"}


def test_every_committed_artifact_validates():
    results = inv.validate_tree(_REPO)
    assert len(results) >= 10, "artifact glob found too few committed files"
    unexpected = {
        name: v for name, v in results.items()
        if v and name not in _KNOWN_BAD
    }
    assert unexpected == {}, unexpected
    for name, marker in _KNOWN_BAD.items():
        assert name in results
        assert any(marker in v for v in results[name]), (
            f"{name} is the committed {marker} failure evidence; the "
            "checker must keep flagging it"
        )


def test_bench_tpu_last_cache_schema_if_present():
    path = os.path.join(_REPO, "BENCH_TPU_LAST.json")
    if not os.path.exists(path):
        pytest.skip("no TPU cache file on this machine")
    assert inv.validate_file(path) == []
