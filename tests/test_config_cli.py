"""Config loading + CLI subcommand smoke tests (CPU, pandas backend)."""

import os

import numpy as np
import pytest

from csmom_tpu.config import RunConfig, load_config, DEFAULT_TICKERS
from csmom_tpu.cli.main import build_parser, main

from tests.conftest import REFERENCE_DATA, requires_reference


def test_defaults_are_reference_constants():
    cfg = RunConfig()
    assert tuple(cfg.universe.tickers) == DEFAULT_TICKERS
    assert (cfg.momentum.lookback, cfg.momentum.skip) == (12, 1)
    assert cfg.intraday.size_shares == 50
    assert cfg.intraday.threshold == 1e-5
    assert cfg.intraday.cash0 == 1_000_000.0
    assert cfg.costs.impact_k == 0.1
    assert cfg.costs.spread == 0.001
    assert cfg.results_dir == "results"


def test_load_toml_roundtrip(tmp_path):
    p = tmp_path / "run.toml"
    p.write_text(
        """
backend = "pandas"
results_dir = "out"

[universe]
tickers = ["AAPL", "MSFT"]
data_dir = "/data"

[momentum]
lookback = 6
skip = 0

[grid]
Js = [3, 6]
Ks = [1]
"""
    )
    cfg = load_config(str(p))
    assert cfg.backend == "pandas"
    assert cfg.results_dir == "out"
    assert cfg.universe.tickers == ("AAPL", "MSFT")
    assert cfg.momentum.lookback == 6 and cfg.momentum.skip == 0
    assert cfg.grid.Js == (3, 6) and cfg.grid.Ks == (1,)
    # untouched sections keep reference defaults
    assert cfg.intraday.window_minutes == 30


def test_load_toml_unknown_key_raises(tmp_path):
    p = tmp_path / "bad.toml"
    p.write_text("[momentum]\nlookbak = 6\n")
    with pytest.raises(ValueError, match="lookbak"):
        load_config(str(p))
    p2 = tmp_path / "bad2.toml"
    p2.write_text("backnd = 'tpu'\n")
    with pytest.raises(ValueError, match="backnd"):
        load_config(str(p2))


def test_parser_subcommands():
    p = build_parser()
    args = p.parse_args(["replicate", "--backend", "pandas", "--lookback", "6"])
    assert args.command == "replicate" and args.lookback == 6
    args = p.parse_args(["grid", "--js", "3,6", "--ks", "1"])
    assert args.js == "3,6"
    args = p.parse_args(["sweep", "--min-months", "12"])
    assert args.min_months == 12


def test_no_command_prints_help(capsys):
    assert main([]) == 0
    assert "replicate" in capsys.readouterr().out


@requires_reference
def test_cli_replicate_pandas(tmp_path, capsys):
    rc = main([
        "replicate", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--backend", "pandas",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Mean monthly spread" in out
    assert os.path.exists(tmp_path / "monthly_mom_cum.png")


@requires_reference
@pytest.mark.slow
def test_cli_horizons_writes_plot(tmp_path, capsys):
    rc = main([
        "horizons", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--platform", "cpu", "--max-h", "12",
    ])
    assert rc == 0
    assert "event-time profile" in capsys.readouterr().out
    assert os.path.exists(tmp_path / "horizon_profile.png")


@pytest.mark.slow
def test_horizon_plot_both_profile_shapes(tmp_path, rng):
    """save_horizon_plot accepts the plain [H] profile and the [V, H]
    volume-conditioned one (one line per tercile)."""
    from csmom_tpu.analytics.plots import save_horizon_plot
    from csmom_tpu.backtest import horizon_profile, volume_horizon_profile
    import numpy as np

    A, M = 24, 50
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, M)), axis=1))
    mask = np.ones((A, M), bool)
    hp = horizon_profile(prices, mask, lookback=6, max_h=8, n_bins=4)
    p1 = save_horizon_plot(hp, str(tmp_path), fname="h1.png")
    turn = np.abs(rng.normal(2, 1, size=(A, M)))
    vhp = volume_horizon_profile(prices, mask, turn, np.ones((A, M), bool),
                                 lookback=6, max_h=8, n_bins=4)
    p2 = save_horizon_plot(vhp, str(tmp_path), fname="h2.png")
    assert os.path.getsize(p1) > 0 and os.path.getsize(p2) > 0


@requires_reference
def test_cli_fetch_cache_hit_and_miss(tmp_path, capsys):
    """fetch is cache-first: reference caches count as hits without any
    network; a missing ticker in an empty dir is skipped loudly and the
    command reports failure."""
    rc = main(["fetch", "--data-dir", REFERENCE_DATA,
               "--tickers", "AMD,NVDA", "--kind", "daily"])
    assert rc == 0
    assert "daily: 2/2" in capsys.readouterr().out

    rc = main(["fetch", "--data-dir", str(tmp_path), "--tickers", "ZZZZ",
               "--kind", "daily"])
    assert rc == 1
    assert "daily: 0/1" in capsys.readouterr().out


@requires_reference
def test_cli_replicate_flag_overrides(tmp_path, capsys):
    main([
        "replicate", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--backend", "pandas", "--lookback", "6", "--skip", "0",
    ])
    out6 = capsys.readouterr().out
    main([
        "replicate", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--backend", "pandas",
    ])
    out12 = capsys.readouterr().out
    assert out6 != out12


@requires_reference
def test_cli_replicate_tearsheet(tmp_path, capsys):
    rc = main([
        "replicate", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--backend", "pandas", "--tearsheet",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Max drawdown" in out
    assert "Per-year compounded spread" in out
    # every year of the reference's post-warmup span (2019-2024) appears
    for yy in range(2019, 2025):
        assert str(yy) in out


def test_cli_strategies_lists_registry(capsys):
    assert main(["strategies"]) == 0
    out = capsys.readouterr().out
    for name in ("momentum", "reversal", "residual_momentum",
                 "volume_z_momentum", "zscore_combo"):
        assert name in out
    assert "est_window=36" in out


def test_cli_strategies_robust_to_bare_plugins(capsys):
    """A user plugin with no docstring and a required field must not break
    the listing."""
    import dataclasses as dc

    from csmom_tpu.registry import unregister_engine
    from csmom_tpu.strategy import register_strategy
    from csmom_tpu.strategy.base import Strategy

    @register_strategy("_bare_test_plugin")
    @dc.dataclass(frozen=True)
    class Bare(Strategy):
        required_knob: float = dc.field()  # no default

        def signal(self, prices, mask, **panels):  # pragma: no cover
            return prices, mask

    Bare.__doc__ = None
    try:
        assert main(["strategies"]) == 0
        out = capsys.readouterr().out
        assert "_bare_test_plugin(required_knob)" in out
        assert "_MISSING_TYPE" not in out
    finally:
        unregister_engine("_bare_test_plugin", kind="strategy")


@requires_reference
@pytest.mark.slow
def test_cli_replicate_sector_neutral_and_costs(tmp_path, capsys):
    sm = tmp_path / "sectors.csv"
    sm.write_text(
        "ticker,sector\n" + "\n".join(
            f"{t},{'tech' if i % 2 else 'other'}"
            for i, t in enumerate(
                ["MSFT", "AMZN", "GOOGL", "NVDA", "TSLA", "META", "JPM",
                 "BAC", "WMT", "PG", "KO", "DIS", "CSCO", "ORCL", "INTC",
                 "AMD", "NFLX", "C", "GS", "AAPL"])
        ) + "\n"
    )
    rc = main([
        "replicate", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--sector-map", str(sm), "--tc-bps", "5",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "sector-neutral ranking: 2 sectors" in out
    assert "net of 5 bps" in out
    # costs can only reduce the mean spread
    import re

    gross = float(re.search(r"Mean monthly spread: (\S+)", out).group(1))
    net = float(re.search(r"net of 5 bps.*mean ([+-][0-9.]+)", out).group(1))
    assert net < gross


def test_run_monthly_sector_guards(rng):
    import numpy as np

    from csmom_tpu.backends import run_monthly
    from csmom_tpu.panel.panel import Panel
    from csmom_tpu.strategy import make_strategy

    A, M = 12, 40
    prices = 50 * np.exp(np.cumsum(rng.normal(0, 0.05, size=(A, M)), axis=1))
    panel = Panel(values=prices, mask=np.ones((A, M), bool),
                  tickers=np.array([f"T{i}" for i in range(A)]),
                  times=np.arange(M))
    ids = np.zeros(A, np.int32)
    with pytest.raises(NotImplementedError, match="sector"):
        run_monthly(panel, backend="pandas", sector_ids=ids, n_sectors=1)
    # strategy + sector on the TPU backend is now supported: with the
    # built-in momentum strategy it must equal the dedicated sector engine
    from csmom_tpu.backtest import sector_neutral_backtest

    ids = np.arange(A, dtype=np.int32) % 3
    rep = run_monthly(panel, strategy=make_strategy("momentum"),
                      sector_ids=ids, n_sectors=3)
    want = sector_neutral_backtest(prices, np.ones((A, M), bool), ids, 3,
                                   lookback=12, skip=1)
    got_spread = np.asarray(rep.spread)
    want_spread = np.where(np.asarray(want.spread_valid),
                           np.asarray(want.spread), np.nan)
    np.testing.assert_array_equal(np.isfinite(got_spread),
                                  np.isfinite(want_spread))
    live = np.isfinite(want_spread)
    np.testing.assert_allclose(got_spread[live], want_spread[live],
                               rtol=0, atol=0)


@requires_reference
def test_cli_sector_map_combo_rejected_cleanly(tmp_path, capsys):
    sm = tmp_path / "s.csv"
    sm.write_text("ticker,sector\nMSFT,t\n")
    rc = main([
        "replicate", "--data-dir", REFERENCE_DATA, "--backend", "pandas",
        "--sector-map", str(sm),
    ])
    assert rc == 2
    assert "TPU engine" in capsys.readouterr().err


@requires_reference
def test_cli_grid_tearsheet_tables(tmp_path, capsys):
    # same grid cell set/statics as test_cli_grid_tc_sweep: the two CLI
    # grid tests share one compile of the grid stack
    rc = main([
        "grid", "--data-dir", REFERENCE_DATA, "--js", "6", "--ks", "1,3",
        "--mode", "rank", "--n-bins", "5",
        "--tearsheet", "--bootstrap", "0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    for name in ("max drawdown", "Calmar", "hit rate"):
        assert name in out


@requires_reference
def test_cli_sector_map_no_match_errors(tmp_path, capsys):
    sm = tmp_path / "s.csv"
    sm.write_text("ticker,sector\nZZZQ,none\n")
    with pytest.raises(SystemExit, match="no entry matches"):
        main(["replicate", "--data-dir", REFERENCE_DATA,
              "--sector-map", str(sm)])


@requires_reference
def test_cli_tc_bps_zero_reports_net_equals_gross(tmp_path, capsys):
    rc = main([
        "replicate", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--backend", "pandas", "--tc-bps", "0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    gross = float(re.search(r"Mean monthly spread: (\S+)", out).group(1))
    net = float(re.search(r"net of 0 bps.*mean ([+-][0-9.]+)", out).group(1))
    assert net == pytest.approx(gross, abs=1e-6)


@requires_reference
@pytest.mark.slow
def test_cli_residual_sweep_tables(capsys):
    rc = main([
        "residual", "--data-dir", REFERENCE_DATA, "--js", "3,6",
        "--est-windows", "12,24", "--tearsheet",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "est_window" in out
    for name in ("mean monthly spread", "Newey-West t-stat",
                 "annualized Sharpe", "max drawdown", "Calmar"):
        assert name in out


@requires_reference
def test_cli_residual_walkforward(capsys):
    rc = main([
        "residual", "--data-dir", REFERENCE_DATA, "--js", "3,6",
        "--est-windows", "12,24", "--sweep",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "walk-forward" in out
    assert "most-picked cell" in out


@requires_reference
@pytest.mark.slow
def test_cli_intraday_daily_tearsheet(tmp_path, capsys):
    rc = main([
        "intraday", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--tearsheet",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "daily PnL" in out
    assert "Max drawdown" in out


@requires_reference
@pytest.mark.slow
def test_cli_intraday_threshold_sweep(tmp_path, capsys):
    rc = main([
        "intraday", "--data-dir", REFERENCE_DATA, "--out", str(tmp_path),
        "--threshold-sweep", "1e-6,1e-5,1e-3",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "threshold sensitivity" in out
    # the reference threshold lane reproduces the golden trade count
    # (all-20-ticker panel: 28,020 + the AAPL trades the reference loses)
    import re

    row = re.search(r"1e-05\s+(\d+)", out)
    assert row and int(row.group(1)) > 28_000


@requires_reference
@pytest.mark.slow
def test_cli_grid_tc_bps(capsys):
    rc = main([
        "grid", "--data-dir", REFERENCE_DATA, "--js", "6", "--ks", "1,6",
        "--tc-bps", "5", "--bootstrap", "0",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "NET of 5 bps" in out


class TestPlatformFailFast:
    """The default-platform init probe (_apply_platform): a pinned non-cpu
    platform whose backend hangs at init must fail fast with the workaround
    printed, not hang the CLI (VERDICT r3 weak #4)."""

    def test_dead_default_platform_exits_3(self, monkeypatch, capsys):
        import jax

        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("CSMOM_PLATFORM_PROBE_S", "1")
        # the suite's conftest pins the in-process backend to cpu, which
        # (correctly) short-circuits the probe; clear it to exercise the
        # dead-tunnel path, restore afterwards
        jax.config.update("jax_platforms", "")
        try:
            rc = main(["replicate", "--data-dir", "/nonexistent"])
        finally:
            jax.config.update("jax_platforms", "cpu")
        assert rc == 3
        err = capsys.readouterr().err
        assert "--platform cpu" in err
        assert "CSMOM_PLATFORM_PROBE_S" in err

    def test_in_process_cpu_pin_short_circuits_probe(self, monkeypatch):
        # embedders (this suite) that already config.update'd to cpu must
        # not pay a probe: a bogus data dir reaches the command itself,
        # whose ingest exception (not a clean rc=3) is the proof
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("CSMOM_PLATFORM_PROBE_S", "1")
        with pytest.raises((Exception, SystemExit)):
            main(["replicate", "--data-dir", "/nonexistent"])

    def test_explicit_platform_skips_probe(self, monkeypatch, tmp_path):
        # --platform cpu never probes: an empty data dir must reach the real
        # command, whose own failure (an ingest exception) proves the probe
        # did not intercept with a clean rc=3 return
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("CSMOM_PLATFORM_PROBE_S", "1")
        with pytest.raises((Exception, SystemExit)):
            main(["replicate", "--data-dir", str(tmp_path),
                  "--platform", "cpu"])

    def test_device_free_command_skips_probe(self, monkeypatch, capsys):
        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("CSMOM_PLATFORM_PROBE_S", "1")
        rc = main(["strategies"])
        assert rc == 0
        assert "momentum" in capsys.readouterr().out

    def test_probe_disabled_via_env_zero(self, monkeypatch):
        # CSMOM_PLATFORM_PROBE_S=0 skips the probe entirely: the command
        # proceeds on the env default (here: in-process cpu via conftest)
        import jax

        monkeypatch.setenv("JAX_PLATFORMS", "axon")
        monkeypatch.setenv("CSMOM_PLATFORM_PROBE_S", "0")
        jax.config.update("jax_platforms", "")
        try:
            with pytest.raises((Exception, SystemExit)):
                main(["replicate", "--data-dir", "/nonexistent"])
        finally:
            jax.config.update("jax_platforms", "cpu")


@requires_reference
def test_cli_grid_tc_sweep(capsys):
    rc = main(["grid", "--data-dir", REFERENCE_DATA, "--js", "6", "--ks",
               "1,3", "--mode", "rank", "--n-bins", "5", "--tc-bps", "5",
               "--tc-sweep", "0,5,25", "--bootstrap", "0"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "cost sweep" in out
    for col in ("0bps", "5bps", "25bps"):
        assert col in out
    # the linear re-pricer itself is oracle-tested in
    # tests/test_grid.py::test_net_from_unit_matches_direct; this is the
    # CLI plumbing smoke


def test_cli_grid_tc_sweep_fails_fast(capsys):
    # without --tc-bps: rc=2 BEFORE any backtest compute
    rc = main(["grid", "--data-dir", "/nonexistent", "--tc-sweep", "0,5"])
    assert rc == 2
    assert "--tc-bps" in capsys.readouterr().err
    # malformed levels: rc=2 with a readable message
    rc = main(["grid", "--data-dir", "/nonexistent", "--tc-bps", "5",
               "--tc-sweep", "5bps,10"])
    assert rc == 2
    assert "plain numbers" in capsys.readouterr().err


@requires_reference
def test_cli_sweep_net_of_costs(capsys):
    rc = main(["sweep", "--data-dir", REFERENCE_DATA, "--js", "3,6",
               "--ks", "1,3", "--mode", "rank", "--n-bins", "5",
               "--tc-bps", "5"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "Selection basis:   net of 5 bps" in out
    rc = main(["sweep", "--data-dir", REFERENCE_DATA, "--js", "3,6",
               "--ks", "1,3", "--mode", "rank", "--n-bins", "5"])
    assert rc == 0
    assert "Selection basis:   gross" in capsys.readouterr().out


@requires_reference
def test_cli_replicate_break_even_line(capsys, tmp_path):
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--tc-bps", "5",
               "--out", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    m = re.search(r"break-even half-spread: ([+-][\d.]+) bps "
                  r"\(mean monthly turnover ([\d.]+)\)", out)
    assert m, out
    be, turn = float(m.group(1)), float(m.group(2))
    g = re.search(r"Mean monthly spread: ([-\d.]+)", out)
    n = re.search(r"net of 5 bps.*mean ([+-][\d.]+)", out)
    gross, net5 = float(g.group(1)), float(n.group(1))
    # linearity: gross - 5e-4 * turn == net at 5 bps; be * turn == gross.
    # tolerances reflect the printed precision (be at 0.1 bps, turn at 1e-3)
    assert abs(gross - 5e-4 * turn - net5) < 2e-6
    assert abs(be / 1e4 * turn - gross) < 0.06 / 1e4 * turn + 1e-6


@requires_reference
def test_cli_replicate_band(capsys, tmp_path):
    """--band smoke on the reference data: banded turnover is reported,
    lower than plain, and the banded break-even exceeds the plain one
    (the band's whole point); incompatible modes fail fast."""
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--tc-bps", "10",
               "--band", "1", "--bootstrap", "50", "--out", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    # the banded series gets its own block-bootstrap CI line
    assert re.search(r"95% CI mean: \[[-\d.]+, [-\d.]+\] \(50 block", out)

    m = re.search(r"turnover ([\d.]+) vs plain ([\d.]+)", out)
    assert m, out
    banded_turn, plain_turn = float(m.group(1)), float(m.group(2))
    assert banded_turn < plain_turn
    bes = [float(x) for x in
           re.findall(r"break-even half-spread: \+?([-\d.]+) bps", out)]
    assert len(bes) == 2 and bes[1] > bes[0]

    # the band applies to whatever labels the plain run made: the pandas
    # backend produces identical labels, so its banded numbers must equal
    # the TPU run's above (parity tested against the captured output, not
    # a hardcoded golden)
    tpu_gross = re.search(r"gross mean ([+-][\d.]+)", out).group(1)
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--band", "1",
               "--backend", "pandas", "--out", str(tmp_path)])
    assert rc == 0
    pd_out = capsys.readouterr().out
    m2 = re.search(r"gross mean ([+-][\d.]+)", pd_out)
    assert m2 and m2.group(1) == tpu_gross

    # invalid band width: readable error, rc=2
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--band", "7",
               "--out", str(tmp_path)])
    assert rc == 2
    assert "stay-zones" in capsys.readouterr().err


@requires_reference
def test_cli_replicate_vol_target(capsys, tmp_path):
    """--vol-target smoke: the overlay reports, and managed realized vol
    lands well under the raw spread's (the mechanism working)."""
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--vol-target",
               "12", "--out", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    m = re.search(r"raw ([\d.]+)% -> managed ([\d.]+)%", out)
    assert m, out
    raw_v, man_v = float(m.group(1)), float(m.group(2))
    assert man_v < 0.6 * raw_v
    assert "vol-managed overlay" in out

    # non-positive target: fail fast BEFORE any backtest, rc=2
    rc = main(["replicate", "--data-dir", "/nonexistent", "--vol-target",
               "0", "--out", str(tmp_path)])
    assert rc == 2
    assert "must be positive" in capsys.readouterr().err


@requires_reference
def test_cli_replicate_band_sweep(capsys, tmp_path):
    """--band-sweep: one table row per width, turnover strictly falling
    with the band (its purpose); malformed widths fail fast."""
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--band-sweep",
               "0,1,2", "--tc-bps", "10", "--out", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    rows = re.findall(r"^\s+([012])\s+([+-][\d.]+)\s+([\d.]+)\s+", out,
                      flags=re.M)
    assert [r[0] for r in rows] == ["0", "1", "2"]
    turns = [float(r[2]) for r in rows]
    assert turns[0] > turns[1] > turns[2]

    rc = main(["replicate", "--data-dir", "/nonexistent",
               "--band-sweep", "1,zig", "--out", str(tmp_path)])
    assert rc == 2
    assert "plain integers" in capsys.readouterr().err

    rc = main(["replicate", "--data-dir", "/nonexistent",
               "--band-sweep", "0,7", "--out", str(tmp_path)])
    assert rc == 2
    assert "invalid widths" in capsys.readouterr().err


@requires_reference
def test_cli_intraday_hysteresis(capsys, tmp_path):
    """--threshold-lo adds the Schmitt-trigger report: far fewer trades
    than the accumulate-every-signal engine; bad threshold order fails."""
    rc = main(["intraday", "--data-dir", REFERENCE_DATA, "--out",
               str(tmp_path), "--threshold-hi", "1e-4",
               "--threshold-lo", "2e-5"])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    m = re.search(r"trades (\d+) \(plain engine: (\d+)\)", out)
    assert m, out
    assert int(m.group(1)) < int(m.group(2)) // 10

    rc = main(["intraday", "--data-dir", REFERENCE_DATA, "--out",
               str(tmp_path), "--threshold-hi", "1e-5",
               "--threshold-lo", "1e-4"])
    assert rc == 2
    assert "must not exceed" in capsys.readouterr().err

    # --threshold-hi alone would silently do nothing: refuse it instead
    rc = main(["intraday", "--data-dir", REFERENCE_DATA, "--out",
               str(tmp_path), "--threshold-hi", "1e-4"])
    assert rc == 2
    assert "--threshold-lo" in capsys.readouterr().err


@requires_reference
def test_cli_replicate_band_select(capsys, tmp_path):
    """--band-select: strictly out-of-sample width selection through the
    generic walk_forward_select; selection counts only name given widths."""
    rc = main(["replicate", "--data-dir", REFERENCE_DATA, "--band-select",
               "0,1,2", "--tc-bps", "10", "--out", str(tmp_path)])
    assert rc == 0
    out = capsys.readouterr().out
    import re

    m = re.search(r"OOS months (\d+), mean ([+-][\d.]+)", out)
    assert m and int(m.group(1)) > 20
    assert re.search(r"selections: (band [012] x\d+(, )?)+", out)

    rc = main(["replicate", "--data-dir", "/nonexistent", "--band-select",
               "1", "--out", str(tmp_path)])
    assert rc == 2
    assert "at least two" in capsys.readouterr().err

    rc = main(["replicate", "--data-dir", "/nonexistent", "--band-select",
               "0,9", "--out", str(tmp_path)])
    assert rc == 2
    assert "invalid widths" in capsys.readouterr().err


def test_cli_run_chains_replicate_then_intraday(monkeypatch):
    """`csmom run` is the reference's one-shot ``main()`` analogue
    (``run_demo.py:193-207``): replicate first, intraday second, and a
    failing monthly leg short-circuits (its rc propagates, the intraday
    leg never starts)."""
    import csmom_tpu.cli.main as climod

    calls = []

    def fake_replicate(args):
        """stub (the parser reads each command fn's docstring)"""
        calls.append("replicate")
        return 0

    def fake_intraday(args):
        """stub"""
        calls.append("intraday")
        return 0

    monkeypatch.setattr(climod, "cmd_replicate", fake_replicate)
    monkeypatch.setattr(climod, "cmd_intraday", fake_intraday)
    rc = main(["run", "--platform", "cpu"])
    assert rc == 0
    assert calls == ["replicate", "intraday"]

    calls.clear()

    def failing_replicate(args):
        """stub"""
        calls.append("replicate")
        return 3

    monkeypatch.setattr(climod, "cmd_replicate", failing_replicate)
    rc = main(["run", "--platform", "cpu"])
    assert rc == 3
    assert calls == ["replicate"]  # intraday never ran
