"""Cost models vs reference formulas (execution_models.py re-derived)."""

import numpy as np
import jax

from csmom_tpu.costs import square_root_impact, market_fill, limit_fill


def ref_impact(size, adv, vol, k=0.1, expo=0.5):
    if adv <= 0:
        return 0.0
    return k * vol * (abs(size) / adv) ** expo


def test_impact_matches_reference():
    for size, adv, vol in [(50, 1e5, 0.02), (-500, 2e6, 0.35), (50, 0.0, 0.02), (0, 1e5, 0.02)]:
        got = float(square_root_impact(size, adv, vol))
        assert abs(got - ref_impact(size, adv, vol)) < 1e-15


def test_market_fill_matches_reference():
    price, size, adv, vol = 231.5, 50, 120000.0, 0.018
    for side in (1, -1):
        exec_p, imp = market_fill(price, size, adv, vol, side)
        want = price * (1 + side * (0.001 / 2 + ref_impact(size, adv, vol)))
        assert abs(float(exec_p) - want) < 1e-10


def test_market_fill_vectorized():
    prices = np.array([10.0, 20.0, 30.0])
    sizes = np.array([50.0, -50.0, 50.0])
    advs = np.array([1e5, 1e5, 0.0])
    vols = np.array([0.02, 0.05, 0.02])
    sides = np.sign(sizes)
    exec_p, imp = market_fill(prices, sizes, advs, vols, sides)
    assert exec_p.shape == (3,)
    assert float(imp[2]) == 0.0  # zero-ADV guard


def test_long_short_weights_and_turnover_cost(rng):
    from csmom_tpu.costs import long_short_weights, turnover_cost
    from csmom_tpu.backtest import monthly_spread_backtest
    from csmom_tpu.backtest.monthly import net_of_costs

    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.07, size=(30, 48)), axis=1))
    res = monthly_spread_backtest(prices, np.isfinite(prices))
    w = np.asarray(long_short_weights(res.labels, res.decile_counts, 10))
    valid = np.asarray(res.spread_valid)
    # weights sum to ~0 (dollar-neutral) and each live leg to +/-1
    live = np.where(valid)[0]
    np.testing.assert_allclose(w[:, live].sum(axis=0), 0.0, atol=1e-12)
    np.testing.assert_allclose(np.abs(w[:, live]).sum(axis=0), 2.0, atol=1e-12)

    cost = np.asarray(turnover_cost(w, half_spread=0.0005))
    # oracle: manual |dw| sum
    prev = np.concatenate([np.zeros((w.shape[0], 1)), w[:, :-1]], axis=1)
    want = np.abs(w - prev).sum(axis=0) * 0.0005
    np.testing.assert_allclose(cost, want, rtol=1e-12)

    net, net_mean, net_sharpe = net_of_costs(res, half_spread=0.0005)
    gross = np.asarray(res.spread)[valid]
    assert float(net_mean) < float(res.mean_spread)  # costs strictly reduce
    np.testing.assert_allclose(np.asarray(net)[valid], gross - cost[valid], rtol=1e-10)


def test_limit_fill_probabilities():
    key = jax.random.PRNGKey(0)
    keys = jax.random.split(key, 2000)
    filled = np.array([
        bool(limit_fill(k, 100.0, 50, 1e5, 0.02, aggressiveness=0.5)[0]) for k in keys[:300]
    ])
    p = filled.mean()
    # p_full ~= (0.2+0.35)*(1-0.5*5e-4) ~= 0.5499
    assert 0.40 < p < 0.70
