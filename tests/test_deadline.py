"""The shared deadline guard: one summary line, partial or full, always.

The guard backs every capture process (bench children, scaling, phases);
its contract — partial dump on deadline, exit 3 when nothing is
measured, full line wins when it gets there first — is what keeps an
external SIGKILL from discarding measured data.  The firing paths need a
subprocess (the guard calls os._exit); the cancel path runs in-process.
"""

import os
import subprocess
import sys
import textwrap

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_guard_script(body: str, budget: str = "1"):
    env = dict(os.environ)
    env["GUARD_TEST_BUDGET"] = budget
    return subprocess.run(
        [sys.executable, "-c", textwrap.dedent(f"""
            import sys, time
            sys.path.insert(0, {_REPO!r})
            from csmom_tpu.utils.deadline import deadline_guard
            t0 = time.monotonic()
            {body}
        """)],
        capture_output=True, text=True, timeout=60, env=env,
    )


def test_deadline_fires_partial_and_exits_zero():
    p = _run_guard_script("""
            finish = deadline_guard("GUARD_TEST_BUDGET", lambda: '{"partial": true}',
                                    t0=t0, margin_s=0.0, min_delay_s=0.3)
            time.sleep(30)  # the hang the guard exists to outrun
    """)
    assert p.returncode == 0
    assert p.stdout.strip() == '{"partial": true}'


def test_deadline_with_nothing_measured_exits_three_silently():
    p = _run_guard_script("""
            finish = deadline_guard("GUARD_TEST_BUDGET", lambda: None,
                                    t0=t0, margin_s=0.0, min_delay_s=0.3)
            time.sleep(30)
    """)
    assert p.returncode == 3
    assert p.stdout.strip() == ""


def test_finish_beats_timer_and_prints_once():
    p = _run_guard_script("""
            finish = deadline_guard("GUARD_TEST_BUDGET", lambda: '{"partial": true}',
                                    t0=t0, margin_s=0.0, min_delay_s=0.5)
            finish('{"full": true}')
            time.sleep(1.5)  # outlive the timer: it must never also print
    """)
    assert p.returncode == 0
    assert p.stdout.strip() == '{"full": true}'


def test_unset_budget_arms_nothing():
    p = _run_guard_script("""
            finish = deadline_guard("GUARD_TEST_BUDGET_UNSET", lambda: None,
                                    t0=t0, min_delay_s=0.1)
            finish('{"full": true}')
    """)
    assert p.returncode == 0
    assert p.stdout.strip() == '{"full": true}'


def test_summary_line_survives_interleaved_progress_prints():
    """ADVICE r5 #2: the watchdog fires while the caller is mid-way
    through a progress print — the driver-parsed TRAILING JSON line must
    still be intact.  The summary is one os.write preceded by a newline,
    so a half-written progress row can never splice into it."""
    p = _run_guard_script("""
            import json
            finish = deadline_guard("GUARD_TEST_BUDGET",
                                    lambda: json.dumps({"partial": True,
                                                        "rows": 3}),
                                    t0=t0, margin_s=0.0, min_delay_s=0.3)
            # hammer stdout with unterminated progress fragments until the
            # guard fires (os._exit) — worst-case interleaving pressure
            while True:
                sys.stdout.write("row 1234 wall 0.123")   # no newline
                sys.stdout.write(" ...still going")
                time.sleep(0.001)
    """)
    assert p.returncode == 0
    lines = [ln for ln in p.stdout.splitlines() if ln.strip()]
    # trailing line parses clean and starts at column 0
    import json

    assert json.loads(lines[-1]) == {"partial": True, "rows": 3}


def test_finish_flushes_progress_before_summary():
    """finish() on the caller's thread: buffered progress rows land BEFORE
    the summary, which stays the trailing (parsed) line."""
    p = _run_guard_script("""
            finish = deadline_guard("GUARD_TEST_BUDGET", lambda: None,
                                    t0=t0, margin_s=0.0, min_delay_s=30)
            sys.stdout.write("progress row without newline")
            finish('{"full": true}')
    """)
    assert p.returncode == 0
    lines = p.stdout.splitlines()
    assert lines[-1] == '{"full": true}'
    assert any("progress row" in ln for ln in lines[:-1])


def test_late_armed_guard_still_fires_before_external_budget():
    """The t0 anchor: a guard armed 0.8s after 'process start' with a 1s
    budget must compute a near-zero fuse (floored by min_delay_s), not a
    fresh full-budget one — jax init time counts against the budget."""
    p = _run_guard_script("""
            time.sleep(0.8)  # slow 'jax init' before the guard is armed
            finish = deadline_guard("GUARD_TEST_BUDGET", lambda: '{"partial": true}',
                                    t0=t0, margin_s=0.0, min_delay_s=0.1)
            time.sleep(30)
    """)
    assert p.returncode == 0
    assert p.stdout.strip() == '{"partial": true}'
