"""Volume double sort vs a loop oracle + LeSw-style volume effect on synthetic data."""

import numpy as np
import pandas as pd

from csmom_tpu.backtest import volume_double_sort
from tests.test_ranking import oracle_deciles


def oracle_memberships(prices: pd.DataFrame, turn: pd.DataFrame,
                       s: int, J=6, skip=1, n_vol=3):
    """Month s's (mlab, vlab, live, next_ret) — the ONE oracle rendering of
    the engine's sort convention, shared by the spread and turnover tests
    so the two cannot drift apart."""
    ret = prices.pct_change()
    mom = prices.shift(skip) / prices.shift(skip + J) - 1
    bad = ret.isna().astype(int)
    wb = bad.shift(skip).rolling(J, min_periods=J).sum()
    mom = mom.where(wb == 0)
    mlab = oracle_deciles(mom.iloc[s].values)
    both = (mlab >= 0) & turn.iloc[s].notna().values
    vlab = oracle_deciles(np.where(both, turn.iloc[s].values, np.nan), n=n_vol)
    M = len(prices)
    nr = ret.iloc[s + 1].values if s + 1 < M else np.full(prices.shape[1], np.nan)
    live = both & (vlab >= 0) & np.isfinite(nr)
    return mlab, vlab, live, nr


def oracle_double_sort(prices: pd.DataFrame, turn: pd.DataFrame, J=6, skip=1, n_vol=3):
    out = {v: {} for v in range(n_vol)}
    for s in range(len(prices) - 1):
        mlab, vlab, live, nr = oracle_memberships(prices, turn, s, J, skip, n_vol)
        for v in range(n_vol):
            top = live & (vlab == v) & (mlab == 9)
            bot = live & (vlab == v) & (mlab == 0)
            if top.any() and bot.any():
                out[v][s] = nr[top].mean() - nr[bot].mean()
    return out


def test_double_sort_matches_oracle(rng):
    M, A = 60, 60
    prices = pd.DataFrame(50 * np.exp(np.cumsum(rng.normal(0.004, 0.08, (M, A)), axis=0)))
    turn = pd.DataFrame(rng.lognormal(-4, 1, size=(M, A)))
    turn.iloc[:, :5] = np.nan  # some assets lack turnover data

    pv = prices.values.T
    tv = turn.values.T
    res = volume_double_sort(
        pv, np.isfinite(pv), tv, np.isfinite(tv), lookback=6, skip=1
    )
    want = oracle_double_sort(prices, turn)
    got = np.asarray(res.spreads)
    got_valid = np.asarray(res.spread_valid)
    for v in range(3):
        np.testing.assert_array_equal(np.where(got_valid[v])[0], sorted(want[v]))
        for s, val in want[v].items():
            assert abs(got[v, s] - val) < 1e-9


def test_volume_amplifies_planted_momentum(rng):
    """Plant a momentum effect whose strength scales with turnover; V3 spread
    must exceed V1 spread (the LeSw signature)."""
    M, A = 120, 200
    turn = np.tile(rng.lognormal(-4, 1.2, size=(1, A)), (M, 1))
    turn_strength = (pd.Series(turn[0]).rank(pct=True)).values  # high-vol names
    shocks = rng.normal(0, 0.05, size=(M, A))
    drift = np.zeros((M, A))
    # persistent per-asset drift, stronger among high-turnover names
    base = rng.normal(0, 0.02, size=A)
    drift += base * (0.2 + turn_strength)
    prices = pd.DataFrame(50 * np.exp(np.cumsum(drift + shocks, axis=0)))

    pv = prices.values.T
    tv = turn.T
    res = volume_double_sort(pv, np.isfinite(pv), tv, np.isfinite(tv), lookback=6)
    means = np.asarray(res.mean_spread)
    assert np.isfinite(means).all()
    assert means[2] > means[0], means


def test_book_turnover_matches_weight_oracle(rng):
    """Each tercile's book_turnover equals sum |dw| of the equal-weight
    long-short book recomputed by loops from the same memberships (dead
    months hold no book; the first live month pays full entry)."""
    M, A = 48, 40
    prices = pd.DataFrame(
        50 * np.exp(np.cumsum(rng.normal(0.004, 0.08, (M, A)), axis=0))
    )
    turn = pd.DataFrame(rng.lognormal(-4, 1, size=(M, A)))
    pv = prices.values.T
    tv = turn.values.T
    res = volume_double_sort(
        pv, np.isfinite(pv), tv, np.isfinite(tv), lookback=6, skip=1
    )

    got_turn = np.asarray(res.book_turnover)
    got_valid = np.asarray(res.spread_valid)
    for v in range(3):
        w_prev = np.zeros(A)
        for s in range(M):
            w = np.zeros(A)
            if got_valid[v, s]:
                mlab, vlab, live, _ = oracle_memberships(prices, turn, s)
                top = live & (vlab == v) & (mlab == 9)
                bot = live & (vlab == v) & (mlab == 0)
                w[top] = 1.0 / top.sum()
                w[bot] -= 1.0 / bot.sum()
            want = np.abs(w - w_prev).sum()
            np.testing.assert_allclose(got_turn[v, s], want, atol=1e-9)
            w_prev = w
