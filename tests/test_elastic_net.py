"""FISTA elastic-net / lasso vs sklearn's coordinate-descent solver."""

import numpy as np
import pytest

sklearn = pytest.importorskip("sklearn")
from sklearn.linear_model import ElasticNet, Lasso
from sklearn.preprocessing import StandardScaler

from csmom_tpu.models import (
    as_ridge_fit,
    elastic_net_time_series_cv,
    ridge_time_series_cv,
)

from tests.test_ridge import _padded


def _sk_final(flatX, flaty, split, alpha, l1_ratio):
    """Reference pipeline shape: scaler on the training block, model on the
    scaled training block."""
    scaler = StandardScaler().fit(flatX[:split])
    Xs = scaler.transform(flatX[:split])
    if l1_ratio == 1.0:
        m = Lasso(alpha=alpha, max_iter=50000, tol=1e-14)
    else:
        m = ElasticNet(alpha=alpha, l1_ratio=l1_ratio, max_iter=50000, tol=1e-14)
    m.fit(Xs, flaty[:split])
    return m, scaler


@pytest.mark.parametrize("l1_ratio", [1.0, 0.5])
def test_matches_sklearn_solution(rng, l1_ratio):
    X, y, valid, flatX, flaty = _padded(rng)
    split = int(len(flatX) * 0.7)
    alpha = 2e-4

    fit = elastic_net_time_series_cv(
        X, y, valid, n_splits=3, alpha=alpha, l1_ratio=l1_ratio, n_iter=4000
    )
    m, scaler = _sk_final(flatX, flaty, split, alpha, l1_ratio)

    assert int(fit.n_train) == split
    np.testing.assert_allclose(np.asarray(fit.scale_mean), scaler.mean_, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(fit.coef), m.coef_, rtol=1e-6, atol=1e-10)
    assert abs(float(fit.intercept) - m.intercept_) < 1e-10

    want = m.predict(scaler.transform(flatX))
    got = np.asarray(fit.scores).reshape(-1)[valid.reshape(-1)]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-12)


def test_lasso_sparsity_and_nonzero_count(rng):
    """A strong enough l1 penalty must actually zero out weak features."""
    A, R, F = 2, 500, 5
    valid = np.ones((A, R), bool)
    X = rng.normal(size=(A, R, F))
    # y depends on features 0 and 2 only
    y = 0.8 * X[..., 0] - 0.5 * X[..., 2] + 0.01 * rng.normal(size=(A, R))
    fit = elastic_net_time_series_cv(
        X, y, valid, alpha=0.05, l1_ratio=1.0, n_iter=3000
    )
    coef = np.asarray(fit.coef)
    assert coef[0] > 0 and coef[2] < 0
    assert abs(coef[1]) < 1e-10 and abs(coef[3]) < 1e-10 and abs(coef[4]) < 1e-10
    assert int(fit.n_nonzero) == 2


def test_l1_ratio_zero_approaches_ridge(rng):
    """l1_ratio=0 is ridge up to the 1/n objective scaling: FISTA with
    alpha*n matches the closed-form ridge solve with penalty alpha."""
    X, y, valid, flatX, _ = _padded(rng, A=2, R=300)
    n_train = int(valid.sum() * 0.7)
    alpha = 1.0
    ridge = ridge_time_series_cv(X, y, valid, alpha=alpha)
    enet = elastic_net_time_series_cv(
        X, y, valid, alpha=alpha / n_train, l1_ratio=0.0, n_iter=6000
    )
    np.testing.assert_allclose(
        np.asarray(enet.coef), np.asarray(ridge.coef), rtol=1e-6, atol=1e-12
    )
    assert abs(float(enet.intercept) - float(ridge.intercept)) < 1e-9


def test_cv_mses_match_sklearn_folds(rng):
    from sklearn.model_selection import TimeSeriesSplit
    from sklearn.metrics import mean_squared_error

    X, y, valid, flatX, flaty = _padded(rng, A=2, R=350)
    split = int(len(flatX) * 0.7)
    alpha, l1_ratio = 3e-4, 0.5

    fit = elastic_net_time_series_cv(
        X, y, valid, n_splits=3, alpha=alpha, l1_ratio=l1_ratio, n_iter=4000
    )
    scaler = StandardScaler().fit(flatX[:split])
    Xs = scaler.transform(flatX[:split])
    mses = []
    for tr, te in TimeSeriesSplit(n_splits=3).split(Xs):
        m = ElasticNet(alpha=alpha, l1_ratio=l1_ratio, max_iter=50000, tol=1e-14)
        m.fit(Xs[tr], flaty[:split][tr])
        mses.append(mean_squared_error(flaty[:split][te], m.predict(Xs[te])))
    np.testing.assert_allclose(np.asarray(fit.cv_mse), mses, rtol=1e-6)


@pytest.mark.slow
def test_intraday_pipeline_model_selection(rng):
    """--model wiring: elastic_net/lasso run end-to-end through the intraday
    pipeline; unknown model raises."""
    from csmom_tpu.api import intraday_pipeline
    from tests.test_intraday import _toy_minutes

    minutes = _toy_minutes(rng, n_assets=3, n_min=220)
    res_r, fit_r, *_ = intraday_pipeline(minutes, None, model="ridge", alpha=1.0)
    res_l, fit_l, *_ = intraday_pipeline(
        minutes, None, model="lasso", alpha=1e-9
    )
    assert np.isfinite(np.asarray(fit_l.cv_mse)).all()
    # a scale-appropriate alpha keeps the model live: coefficients survive
    # and scores actually vary
    assert np.count_nonzero(np.asarray(fit_l.coef)) > 0
    assert np.nanstd(np.asarray(fit_l.scores)) > 0
    # the two models score differently in general
    a, b = np.asarray(fit_r.scores), np.asarray(fit_l.scores)
    assert not np.allclose(np.nan_to_num(a), np.nan_to_num(b))
    with pytest.raises(ValueError, match="unknown model"):
        intraday_pipeline(minutes, None, model="svm")


@pytest.mark.slow
def test_intraday_pipeline_warns_on_zeroed_model(rng):
    """A ridge-scale alpha on the l1 objective zeroes everything; the API
    must say so instead of silently going flat.  (The package logger has
    propagate=False, so capture via an attached handler, not caplog.)"""
    from csmom_tpu.api import intraday_pipeline
    from tests.test_guards_profiling import _captured_logs
    from tests.test_intraday import _toy_minutes

    minutes = _toy_minutes(rng, n_assets=2, n_min=180)
    with _captured_logs() as msgs:
        _, fit, *_ = intraday_pipeline(minutes, None, model="lasso", alpha=1.0)
    assert any("zeroed every coefficient" in m for m in msgs)
    assert np.count_nonzero(np.asarray(fit.coef)) == 0


def test_as_ridge_fit_schema(rng):
    X, y, valid, *_ = _padded(rng, A=2, R=200)
    fit = elastic_net_time_series_cv(X, y, valid, n_iter=500)
    rf = as_ridge_fit(fit)
    np.testing.assert_array_equal(np.asarray(rf.scores), np.asarray(fit.scores))
    np.testing.assert_array_equal(np.asarray(rf.coef), np.asarray(fit.coef))
