"""Event engine: loop-oracle equivalence + the shipped golden fingerprint."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.backtest.event import event_backtest, trades_dataframe
from csmom_tpu.costs import market_fill
from tests.conftest import DEMO_TICKERS, MEASURED_TICKERS, requires_reference, REFERENCE_DATA


def oracle_event_loop(price, valid, score, adv, vol, size=50, thr=1e-5, cash0=1e6):
    """Reference SimpleEventBacktester semantics (backtester.py:20-65) as a
    plain Python loop over the dense panel."""
    A, T = price.shape
    positions = np.zeros(A, dtype=int)
    cash = cash0
    last_pv = None
    pnl = []
    trades = []
    last_price = np.full(A, np.nan)
    for t in range(T):
        if not valid[:, t].any():
            continue
        for a in range(A):
            if valid[a, t]:
                s = score[a, t]
                if s > thr or s < -thr:
                    side = 1 if s > thr else -1
                    ep, imp = market_fill(price[a, t], size, adv[a], vol[a], side)
                    positions[a] += side * size
                    cash -= float(ep) * side * size
                    trades.append((t, a, side * size, float(ep), float(imp), s))
        for a in range(A):
            if valid[a, t]:
                last_price[a] = price[a, t]
        pv = cash + np.nansum(np.where(np.isfinite(last_price), positions * last_price, 0.0))
        pnl.append(0.0 if last_pv is None else pv - last_pv)
        last_pv = pv
    return np.array(pnl), trades, positions, cash


def _scenario(rng, A=5, T=120):
    price = 100 * np.exp(np.cumsum(rng.normal(0, 1e-3, size=(A, T)), axis=1))
    valid = rng.random((A, T)) > 0.2
    valid[:, 0] = [True, True, False, False, True]  # staggered starts
    score = rng.normal(0, 1e-4, size=(A, T))
    score[np.abs(score) < 2e-5] = 0.0  # exercise the threshold edge
    adv = np.array([1e5, 2e6, 1e5, 5e4, 1e7])
    vol = np.array([0.02, 0.4, 0.02, 0.01, 0.15])
    price[~valid] = np.nan
    return price, valid, score, adv, vol


def test_matches_loop_oracle(rng):
    price, valid, score, adv, vol = _scenario(rng)
    res = event_backtest(price, valid, np.nan_to_num(score), adv, vol)
    pnl_o, trades_o, pos_o, cash_o = oracle_event_loop(price, valid, score, adv, vol)

    got_pnl = np.asarray(res.pnl)[np.asarray(res.bar_mask)]
    np.testing.assert_allclose(got_pnl, pnl_o, rtol=1e-9, atol=1e-8)
    assert int(res.n_trades) == len(trades_o)
    np.testing.assert_array_equal(np.asarray(res.positions)[:, -1], pos_o)
    assert abs(float(res.cash[-1]) - cash_o) < 1e-6
    assert abs(float(res.total_pnl) - pnl_o.sum()) < 1e-6


def test_no_trades_flat_pnl(rng):
    price, valid, score, adv, vol = _scenario(rng)
    res = event_backtest(price, valid, np.zeros_like(price), adv, vol)
    assert int(res.n_trades) == 0
    np.testing.assert_allclose(np.asarray(res.pnl), 0.0, atol=1e-12)


@requires_reference
def test_golden_fingerprint():
    """SURVEY §2 row 17 / BASELINE.md: the shipped results/trades.csv is exactly
    reproducible — 28,020 trades (17,433 buys / 10,587 sells), net notional
    $90,084,558.39, sum(impact) 0.14418347, total PnL $765,431.87, and the
    ridge CV MSEs.  Daily maps use 19 tickers (the reference's own AAPL cache
    bug), intraday all 20."""
    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
    daily_df = load_daily(REFERENCE_DATA, MEASURED_TICKERS)
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df
    )

    np.testing.assert_allclose(
        np.asarray(fit.cv_mse), [2.971e-07, 1.801e-06, 3.346e-07], rtol=1e-3
    )
    assert int(res.n_trades) == 28_020
    assert int(res.n_buys) == 17_433
    assert int(res.n_sells) == 10_587
    assert abs(float(res.net_notional) - 90_084_558.39) < 0.01
    assert abs(float(res.total_pnl) - 765_431.87) < 0.01
    impact_sum = float(
        np.asarray(res.impact) @ np.abs(np.asarray(res.trade_side)).sum(axis=1)
    )
    assert abs(impact_sum - 0.14418347) < 1e-7

    # trade log matches the shipped golden CSV row-for-row
    golden = pd.read_csv(f"{REFERENCE_DATA}/../results/trades.csv")
    ours = trades_dataframe(res, compact.tickers, compact.times, np.asarray(dense_score))
    assert len(ours) == len(golden)
    np.testing.assert_array_equal(ours["ticker"].values, golden["ticker"].values)
    np.testing.assert_array_equal(ours["size"].values, golden["size"].values)
    np.testing.assert_allclose(ours["price"].values, golden["price"].values, rtol=1e-9)
    np.testing.assert_allclose(ours["impact"].values, golden["impact"].values, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(ours["score"].values, golden["score"].values, rtol=1e-6, atol=1e-12)


@requires_reference
def test_golden_fingerprint_f32():
    """The same golden workload in float32 — the dtype the TPU path actually
    runs (tests run on CPU but the numerics are the panel program's, not the
    platform's).  The documented f32 tolerance (bench.py GOLDEN_TRADE_TOL):
    a handful of threshold crossings sit within one f32 ulp of the 1e-5
    score threshold, so the trade count may drift by up to ±4; the dollar
    aggregates stay within float32 relative error of the f64 answers."""
    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
    daily_df = load_daily(REFERENCE_DATA, MEASURED_TICKERS)
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df, dtype=np.float32
    )
    assert np.asarray(dense_price).dtype == np.float32
    assert abs(int(res.n_trades) - 28_020) <= 4
    assert abs(int(res.n_buys) - 17_433) <= 4
    assert abs(int(res.n_sells) - 10_587) <= 4
    # ~$90M notional at f32 precision (2^-24 relative): dollars, not cents
    assert abs(float(res.net_notional) - 90_084_558.39) / 90_084_558.39 < 1e-4
    assert abs(float(res.total_pnl) - 765_431.87) / 765_431.87 < 5e-3
