"""Event engine: loop-oracle equivalence + the shipped golden fingerprint."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.backtest.event import event_backtest, trades_dataframe
from csmom_tpu.costs import market_fill
from tests.conftest import DEMO_TICKERS, MEASURED_TICKERS, requires_reference, REFERENCE_DATA


def oracle_event_loop(price, valid, score, adv, vol, size=50, thr=1e-5, cash0=1e6):
    """Reference SimpleEventBacktester semantics (backtester.py:20-65) as a
    plain Python loop over the dense panel."""
    A, T = price.shape
    positions = np.zeros(A, dtype=int)
    cash = cash0
    last_pv = None
    pnl = []
    trades = []
    last_price = np.full(A, np.nan)
    for t in range(T):
        if not valid[:, t].any():
            continue
        for a in range(A):
            if valid[a, t]:
                s = score[a, t]
                if s > thr or s < -thr:
                    side = 1 if s > thr else -1
                    ep, imp = market_fill(price[a, t], size, adv[a], vol[a], side)
                    positions[a] += side * size
                    cash -= float(ep) * side * size
                    trades.append((t, a, side * size, float(ep), float(imp), s))
        for a in range(A):
            if valid[a, t]:
                last_price[a] = price[a, t]
        pv = cash + np.nansum(np.where(np.isfinite(last_price), positions * last_price, 0.0))
        pnl.append(0.0 if last_pv is None else pv - last_pv)
        last_pv = pv
    return np.array(pnl), trades, positions, cash


def _scenario(rng, A=5, T=120):
    price = 100 * np.exp(np.cumsum(rng.normal(0, 1e-3, size=(A, T)), axis=1))
    valid = rng.random((A, T)) > 0.2
    valid[:, 0] = [True, True, False, False, True]  # staggered starts
    score = rng.normal(0, 1e-4, size=(A, T))
    score[np.abs(score) < 2e-5] = 0.0  # exercise the threshold edge
    adv = np.array([1e5, 2e6, 1e5, 5e4, 1e7])
    vol = np.array([0.02, 0.4, 0.02, 0.01, 0.15])
    price[~valid] = np.nan
    return price, valid, score, adv, vol


def test_matches_loop_oracle(rng):
    price, valid, score, adv, vol = _scenario(rng)
    res = event_backtest(price, valid, np.nan_to_num(score), adv, vol)
    pnl_o, trades_o, pos_o, cash_o = oracle_event_loop(price, valid, score, adv, vol)

    got_pnl = np.asarray(res.pnl)[np.asarray(res.bar_mask)]
    np.testing.assert_allclose(got_pnl, pnl_o, rtol=1e-9, atol=1e-8)
    assert int(res.n_trades) == len(trades_o)
    np.testing.assert_array_equal(np.asarray(res.positions)[:, -1], pos_o)
    assert abs(float(res.cash[-1]) - cash_o) < 1e-6
    assert abs(float(res.total_pnl) - pnl_o.sum()) < 1e-6


def test_no_trades_flat_pnl(rng):
    price, valid, score, adv, vol = _scenario(rng)
    res = event_backtest(price, valid, np.zeros_like(price), adv, vol)
    assert int(res.n_trades) == 0
    np.testing.assert_allclose(np.asarray(res.pnl), 0.0, atol=1e-12)


@requires_reference
def test_golden_fingerprint():
    """SURVEY §2 row 17 / BASELINE.md: the shipped results/trades.csv is exactly
    reproducible — 28,020 trades (17,433 buys / 10,587 sells), net notional
    $90,084,558.39, sum(impact) 0.14418347, total PnL $765,431.87, and the
    ridge CV MSEs.  Daily maps use 19 tickers (the reference's own AAPL cache
    bug), intraday all 20."""
    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
    daily_df = load_daily(REFERENCE_DATA, MEASURED_TICKERS)
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df
    )

    np.testing.assert_allclose(
        np.asarray(fit.cv_mse), [2.971e-07, 1.801e-06, 3.346e-07], rtol=1e-3
    )
    assert int(res.n_trades) == 28_020
    assert int(res.n_buys) == 17_433
    assert int(res.n_sells) == 10_587
    assert abs(float(res.net_notional) - 90_084_558.39) < 0.01
    assert abs(float(res.total_pnl) - 765_431.87) < 0.01
    impact_sum = float(
        np.asarray(res.impact) @ np.abs(np.asarray(res.trade_side)).sum(axis=1)
    )
    assert abs(impact_sum - 0.14418347) < 1e-7

    # trade log matches the shipped golden CSV row-for-row
    golden = pd.read_csv(f"{REFERENCE_DATA}/../results/trades.csv")
    ours = trades_dataframe(res, compact.tickers, compact.times, np.asarray(dense_score))
    assert len(ours) == len(golden)
    np.testing.assert_array_equal(ours["ticker"].values, golden["ticker"].values)
    np.testing.assert_array_equal(ours["size"].values, golden["size"].values)
    np.testing.assert_allclose(ours["price"].values, golden["price"].values, rtol=1e-9)
    np.testing.assert_allclose(ours["impact"].values, golden["impact"].values, rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(ours["score"].values, golden["score"].values, rtol=1e-6, atol=1e-12)


@requires_reference
def test_cv_mse_golden():
    """BASELINE.md's measured intraday ridge CV fold MSEs, pinned as their
    own golden: the reference's ``train_ridge_time_series``
    (``/root/reference/src/models.py:8-22``) on the shipped caches produces
    per-fold MSEs [2.97e-07, 1.80e-06, 3.35e-07] (3 expanding folds,
    alpha=1.0, scaler leak replicated by design — SURVEY §2.1.4); our
    one-jit harness must land on all three."""
    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
    daily_df = load_daily(REFERENCE_DATA, MEASURED_TICKERS)
    _, fit, *_ = intraday_pipeline(minute_df, daily_df)
    got = np.asarray(fit.cv_mse)
    want = np.array([2.97e-07, 1.80e-06, 3.35e-07])  # BASELINE.md:22
    assert got.shape == (3,)
    np.testing.assert_allclose(got, want, rtol=5e-3)


@requires_reference
@pytest.mark.slow
def test_golden_fingerprint_f32():
    """The same golden workload in float32 — the dtype the TPU path actually
    runs (tests run on CPU but the numerics are the panel program's, not the
    platform's).  The documented f32 tolerance (bench.py GOLDEN_TRADE_TOL):
    a handful of threshold crossings sit within one f32 ulp of the 1e-5
    score threshold, so the trade count may drift by up to ±4; the dollar
    aggregates stay within float32 relative error of the f64 answers."""
    from csmom_tpu.api import intraday_pipeline
    from csmom_tpu.panel.ingest import load_daily, load_intraday

    minute_df = load_intraday(REFERENCE_DATA, DEMO_TICKERS)
    daily_df = load_daily(REFERENCE_DATA, MEASURED_TICKERS)
    res, fit, compact, dense_score, dense_price, dense_valid = intraday_pipeline(
        minute_df, daily_df, dtype=np.float32
    )
    assert np.asarray(dense_price).dtype == np.float32
    assert abs(int(res.n_trades) - 28_020) <= 4
    assert abs(int(res.n_buys) - 17_433) <= 4
    assert abs(int(res.n_sells) - 10_587) <= 4
    # ~$90M notional at f32 precision (2^-24 relative): dollars, not cents
    assert abs(float(res.net_notional) - 90_084_558.39) / 90_084_558.39 < 1e-4
    assert abs(float(res.total_pnl) - 765_431.87) / 765_431.87 < 5e-3


class TestCostAttribution:
    def _run(self, rng, order_type="market", **kw):
        from csmom_tpu.backtest.event import cost_attribution, event_backtest

        A, T = 6, 120
        price = np.abs(rng.normal(100, 5, size=(A, T)))
        valid = rng.random((A, T)) > 0.1
        score = rng.normal(0, 3e-5, size=(A, T))
        adv = np.full(A, 1e5)
        vol = np.full(A, 0.02)
        price = np.where(valid, price, np.nan)
        res = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                             order_type=order_type, **kw)
        return res, cost_attribution(res, price)

    def test_identities_market(self, rng):
        """gross = net + cost; the formula split is exact for market fills
        (residual ~ 0); every leg is non-negative."""
        res, tca = self._run(rng)
        assert int(res.n_trades) > 0
        assert float(tca.gross_pnl) == pytest.approx(
            float(tca.net_pnl) + float(tca.total_cost), abs=1e-9
        )
        assert abs(float(tca.residual)) < 1e-9 * max(1.0, float(tca.total_cost))
        assert float(tca.spread_cost) > 0
        assert float(tca.impact_cost) > 0
        assert float(tca.total_cost) > 0
        assert 0 < float(tca.cost_bps) < 100

    def test_matches_trade_log(self, rng):
        """total_cost equals per-trade slippage reconstructed independently:
        mid = fill / (1 + side*(spread/2 + impact)) inverts the market-fill
        formula, so |fill - mid| * size summed over fills is the cost."""
        res, tca = self._run(rng)
        side = np.asarray(res.trade_side, dtype=np.float64)
        fill = np.asarray(res.exec_price)
        traded = side != 0
        frac = 0.001 / 2 + np.asarray(res.impact)[:, None]
        mid = fill / (1 + side * np.where(traded, frac, 0))
        want = (np.abs(fill - mid)[traded] * 50).sum()
        assert float(tca.total_cost) == pytest.approx(want, rel=1e-9)

    def test_limit_mode_cost_identity(self, rng):
        """Limit fills execute at mid*(1 - 0.5*agg*spread) regardless of
        side, so total cost reduces exactly to
        0.5*agg*spread*size*(sell mid notional - buy mid notional) —
        buys earn the improvement, sells pay it."""
        import jax

        agg, spread = 0.5, 0.001
        res, tca = self._run(rng, order_type="limit",
                             fill_key=jax.random.PRNGKey(3),
                             aggressiveness=agg)
        if int(res.n_trades) == 0:
            pytest.skip("no limit fills under this seed")
        side = np.asarray(res.trade_side, dtype=np.float64)
        fill = np.asarray(res.exec_price)
        mid = fill / (1 - 0.5 * agg * spread)
        want = 0.5 * agg * spread * 50 * (
            mid[side < 0].sum() - mid[side > 0].sum()
        )
        assert float(tca.total_cost) == pytest.approx(want, rel=1e-9)

    def test_latency_needs_valid_mask(self, rng):
        from csmom_tpu.backtest.event import cost_attribution

        res, _ = self._run(rng)
        with pytest.raises(ValueError, match="valid"):
            cost_attribution(res, np.ones((6, 120)), latency_bars=2)

    def test_latency_shortfall_decomposition(self, rng):
        """With a fill delay, total shortfall (vs the decision mid) splits
        into drift (decision->settlement mid) + the execution legs priced
        off the settlement mid, residual ~0 for market fills; the
        execution leg reconstructs independently by inverting the fill
        formula per trade."""
        from csmom_tpu.backtest.event import cost_attribution, event_backtest

        A, T, lat = 6, 120, 3
        price = np.abs(rng.normal(100, 5, size=(A, T)))
        valid = rng.random((A, T)) > 0.1
        score = rng.normal(0, 3e-5, size=(A, T))
        adv = np.full(A, 1e5)
        vol = np.full(A, 0.02)
        price = np.where(valid, price, np.nan)
        res = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                             latency_bars=lat)
        assert int(res.n_trades) > 0
        tca = cost_attribution(res, price, latency_bars=lat, valid=valid)

        # identities
        assert float(tca.gross_pnl) == pytest.approx(
            float(tca.net_pnl) + float(tca.total_cost), abs=1e-9
        )
        scale = max(1.0, abs(float(tca.total_cost)))
        assert abs(float(tca.residual)) < 1e-9 * scale
        assert float(tca.spread_cost) > 0 and float(tca.impact_cost) > 0

        # independent oracle: settlement mid from the fill formula inverse,
        # decision mid from the panel; drift = settle - decision per trade
        side = np.asarray(res.trade_side, dtype=np.float64)
        fill = np.asarray(res.exec_price)
        traded = side != 0
        frac = 0.001 / 2 + np.asarray(res.impact)[:, None]
        settle_mid = fill / (1 + side * np.where(traded, frac, 0))
        dec_mid = np.nan_to_num(price)
        want_delay = ((settle_mid - dec_mid) * side)[traded].sum() * 50
        want_total = ((fill - dec_mid) * side)[traded].sum() * 50
        assert float(tca.delay_cost) == pytest.approx(want_delay, rel=1e-9)
        assert float(tca.total_cost) == pytest.approx(want_total, rel=1e-9)

    def test_zero_latency_has_zero_delay_cost(self, rng):
        res, tca = self._run(rng)
        assert float(tca.delay_cost) == 0.0


def test_threshold_sweep_matches_single_runs(rng):
    """Each sweep lane equals a standalone run at that threshold; the trade
    count is non-increasing in the threshold."""
    from csmom_tpu.backtest.event import event_backtest, threshold_sweep

    A, T = 5, 150
    price = np.abs(rng.normal(100, 5, size=(A, T)))
    valid = rng.random((A, T)) > 0.1
    score = rng.normal(0, 3e-5, size=(A, T))
    price = np.where(valid, price, np.nan)
    adv = np.full(A, 1e5)
    vol = np.full(A, 0.02)
    ths = np.array([1e-6, 1e-5, 5e-5])

    pnl, ntr, bps = threshold_sweep(price, valid, np.nan_to_num(score),
                                    adv, vol, ths)
    assert (np.diff(np.asarray(ntr)) <= 0).all()
    for k, th in enumerate(ths):
        one = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                             threshold=float(th))
        assert int(ntr[k]) == int(one.n_trades)
        np.testing.assert_allclose(float(pnl[k]), float(one.total_pnl),
                                   rtol=1e-12)


def test_threshold_sweep_supports_latency(rng):
    """Latency sweeps attribute through the shortfall path (the old guard
    raised here): the lane matches a standalone latency run."""
    from csmom_tpu.backtest.event import (
        cost_attribution, event_backtest, threshold_sweep,
    )

    price, valid, score, adv, vol = _scenario(rng)
    pnl, ntr, bps = threshold_sweep(price, valid, np.nan_to_num(score),
                                    adv, vol, np.array([1e-5]),
                                    latency_bars=2)
    res = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         latency_bars=2)
    tca = cost_attribution(res, price, latency_bars=2, valid=valid)
    assert float(pnl[0]) == pytest.approx(float(res.total_pnl), abs=1e-6)
    assert int(ntr[0]) == int(res.n_trades)
    assert float(bps[0]) == pytest.approx(float(tca.cost_bps), rel=1e-9)
