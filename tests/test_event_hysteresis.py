"""Schmitt-trigger event engine vs a sequential state-machine oracle."""

import numpy as np
import pytest

from csmom_tpu.backtest import hysteresis_event_backtest
from csmom_tpu.costs.impact import square_root_impact


def _workload(rng, A=6, T=200):
    price = 100 * np.exp(np.cumsum(rng.normal(0, 1e-3, size=(A, T)), axis=1))
    valid = rng.random((A, T)) > 0.15
    score = rng.normal(0, 1e-4, size=(A, T))
    price = np.where(valid, price, np.nan)
    adv = np.full(A, 1e5)
    vol = np.full(A, 0.02)
    return price, valid, score, adv, vol


def _oracle_states(valid, score, hi, lo):
    """The sequential trigger, written as the obvious per-asset loop."""
    A, T = score.shape
    tgt = np.zeros((A, T), np.int32)
    for a in range(A):
        st = 0
        for t in range(T):
            if valid[a, t]:
                s = score[a, t]
                if s > hi:
                    st = 1
                elif s < -hi:
                    st = -1
                elif abs(s) < lo:
                    st = 0
                # else: hold (the hysteresis band)
            tgt[a, t] = st
    return tgt


def test_states_match_sequential_oracle(rng):
    price, valid, score, adv, vol = _workload(rng)
    hi, lo = 1.2e-4, 4e-5
    res = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=hi, threshold_lo=lo,
                                    size_shares=50)
    want = _oracle_states(valid, score, hi, lo) * 50
    np.testing.assert_array_equal(np.asarray(res.positions), want)


def test_accounting_and_fills(rng):
    """Trades only at valid cells; positions bounded at one unit; cash +
    marked positions == portfolio value; fills follow the market formula."""
    price, valid, score, adv, vol = _workload(rng)
    res = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=1e-4, threshold_lo=3e-5,
                                    size_shares=50, cash0=1e6, spread=0.001)
    side = np.asarray(res.trade_side)
    assert (side[~valid] == 0).all()
    pos = np.asarray(res.positions)
    assert np.abs(pos).max() <= 50
    assert int(res.n_trades) > 0

    # accounting identity at the last bar
    T = price.shape[1]
    mark = np.zeros_like(np.nan_to_num(price))
    for a in range(price.shape[0]):
        last = 0.0
        for t in range(T):
            if valid[a, t]:
                last = price[a, t]
            mark[a, t] = last
    pv_want = np.asarray(res.cash) + (pos * mark).sum(axis=0)
    np.testing.assert_allclose(np.asarray(res.portfolio_value), pv_want,
                               rtol=1e-12)

    # fill price formula at traded cells: the market-fill side is the
    # DIRECTION (±1) even when the stored trade units are ±2 (a flip)
    imp = np.asarray(
        square_root_impact(np.float64(50), adv.astype(float),
                           vol.astype(float)))
    a_idx, t_idx = np.nonzero(side)
    want_fill = price[a_idx, t_idx] * (
        1.0 + np.sign(side[a_idx, t_idx]) * (0.001 / 2.0 + imp[a_idx]))
    np.testing.assert_allclose(np.asarray(res.exec_price)[a_idx, t_idx],
                               want_fill, rtol=1e-12)


def test_wider_band_trades_less(rng):
    """Lowering the exit threshold widens the hold band, which can only
    remove exits (and the re-entries they enable): trades nonincreasing."""
    price, valid, score, adv, vol = _workload(rng, A=10, T=400)
    hi = 1e-4
    counts = []
    for lo in (1e-4, 5e-5, 1e-5):
        r = hysteresis_event_backtest(price, valid, score, adv, vol,
                                      threshold_hi=hi, threshold_lo=lo)
        counts.append(int(r.n_trades))
    assert counts[0] >= counts[1] >= counts[2]


def test_threshold_order_validated(rng):
    price, valid, score, adv, vol = _workload(rng, A=2, T=20)
    with pytest.raises(ValueError, match="must not exceed"):
        hysteresis_event_backtest(price, valid, score, adv, vol,
                                  threshold_hi=1e-5, threshold_lo=1e-4)


def test_flip_reports_two_units(rng):
    """A long->short flip is one 2-unit fill: the trade log reports ±100
    shares (size_shares=50) and TCA weights the fill's spread/impact legs
    twice — the consumers must see true size, not the ±1 direction."""
    from csmom_tpu.backtest import cost_attribution, trades_dataframe

    T = 8
    price = np.full((1, T), 100.0)
    valid = np.ones((1, T), bool)
    # enter long at t=1, flip short at t=3, exit at t=5
    score = np.array([[0.0, 2e-4, 5e-5, -2e-4, -5e-5, 1e-6, 0.0, 0.0]])
    adv = np.full(1, 1e5)
    vol = np.full(1, 0.02)
    res = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=1e-4, threshold_lo=1e-5,
                                    size_shares=50)
    side = np.asarray(res.trade_side)[0]
    np.testing.assert_array_equal(side, [0, 1, 0, -2, 0, 1, 0, 0])

    trades = trades_dataframe(res, ["X"], np.arange(T), score,
                              size_shares=50)
    assert list(trades["size"]) == [50, -100, 50]

    tca = cost_attribution(res, price, size_shares=50)
    # 4 units traded at mid 100: gross notional = 4 * 50 * 100
    np.testing.assert_allclose(float(tca.gross_notional), 4 * 50 * 100.0)
    # exact slippage == formula split (market fills): residual ~ 0
    np.testing.assert_allclose(float(tca.residual), 0.0, atol=1e-9)


def test_latency_settles_at_next_valid_row(rng):
    """Delayed hysteresis fills: per-trade loop oracle — each kept
    decision's shares land at the first valid row >= decision+L at that
    row's fill price; tail decisions with no settlement row are dropped;
    positions are the cumsum of settled shares."""
    price, valid, score, adv, vol = _workload(rng)
    hi, lo, L, sz = 1.2e-4, 4e-5, 3, 50
    res = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=hi, threshold_lo=lo,
                                    size_shares=sz, latency_bars=L)
    A, T = price.shape
    tgt = _oracle_states(valid, score, hi, lo)
    delta = np.diff(np.pad(tgt, ((0, 0), (1, 0))), axis=1)
    imp = np.asarray(square_root_impact(float(sz), adv, vol))

    shares_settle = np.zeros((A, T))
    notional = np.zeros((A, T))
    kept = np.zeros((A, T), np.int32)
    for a in range(A):
        vrows = np.where(valid[a])[0]
        for t in np.where(delta[a] != 0)[0]:
            if t + L > T - 1:
                continue
            later = vrows[vrows >= t + L]
            if len(later) == 0:
                continue
            f = later[0]
            sgn = np.sign(delta[a, t])
            px = price[a, f] * (1 + sgn * (0.001 / 2 + imp[a]))
            shares_settle[a, f] += delta[a, t] * sz
            notional[a, f] += px * delta[a, t] * sz
            kept[a, t] = delta[a, t]
    np.testing.assert_array_equal(
        np.asarray(res.positions), np.cumsum(shares_settle, axis=1)
    )
    np.testing.assert_array_equal(np.asarray(res.trade_side), kept)
    # cash path: cash0 - cumulative settled notional
    np.testing.assert_allclose(
        np.asarray(res.cash),
        1_000_000.0 - np.cumsum(notional.sum(axis=0)),
        rtol=1e-12,
    )


def test_latency_tca_on_hysteresis(rng):
    """Shortfall decomposition holds for the ±2-unit flips under delay."""
    from csmom_tpu.backtest import cost_attribution

    price, valid, score, adv, vol = _workload(rng)
    res = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=1.2e-4, threshold_lo=4e-5,
                                    size_shares=50, latency_bars=2)
    if int(res.n_trades) == 0:
        pytest.skip("no trades under this seed")
    tca = cost_attribution(res, price, latency_bars=2, valid=valid)
    assert float(tca.gross_pnl) == pytest.approx(
        float(tca.net_pnl) + float(tca.total_cost), abs=1e-9
    )
    scale = max(1.0, abs(float(tca.total_cost)))
    assert abs(float(tca.residual)) < 1e-9 * scale
