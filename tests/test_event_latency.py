"""Latency simulation in the event engine vs a numpy loop oracle.

The reference stores ``latency_ms`` but never uses it (backtester.py:8,14,
SURVEY §2.1.7); this extension makes the delay real: decision at row t,
execution at the first event row >= t+L at that row's price.
"""

import pytest

import numpy as np
import jax.numpy as jnp

from csmom_tpu.backtest.event import event_backtest
from csmom_tpu.costs import market_fill


def _workload(rng, a=6, t=40):
    price = 50 + np.cumsum(rng.normal(0, 0.2, size=(a, t)), axis=1)
    valid = rng.random((a, t)) > 0.3
    price = np.where(valid, price, np.nan)
    score = np.where(valid, rng.normal(0, 1e-3, size=(a, t)), 0.0)
    adv = np.full(a, 1e5)
    vol = np.full(a, 0.02)
    return price, valid, score, adv, vol


def oracle_latency(price, valid, score, adv, vol, L, size=50, thr=1e-5, cash0=1e6):
    A, T = price.shape
    fills = []  # (fill_t, a, side, exec_price)
    for a in range(A):
        for t in range(T):
            if not valid[a, t]:
                continue
            s = score[a, t]
            if not (s > thr or s < -thr):
                continue
            side = 1 if s > thr else -1
            # first event row >= t+L
            ft = None
            if t + L <= T - 1:
                for u in range(t + L, T):
                    if valid[a, u]:
                        ft = u
                        break
            if ft is None:
                continue
            ep, _ = market_fill(price[a, ft], size, adv[a], vol[a], side)
            fills.append((ft, a, side, float(ep)))

    positions = np.zeros((A, T), dtype=int)
    notional = np.zeros(T)
    for ft, a, side, ep in fills:
        positions[a, ft:] += side * size
        notional[ft] += ep * side * size
    cash = cash0 - np.cumsum(notional)

    last_price = np.full(A, np.nan)
    pv = np.zeros(T)
    for t in range(T):
        for a in range(A):
            if valid[a, t]:
                last_price[a] = price[a, t]
        marks = np.where(np.isfinite(last_price), last_price, 0.0)
        pv[t] = cash[t] + (positions[:, t] * marks).sum()
    return positions, cash, pv


def test_latency_zero_unchanged(rng):
    """latency_bars=0 must be byte-identical to the parity path."""
    price, valid, score, adv, vol = _workload(rng)
    base = event_backtest(jnp.asarray(price), jnp.asarray(valid),
                          jnp.asarray(score), jnp.asarray(adv), jnp.asarray(vol))
    lat0 = event_backtest(jnp.asarray(price), jnp.asarray(valid),
                          jnp.asarray(score), jnp.asarray(adv), jnp.asarray(vol),
                          latency_bars=0)
    np.testing.assert_array_equal(np.asarray(base.positions), np.asarray(lat0.positions))
    np.testing.assert_array_equal(np.asarray(base.cash), np.asarray(lat0.cash))
    np.testing.assert_array_equal(np.asarray(base.pnl), np.asarray(lat0.pnl))


@pytest.mark.slow
def test_latency_matches_oracle(rng):
    for L in (1, 3, 7):
        price, valid, score, adv, vol = _workload(rng)
        res = event_backtest(jnp.asarray(price), jnp.asarray(valid),
                             jnp.asarray(score), jnp.asarray(adv), jnp.asarray(vol),
                             latency_bars=L)
        w_pos, w_cash, w_pv = oracle_latency(price, valid, score, adv, vol, L)
        np.testing.assert_array_equal(np.asarray(res.positions), w_pos)
        np.testing.assert_allclose(np.asarray(res.cash), w_cash, rtol=1e-12)
        np.testing.assert_allclose(np.asarray(res.portfolio_value), w_pv, rtol=1e-12)


def test_late_orders_dropped(rng):
    """Orders within the last L rows can never fill."""
    price, valid, score, adv, vol = _workload(rng, a=3, t=12)
    L = 100  # > T: nothing fills
    res = event_backtest(jnp.asarray(price), jnp.asarray(valid),
                         jnp.asarray(score), jnp.asarray(adv), jnp.asarray(vol),
                         latency_bars=L)
    assert int(res.n_trades) == 0
    assert (np.asarray(res.positions) == 0).all()
    np.testing.assert_allclose(np.asarray(res.cash), 1e6)


def test_latency_costs_pnl_on_trend(rng):
    """On a strongly trending tape with momentum-sign scores, delayed fills
    execute at worse prices; realized cash spent on buys must be higher."""
    a, t = 4, 60
    price = 50 * np.exp(np.outer(np.ones(a), np.linspace(0, 0.2, t)))
    valid = np.ones((a, t), dtype=bool)
    score = np.full((a, t), 1e-3)  # always buy
    adv = np.full(a, 1e5)
    vol = np.full(a, 0.02)
    r0 = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                        jnp.asarray(adv), jnp.asarray(vol), latency_bars=0)
    r5 = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                        jnp.asarray(adv), jnp.asarray(vol), latency_bars=5)
    # same number of shares bought per surviving order, later+pricier fills
    assert float(r5.net_notional) / int(r5.n_trades) > float(r0.net_notional) / int(r0.n_trades)
