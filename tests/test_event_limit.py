"""Limit-order mode of the event engine (reference's dead code made live)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from csmom_tpu.backtest.event import event_backtest
from tests.test_event_latency import _workload


def test_limit_requires_key(rng):
    price, valid, score, adv, vol = _workload(rng)
    with pytest.raises(ValueError, match="fill_key"):
        event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                       jnp.asarray(adv), jnp.asarray(vol), order_type="limit")


def test_limit_matches_numpy_oracle(rng):
    price, valid, score, adv, vol = _workload(rng, a=5, t=50)
    key = jax.random.PRNGKey(42)
    agg, spread, size, thr = 0.7, 0.001, 50, 1e-5
    res = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                         jnp.asarray(adv), jnp.asarray(vol),
                         order_type="limit", aggressiveness=agg, fill_key=key)

    # oracle: same uniforms (the counter-keyed stream is the engine's PRNG
    # contract — shard-invariance is what's being bought), reference formulas
    from csmom_tpu.backtest.event import counter_uniform

    u = np.asarray(counter_uniform(key, price.shape, 0, 0,
                                   jnp.asarray(price).dtype))
    p_fill = (0.2 + 0.7 * agg) * (1 - 0.5 * np.minimum(1.0, size / np.maximum(1.0, adv)))
    side = np.where(valid & (score > thr), 1, np.where(valid & (score < -thr), -1, 0))
    side = np.where(u < p_fill[:, None], side, 0)
    fillp = np.where(side != 0, np.nan_to_num(price) * (1 - 0.5 * agg * spread), 0.0)
    positions = np.cumsum(side * size, axis=1)
    cash = 1e6 - np.cumsum((fillp * side * size).sum(axis=0))

    np.testing.assert_array_equal(np.asarray(res.positions), positions)
    np.testing.assert_allclose(np.asarray(res.cash), cash, rtol=1e-12)
    assert int(res.n_trades) == int((side != 0).sum())


def test_limit_fills_subset_of_market(rng):
    price, valid, score, adv, vol = _workload(rng, a=8, t=60)
    mkt = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                         jnp.asarray(adv), jnp.asarray(vol))
    lim = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                         jnp.asarray(adv), jnp.asarray(vol),
                         order_type="limit", aggressiveness=0.5,
                         fill_key=jax.random.PRNGKey(0))
    ms, ls = np.asarray(mkt.trade_side), np.asarray(lim.trade_side)
    assert 0 < int(lim.n_trades) < int(mkt.n_trades)
    # every limit fill is a market order that survived the draw
    assert ((ls != 0) <= (ms != 0)).all()
    np.testing.assert_array_equal(ls[ls != 0], ms[ls != 0])


def test_unknown_order_type_raises(rng):
    price, valid, score, adv, vol = _workload(rng)
    with pytest.raises(ValueError, match="order_type"):
        event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                       jnp.asarray(adv), jnp.asarray(vol), order_type="iceberg")
