"""The examples/ scripts must keep running (and keep their golden checks)."""

import os
import runpy
import sys

import pytest

from tests.conftest import REFERENCE_DATA, requires_reference

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(__file__)), "examples")


def _run(script, argv):
    path = os.path.join(EXAMPLES, script)
    old = sys.argv
    sys.argv = [path] + argv
    try:
        runpy.run_path(path, run_name="__main__")
    finally:
        sys.argv = old


@requires_reference
def test_replicate_reference_example(capsys):
    """Runs end to end and its own golden asserts hold (the script raises
    AssertionError on parity drift)."""
    _run("replicate_reference.py", ["--data-dir", REFERENCE_DATA])
    assert "parity OK" in capsys.readouterr().out


@requires_reference
@pytest.mark.slow
def test_strategy_zoo_example(capsys):
    _run("strategy_zoo.py", ["--data-dir", REFERENCE_DATA, "--n-bins", "5"])
    out = capsys.readouterr().out
    for label in ("momentum J=12", "reversal 1m", "residual mom",
                  "52w high (rank)", "volume-z mom"):
        assert label in out


@pytest.mark.slow
def test_north_star_grid_example(capsys):
    _run("north_star_grid.py", ["--assets", "64", "--years", "4"])
    out = capsys.readouterr().out
    assert "16-cell grid in" in out
    assert "walk-forward" in out


def test_pack_at_scale_example(capsys, tmp_path):
    """The at-scale pack workflow demo: its own bit-identity assert holds
    (the script raises on any packed-vs-memory divergence)."""
    _run("pack_at_scale.py", ["--assets", "48", "--years", "4",
                              "--keep", str(tmp_path / "pack")])
    out = capsys.readouterr().out
    assert "bit-identical" in out and "pack kept" in out


@requires_reference
def test_cost_frontier_example(capsys):
    """The band x cost-level frontier runs with its own sanity asserts
    (falling turnover; widest band wins at the highest cost level)."""
    _run("cost_frontier.py", ["--data-dir", REFERENCE_DATA])
    assert "frontier sanity checks passed" in capsys.readouterr().out
