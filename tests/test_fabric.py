"""The r18 horizontal serving fabric: routes view/publisher, router
replicas as supervised processes, client-tier failover, and the
SERVE_FABRIC artifact contract.

Coverage mirrors the tier's layers (ISSUE 14):

- routes: the atomically-published admission view every replica reads
  (roundtrip, torn-file degradation with the reason carried, publisher
  writes only on change);
- client tier: failover on a reset/killed replica converges on
  survivors with CLOSED client books (the fabric's outermost ledger);
- the three-tier end to end: stub-engine worker PROCESSES + real
  supervised router-replica PROCESSES over TCP, one router AND one
  worker SIGKILLed mid-burst — availability 1.0, books closed, the
  artifact schema-valid, ledger rows ingested;
- contracts: the ``serve_fabric`` kind's rejections (broken books, one
  replica, stale hits) and the committable-sidecar naming rule.

No jax in any process (stub engine, serve-smoke buckets) — the fabric's
control plane is deliberately jax-free.
"""

import copy
import json
import os
import threading
import time

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.serve import proto
from csmom_tpu.serve.fabric import (
    FabricClient,
    FabricClientConfig,
    RoutesPublisher,
    RoutesView,
    write_routes,
)
from csmom_tpu.serve.loadgen import (
    LoadConfig,
    run_fabric_loadgen,
    write_artifact,
)
from csmom_tpu.serve.supervisor import PoolConfig

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMOKE = dict(profile="serve-smoke", engine="stub", ready_timeout_s=30.0,
              poll_interval_s=0.05, backoff_base_s=0.05, backoff_cap_s=0.5)


def _panel(n_assets: int, months: int, seed: int = 0):
    r = np.random.default_rng(seed)
    v = 100.0 * np.exp(np.cumsum(r.normal(0, 0.03, (n_assets, months)),
                                 axis=1)).astype(np.float32)
    return v, np.ones((n_assets, months), bool)


# ---------------------------------------------------------------- routes ----

def test_routes_roundtrip_and_view(tmp_path):
    path = str(tmp_path / "routes.json")
    write_routes(path, [("w0", "unix:/tmp/w0.sock"),
                        ("w1", "tcp:127.0.0.1:9001")],
                 retry_after_s=None, cache_version="cv-1")
    view = RoutesView(path)
    workers = view.workers()
    assert [(w.worker_id, w.socket_path) for w in workers] == [
        ("w0", "unix:/tmp/w0.sock"), ("w1", "tcp:127.0.0.1:9001")]
    assert view.retry_after_s() is None
    assert view.cache_version() == "cv-1"
    ok, reason = view.status()
    assert ok and reason is None
    # an empty fleet publishes the backoff hint instead
    write_routes(path, [], retry_after_s=0.8)
    assert view.workers() == []
    assert view.retry_after_s() == 0.8


def test_routes_view_degrades_on_garbage_with_reason(tmp_path):
    path = str(tmp_path / "routes.json")
    view = RoutesView(path)
    ok, reason = view.status()
    assert not ok and "unreadable" in reason
    with open(path, "w") as f:
        f.write("{torn")
    assert view.workers() == []
    ok, reason = view.status()
    assert not ok and "unparseable" in reason
    # a later good write recovers the view
    write_routes(path, [("w0", "/x.sock")], retry_after_s=None)
    assert [w.worker_id for w in view.workers()] == ["w0"]
    assert view.status()[0]


class _FakeSup:
    """Duck-typed supervisor for the publisher: ready set + hint."""

    expect_cache_version = "cv-test"

    def __init__(self):
        self.ready: list = []
        self.hint = 1.5

    def ready_workers(self):
        return list(self.ready)

    def retry_after_s(self):
        return self.hint


class _H:
    def __init__(self, wid, addr):
        self.worker_id = wid
        self.socket_path = addr


def test_routes_view_error_clears_hint_and_version(tmp_path):
    """A broken routes file invalidates the WHOLE view: a retry-after
    hint or cache version surviving from the last good parse would stamp
    outdated state onto every no-worker rejection."""
    path = str(tmp_path / "routes.json")
    write_routes(path, [], retry_after_s=0.8, cache_version="cv-1")
    view = RoutesView(path)
    assert view.retry_after_s() == 0.8
    assert view.cache_version() == "cv-1"
    os.unlink(path)
    assert view.workers() == []
    assert view.retry_after_s() is None, (
        "an unreadable routes file must not keep serving the stale hint")
    assert view.cache_version() is None
    with open(path, "w") as f:
        f.write("{torn")
    assert view.retry_after_s() is None
    assert view.cache_version() is None


def test_routes_publisher_writes_only_on_change(tmp_path):
    path = str(tmp_path / "routes.json")
    sup = _FakeSup()
    sup.ready = [_H("w0", "/a.sock")]
    pub = RoutesPublisher(sup, path, interval_s=10.0)
    assert pub.publish_once() is True
    assert pub.publish_once() is False, "an unchanged fleet must not churn"
    sup.ready = []
    assert pub.publish_once() is True
    view = RoutesView(path)
    assert view.workers() == []
    assert view.retry_after_s() == 1.5, (
        "an empty fleet must publish the backoff hint")
    sup.ready = [_H("w0", "/a.sock")]
    assert pub.publish_once() is True
    assert view.retry_after_s() is None, (
        "a healthy fleet publishes no hint")
    assert pub.publishes == 3


# ----------------------------------------------------------- client tier ----

class _FakeReplica:
    """A hand-rolled router replica speaking the persistent-channel
    serve loop (or resetting every connection when ``reset=True``) —
    the controllable peer the failover tests need."""

    def __init__(self, tmp, rid: str, reset: bool = False):
        self.worker_id = rid
        self.socket_path = os.path.join(tmp, f"{rid}.sock")
        self.reset = reset
        self.scores = 0
        self._stop = threading.Event()
        self._srv = proto.listen(self.socket_path)
        self._srv.settimeout(0.1)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        import socket as _socket

        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except _socket.timeout:
                continue
            except OSError:
                return
            if self.reset:
                conn.close()  # the SIGKILLed replica, as seen by a peer
                continue
            threading.Thread(target=proto.serve_connection,
                             args=(conn, self._handle),
                             daemon=True).start()

    def _handle(self, obj, arrays):
        if obj.get("op") == "score":
            self.scores += 1
            n = arrays["values"].shape[0]
            return ({"state": "served", "router_id": self.worker_id,
                     "worker_id": "w0", "cache_hit": False,
                     "hedged": False},
                    {"result": np.zeros(n, np.float32)})
        return {"ok": True}, None

    def close(self):
        self._stop.set()
        self._srv.close()


def test_fabric_client_fails_over_on_replica_reset(tmp_path):
    """A reset replica (the wire face of a SIGKILL) costs each request
    one failover to the survivor — never a lost request, and the
    client's books close over every attempt."""
    dead = _FakeReplica(str(tmp_path), "r0", reset=True)
    live = _FakeReplica(str(tmp_path), "r1")
    try:
        client = FabricClient(lambda: [dead, live], FabricClientConfig(
            default_deadline_s=5.0))
        v, m = _panel(4, 24)
        reqs = [client.submit("momentum", v, m) for _ in range(6)]
        for r in reqs:
            assert r.wait(8.0) and r.state == "served", (r.state, r.error)
        a = client.accounting()
        assert a["served"] == 6 and a["admitted"] == 6
        assert a["router_conn_failures"] >= 1, (
            "the reset replica never registered as a connection failure")
        assert a["failovers"] >= 1
        assert all(r.router_id == "r1" for r in reqs)
        assert client.invariant_violations() == []
        assert client.availability() == 1.0
    finally:
        dead.close()
        live.close()


def test_fabric_client_rejects_infra_when_no_replica_lives(tmp_path):
    client = FabricClient(lambda: [], FabricClientConfig(
        default_deadline_s=1.0))
    v, m = _panel(4, 24)
    r = client.submit("momentum", v, m)
    assert r.wait(3.0) and r.state == "rejected"
    assert "no ready router replica" in (r.error or "")
    a = client.accounting()
    assert a["rejected_infra"] == 1
    assert client.availability() == 0.0
    assert client.invariant_violations() == []


class _RejectingReplica(_FakeReplica):
    """A replica replying a fixed rejection to every ``score``."""

    def __init__(self, tmp, rid, error, retry_after_s=None, infra=None):
        self.error = error
        self.retry_after_s = retry_after_s
        self.infra = infra
        super().__init__(tmp, rid)

    def _handle(self, obj, arrays):
        if obj.get("op") == "score":
            self.scores += 1
            reply = {
                "state": "rejected", "router_id": self.worker_id,
                "error": self.error,
                "retry_after_s": self.retry_after_s}
            if self.infra is not None:
                reply["infra"] = self.infra
            return reply, None
        return {"ok": True}, None


def test_fabric_client_settles_parked_fleet_rejection_in_one_attempt(
        tmp_path):
    """The door's no-ready-worker rejection mentions "draining" — it must
    settle as rejected_infra on the FIRST replica, not be misread as a
    draining replica and fanned across the whole fabric mid-outage."""
    door = ("no ready worker in the pool (all crashed, parked, or "
            "draining); retry after 0.5s")
    r0 = _RejectingReplica(str(tmp_path), "r0", door, retry_after_s=0.5)
    r1 = _RejectingReplica(str(tmp_path), "r1", door, retry_after_s=0.5)
    try:
        client = FabricClient(lambda: [r0, r1], FabricClientConfig(
            default_deadline_s=5.0))
        v, m = _panel(4, 24)
        req = client.submit("momentum", v, m)
        assert req.wait(8.0) and req.state == "rejected"
        assert req.retry_after_s == 0.5
        assert r0.scores + r1.scores == 1, (
            "a parked-fleet door rejection fanned out across replicas")
        assert client.accounting()["rejected_infra"] == 1
    finally:
        r0.close()
        r1.close()


def test_fabric_client_reads_infra_flag_from_the_wire(tmp_path):
    """A replica whose attempts ALL died on dead wires replies with its
    infra classification ON the reply — the client must count it into
    rejected_infra (availability drops) instead of substring-matching
    error text that doesn't say \"no ready worker\"."""
    err = "all 3 attempt(s) failed: w0: connection failed (reset)"
    r0 = _RejectingReplica(str(tmp_path), "r0", err, infra=True)
    try:
        client = FabricClient(lambda: [r0], FabricClientConfig(
            default_deadline_s=5.0))
        v, m = _panel(4, 24)
        req = client.submit("momentum", v, m)
        assert req.wait(8.0) and req.state == "rejected"
        a = client.accounting()
        assert a["rejected_infra"] == 1, (
            "an infra rejection crossed the wire unclassified — "
            "availability would read 1.0 over lost requests")
        assert client.availability() == 0.0
    finally:
        r0.close()


def test_fabric_client_fails_over_a_genuinely_draining_replica(tmp_path):
    """The replica's OWN drain refusal (rolling restart) is a routing
    miss: the client must try a survivor and serve."""
    draining = _RejectingReplica(str(tmp_path), "r0", "router draining")
    live = _FakeReplica(str(tmp_path), "r1")
    try:
        client = FabricClient(lambda: [draining, live],
                              FabricClientConfig(default_deadline_s=5.0))
        v, m = _panel(4, 24)
        reqs = [client.submit("momentum", v, m) for _ in range(4)]
        for r in reqs:
            assert r.wait(8.0) and r.state == "served", (r.state, r.error)
        assert live.scores == 4
        assert client.accounting()["served"] == 4
    finally:
        draining.close()
        live.close()


# ------------------------------------------------------------ end to end ----

def _build_fabric(tmp, n_workers=2, n_routers=2, transport="tcp",
                  deadline_s=3.0):
    from csmom_tpu.serve.fabric import build_fabric

    return build_fabric(
        PoolConfig(n_workers=n_workers, transport=transport, **_SMOKE),
        PoolConfig(n_workers=n_routers, transport=transport, **_SMOKE),
        tmp, deadline_ms=deadline_s * 1e3, client_deadline_s=deadline_s)


def test_fabric_three_tiers_over_tcp_survive_double_kill(tmp_path):
    """The r18 acceptance shape in miniature: TCP everywhere, 2 router
    replicas x 2 workers, one ROUTER and one WORKER SIGKILLed mid-burst
    — availability 1.0 (no admitted request dies with a corpse), closed
    client books, a schema-valid SERVE_FABRIC artifact, and ledger rows
    ingested from it."""
    wsup, pub, rsup, client = _build_fabric(str(tmp_path))
    try:
        load = LoadConfig(schedule="1.4x40", seed=5, deadline_s=3.0,
                          reuse_fraction=0.5, run_id="r99")

        def double_kill():
            time.sleep(0.3)
            rsup.kill_worker(rsup.handles[0].worker_id)
            time.sleep(0.2)
            wsup.kill_worker(wsup.handles[0].worker_id)
            give_up = time.monotonic() + 30.0
            while time.monotonic() < give_up:
                if all(any(h.generation >= 1 and h.state == "ready"
                           for h in sup.handles)
                       for sup in (rsup, wsup)):
                    return
                time.sleep(0.05)

        art = run_fabric_loadgen(client, rsup, wsup, load,
                                 concurrent=double_kill)
    finally:
        pub.stop()
        rsup.stop()
        wsup.stop()
    assert inv.validate(art, "serve_fabric") == []
    req = art["requests"]
    assert req["admitted"] == req["served"] + req["rejected"] + \
        req["expired"]
    assert art["availability"] == 1.0, (art["availability"], req)
    assert art["routers"]["kills"] == 1 and art["workers"]["kills"] == 1
    assert art["routers"]["restarts"] >= 1
    assert art["workers"]["restarts"] >= 1
    assert req["served"] > 0
    assert art["transport"]["scheme"] == "tcp"
    # repeats exist (reuse 0.5) and affinity lands them on one worker's
    # cache: the PLUMBING must report pool-level hits (the >0.246 claim
    # is the committed r18 artifact's, not this smoke burst's)
    assert req["served_cache_hits"] > 0, (
        "no pool-level cache hit despite 50% panel reuse — the "
        "cache_hit flag or the affinity routing broke")
    assert client.invariant_violations() == []

    # the artifact lands, validates from disk, and feeds the ledger
    path = write_artifact(str(tmp_path), art, prefix="SERVE_FABRIC")
    assert os.path.basename(path) == "SERVE_FABRIC_r99.json"
    assert inv.validate_file(path) == []
    from csmom_tpu.obs import ledger

    rows, notes = ledger.ingest_file(path)
    metrics = {r.metric for r in rows}
    assert {"serve_fabric_throughput_rps", "serve_fabric_availability",
            "serve_fabric_cache_hit_rate",
            "serve_fabric_hedge_rate"} <= metrics, metrics
    p99 = [r for r in rows if r.metric == "serve_fabric_p99_ms"]
    assert p99 and p99[0].samples, "fabric p99 rows must carry samples"


# -------------------------------------------------------------- contracts ----

def _min_fabric_art() -> dict:
    """A minimal VALID serve_fabric artifact (hand-rolled so the
    rejection tests mutate known-good ground)."""
    return {
        "kind": "serve_fabric",
        "schema_version": 1,
        "run_id": "r99",
        "metric": "serve_fabric_throughput_rps",
        "value": 50.0,
        "unit": "req/s",
        "vs_baseline": 1.0,
        "wall_s": 2.0,
        "offered_limited": True,
        "transport": {"scheme": "tcp", "routers": 2, "workers": 2},
        "requests": {"admitted": 10, "served": 9, "rejected": 1,
                     "expired": 0, "rejected_infra": 0,
                     "served_cache_hits": 3, "served_hedged": 1,
                     "router_conn_failures": 1, "failovers": 1},
        "availability": 1.0,
        "cache": {"pool_hit_rate": round(3 / 9, 4),
                  "served_cache_hits": 3, "served": 9,
                  "per_worker_baseline": 0.246,
                  "workers": {"hits": 3, "misses": 6, "lookups": 9,
                              "stale_hits": 0, "stale_blocked": 0,
                              "reporting": 2, "lost": []}},
        "hedge": {"served_hedged": 1, "rate": 0.1,
                  "router_tier": {"hedged": 2, "wins": 1,
                                  "suppressed": 1, "books_lost": []}},
        "latency_ms": {"total": {"p50": 3.0, "p95": 8.0, "p99": 9.0}},
        "routers": {"replicas": [{"router_id": "r0"}, {"router_id": "r1"}],
                    "n_slots": 2, "ready_end": 2, "kills": 1,
                    "restarts": 1, "rolls_completed": 0, "events": []},
        "workers": {"stats": [{"worker_id": "w0"}, {"worker_id": "w1"}],
                    "n_slots": 2, "ready_end": 2, "kills": 1,
                    "restarts": 1, "rolls_completed": 0, "events": []},
        "compile": {"in_window_fresh_compiles": 0},
        "offered": {"schedule": "1x10", "offered_rps": 10.0},
        "extra": {"platform": "stub", "workload": "test"},
    }


def test_serve_fabric_validator_accepts_minimal():
    assert inv.validate(_min_fabric_art(), "serve_fabric") == []
    assert inv.detect_kind(_min_fabric_art()) == "serve_fabric"


def test_serve_fabric_validator_rejects_broken_books():
    art = _min_fabric_art()
    art["requests"]["served"] = 8  # 8 + 1 + 0 != 10
    viols = inv.validate(art, "serve_fabric")
    assert any("client books broken" in v for v in viols), viols


def test_serve_fabric_validator_rejects_single_router():
    art = _min_fabric_art()
    art["transport"]["routers"] = 1
    viols = inv.validate(art, "serve_fabric")
    assert any(">= 2 router replicas" in v for v in viols), viols


def test_serve_fabric_validator_rejects_stale_hit_anywhere():
    art = _min_fabric_art()
    art["cache"]["workers"]["stale_hits"] = 1
    viols = inv.validate(art, "serve_fabric")
    assert any("stale_hits" in v and "structurally" in v
               for v in viols), viols


def test_serve_fabric_validator_rejects_unreconciled_figures():
    art = _min_fabric_art()
    art["availability"] = 0.5
    viols = inv.validate(art, "serve_fabric")
    assert any("does not reconcile" in v for v in viols), viols
    art = _min_fabric_art()
    art["cache"]["pool_hit_rate"] = 0.9
    viols = inv.validate(art, "serve_fabric")
    assert any("pool_hit_rate" in v for v in viols), viols
    art = _min_fabric_art()
    art["hedge"]["rate"] = 0.9
    viols = inv.validate(art, "serve_fabric")
    assert any("hedge.rate" in v for v in viols), viols


def test_serve_fabric_validator_reports_malformed_counters():
    """Malformed request counters must come back as VIOLATIONS, not a
    TypeError out of validate() — the reconcile blocks divide by them."""
    for bad in ("10", None, 10.5, True):
        art = _min_fabric_art()
        art["requests"]["admitted"] = bad
        viols = inv.validate(art, "serve_fabric")
        assert any("requests.admitted" in v for v in viols), (bad, viols)


def test_kill_mid_burst_tied_offsets_do_not_crash():
    """Tied kill offsets used to fall through the tuple sort to
    comparing unorderable supervisors — the TypeError surfaced only
    after the whole load burst, losing the artifact."""
    from csmom_tpu.serve.fabric import kill_mid_burst

    class _Handle:
        def __init__(self, wid, generation=0):
            self.worker_id = wid
            self.generation = generation
            self.state = "ready"

    class _Sup:
        def __init__(self, *handles):
            self.handles = list(handles)
            self.killed = []

        def kill_worker(self, wid):
            self.killed.append(wid)
            self.handles[0].generation += 1  # "replacement" is ready

    r, w = _Sup(_Handle("r0")), _Sup(_Handle("w0"))
    assert kill_mid_burst([(0.01, r, "router"), (0.01, w, "worker")],
                          settle_timeout_s=5.0) is True
    assert r.killed == ["r0"] and w.killed == ["w0"]
    # falsy offsets are dropped (the single-kill CLI paths)
    r2 = _Sup(_Handle("r0"))
    assert kill_mid_burst([(0.0, r2, "router")], settle_timeout_s=1.0)
    assert r2.killed == []


def test_kill_mid_burst_settles_on_the_victims_slot_only():
    """A previously-flaky NON-victim slot already at generation >= 1
    must not read as settled while the victim's replacement is still
    spawning — books are built only from a SETTLED fleet."""
    from csmom_tpu.serve.fabric import kill_mid_burst

    class _Handle:
        def __init__(self, wid, generation=0):
            self.worker_id = wid
            self.generation = generation
            self.state = "ready"

    class _Sup:
        def __init__(self, *handles):
            self.handles = list(handles)

        def kill_worker(self, wid):
            pass  # the replacement never arrives

    sup = _Sup(_Handle("w0"), _Handle("w1", generation=1))
    assert kill_mid_burst([(0.01, sup, "worker")],
                          settle_timeout_s=0.3,
                          poll_interval_s=0.02) is False, (
        "the flaky non-victim slot must not satisfy the settle check")


def test_fabric_committable_sidecar_naming():
    assert inv.committable_sidecar("SERVE_FABRIC_r18.json")
    assert not inv.committable_sidecar("SERVE_FABRIC_smoke.json")
    assert not inv.committable_sidecar("SERVE_FABRIC_rehearse_x.json")
    assert not inv.committable_sidecar("SERVE_FABRIC_loadgen-123.json")


def test_ledger_refuses_unknown_serve_fabric_schema(tmp_path):
    from csmom_tpu.obs import ledger

    art = _min_fabric_art()
    art["schema_version"] = 99
    p = tmp_path / "SERVE_FABRIC_r99.json"
    p.write_text(json.dumps(art))
    rows, notes = ledger.ingest_file(str(p))
    assert rows == []
    assert notes and "unknown serve_fabric schema_version" in \
        notes[0]["note"]


def test_committed_serve_fabric_artifacts_validate():
    """Every committed SERVE_FABRIC_rNN.json at the repo root must pass
    its own schema — same rule as every other artifact family."""
    import glob

    paths = sorted(glob.glob(os.path.join(_REPO, "SERVE_FABRIC_*.json")))
    for path in paths:
        base = os.path.basename(path)
        assert inv.committable_sidecar(base), (
            f"{base} is committed but is not a round artifact name")
        assert inv.validate_file(path) == [], base
