"""Fetch layer: injectable backend, cache roundtrip, fault isolation."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.panel.fetch import (
    cache_path,
    fetch_daily,
    fetch_intraday,
    get_shares_info,
    CACHE_VERSION,
)
from tests.conftest import REFERENCE_DATA, requires_reference


def fake_daily_vendor(ticker, start, end):
    """yfinance-shaped daily frame: datetime index, title-case columns."""
    idx = pd.date_range(start, periods=40, freq="B")
    base = {"A": 100.0, "B": 50.0, "C": 20.0}.get(ticker, 10.0)
    close = base + np.arange(40) * 0.5
    return pd.DataFrame(
        {
            "Open": close - 0.2,
            "High": close + 0.3,
            "Low": close - 0.4,
            "Close": close,
            "Adj Close": close * 0.99,
            "Volume": 1_000_000 + np.arange(40),
        },
        index=idx,
    )


def fake_intraday_vendor(ticker, period, interval):
    idx = pd.date_range("2025-01-02 09:30", periods=30, freq="min")
    return pd.DataFrame(
        {"Close": 100 + np.arange(30) * 0.01, "Volume": 500 + np.arange(30)},
        index=idx,
    )


def test_fetch_daily_writes_versioned_cache(tmp_path):
    df = fetch_daily(["A", "B"], data_dir=str(tmp_path), fetcher=fake_daily_vendor)
    assert set(df.ticker) == {"A", "B"}
    assert len(df) == 80
    assert list(df.columns) == [
        "date", "ticker", "open", "high", "low", "close", "adj_close", "volume"
    ]
    p = cache_path(str(tmp_path), "A", "daily")
    first = open(p).readline()
    assert CACHE_VERSION in first


def test_cache_roundtrip_identical(tmp_path):
    """A cache written by this fetcher always re-reads to the same frame —
    the §2.1.1 bug class (write-ok/read-zero) is structurally excluded."""
    df1 = fetch_daily(["A"], data_dir=str(tmp_path), fetcher=fake_daily_vendor)

    def exploding(t, s, e):
        raise AssertionError("network must not be touched on cache hit")

    df2 = fetch_daily(["A"], data_dir=str(tmp_path), fetcher=exploding)
    pd.testing.assert_frame_equal(df1, df2)


def test_force_refresh_busts_cache(tmp_path):
    fetch_daily(["A"], data_dir=str(tmp_path), fetcher=fake_daily_vendor)
    calls = []

    def counting(t, s, e):
        calls.append(t)
        return fake_daily_vendor(t, s, e)

    fetch_daily(["A"], data_dir=str(tmp_path), force_refresh=True, fetcher=counting)
    assert calls == ["A"]


def test_per_ticker_fault_isolation(tmp_path):
    """One failing ticker is skipped with a warning, not fatal
    (data_io.py:173-175 behaviour)."""

    def flaky(t, s, e):
        if t == "BAD":
            raise ConnectionError("boom")
        return fake_daily_vendor(t, s, e)

    df = fetch_daily(["A", "BAD", "B"], data_dir=str(tmp_path), fetcher=flaky)
    assert set(df.ticker) == {"A", "B"}


def test_empty_universe_returns_schema_frame(tmp_path):
    df = fetch_daily([], data_dir=str(tmp_path))
    assert len(df) == 0
    assert "adj_close" in df.columns


def test_corrupt_cache_is_loud_not_silent(tmp_path):
    p = cache_path(str(tmp_path), "A", "daily")
    with open(p, "w") as f:
        f.write("garbage,header\nonly,junk\n")
    # per-ticker isolation turns the raise into a skip-with-warning;
    # the ticker must NOT come back with silently-zero rows
    df = fetch_daily(["A"], data_dir=str(tmp_path), fetcher=None)
    assert len(df) == 0


def test_fetch_intraday_roundtrip(tmp_path):
    df = fetch_intraday(["A"], data_dir=str(tmp_path), fetcher=fake_intraday_vendor)
    assert list(df.columns) == ["datetime", "ticker", "price", "volume"]
    assert len(df) == 30
    df2 = fetch_intraday(["A"], data_dir=str(tmp_path),
                         fetcher=lambda *a: (_ for _ in ()).throw(AssertionError()))
    pd.testing.assert_frame_equal(df, df2)


@requires_reference
def test_reference_caches_are_valid_cache_hits(tmp_path):
    """The reference's shipped data/ dir (both dialects) is directly usable
    as a cache directory — including AAPL's dialect-B file."""
    df = fetch_daily(["AAPL", "AMD"], data_dir=REFERENCE_DATA,
                     fetcher=lambda *a: (_ for _ in ()).throw(AssertionError()))
    assert (df.ticker == "AAPL").sum() > 1700
    assert (df.ticker == "AMD").sum() > 1700


def test_get_shares_info_injection_and_isolation():
    def info(t):
        if t == "BAD":
            raise KeyError("no info")
        return {"sharesOutstanding": 1000, "marketCap": 5000}

    out = get_shares_info(["A", "BAD"], info_fn=info)
    assert out["A"] == {"shares_outstanding": 1000, "market_cap": 5000}
    assert out["BAD"] == {"shares_outstanding": None, "market_cap": None}


def test_multiindex_vendor_columns(tmp_path):
    """Modern yfinance returns MultiIndex (field, ticker) columns."""

    def mi_vendor(t, s, e):
        df = fake_daily_vendor(t, s, e)
        df.columns = pd.MultiIndex.from_product([df.columns, [t]])
        return df

    df = fetch_daily(["A"], data_dir=str(tmp_path), fetcher=mi_vendor)
    assert len(df) == 40
    assert df["adj_close"].notna().all()
