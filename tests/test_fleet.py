"""Fleet observatory (ISSUE 19): continuous cross-process metrics time
series, kill-window capacity accounting, and demand telemetry.

The contracts pinned here:

- **snapshot identity + sequence** (obs/metrics satellites): every
  snapshot is process-identity-stamped and carries a strictly advancing
  per-process sequence number; ``snapshot_delta`` refuses cross-process
  splices, non-advancing sequences, and counters that went backwards —
  loudly, because a smoothed-over regression would poison every
  downstream cumulative series;
- **zero-cost disarmed**: with no aggregator armed, the demand hook is
  one global load + compare and does no allocation-visible work;
- **stream books close with a reason, always**: a clean emitter fins, a
  severed connection (the SIGKILL signature) reason-closes on EOF, and a
  dead aggregator costs the emitter one counted drop per tick — never a
  stalled thread;
- the **capacity account** is pure arithmetic over measured lifecycle
  stamps: chaos kills AND monitor-detected deaths open kill windows
  (deduped per incident), unreplaced victims stay honestly open-ended;
- the ``fleet`` artifact schema refuses doctored evidence (non-monotone
  counter series, unreconciled demand, orphan series, unclosed books),
  its sidecars obey the committable-naming rule, and the ledger ingests
  its rows with CI-backing samples.
"""

import gc
import json
import os
import sys
import time

import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.obs import fleet as obs_fleet
from csmom_tpu.obs import metrics
from csmom_tpu.obs import spans as obs_spans
from csmom_tpu.utils.deadline import mono_now_s


@pytest.fixture(autouse=True)
def _clean_observatory():
    obs_fleet.disarm("test setup")
    metrics.reset()
    yield
    obs_fleet.disarm("test teardown")
    obs_spans.disarm()
    metrics.reset()


def _snap():
    return metrics.snapshot(include_compile=False)


# ------------------------------------------ snapshot identity + deltas ----

def test_snapshot_carries_identity_and_advancing_seq():
    obs_spans.arm(None, run_id="fleet-unit", proc="t")
    metrics.set_identity("worker", "w3")
    s1, s2 = _snap(), _snap()
    assert s2["seq"] > s1["seq"], "seq is a per-process lifetime counter"
    assert s1["identity"] == {"pid": os.getpid(), "role": "worker",
                              "slot": "w3"}


def test_snapshot_delta_counters_gauges_and_histograms():
    obs_spans.arm(None, run_id="fleet-unit", proc="t")
    c = metrics.counter("unit.reqs")
    g = metrics.gauge("unit.depth")
    h = metrics.histogram("unit.lat")
    c.inc(3)
    g.set(5)
    h.observe(1.0)
    prev = _snap()
    c.inc(2)
    g.set(9)
    h.observe(2.0)
    h.observe(3.0)
    d = metrics.snapshot_delta(prev, _snap())
    assert d["counters"]["unit.reqs"] == 2, "counters delta"
    assert d["gauges"]["unit.depth"] == 9, "gauges carry current value"
    assert d["histograms"]["unit.lat"]["count"] == 2


def test_snapshot_delta_refuses_splices_and_regressions():
    obs_spans.arm(None, run_id="fleet-unit", proc="t")
    metrics.counter("unit.reqs").inc()
    prev, cur = _snap(), _snap()
    other = json.loads(json.dumps(cur))
    other["identity"]["pid"] = prev["identity"]["pid"] + 1
    with pytest.raises(ValueError, match="across processes"):
        metrics.snapshot_delta(prev, other)
    with pytest.raises(ValueError, match="advancing seq"):
        metrics.snapshot_delta(cur, prev)
    doctored = json.loads(json.dumps(prev))
    doctored["counters"]["unit.reqs"] = 99
    with pytest.raises(ValueError, match="monotone"):
        metrics.snapshot_delta(doctored, cur)


# ------------------------------------------------- disarmed = zero cost ----

def test_disarmed_demand_hook_is_allocation_free():
    assert not obs_fleet.armed()
    for _ in range(2000):  # warm the code path first
        obs_fleet.demand("offered", "interactive")
        obs_fleet.open_demand_window()
    gc.collect()
    before = sys.getallocatedblocks()
    for _ in range(5000):
        obs_fleet.demand("offered", "interactive")
    gc.collect()
    grown = sys.getallocatedblocks() - before
    assert grown < 50, (
        f"disarmed demand hooks allocated {grown} blocks over 5000 calls "
        "— the unarmed serve submit path must pay one load + compare")


# --------------------------------------------- emitter/aggregator loop ----

def test_arm_exports_env_contract_and_disarm_retracts(tmp_path):
    agg = obs_fleet.arm("unit-run", cadence_s=60.0,
                        scratch_dir=str(tmp_path))
    try:
        assert obs_fleet.armed()
        assert obs_fleet.current_aggregator() is agg
        assert os.environ[obs_fleet.ENV_ADDR] == agg.address
        assert os.environ[obs_fleet.ENV_RUN] == "unit-run"
        assert float(os.environ[obs_fleet.ENV_CADENCE]) == 60.0
    finally:
        obs_fleet.disarm("unit over")
    assert not obs_fleet.armed()
    for k in (obs_fleet.ENV_ADDR, obs_fleet.ENV_RUN,
              obs_fleet.ENV_CADENCE):
        assert k not in os.environ, f"disarm must retract {k}"
    assert obs_fleet.arm_emitter_from_env("worker", "w0") is None, (
        "after disarm a fresh spawn must stay dark, not dial a dead "
        "socket")


def _poll(pred, timeout_s=5.0):
    give_up = time.monotonic() + timeout_s
    while time.monotonic() < give_up:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_loopback_stream_opens_at_arm_and_fin_closes(tmp_path):
    agg = obs_fleet.arm("unit-run", cadence_s=0.05,
                        scratch_dir=str(tmp_path))
    try:
        metrics.counter("unit.work").inc(4)
        # the hello frame opens the book at arm time; cadence ticks add
        # samples and carry the counter delta
        assert _poll(lambda: any(
            b["samples"] >= 2
            for b in agg.snapshot()["processes"].values()))
        obs_fleet.disarm_emitter("drained for the unit")
        snap = agg.snapshot()
        (name, book), = snap["processes"].items()
        assert name.startswith("loadgen@")
        assert book["closed"] and book["close_reason"] == \
            "fin: drained for the unit"
        assert book["first_seq"] == 1 and book["seq_gaps"] == 0
        series = snap["points"][f"{name}|unit.work"]
        assert series["kind"] == "counter"
        assert series["v"][-1] == 4.0, "cum reconstruction from deltas"
        assert all(b >= a for a, b in zip(series["v"], series["v"][1:])), \
            "counter series are monotone by construction"
    finally:
        obs_fleet.disarm("unit over")


def test_severed_connection_reason_closes_the_stream_book(tmp_path):
    agg = obs_fleet.arm("unit-run", cadence_s=60.0,
                        scratch_dir=str(tmp_path))
    try:
        # a second process's emitter, long cadence: only the hello frame
        em = obs_fleet.FleetEmitter(agg.address, "unit-run", "worker",
                                    "w9", cadence_s=60.0).start()
        proc = em.proc
        assert _poll(lambda: proc in agg.snapshot()["processes"])
        # kill the connection WITHOUT a fin — the SIGKILL signature
        em._stop.set()
        em._channel.close("unit: abrupt death")
        assert _poll(lambda: agg.snapshot()["processes"][proc]["closed"])
        reason = agg.snapshot()["processes"][proc]["close_reason"]
        assert "severed" in reason, (
            f"EOF without fin closed as {reason!r} — a killed emitter "
            "must read as a reason-closed gap, never silence")
    finally:
        obs_fleet.disarm("unit over")


def test_dead_aggregator_costs_counted_drops_never_a_crash(tmp_path):
    em = obs_fleet.FleetEmitter(
        str(tmp_path / "nobody-listens.sock"), "unit-run", "worker", "w0",
        cadence_s=60.0).start()
    try:
        assert em.dropped == 1, "the hello frame's failure is COUNTED"
        em._tick()
        assert em.dropped == 2, "every failed tick is one counted drop"
    finally:
        em.stop("unit over")


# ---------------------------------------------------- capacity account ----

def _ev(event, wid, t):
    return {"event": event, "worker_id": wid, "t_s": t}


def test_capacity_account_kill_window_and_death_dedup():
    events = [
        _ev("ready", "w0", 0.0), _ev("ready", "w1", 0.0),
        _ev("chaos_kill", "w1", 2.0),
        # the monitor's death notice for the SAME incident must not
        # double-open the window
        _ev("death", "w1", 2.1),
        _ev("ready", "w1", 4.0),
    ]
    cap = obs_fleet.capacity_account(events, 2, (0.0, 10.0))
    assert len(cap["kill_windows"]) == 1, "one incident, one window"
    kw = cap["kill_windows"][0]
    assert kw["worker_id"] == "w1" and not kw["open_ended"]
    assert kw["t_kill_s"] == pytest.approx(2.0)
    assert kw["t_ready_s"] == pytest.approx(4.0)
    assert kw["width_s"] == pytest.approx(2.0)
    assert cap["nominal_worker_s"] == pytest.approx(20.0)
    assert cap["available_worker_s"] == pytest.approx(18.0)
    assert kw["loss_frac"] == pytest.approx(0.5), \
        "one of two slots dark across the window"
    assert cap["kill_window_loss_frac"] == pytest.approx(0.5)
    assert cap["steady_state_loss_frac"] == pytest.approx(0.0), \
        "steady-state loss ~ 0 is a measured result, not an assumption"


def test_capacity_account_organic_death_digs_the_same_hole():
    events = [_ev("ready", "w0", 0.0), _ev("death", "w0", 3.0),
              _ev("ready", "w0", 5.0)]
    cap = obs_fleet.capacity_account(events, 1, (0.0, 10.0))
    assert len(cap["kill_windows"]) == 1, (
        "a monitor-detected death (or a fault-plan self-kill inside the "
        "worker) is the same capacity hole as an explicit chaos kill")
    assert cap["kill_windows"][0]["width_s"] == pytest.approx(2.0)


def test_capacity_account_unreplaced_victim_stays_open_ended():
    events = [_ev("ready", "w0", 0.0), _ev("chaos_kill", "w0", 6.0)]
    cap = obs_fleet.capacity_account(events, 1, (0.0, 10.0))
    kw = cap["kill_windows"][0]
    assert kw["open_ended"], "the capacity never came back in-window"
    assert kw["t_ready_s"] == pytest.approx(10.0)
    assert cap["available_worker_s"] == pytest.approx(6.0)


def test_lifecycle_walls_one_sample_per_respawn():
    events = [
        {"event": "spawn", "worker_id": "w0", "t_s": 0.0},
        {"event": "ready", "worker_id": "w0", "t_s": 1.4,
         "generation": 0, "wall_s": 1.4,
         "walls": {"main_to_bind_s": 0.2, "warm_s": 0.9}},
        {"event": "death", "worker_id": "w0", "t_s": 3.0},
        {"event": "ready", "worker_id": "w0", "t_s": 4.2,
         "generation": 1, "wall_s": 1.1, "walls": {}},
    ]
    walls = obs_fleet.lifecycle_walls(events)
    assert [w["generation"] for w in walls] == [0, 1]
    assert [w["wall_s"] for w in walls] == [1.4, 1.1]
    assert walls[0]["walls"]["warm_s"] == 0.9


def test_absolute_events_shift_onto_the_shared_mono_timeline():
    shifted = obs_fleet.absolute_events(
        [_ev("ready", "w0", 1.5)], 1000.0)
    assert shifted[0]["t_s"] == pytest.approx(1001.5)


# ------------------------------------- artifact schema + doctored bytes ----

def _mini_fleet_artifact(tmp_path, run_id="r99"):
    """A REAL loopback capture: armed aggregator + local emitter, a
    demand window, synthetic supervisor events — the smallest artifact
    the schema accepts."""
    agg = obs_fleet.arm(run_id, cadence_s=0.05, scratch_dir=str(tmp_path))
    obs_fleet.open_demand_window()
    t0 = mono_now_s()
    metrics.counter("unit.work").inc(2)
    for _ in range(5):
        obs_fleet.demand("offered", "interactive")
        obs_fleet.demand("admitted", "interactive")
    for _ in range(4):
        obs_fleet.demand("served", "interactive")
    assert _poll(lambda: any(b["samples"] >= 2 for b in
                             agg.snapshot()["processes"].values()))
    obs_fleet.disarm_emitter("drained for the unit")
    agg.close_all("run-end")
    events = [
        dict(_ev("ready", "w0", t0 - 0.5), generation=0, wall_s=1.2,
             walls={}),
        _ev("chaos_kill", "w0", t0 + 0.01),
        dict(_ev("ready", "w0", t0 + 0.05), generation=1, wall_s=1.3,
             walls={}),
    ]
    art = obs_fleet.build_artifact(
        agg, run_id,
        requests={"admitted": 5, "served": 4, "rejected": 1,
                  "expired": 0},
        worker_events=events, n_workers=1, window=(t0, t0 + 0.2),
        fresh_compiles=0, platform="stub", workload="unit loopback")
    obs_fleet.disarm("unit over")
    return art


def test_fleet_artifact_validates_and_refuses_doctored_bytes(tmp_path):
    art = _mini_fleet_artifact(tmp_path)
    assert inv.validate(art, "fleet") == []
    assert inv.detect_kind(art) == "fleet", "kind detection by signature"

    def doctored(mutate):
        obj = json.loads(json.dumps(art))
        mutate(obj)
        return inv.validate(obj, "fleet")

    # a counter series edited to decrease after landing
    def _bend_counter(obj):
        for s in obj["series"]["points"].values():
            if s["kind"] == "counter" and len(s["v"]) >= 2:
                s["v"][-1] = s["v"][-2] - 1
                return
        pytest.fail("no counter series with >= 2 samples to doctor")
    assert any("monotone" in v for v in doctored(_bend_counter))

    # demand totals no longer matching the embedded serve book
    def _bend_demand(obj):
        obj["demand"]["classes"]["interactive"]["served"] += 1
        obj["demand"]["per_second"][0]["interactive"]["served"] = \
            obj["demand"]["per_second"][0]["interactive"].get(
                "served", 0) + 1
    assert any("unreconciled demand" in v for v in doctored(_bend_demand))

    # per-second buckets disagreeing with the class totals
    def _bend_buckets(obj):
        obj["demand"]["per_second"][0]["interactive"]["offered"] += 2
    assert any("cannot disagree" in v for v in doctored(_bend_buckets))

    # a series from a process the aggregator never opened
    def _orphan(obj):
        obj["series"]["points"]["ghost|unit.x"] = {
            "proc": "ghost", "metric": "unit.x", "kind": "gauge",
            "t_s": [0.0], "v": [1.0]}
    assert any("orphan series" in v for v in doctored(_orphan))

    # a stream book left open (silent truncation)
    def _unclose(obj):
        book = next(iter(obj["series"]["processes"].values()))
        book["closed"] = False
        book["close_reason"] = None
    assert any("reason-closed" in v for v in doctored(_unclose))

    # an unknown schema era must be refused whole, not half-parsed
    def _era(obj):
        obj["schema_version"] = 99
    assert any("schema_version" in v for v in doctored(_era))


def test_fleet_sidecar_naming_rule():
    assert inv.committable_sidecar("FLEET_r20.json")
    assert not inv.committable_sidecar("FLEET_rehearse_kill.json")
    assert not inv.committable_sidecar("FLEET_smoke-fleet.json")
    assert not inv.committable_sidecar("FLEET_loadgen-abc.json")


def test_validate_file_and_tree_pick_up_fleet(tmp_path):
    art = _mini_fleet_artifact(tmp_path)
    p = tmp_path / "FLEET_r99.json"
    with open(p, "w") as f:
        json.dump(art, f)
    assert inv.validate_file(str(p)) == []
    bad = json.loads(json.dumps(art))
    bad["capacity"]["kill_window_loss_frac"] = 1.5
    with open(tmp_path / "FLEET_r98.json", "w") as f:
        json.dump(bad, f)
    report = inv.validate_tree(str(tmp_path))
    assert report.get("FLEET_r99.json") == []
    assert report.get("FLEET_r98.json"), (
        "validate_tree must sweep the FLEET family and surface the "
        "damaged artifact")


# ------------------------------------------------------ ledger ingestion ----

def test_ledger_ingests_fleet_rows_with_samples(tmp_path):
    art = _mini_fleet_artifact(tmp_path)
    with open(tmp_path / "FLEET_r99.json", "w") as f:
        json.dump(art, f)
    from csmom_tpu.obs import ledger as ld

    L = ld.load(str(tmp_path))
    rows = {}
    for r in L.rows:
        rows.setdefault(r.metric, []).append(r)
    loss = rows["fleet_kill_window_capacity_loss_frac"][0]
    assert loss.direction == "lower"
    assert loss.value == art["value"]
    wall = rows["fleet_worker_ready_wall_s"][0]
    assert wall.direction == "lower"
    assert wall.value == pytest.approx(1.3), "the max (re)spawn wall"
    assert wall.samples, "ready-wall rows carry their CI backing"
    demand_rows = [m for m in rows if m.startswith("fleet_demand_")]
    assert "fleet_demand_interactive_rps" in demand_rows
    assert not rows["fleet_demand_interactive_rps"][0].gate_eligible(), (
        "demand rate is workload-descriptive, info only — a gate on "
        "offered load would gate the question, not the answer")


def test_ledger_refuses_unknown_fleet_schema_era(tmp_path):
    art = _mini_fleet_artifact(tmp_path)
    art["schema_version"] = 99
    with open(tmp_path / "FLEET_r99.json", "w") as f:
        json.dump(art, f)
    from csmom_tpu.obs import ledger as ld

    L = ld.load(str(tmp_path))
    assert not any(r.metric.startswith("fleet_") for r in L.rows), (
        "an unknown schema era must contribute zero rows, not "
        "half-parsed ones")
