"""The elastic fleet tier (ISSUE 20): hot spares, promotion, the
demand-driven autoscaler, and the ``fleet.elastic`` evidence block.

The contracts pinned here:

- **promotion is O(swap), not O(re-warm)**: a parked, demonstrated-ready
  spare fills a SIGKILLed slot in well under the re-warm wall, the
  victim's slot keeps its own id through the swap, and a routes publish
  in flight never wedges the promotion;
- **spares never enter the serving books**: a spare that dies parked
  opens no kill window and lands no lifecycle sample — the backfill
  refills the pool off the hot path;
- **double-kill honesty**: with one spare, the second victim re-warms
  the slow way and the books SAY so (``spawn_kind="respawn"`` plus a
  ``spare_promotion_missed`` event) — no pretending two spares existed;
- the **capacity account** credits spare reserve intervals, so a kill
  window covered by a parked spare reads as ~zero capacity loss (and
  loss never reads negative);
- the **autoscaler policy** is pure and clock-passed-in: hysteresis
  band, sustain, cooldown, floor/ceiling — every decision reasoned;
- quota auto-tune retunes the live admission bucket in place, bounded
  by the declared floor/ceiling;
- the ``fleet.elastic`` schema refuses doctored evidence, and the
  ledger ingests the per-spawn-kind ready-wall rows.
"""

import json
import signal
import threading
import time

import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.obs import fleet as obs_fleet
from csmom_tpu.obs import metrics
from csmom_tpu.obs import spans as obs_spans
from csmom_tpu.serve.fleet import AutoscalerPolicy, FleetConfig, FleetController
from csmom_tpu.serve.queue import AdmissionQueue
from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor
from csmom_tpu.utils.deadline import mono_now_s


@pytest.fixture(autouse=True)
def _clean_observatory():
    obs_fleet.disarm("test setup")
    metrics.reset()
    yield
    obs_fleet.disarm("test teardown")
    obs_spans.disarm()
    metrics.reset()


_SMOKE_POOL = dict(profile="serve-smoke", engine="stub",
                   ready_timeout_s=30.0, poll_interval_s=0.05,
                   backoff_base_s=0.05, backoff_cap_s=0.3)


def _poll(pred, timeout_s=10.0):
    give_up = time.monotonic() + timeout_s
    while time.monotonic() < give_up:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _events(sup, name):
    return [e for e in sup.summary()["events"] if e["event"] == name]


# -------------------------------------------------- autoscaler policy ----

def _policy(**over):
    kw = dict(high_rps_per_worker=10.0, low_rps_per_worker=2.0,
              sustain_s=1.0, cooldown_s=5.0, min_workers=1, max_workers=4)
    kw.update(over)
    return AutoscalerPolicy(**kw)


def test_policy_holds_inside_the_hysteresis_band():
    p = _policy()
    d = p.decide(0.0, 5.0, 1)
    assert d["action"] == "hold" and "band" in d["reason"]
    assert d["offered_rps"] == 5.0 and d["n_ready"] == 1


def test_policy_scale_up_requires_sustain_then_cools_down():
    p = _policy()
    assert p.decide(0.0, 50.0, 1)["action"] == "hold", "breach must sustain"
    d = p.decide(1.2, 50.0, 1)
    assert d["action"] == "scale_up" and "sustained" in d["reason"]
    d = p.decide(1.3, 50.0, 2)
    assert d["action"] == "hold" and "cooldown" in d["reason"], (
        "an action's dead time must absorb the follow-on breach — no "
        "thrash on a single burst")


def test_policy_scale_up_stops_at_the_declared_ceiling():
    p = _policy(cooldown_s=0.1)
    p.decide(0.0, 100.0, 4)
    d = p.decide(1.5, 100.0, 4)
    assert d["action"] == "hold" and "ceiling" in d["reason"], (
        "max_workers is a hard bound, not advice")


def test_policy_scale_down_requires_sustain_and_respects_floor():
    p = _policy()
    assert p.decide(0.0, 1.0, 2)["action"] == "hold"
    assert p.decide(1.5, 1.0, 2)["action"] == "scale_down"
    p2 = _policy()
    p2.decide(0.0, 1.0, 1)
    d = p2.decide(1.5, 1.0, 1)
    assert d["action"] == "hold" and "floor" in d["reason"]


def test_policy_band_dip_resets_the_sustain_timer():
    p = _policy()
    p.decide(0.0, 50.0, 1)          # above, sustaining
    p.decide(0.5, 5.0, 1)           # back in band: timer resets
    d = p.decide(1.2, 50.0, 1)
    assert d["action"] == "hold", (
        "a breach interrupted by an in-band tick must re-sustain from "
        "scratch — hysteresis exists to ignore blips")


def test_policy_refuses_an_inverted_band():
    with pytest.raises(ValueError, match="inverted"):
        _policy(low_rps_per_worker=20.0)


def test_policy_every_decision_is_reasoned():
    p = _policy(cooldown_s=0.5)
    t, seen = 0.0, []
    for rps in (0.0, 0.0, 50.0, 50.0, 50.0, 5.0, 0.5, 0.5, 0.5):
        d = p.decide(t, rps, 2)
        seen.append(d)
        t += 0.7
    for d in seen:
        assert d["action"] in ("scale_up", "scale_down", "hold")
        assert str(d["reason"]).strip(), d


# ------------------------------------------- capacity: spare reserve ----

def _ev(event, wid, t, **kw):
    return dict({"event": event, "worker_id": wid, "t_s": t}, **kw)


def test_spare_reserve_covers_the_kill_window():
    events = [
        _ev("ready", "w0", 0.0), _ev("ready", "w1", 0.0),
        _ev("ready", "w2", 0.0),
        _ev("spare_ready", "s0", 0.5),
        _ev("chaos_kill", "w1", 2.0),
        _ev("spare_promoted", "s0", 2.1),
        _ev("ready", "w1", 2.1, spawn_kind="spare-promotion"),
    ]
    cap = obs_fleet.capacity_account(events, 3, (0.0, 10.0))
    kw = cap["kill_windows"][0]
    assert kw["worker_id"] == "w1" and not kw["open_ended"]
    assert kw["loss_frac"] == pytest.approx(0.0), (
        "a kill window covered by a parked-ready spare is no capacity "
        "hole — the reserve credit is the whole point of the tier")
    assert cap["kill_window_loss_frac"] == pytest.approx(0.0)
    assert cap["spare_reserve_worker_s"] == pytest.approx(1.6), \
        "spare_ready 0.5 → spare_promoted 2.1"
    # the same kill WITHOUT the spare reads as the full hole
    bare = [e for e in events if not e["event"].startswith("spare")]
    cap2 = obs_fleet.capacity_account(bare, 3, (0.0, 10.0))
    assert cap2["kill_window_loss_frac"] == pytest.approx(1 / 3, abs=1e-3)


def test_spare_death_opens_no_kill_window():
    events = [
        _ev("ready", "w0", 0.0),
        _ev("spare_ready", "s0", 0.5),
        _ev("spare_death", "s0", 3.0),
    ]
    cap = obs_fleet.capacity_account(events, 1, (0.0, 10.0))
    assert cap["kill_windows"] == [], (
        "a parked spare dying costs no serving capacity — it was never "
        "routed")
    assert cap["spare_reserve_worker_s"] == pytest.approx(2.5)


def test_loss_fractions_never_read_negative():
    # spare reserve overlapping steady state pushes available past
    # nominal; the account must clamp, not report capacity conjured
    events = [
        _ev("ready", "w0", 0.0),
        _ev("spare_ready", "s0", 0.0),
        _ev("chaos_kill", "w0", 4.0),
        _ev("ready", "w0", 4.2),
    ]
    cap = obs_fleet.capacity_account(events, 1, (0.0, 10.0))
    assert cap["kill_window_loss_frac"] >= 0.0
    assert cap["steady_state_loss_frac"] >= 0.0
    for kw in cap["kill_windows"]:
        assert kw["loss_frac"] >= 0.0


# --------------------------------------------------- demand rate input ----

def test_demand_recent_rps_reads_the_open_window(tmp_path):
    agg = obs_fleet.arm("unit-elastic", cadence_s=60.0,
                        scratch_dir=str(tmp_path))
    try:
        assert agg.demand_recent_rps(2.0) == 0.0, (
            "before the window opens the control input must read 0, "
            "not poison the policy with stale buckets")
        obs_fleet.open_demand_window()
        for _ in range(6):
            obs_fleet.demand("offered", "interactive")
        for _ in range(3):
            obs_fleet.demand("offered", "bulk")
        assert agg.demand_recent_rps(2.0) > 0.0
        assert agg.demand_recent_rps(2.0, slo_class="bulk") > 0.0
        assert agg.demand_recent_rps(2.0, slo_class="bulk") < \
            agg.demand_recent_rps(2.0), "class filter narrows the sum"
        assert agg.demand_recent_rps(2.0, slo_class="nope") == 0.0
    finally:
        obs_fleet.disarm("unit over")


# ---------------------------------------------------- quota auto-tune ----

def test_retune_quota_retunes_the_live_bucket_in_place():
    q = AdmissionQueue(capacity=8)
    assert q.retune_quota("bulk", 32.0)
    b = q._buckets["bulk"]
    assert b.rate == 32.0 and b.burst == pytest.approx(48.0), \
        "burst defaults to 1.5x the retuned rate"
    assert q.retune_quota("bulk", 40.0, quota_burst=50.0)
    assert q._buckets["bulk"].burst == 50.0
    assert q.retune_quota("batch", 20.0), "the r10 alias resolves"
    assert q._buckets["bulk"].rate == 20.0


def test_retune_quota_refuses_unquotad_classes_and_bad_rates():
    q = AdmissionQueue(capacity=8)
    assert not q.retune_quota("interactive", 10.0), (
        "granting an unquota'd class a quota at runtime would change "
        "admission semantics, not tune them")
    assert not q.retune_quota("bulk", 0.0)
    assert not q.retune_quota("bulk", -5.0)


# ------------------------------------------- live pool: promotion seam ----

class _InFlightPublisher:
    """A routes publisher whose publish may be IN FLIGHT when the
    promotion lands — the promotion must queue behind it, not wedge."""

    def __init__(self):
        self.calls = 0
        self.lock = threading.Lock()

    def publish_once(self):
        with self.lock:
            self.calls += 1


def test_promotion_fills_the_slot_with_a_publish_in_flight(tmp_path):
    cfg = PoolConfig(n_workers=1, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    pub = _InFlightPublisher()
    fleet = None
    try:
        fleet = FleetController(
            sup, FleetConfig(spares=1, min_workers=1, max_workers=3),
            publisher=pub).start()
        assert len(fleet.spares) == 1, "start() waits for the spare"
        spare_id = fleet.spares[0].worker_id
        old_pid = sup.handles[0].proc.pid
        with pub.lock:  # a publish is in flight while the kill lands
            assert sup.kill_worker("w0", signal.SIGKILL)
            assert _poll(lambda: fleet.counts["promoted"] == 1)
        h = sup.handles[0]
        assert h.worker_id == "w0", "the slot keeps its own id"
        assert h.spawn_kind == "spare-promotion"
        assert h.generation == 1
        assert h.state == "ready"
        assert h.proc.pid != old_pid, "the spare's PROCESS fills the slot"
        assert _poll(lambda: pub.calls >= 1), (
            "promotion must publish routes once the in-flight publish "
            "releases — queued behind it, never skipped")
        (p,) = fleet.promotions
        assert p["victim"] == "w0" and p["spare"] == spare_id
        assert p["wall_s"] <= 1.5, (
            f"promotion wall {p['wall_s']}s — a parked-ready swap must "
            "be O(publish), nowhere near a re-warm")
        ready = _events(sup, "ready")
        assert ready[-1]["spawn_kind"] == "spare-promotion"
        assert ready[-1]["worker_id"] == "w0"
        # backfill refills the pool off the hot path
        assert _poll(lambda: any(s.state == "ready" for s in fleet.spares))
        assert fleet.counts["backfills"] >= 1
    finally:
        if fleet is not None:
            fleet.stop()
        sup.stop()


def test_double_kill_with_one_spare_rewarns_the_second_honestly(tmp_path):
    cfg = PoolConfig(n_workers=2, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    fleet = None
    try:
        fleet = FleetController(
            sup, FleetConfig(spares=1, min_workers=2, max_workers=4)).start()
        assert sup.kill_worker("w0", signal.SIGKILL)
        assert sup.kill_worker("w1", signal.SIGKILL)
        assert _poll(lambda: all(h.generation >= 1 and h.state == "ready"
                                 for h in sup.handles), timeout_s=20.0)
        kinds = sorted(h.spawn_kind for h in sup.handles)
        # one slot promoted; the other re-warmed the slow way (unless
        # the backfilled second spare landed first, which is also legal
        # — but the books must SAY which happened)
        assert fleet.counts["promoted"] >= 1
        if "respawn" in kinds:
            assert _events(sup, "spare_promotion_missed"), (
                "a victim re-warmed because no spare was parked — the "
                "miss must be a booked event, not silence")
        ready = _events(sup, "ready")
        assert all(e.get("spawn_kind") in ("cold", "respawn",
                                           "spare-promotion")
                   for e in ready)
    finally:
        if fleet is not None:
            fleet.stop()
        sup.stop()


def test_spare_dying_parked_backfills_and_never_enters_the_books(tmp_path):
    cfg = PoolConfig(n_workers=1, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    fleet = None
    try:
        fleet = FleetController(
            sup, FleetConfig(spares=1, min_workers=1, max_workers=3)).start()
        s0 = fleet.spares[0]
        s0.proc.kill()
        assert _poll(lambda: fleet.counts["died_parked"] >= 1)
        deaths = _events(sup, "spare_death")
        assert deaths and deaths[-1]["phase"] == "parked"
        # the backfill restores the reserve without touching the pool
        assert _poll(lambda: any(s.state == "ready" for s in fleet.spares),
                     timeout_s=20.0)
        assert sup.handles[0].generation == 0, (
            "a parked spare's death must not disturb the serving slot")
        spare_ids = set(fleet._all_spare_ids)
        walls = obs_fleet.lifecycle_walls(sup.summary()["events"])
        assert not spare_ids & {w["worker_id"] for w in walls}, (
            "spares must never land lifecycle samples")
        cap = obs_fleet.capacity_account(
            obs_fleet.absolute_events(sup.summary()["events"],
                                      sup.t0_mono_s),
            1, (sup.t0_mono_s, mono_now_s()))
        assert not [kw for kw in cap["kill_windows"]
                    if kw["worker_id"] in spare_ids], (
            "a spare death digs no capacity hole")
    finally:
        if fleet is not None:
            fleet.stop()
        sup.stop()


# ------------------------------------- elastic block schema + doctored ----

def _mini_elastic_artifact(tmp_path, run_id="r97"):
    """A REAL loopback capture with a consistent elastic block and a
    promotion-regime lifecycle sample."""
    agg = obs_fleet.arm(run_id, cadence_s=0.05, scratch_dir=str(tmp_path))
    obs_fleet.open_demand_window()
    t0 = mono_now_s()
    metrics.counter("unit.work").inc(2)
    for _ in range(5):
        obs_fleet.demand("offered", "interactive")
        obs_fleet.demand("admitted", "interactive")
    for _ in range(4):
        obs_fleet.demand("served", "interactive")
    assert _poll(lambda: any(b["samples"] >= 2 for b in
                             agg.snapshot()["processes"].values()))
    obs_fleet.disarm_emitter("drained for the unit")
    agg.close_all("run-end")
    events = [
        dict(_ev("ready", "w0", t0 - 0.5), generation=0, wall_s=6.5,
             spawn_kind="cold", walls={}),
        _ev("spare_ready", "s0", t0 - 0.4),
        _ev("chaos_kill", "w0", t0 + 0.01),
        _ev("spare_promoted", "s0", t0 + 0.02),
        dict(_ev("ready", "w0", t0 + 0.02), generation=1, wall_s=0.01,
             spawn_kind="spare-promotion", walls={}),
    ]
    elastic = {
        "armed": True, "spares_configured": 1, "prefork": False,
        "autoscale": True, "spare_ids": ["s0", "s1"],
        "spares": {"spawned": 2, "ready": 2, "promoted": 1,
                   "backfills": 1, "died_parked": 0},
        "promotions": [{"victim": "w0", "spare": "s0", "generation": 1,
                        "t_kill_s": 0.01, "t_ready_s": 0.02,
                        "wall_s": 0.01}],
        "promotions_missed": 0,
        "decisions": [{"t_s": 0.1, "action": "hold",
                       "reason": "2.0 rps/worker inside hysteresis band "
                                 "[5, 200]", "offered_rps": 2.0,
                       "n_ready": 1}],
        "quota": {"slo_class": "bulk", "floor_rps": 8.0,
                  "ceiling_rps": 64.0,
                  "applied": [{"t_s": 0.2, "slo_class": "bulk",
                               "quota_rps": 12.0,
                               "applied_to": ["w0"]}]},
        "bounds": {"min_workers": 1, "max_workers": 3},
    }
    art = obs_fleet.build_artifact(
        agg, run_id,
        requests={"admitted": 5, "served": 4, "rejected": 1, "expired": 0},
        worker_events=events, n_workers=1, window=(t0, t0 + 0.2),
        fresh_compiles=0, platform="stub", workload="unit loopback",
        elastic=elastic)
    obs_fleet.disarm("unit over")
    return art


def test_elastic_block_validates_and_splits_walls_by_kind(tmp_path):
    art = _mini_elastic_artifact(tmp_path)
    assert inv.validate(art, "fleet") == []
    samples = art["extra"]["samples"]
    assert samples["fleet_worker_ready_wall_cold_s"] == [6.5]
    assert samples["fleet_worker_ready_wall_promotion_s"] == [0.01], (
        "promotion-regime walls gate against their own kind, never "
        "averaged into the cold-spawn distribution")


def test_elastic_schema_refuses_doctored_evidence(tmp_path):
    art = _mini_elastic_artifact(tmp_path)

    def doctored(mutate):
        obj = json.loads(json.dumps(art))
        mutate(obj)
        return inv.validate(obj, "fleet")

    def _time_travel(o):
        o["elastic"]["promotions"][0]["t_ready_s"] = -5.0
    assert any("before the kill" in v for v in doctored(_time_travel))

    def _spare_in_lifecycle(o):
        o["lifecycle"]["events"].append(
            {"worker_id": "s0", "generation": 0, "kind": "cold",
             "wall_s": 0.5, "walls": {}})
    assert any("held out of the serving lifecycle" in v
               for v in doctored(_spare_in_lifecycle))

    def _spare_kill_window(o):
        o["capacity"]["kill_windows"].append(
            {"worker_id": "s0", "t_kill_s": 0.1, "t_ready_s": 0.2,
             "open_ended": False, "width_s": 0.1, "loss_frac": 1.0})
    assert any("digs no capacity hole" in v
               for v in doctored(_spare_kill_window))

    def _double_promotion(o):
        p = dict(o["elastic"]["promotions"][0])
        p["generation"] = 2
        o["elastic"]["promotions"].append(p)
        o["elastic"]["spares"]["promoted"] = 2
    assert any("promoted twice" in v for v in doctored(_double_promotion))

    def _counter_mismatch(o):
        o["elastic"]["spares"]["promoted"] = 3
    assert any("promotion records" in v
               for v in doctored(_counter_mismatch))

    def _unreasoned(o):
        o["elastic"]["decisions"][0]["reason"] = "  "
    assert any("reasoned event" in v for v in doctored(_unreasoned))

    def _bad_action(o):
        o["elastic"]["decisions"][0]["action"] = "yolo"
    assert any("unknown" in v for v in doctored(_bad_action))

    def _quota_breach(o):
        o["elastic"]["quota"]["applied"][0]["quota_rps"] = 9999.0
    assert any("declared bounds" in v for v in doctored(_quota_breach))

    def _undeclared_spare(o):
        o["elastic"]["promotions"][0]["spare"] = "sX"
    assert any("not a declared spare" in v
               for v in doctored(_undeclared_spare))


# ------------------------------------------------------ ledger per-kind ----

def test_ledger_ingests_per_kind_ready_wall_rows(tmp_path):
    art = _mini_elastic_artifact(tmp_path)
    with open(tmp_path / "FLEET_r97.json", "w") as f:
        json.dump(art, f)
    from csmom_tpu.obs import ledger as ld

    L = ld.load(str(tmp_path))
    rows = {r.metric: r for r in L.rows}
    agg = rows["fleet_worker_ready_wall_s"]
    assert agg.value == pytest.approx(6.5), "aggregate keeps the max"
    promo = rows["fleet_worker_ready_wall_promotion_s"]
    assert promo.direction == "lower"
    assert promo.value == pytest.approx(0.01)
    assert list(promo.samples) == [0.01]
    cold = rows["fleet_worker_ready_wall_cold_s"]
    assert cold.value == pytest.approx(6.5)
