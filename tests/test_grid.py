"""J x K grid vs a straightforward numpy/pandas oracle of JT overlapping
portfolios, plus internal consistency with the single monthly engine."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.backtest import monthly_spread_backtest
from csmom_tpu.backtest.grid import jk_grid_backtest
from tests.test_ranking import oracle_deciles


def oracle_grid_cell(prices: pd.DataFrame, J: int, K: int, skip: int = 1):
    """One (J, K) cell with explicit Python loops: form cohorts with qcut
    deciles, hold each for K months equal-weighted, average the K live
    cohorts each holding month (all-K-live months only)."""
    ret = prices.pct_change()
    mom = prices.shift(skip) / prices.shift(skip + J) - 1
    bad = ret.isna().astype(int)
    window_bad = bad.shift(skip).rolling(J, min_periods=J).sum()
    mom = mom.where(window_bad == 0)

    M = len(prices)
    cohort = {}  # s -> (top set, bot set)
    for s in range(M):
        lab = oracle_deciles(mom.iloc[s].values)
        if (lab >= 0).any():
            cohort[s] = (np.where(lab == 9)[0], np.where(lab == 0)[0])

    out = {}
    for m in range(M):
        legs = []
        ok = True
        for h in range(1, K + 1):
            s = m - h
            if s < 0 or s not in cohort:
                ok = False
                break
            top, bot = cohort[s]
            r = ret.iloc[m].values
            tr = r[top]
            br = r[bot]
            tr = tr[np.isfinite(tr)]
            br = br[np.isfinite(br)]
            if len(tr) == 0 or len(br) == 0:
                ok = False
                break
            legs.append(tr.mean() - br.mean())
        if ok and legs:
            out[m] = np.mean(legs)
    return out


def _make_prices(rng, M=80, A=24):
    return pd.DataFrame(
        50 * np.exp(np.cumsum(rng.normal(0.004, 0.07, size=(M, A)), axis=0))
    )


@pytest.mark.parametrize("J,K", [(12, 1), (6, 3), (3, 6), (9, 12)])
def test_grid_cell_matches_oracle(rng, J, K):
    prices = _make_prices(rng)
    vals = prices.values.T
    mask = np.isfinite(vals)
    res = jk_grid_backtest(vals, mask, np.array([J]), np.array([K]), skip=1)
    got = np.asarray(res.spreads)[0, 0]
    got_valid = np.asarray(res.spread_valid)[0, 0]
    want = oracle_grid_cell(prices, J, K)
    np.testing.assert_array_equal(np.where(got_valid)[0], sorted(want))
    for m in want:
        assert abs(got[m] - want[m]) < 1e-9, (m, got[m], want[m])


@pytest.mark.slow
def test_full_16_cell_grid_shapes(rng):
    prices = _make_prices(rng, M=90, A=30)
    vals = prices.values.T
    mask = np.isfinite(vals)
    Js = np.array([3, 6, 9, 12])
    Ks = np.array([3, 6, 9, 12])
    res = jk_grid_backtest(vals, mask, Js, Ks, skip=1)
    assert res.spreads.shape == (4, 4, 90)
    assert res.mean_spread.shape == (4, 4)
    assert np.isfinite(np.asarray(res.ann_sharpe)).all()


def test_K1_matches_single_engine(rng):
    """The K=1 grid column must equal the single monthly engine's spread
    shifted from formation-indexing to holding-month-indexing."""
    prices = _make_prices(rng, M=70, A=20)
    vals = prices.values.T
    mask = np.isfinite(vals)
    single = monthly_spread_backtest(vals, mask, lookback=6, skip=1)
    res = jk_grid_backtest(vals, mask, np.array([6]), np.array([1]), skip=1)

    s_single = np.asarray(single.spread)       # indexed by formation month
    v_single = np.asarray(single.spread_valid)
    s_grid = np.asarray(res.spreads)[0, 0]     # indexed by holding month
    v_grid = np.asarray(res.spread_valid)[0, 0]

    np.testing.assert_array_equal(v_grid[1:], v_single[:-1])
    got = s_grid[1:][v_single[:-1]]
    want = s_single[:-1][v_single[:-1]]
    np.testing.assert_allclose(got, want, rtol=1e-12)


def test_gappy_panel_grid(rng):
    prices = _make_prices(rng, M=60, A=30)
    prices.iloc[:15, :6] = np.nan
    prices.iloc[45:, 24:] = np.nan
    vals = prices.values.T
    mask = np.isfinite(vals)
    res = jk_grid_backtest(vals, mask, np.array([6]), np.array([3]), skip=1)
    got = np.asarray(res.spreads)[0, 0]
    got_valid = np.asarray(res.spread_valid)[0, 0]
    want = oracle_grid_cell(prices, 6, 3)
    np.testing.assert_array_equal(np.where(got_valid)[0], sorted(want))
    for m in want:
        assert abs(got[m] - want[m]) < 1e-9


class TestGridNetOfCosts:
    def _setup(self, rng, A=40, M=90):
        prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, M)), axis=1))
        mask = np.ones((A, M), bool)
        mask[: A // 8, : M // 4] = False
        return prices, mask

    @pytest.mark.slow
    def test_k1_matches_monthly_net_of_costs(self, rng):
        """A K=1 grid cell's netted spread equals the monthly engine's
        net_of_costs, shifted from formation-month to holding-month
        indexing (grid month m = formation month m-1)."""
        from csmom_tpu.backtest.grid import grid_net_of_costs, jk_grid_backtest
        from csmom_tpu.backtest.monthly import monthly_spread_backtest, net_of_costs

        prices, mask = self._setup(rng)
        Js, Ks = np.array([6]), np.array([1])
        hs = 7e-4
        grid = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5,
                                mode="rank")
        net_grid = grid_net_of_costs(prices, mask, grid, half_spread=hs)

        mon = monthly_spread_backtest(prices, mask, lookback=6, skip=1,
                                      n_bins=5, mode="rank")
        net_m, _, _ = net_of_costs(mon, half_spread=hs, n_bins=5)

        g = np.asarray(net_grid.spreads)[0, 0]
        gv = np.asarray(net_grid.spread_valid)[0, 0]
        m_ = np.asarray(net_m)
        # holding month m <-> formation month m-1
        both = gv[1:] & np.isfinite(m_[:-1])
        assert both.any()
        np.testing.assert_allclose(g[1:][both], m_[:-1][both], rtol=1e-9)

    @pytest.mark.slow
    def test_costs_fall_with_k_and_validity_preserved(self, rng):
        """Longer holding replaces ~1/K of the book per month, so the mean
        per-month cost drag must decrease with K; validity is untouched."""
        from csmom_tpu.backtest.grid import grid_net_of_costs, jk_grid_backtest

        prices, mask = self._setup(rng, A=60, M=120)
        Js, Ks = np.array([6]), np.array([1, 3, 6])
        grid = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5,
                                mode="rank")
        net = grid_net_of_costs(prices, mask, grid, half_spread=1e-3)
        np.testing.assert_array_equal(np.asarray(net.spread_valid),
                                      np.asarray(grid.spread_valid))
        drag = []
        for k in range(3):
            v = np.asarray(grid.spread_valid)[0, k]
            d = (np.asarray(grid.spreads)[0, k][v]
                 - np.asarray(net.spreads)[0, k][v])
            assert (d >= -1e-12).all()  # costs only subtract
            drag.append(d.mean())
        assert drag[0] > drag[1] > drag[2]

    def test_result_carries_build_params(self, rng):
        """The GridResult rides its own build parameters, and netting a
        result that has none (residual sweep) fails loudly."""
        from csmom_tpu.backtest.grid import grid_net_of_costs, jk_grid_backtest
        from csmom_tpu.signals.residual import residual_sweep_backtest

        prices, mask = self._setup(rng)
        # single-J / short-K build: the assertions are about METADATA
        # carrying (non-default skip included), so the cheapest grid that
        # has distinct Js/Ks arrays suffices — compile cost scales with
        # max(Ks) and this test was the tier's #2 compile hog
        Js, Ks = np.array([6]), np.array([1, 3])
        grid = jk_grid_backtest(prices, mask, Js, Ks, skip=2, n_bins=5,
                                mode="rank")
        np.testing.assert_array_equal(np.asarray(grid.Js), Js)
        np.testing.assert_array_equal(np.asarray(grid.Ks), Ks)
        assert int(grid.skip) == 2
        assert grid.n_bins == 5 and grid.mode == "rank"
        net = grid_net_of_costs(prices, mask, grid, half_spread=1e-3)
        assert net.n_bins == 5 and int(net.skip) == 2

        res = residual_sweep_backtest(prices, mask, np.array([6]),
                                      np.array([12]), n_bins=5)
        with pytest.raises(ValueError, match="carries none"):
            grid_net_of_costs(prices, mask, res)

    @pytest.mark.slow
    def test_overlapping_book_turnover_vs_loop_oracle(self, rng):
        """K=3 netted costs equal an explicit cohort-loop reconstruction:
        book at month m = mean of the 3 most recent formation books,
        turnover = L1 weight change, cost = half_spread * turnover."""
        from csmom_tpu.backtest.grid import grid_net_of_costs, jk_grid_backtest
        from csmom_tpu.backtest.monthly import monthly_spread_backtest
        from csmom_tpu.costs.impact import long_short_weights

        prices, mask = self._setup(rng, A=30, M=70)
        Js, Ks, K, hs, nb = np.array([6]), np.array([3]), 3, 1e-3, 5
        grid = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=nb,
                                mode="rank")
        net = grid_net_of_costs(prices, mask, grid, half_spread=hs)

        # formation books from the monthly engine's labels (same kernels)
        mon = monthly_spread_backtest(prices, mask, lookback=6, skip=1,
                                      n_bins=nb, mode="rank")
        w_f = np.asarray(long_short_weights(mon.labels, mon.decile_counts, nb))
        A, M = w_f.shape
        prev_book = np.zeros(A)
        want_cost = np.zeros(M)
        for m in range(M):
            cohorts = [w_f[:, s] for s in range(max(m - K, 0), m)]
            # the engine divides by K even during warm-up months (< K
            # cohorts live), matching _holding_month_spreads' 1/K scale
            book = (np.sum(cohorts, axis=0) / K if cohorts else np.zeros(A))
            want_cost[m] = hs * np.abs(book - prev_book).sum()
            prev_book = book

        v = np.asarray(grid.spread_valid)[0, 0]
        got_cost = (np.asarray(grid.spreads)[0, 0] -
                    np.asarray(net.spreads)[0, 0])
        np.testing.assert_allclose(got_cost[v], want_cost[v], rtol=1e-9)

    def test_break_even_bps(self, rng):
        """Netting at the break-even level zeroes the mean spread (the
        cost model is linear in half-spread), and break-evens rise with K
        on a gross-positive planted-momentum panel (1/K book replacement)."""
        from csmom_tpu.backtest.grid import (grid_break_even_bps,
                                             grid_net_of_costs,
                                             jk_grid_backtest)

        # same shapes/statics as test_net_from_unit_matches_direct below:
        # the two tests share one jit compile of the grid + netting stack
        prices, mask = self._setup(rng, A=40, M=140)
        Js, Ks = np.array([6]), np.array([1, 3, 6])
        grid = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5,
                                mode="rank")
        be, mean_turn = grid_break_even_bps(prices, mask, grid)
        assert np.asarray(mean_turn).shape == (1, 3)
        assert (np.asarray(mean_turn) > 0).all()
        # turnover falls with K (the 1/K replacement rate)
        mt = np.asarray(mean_turn)[0]
        assert mt[0] > mt[1] > mt[2]
        for k in range(3):
            hs = float(np.asarray(be)[0, k]) / 1e4
            net = grid_net_of_costs(prices, mask, grid, half_spread=hs)
            assert abs(float(np.asarray(net.mean_spread)[0, k])) < 1e-10

    def test_net_from_unit_matches_direct(self, rng):
        """Re-pricing from the unit-cost run equals a direct netting run
        at the same level, stats included (the CLI path)."""
        from csmom_tpu.backtest.grid import (grid_net_from_unit,
                                             grid_net_of_costs,
                                             jk_grid_backtest)

        # shapes/statics shared with test_break_even_bps (one compile)
        prices, mask = self._setup(rng, A=40, M=140)
        grid = jk_grid_backtest(prices, mask, np.array([6]),
                                np.array([1, 3, 6]), skip=1, n_bins=5,
                                mode="rank")
        unit = grid_net_of_costs(prices, mask, grid, half_spread=1.0)
        hs = 13e-4
        a = grid_net_of_costs(prices, mask, grid, half_spread=hs)
        b = grid_net_from_unit(grid, unit, half_spread=hs)
        for f in ("mean_spread", "ann_sharpe", "tstat", "tstat_nw"):
            np.testing.assert_allclose(np.asarray(getattr(a, f)),
                                       np.asarray(getattr(b, f)),
                                       rtol=1e-9)
        np.testing.assert_allclose(
            np.asarray(a.spreads)[np.asarray(a.spread_valid)],
            np.asarray(b.spreads)[np.asarray(b.spread_valid)], rtol=1e-9)
