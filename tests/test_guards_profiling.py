"""Guards + profiling utilities."""

import contextlib
import logging

import numpy as np
import jax.numpy as jnp
import pytest

from csmom_tpu.utils import fetch, measure_rtt, wall, trace, validate_panel, checked


def test_wall_blocks_and_times():
    x = jnp.ones((256, 256))
    out, dt = wall(lambda a: a @ a, x, warmup=1)
    assert out.shape == (256, 256)
    assert dt >= 0


class _ListHandler(logging.Handler):
    def __init__(self):
        super().__init__()
        self.msgs = []

    def emit(self, record):
        self.msgs.append(record.getMessage())


@contextlib.contextmanager
def _captured_logs():
    h = _ListHandler()
    root = logging.getLogger("csmom_tpu")
    root.addHandler(h)
    try:
        yield h.msgs
    finally:
        root.removeHandler(h)


def test_trace_context_logs():
    with _captured_logs() as msgs:
        with trace("unit-test-block"):
            _ = jnp.arange(10).sum()
    assert any("unit-test-block" in m for m in msgs)


def test_validate_panel_ok():
    v = np.array([[1.0, np.nan], [2.0, 3.0]])
    m = np.isfinite(v)
    validate_panel(v, m, times=np.array([1, 2]))


def test_validate_panel_shape_mismatch():
    with pytest.raises(ValueError, match="vs mask"):
        validate_panel(np.ones((2, 3)), np.ones((2, 2), bool))


def test_validate_panel_inf():
    v = np.array([[1.0, np.inf]])
    with pytest.raises(ValueError, match="Inf"):
        validate_panel(v, np.isfinite(v))


def test_validate_panel_nan_under_valid_mask():
    v = np.array([[1.0, np.nan]])
    m = np.array([[True, True]])
    with pytest.raises(ValueError, match="non-finite"):
        validate_panel(v, m)


def test_validate_panel_bad_times():
    v = np.ones((1, 3))
    with pytest.raises(ValueError, match="increasing"):
        validate_panel(v, np.ones((1, 3), bool), times=np.array([3, 2, 1]))


def test_validate_panel_dead_lane_warns():
    v = np.full((2, 2), np.nan)
    v[0] = 1.0
    with _captured_logs() as msgs:
        validate_panel(v, np.isfinite(v))
    assert any("fully masked" in m for m in msgs)


def test_checked_catches_nan():
    import jax

    def div(a, b):
        return a / b

    g = jax.jit(checked(div))
    err, out = g(jnp.float32(1.0), jnp.float32(0.0))
    with pytest.raises(Exception):
        err.throw()
    err2, out2 = g(jnp.float32(1.0), jnp.float32(2.0))
    err2.throw()  # no error
    assert float(out2) == 0.5


def test_fetch_materializes_and_rtt_positive():
    """fetch returns host numpy (real values, not a future); measure_rtt is a
    plausible per-call floor."""
    import jax

    y = fetch(jax.jit(lambda a: a * 2.0)(jnp.asarray([1.0, 2.0])))
    assert isinstance(y, np.ndarray)
    np.testing.assert_array_equal(y, [2.0, 4.0])
    rtt = measure_rtt(reps=3)
    assert 0.0 < rtt < 5.0
