"""Radix-histogram distributed rank vs the single-device rank kernel.

The contract: ``histogram_rank_labels`` inside shard_map over the asset
axis is bit-identical to ``decile_assign_panel(mode='rank')`` on the
gathered panel, for any shard count (shard-count invariance is the
property that makes "the scaling axis is assets" true past the all_gather
design point — VERDICT r1 weak #5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from csmom_tpu.parallel.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.parallel.histrank import histogram_rank_labels

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


def _sharded_labels(x, valid, n_bins, n_shards):
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("assets",))
    fn = shard_map(
        lambda xl, vl: histogram_rank_labels(xl, vl, n_bins, "assets"),
        mesh=mesh,
        in_specs=(P("assets", None), P("assets", None)),
        out_specs=P("assets", None),
        check_vma=False,
    )
    return np.asarray(jax.jit(fn)(x, valid))


def _reference(x, valid, n_bins):
    labels, _ = decile_assign_panel(jnp.asarray(x), jnp.asarray(valid),
                                    n_bins=n_bins, mode="rank")
    return np.asarray(labels)


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_matches_single_device_and_shard_invariant(rng, n_shards):
    A, M, B = 48, 30, 10
    x = rng.normal(size=(A, M))
    valid = rng.random((A, M)) > 0.2
    x = np.where(valid, x, np.nan)
    got = _sharded_labels(x, valid, B, n_shards)
    np.testing.assert_array_equal(got, _reference(x, valid, B))


def test_heavy_ties(rng):
    """Quantized values force many exact ties; position tie-break must match
    the stable argsort's."""
    A, M, B = 64, 20, 5
    x = np.round(rng.normal(size=(A, M)) * 3) / 3.0   # few distinct values
    valid = rng.random((A, M)) > 0.1
    x = np.where(valid, x, np.nan)
    for s in (2, 8):
        np.testing.assert_array_equal(
            _sharded_labels(x, valid, B, s), _reference(x, valid, B)
        )


def test_all_equal_and_signed_zero(rng):
    A, M, B = 32, 6, 10
    x = np.zeros((A, M))
    x[: A // 2, 0] = -0.0                  # -0.0 must tie with +0.0
    x[:, 1] = 7.25
    x[:, 2] = rng.normal(size=A)
    x[:, 3] = -np.abs(rng.normal(size=A))  # all-negative cross-section
    valid = np.ones((A, M), bool)
    valid[:, 4] = False                    # empty date
    valid[1:, 5] = False                   # single survivor
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, 4), _reference(x, valid, B)
    )


def test_sparse_dates(rng):
    """Dates with fewer valid lanes than bins."""
    A, M, B = 40, 12, 10
    x = rng.normal(size=(A, M))
    valid = rng.random((A, M)) > 0.85      # ~6 lanes/date
    x = np.where(valid, x, np.nan)
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, 8), _reference(x, valid, B)
    )


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_signed_zero_boundary_under_jit(n_shards):
    """Regression for the r2 tie bug: XLA's algebraic simplifier folds
    ``x + 0.0 -> x`` under jit, so a -0.0 canonicalization written as IEEE
    addition silently vanishes and ±0.0 lanes get distinct bit keys.  Force
    a decile boundary *inside* a mixed ±0.0 run so any key split flips
    labels."""
    A, M, B = 48, 4, 5
    x = np.zeros((A, M))
    x[::2, :] = -0.0                       # alternate ±0.0 by position
    x[: A // 4, 1] = -1.0                  # boundary lands mid-zero-run
    x[3 * A // 4 :, 1] = 1.0
    x[:, 2] = np.where(np.arange(A) % 3 == 0, -0.0, 0.0)
    x[: A // 3, 3] = -2.5
    valid = np.ones((A, M), bool)
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, n_shards), _reference(x, valid, B)
    )


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_ties_straddle_shard_boundaries(n_shards):
    """One value occupies whole shards: every boundary rank falls inside a
    tie run that spans multiple shard-local blocks, exercising the
    prev_eq/local_j cross-shard walk."""
    A, M, B = 64, 3, 10
    x = np.zeros((A, M))
    x[:, 0] = np.repeat(np.arange(4), A // 4).astype(float)  # 4 long runs
    x[:, 1] = 1.0                                            # all equal
    x[:, 2] = np.repeat([0.0, 1.0], A // 2)                  # 2 runs of 32
    valid = np.ones((A, M), bool)
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, n_shards), _reference(x, valid, B)
    )


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_fewer_valid_than_bins(rng, n_shards):
    """n < n_bins: multiple boundary ranks collapse onto the same lanes."""
    A, M, B = 32, 8, 10
    x = rng.normal(size=(A, M))
    valid = np.zeros((A, M), bool)
    for m in range(M):
        k = m + 1                          # 1..8 valid lanes (< 10 bins)
        valid[rng.choice(A, size=k, replace=False), m] = True
    x = np.where(valid, x, np.nan)
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, n_shards), _reference(x, valid, B)
    )


@pytest.mark.parametrize("n_shards", [2, 8])
def test_multiple_edges_share_one_value(n_shards):
    """A dominant value swallows several consecutive decile edges; labels
    must still split the tie run by position exactly like the stable
    argsort."""
    A, M, B = 80, 2, 10
    x = np.zeros((A, M))
    x[:8, 0] = -1.0
    x[72:, 0] = 1.0                        # 64/80 lanes equal 0 -> ~8 edges inside
    x[:, 1] = np.where(np.arange(A) < 40, 3.0, -3.0)
    valid = np.ones((A, M), bool)
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, n_shards), _reference(x, valid, B)
    )


def test_grid_engine_rank_hist_mode(rng):
    """sharded_jk_grid_backtest(mode='rank_hist') == mode='rank' end to end."""
    from csmom_tpu.parallel import make_mesh, sharded_jk_grid_backtest
    from csmom_tpu.parallel.mesh import pad_assets

    A, T = 40, 100
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, T)), axis=1))
    mask = np.ones((A, T), bool)
    mask[:6, :25] = False
    mesh = make_mesh(jax.devices()[:4], grid_axis=1)
    pv, mv, _ = pad_assets(prices, mask, mesh.shape["assets"])
    Js = np.array([6, 12])
    Ks = np.array([1, 3])
    out_h = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, skip=1, mode="rank_hist")
    out_r = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, skip=1, mode="rank")
    np.testing.assert_allclose(np.asarray(out_h.spreads), np.asarray(out_r.spreads),
                               rtol=1e-12, equal_nan=True)
    np.testing.assert_array_equal(np.asarray(out_h.spread_valid),
                                  np.asarray(out_r.spread_valid))
