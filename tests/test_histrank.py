"""Radix-histogram distributed rank vs the single-device rank kernel.

The contract: ``histogram_rank_labels`` inside shard_map over the asset
axis is bit-identical to ``decile_assign_panel(mode='rank')`` on the
gathered panel, for any shard count (shard-count invariance is the
property that makes "the scaling axis is assets" true past the all_gather
design point — VERDICT r1 weak #5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.parallel.histrank import histogram_rank_labels


def _sharded_labels(x, valid, n_bins, n_shards):
    mesh = Mesh(np.array(jax.devices()[:n_shards]), ("assets",))
    fn = shard_map(
        lambda xl, vl: histogram_rank_labels(xl, vl, n_bins, "assets"),
        mesh=mesh,
        in_specs=(P("assets", None), P("assets", None)),
        out_specs=P("assets", None),
        check_vma=False,
    )
    return np.asarray(jax.jit(fn)(x, valid))


def _reference(x, valid, n_bins):
    labels, _ = decile_assign_panel(jnp.asarray(x), jnp.asarray(valid),
                                    n_bins=n_bins, mode="rank")
    return np.asarray(labels)


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_matches_single_device_and_shard_invariant(rng, n_shards):
    A, M, B = 48, 30, 10
    x = rng.normal(size=(A, M))
    valid = rng.random((A, M)) > 0.2
    x = np.where(valid, x, np.nan)
    got = _sharded_labels(x, valid, B, n_shards)
    np.testing.assert_array_equal(got, _reference(x, valid, B))


def test_heavy_ties(rng):
    """Quantized values force many exact ties; position tie-break must match
    the stable argsort's."""
    A, M, B = 64, 20, 5
    x = np.round(rng.normal(size=(A, M)) * 3) / 3.0   # few distinct values
    valid = rng.random((A, M)) > 0.1
    x = np.where(valid, x, np.nan)
    for s in (2, 8):
        np.testing.assert_array_equal(
            _sharded_labels(x, valid, B, s), _reference(x, valid, B)
        )


def test_all_equal_and_signed_zero(rng):
    A, M, B = 32, 6, 10
    x = np.zeros((A, M))
    x[: A // 2, 0] = -0.0                  # -0.0 must tie with +0.0
    x[:, 1] = 7.25
    x[:, 2] = rng.normal(size=A)
    x[:, 3] = -np.abs(rng.normal(size=A))  # all-negative cross-section
    valid = np.ones((A, M), bool)
    valid[:, 4] = False                    # empty date
    valid[1:, 5] = False                   # single survivor
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, 4), _reference(x, valid, B)
    )


def test_sparse_dates(rng):
    """Dates with fewer valid lanes than bins."""
    A, M, B = 40, 12, 10
    x = rng.normal(size=(A, M))
    valid = rng.random((A, M)) > 0.85      # ~6 lanes/date
    x = np.where(valid, x, np.nan)
    np.testing.assert_array_equal(
        _sharded_labels(x, valid, B, 8), _reference(x, valid, B)
    )


def test_grid_engine_rank_hist_mode(rng):
    """sharded_jk_grid_backtest(mode='rank_hist') == mode='rank' end to end."""
    from csmom_tpu.parallel import make_mesh, sharded_jk_grid_backtest
    from csmom_tpu.parallel.mesh import pad_assets

    A, T = 40, 100
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, T)), axis=1))
    mask = np.ones((A, T), bool)
    mask[:6, :25] = False
    mesh = make_mesh(jax.devices()[:4], grid_axis=1)
    pv, mv, _ = pad_assets(prices, mask, mesh.shape["assets"])
    Js = np.array([6, 12])
    Ks = np.array([1, 3])
    out_h = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, skip=1, mode="rank_hist")
    out_r = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, skip=1, mode="rank")
    np.testing.assert_allclose(np.asarray(out_h[0]), np.asarray(out_r[0]),
                               rtol=1e-12, equal_nan=True)
    np.testing.assert_array_equal(np.asarray(out_h[1]), np.asarray(out_r[1]))
