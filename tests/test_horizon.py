"""Event-time horizon profile vs a pandas loop oracle.

The profile reuses the grid engine's cohort tensor, so the oracle here is
an independent per-(formation, horizon) pandas computation of the same
quantity: decile-sort at s, equal-weighted top-minus-bottom return h+1
months later."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.backtest import horizon_profile
from csmom_tpu.analytics.tables import horizon_table


def _panel(rng, A=30, M=70):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.07, size=(A, M)), axis=1))
    prices[:4, :10] = np.nan  # late entrants
    mask = np.isfinite(prices)
    return prices, mask


def _oracle(prices, mask, J, skip, n_bins, max_h):
    """Independent pandas implementation over the wide frame."""
    A, M = prices.shape
    px = pd.DataFrame(prices.T)  # [M, A]
    ret = px.pct_change()
    mom = px.shift(skip) / px.shift(skip + J) - 1.0

    out = np.full((M, max_h), np.nan)
    for s in range(M):
        sig = mom.iloc[s]
        live = sig.notna() & mask[:, s]
        if live.sum() < 2:
            continue
        q = pd.qcut(sig[live], n_bins, labels=False, duplicates="drop")
        top = q.index[q == q.max()]
        bot = q.index[q == 0]
        if q.max() == 0:
            continue
        for h in range(1, max_h + 1):
            if s + h >= M:
                break
            r = ret.iloc[s + h]
            rt, rb = r[top].dropna(), r[bot].dropna()
            if len(rt) and len(rb):
                out[s, h - 1] = rt.mean() - rb.mean()
    return out


@pytest.mark.parametrize("J,skip", [(6, 1), (12, 0)])
def test_matches_pandas_oracle(rng, J, skip):
    prices, mask = _panel(rng)
    max_h = 8
    hp = horizon_profile(prices, mask, lookback=J, skip=skip, n_bins=5,
                         mode="qcut", max_h=max_h)
    oracle = _oracle(prices, mask, J, skip, 5, max_h)
    want_mean = np.nanmean(oracle, axis=0)
    np.testing.assert_allclose(np.asarray(hp.mean_spread), want_mean, rtol=1e-9)
    want_n = np.sum(~np.isnan(oracle), axis=0)
    np.testing.assert_array_equal(np.asarray(hp.n_cohorts), want_n)


def test_cum_is_cumsum_and_shapes(rng):
    # canonical horizon cell (default _panel, lookback=6, n_bins=5,
    # max_h=8): shares ONE jit compile with the [6-1] oracle test and the
    # persistence test — these three were ~21s of tier compile wall when
    # each picked its own shapes
    prices, mask = _panel(rng)
    hp = horizon_profile(prices, mask, lookback=6, n_bins=5, max_h=8)
    assert np.asarray(hp.mean_spread).shape == (8,)
    np.testing.assert_allclose(
        np.asarray(hp.cum_spread),
        np.cumsum(np.nan_to_num(np.asarray(hp.mean_spread))),
        rtol=1e-12,
    )
    # NW inference present at every live horizon
    live = np.asarray(hp.n_cohorts) > 1
    assert np.isfinite(np.asarray(hp.tstat_nw)[live]).all()


def test_horizon_table_buckets(rng):
    prices, mask = _panel(rng, A=25, M=60)
    hp = horizon_profile(prices, mask, lookback=6, max_h=12)
    df = horizon_table(hp, group=6)
    assert list(df.index) == ["m1-6", "m7-12"]
    assert abs(df.loc["m1-6", "mean_spread"]
               - np.nanmean(np.asarray(hp.mean_spread)[:6])) < 1e-12
    assert df.loc["m7-12", "cum_spread"] == pytest.approx(
        float(np.asarray(hp.cum_spread)[11])
    )
    per_month = horizon_table(hp, group=1)
    assert list(per_month.index)[0] == "m1" and len(per_month) == 12


def _oracle_by_volume(prices, mask, turn, turn_valid, J, skip, n_bins, V, max_h):
    """Pandas loop oracle for the volume-conditioned profile (independent
    double sort at formation; qcut semantics for both sorts)."""
    A, M = prices.shape
    px = pd.DataFrame(prices.T)
    ret = px.pct_change()
    mom = px.shift(skip) / px.shift(skip + J) - 1.0

    out = np.full((V, M, max_h), np.nan)
    for s in range(M):
        sig = mom.iloc[s]
        live_m = sig.notna() & mask[:, s]
        if live_m.sum() < 2:
            continue
        q = pd.qcut(sig[live_m], n_bins, labels=False, duplicates="drop")
        if q.max() == 0:
            continue
        tv = pd.Series(turn[:, s])
        live_v = live_m & tv.notna() & turn_valid[:, s]
        if live_v.sum() < 2:
            continue
        vq = pd.qcut(tv[live_v], V, labels=False, duplicates="drop")
        for v in range(V):
            in_v = vq.index[vq == v]
            top = [a for a in q.index[q == q.max()] if a in set(in_v)]
            bot = [a for a in q.index[q == 0] if a in set(in_v)]
            for h in range(1, max_h + 1):
                if s + h >= M:
                    break
                r = ret.iloc[s + h]
                rt, rb = r[top].dropna(), r[bot].dropna()
                if len(rt) and len(rb):
                    out[v, s, h - 1] = rt.mean() - rb.mean()
    return out


@pytest.mark.slow
def test_volume_profile_matches_pandas_oracle(rng):
    from csmom_tpu.backtest import volume_horizon_profile

    A, M, V, max_h = 36, 60, 3, 5
    prices, mask = _panel(rng, A=A, M=M)
    turn = np.abs(rng.normal(2, 1, size=(A, M)))
    turn_valid = rng.random((A, M)) > 0.1
    turn = np.where(turn_valid, turn, np.nan)

    vhp = volume_horizon_profile(prices, mask, turn, turn_valid, lookback=6,
                                 skip=1, n_bins=4, n_vol_bins=V,
                                 mode="qcut", max_h=max_h)
    oracle = _oracle_by_volume(prices, mask, turn, turn_valid, 6, 1, 4, V, max_h)
    want_mean = np.nanmean(oracle, axis=1)            # [V, H]
    np.testing.assert_allclose(np.asarray(vhp.mean_spread), want_mean,
                               rtol=1e-9, equal_nan=True)
    want_n = np.sum(~np.isnan(oracle), axis=1)
    np.testing.assert_array_equal(np.asarray(vhp.n_cohorts), want_n)
    # the high-minus-low contrast uses only jointly-live (s, h) cells
    both = ~np.isnan(oracle[-1]) & ~np.isnan(oracle[0])
    want_diff = np.array([
        np.mean((oracle[-1] - oracle[0])[both[:, h], h]) if both[:, h].any()
        else np.nan
        for h in range(max_h)
    ])
    np.testing.assert_allclose(np.asarray(vhp.diff_mean), want_diff,
                               rtol=1e-9, equal_nan=True)


@pytest.mark.slow
def test_volume_horizon_table_shape(rng):
    from csmom_tpu.backtest import volume_horizon_profile
    from csmom_tpu.analytics.tables import volume_horizon_table

    prices, mask = _panel(rng, A=30, M=60)
    turn = np.abs(rng.normal(2, 1, size=prices.shape))
    tv = np.ones(prices.shape, bool)
    vhp = volume_horizon_profile(prices, mask, turn, tv, lookback=6,
                                 n_bins=4, max_h=12)
    df = volume_horizon_table(vhp, group=6)
    assert list(df.index) == ["m1-6", "m7-12"]
    assert list(df.columns) == ["V1 (low)", "V2", "V3 (high)", "Vhigh-Vlow",
                                "diff_t_nw"]


def test_persistence_signal_on_trending_panel(rng):
    """A panel with persistent per-asset drifts must show positive spreads
    at every horizon (winners keep winning when drifts are permanent)."""
    A, M = 30, 70  # the canonical horizon cell's shapes (shared compile)
    drift = np.linspace(-0.02, 0.02, A)[:, None]
    prices = 50 * np.exp(np.cumsum(
        drift + rng.normal(0, 0.001, size=(A, M)), axis=1))
    mask = np.ones((A, M), bool)
    hp = horizon_profile(prices, mask, lookback=6, max_h=8, n_bins=5)
    assert (np.asarray(hp.mean_spread) > 0).all()
    assert float(hp.cum_spread[-1]) > float(hp.cum_spread[0])
