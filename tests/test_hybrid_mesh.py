"""Multi-host hybrid mesh (DCN x ICI) on the mocked 8-device CPU mesh.

Simulates 2 hosts x 4 chips: the collective-free grid/bootstrap axis spans
"hosts" while the asset axis (all_gather + psum) stays host-local, and the
sharded engines still match the single-device engines exactly.
"""

import dataclasses

import numpy as np
import pytest

import jax

from csmom_tpu.backtest import jk_grid_backtest
from csmom_tpu.parallel import (
    make_hybrid_mesh,
    mesh_topology,
    distributed_init,
    sharded_jk_grid_backtest,
)
from csmom_tpu.parallel.mesh import _group_by_host, pad_assets

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("8 virtual CPU devices not configured")
    return jax.devices()[:8]


def _panel(rng, A=29, M=72):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.003, 0.07, size=(A, M)), axis=1))
    prices[:5, :12] = np.nan
    mask = np.isfinite(prices)
    return prices, mask


def test_hybrid_mesh_shape_and_grouping(eight_devices):
    mesh = make_hybrid_mesh(eight_devices, n_hosts=2)
    assert dict(mesh.shape) == {"grid": 2, "assets": 4}
    # each "host" row is a contiguous block of the device list (ICI domain)
    assert list(mesh.devices[0]) == list(eight_devices[:4])
    assert list(mesh.devices[1]) == list(eight_devices[4:])
    topo = mesh_topology(mesh)
    assert topo["grid"]["size"] == 2 and topo["assets"]["size"] == 4
    # simulated hosts share one process, so nothing truly crosses
    assert not topo["assets"]["crosses_hosts"]


def test_group_by_host_uses_process_index():
    """Real multi-process grouping: rows follow device.process_index."""

    @dataclasses.dataclass
    class FakeDev:
        id: int
        process_index: int

    devs = [FakeDev(i, i % 2) for i in range(8)]  # interleaved processes
    rows = _group_by_host(devs, None)
    assert [d.process_index for d in rows[0]] == [0] * 4
    assert [d.process_index for d in rows[1]] == [1] * 4
    with pytest.raises(ValueError, match="n_hosts=3"):
        _group_by_host(devs, 3)
    uneven = [FakeDev(0, 0), FakeDev(1, 0), FakeDev(2, 1)]
    with pytest.raises(ValueError, match="uneven"):
        _group_by_host(uneven, None)


def test_group_by_host_single_process_split():
    @dataclasses.dataclass
    class FakeDev:
        id: int
        process_index: int = 0

    devs = [FakeDev(i) for i in range(6)]
    rows = _group_by_host(devs, 3)
    assert [len(r) for r in rows] == [2, 2, 2]
    with pytest.raises(ValueError, match="not divisible"):
        _group_by_host(devs, 4)


def test_grid_engine_on_hybrid_mesh_matches_single(rng, eight_devices):
    """2 simulated hosts x 4 chips: J cells across 'hosts', assets within."""
    prices, mask = _panel(rng)
    mesh = make_hybrid_mesh(eight_devices, n_hosts=2)
    pv, mv, _ = pad_assets(prices, mask, mesh.shape["assets"])

    Js = np.array([6, 12])
    Ks = np.array([1, 3, 6])
    res = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh)
    single = jk_grid_backtest(prices, mask, Js, Ks)

    live = np.asarray(res.spread_valid)
    np.testing.assert_array_equal(live, np.asarray(single.spread_valid))
    np.testing.assert_allclose(
        np.asarray(res.spreads)[live],
        np.asarray(single.spreads)[np.asarray(single.spread_valid)],
        rtol=1e-11,
    )
    np.testing.assert_allclose(np.asarray(res.ann_sharpe),
                               np.asarray(single.ann_sharpe),
                               rtol=1e-10, equal_nan=True)
    np.testing.assert_allclose(np.asarray(res.tstat_nw),
                               np.asarray(single.tstat_nw),
                               rtol=1e-10, equal_nan=True)


def test_distributed_init_single_process_and_errors(monkeypatch):
    """No cluster env -> False; real failures propagate; already-up -> False.

    jax.distributed.initialize is monkeypatched: really initializing (or
    running cluster auto-detection) inside a sandboxed test process would
    touch the network/backend.
    """
    calls = {}

    def fake_initialize(coordinator_address=None, num_processes=None, process_id=None):
        calls["args"] = (coordinator_address, num_processes, process_id)
        raise ValueError("coordinator_address should be defined.")

    monkeypatch.setattr(jax.distributed, "initialize", fake_initialize)
    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: False)
    assert distributed_init() is False  # plain single-process run
    assert calls["args"] == (None, None, None)

    # an explicit coordinator means the same error is a genuine failure
    with pytest.raises(ValueError, match="coordinator_address"):
        distributed_init(coordinator_address="10.0.0.1:1234")

    def boom(**kw):
        raise RuntimeError("backend already initialized")

    monkeypatch.setattr(jax.distributed, "initialize", boom)
    with pytest.raises(RuntimeError, match="already initialized"):
        distributed_init()

    monkeypatch.setattr(jax.distributed, "is_initialized", lambda: True)
    assert distributed_init() is False  # launcher brought the service up
