"""Ingest tests: both cache dialects, fault isolation, panel pivot."""

import os

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.panel import ingest
from tests.conftest import DEMO_TICKERS, requires_reference, REFERENCE_DATA

DIALECT_A = """Date,Adj Close,Close,High,Low,Open,Volume
,FAKE,FAKE,FAKE,FAKE,FAKE,FAKE
2020-01-02,10.0,10.5,11.0,9.5,10.0,1000
2020-01-03,10.2,10.7,11.2,9.7,10.1,1100
"""

DIALECT_B = """Price,Close,High,Low,Open,Volume
Ticker,FAKE,FAKE,FAKE,FAKE,FAKE
Date,,,,,
2020-01-02,10.0,11.0,9.5,10.0,1000
2020-01-03,10.2,11.2,9.7,10.1,1100
"""

INTRADAY = """Datetime,Adj Close,Close,High,Low,Open,Volume
,FAKE,FAKE,FAKE,FAKE,FAKE,FAKE
2025-08-18 13:30:00+00:00,100.0,100.0,100.5,99.5,100.0,500
2025-08-18 13:31:00+00:00,100.2,100.2,100.6,99.9,100.1,400
"""


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def test_dialect_a(tmp_path):
    p = _write(tmp_path, "FAKE_daily.csv", DIALECT_A)
    df = ingest.read_price_csv(p, "FAKE", kind="daily")
    assert list(df.columns) == ingest.DAILY_SCHEMA
    assert len(df) == 2
    assert df["adj_close"].tolist() == [10.0, 10.2]
    assert df["close"].tolist() == [10.5, 10.7]
    assert df["date"].iloc[0] == pd.Timestamp("2020-01-02")


def test_dialect_b_fixes_reference_bug(tmp_path):
    """Dialect B (the AAPL header) must survive the roundtrip — the reference
    silently loses the whole file (SURVEY §2.1.1)."""
    p = _write(tmp_path, "FAKE_daily.csv", DIALECT_B)
    df = ingest.read_price_csv(p, "FAKE", kind="daily")
    assert len(df) == 2
    # no Adj Close column in dialect B -> adj_close falls back to close
    assert df["adj_close"].tolist() == [10.0, 10.2]
    assert df["volume"].tolist() == [1000.0, 1100.0]


def test_intraday_dialect(tmp_path):
    p = _write(tmp_path, "FAKE_intraday.csv", INTRADAY)
    df = ingest.read_price_csv(p, "FAKE", kind="intraday")
    assert list(df.columns) == ingest.INTRADAY_SCHEMA
    assert df["price"].tolist() == [100.0, 100.2]
    assert df["datetime"].iloc[0] == pd.Timestamp("2025-08-18 13:30:00")


def test_fault_isolation(tmp_path):
    """A missing or corrupt ticker is skipped, never fatal (data_io.py:173-175)."""
    _write(tmp_path, "GOOD_daily.csv", DIALECT_A.replace("FAKE", "GOOD"))
    _write(tmp_path, "BAD_daily.csv", "not,a,csv\nat all")
    df = ingest.load_daily(str(tmp_path), ["GOOD", "BAD", "MISSING"])
    assert set(df["ticker"]) == {"GOOD"}


def test_long_to_panel_masks_gaps(tmp_path):
    df = pd.DataFrame(
        {
            "date": pd.to_datetime(["2020-01-02", "2020-01-03", "2020-01-02"]),
            "ticker": ["A", "A", "B"],
            "adj_close": [1.0, 2.0, 3.0],
        }
    )
    panel = ingest.long_to_panel(df, "adj_close")
    assert panel.shape == (2, 2)
    assert panel.mask.tolist() == [[True, True], [True, False]]
    assert np.isnan(panel.values[1, 1])
    assert panel.values[0, 1] == 2.0


@requires_reference
def test_reference_daily_roundtrip_full_universe():
    """All 20 shipped daily caches load — including AAPL's dialect B."""
    df = ingest.load_daily(REFERENCE_DATA, DEMO_TICKERS)
    per = df.groupby("ticker").size()
    assert set(per.index) == set(DEMO_TICKERS)
    # AAPL has ~1762 bars (SURVEY §2 row 16); all tickers span 2018..2024
    assert per["AAPL"] > 1700
    assert df["adj_close"].notna().mean() > 0.99


@requires_reference
def test_reference_intraday_roundtrip():
    df = ingest.load_intraday(REFERENCE_DATA, DEMO_TICKERS)
    assert set(df["ticker"]) == set(DEMO_TICKERS)
    per = df.groupby("ticker").size()
    assert (per > 2000).all()


class TestVendoredDialectFixtures:
    """Committed SYNTHETIC fixtures in both yfinance header dialects:
    dialect handling stays tested on a bare checkout (without these, the
    dialect-B path was only exercised through the reference mount's AAPL
    file and skipped offline)."""

    FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")

    def test_dialect_a_junk_ticker_row(self):
        df = ingest.load_daily(self.FIXTURES, ["SYNA"])
        assert len(df) == 24                       # junk row dropped
        assert set(df["ticker"]) == {"SYNA"}
        assert df["adj_close"].notna().all()
        assert str(df["date"].iloc[0].date()) == "2020-01-03"

    def test_dialect_b_three_row_preamble(self):
        """The dialect the reference's own loader silently loses (SURVEY
        §2.1.1): must parse all rows, adj_close falling back to close."""
        df = ingest.load_daily(self.FIXTURES, ["SYNB"])
        assert len(df) == 24
        assert df["adj_close"].notna().all()       # close fallback applied
        assert df["close"].iloc[0] == df["adj_close"].iloc[0]

    def test_both_dialects_pivot_to_one_panel(self):
        df = ingest.load_daily(self.FIXTURES, ["SYNA", "SYNB"])
        panel = ingest.long_to_panel(df, "adj_close")
        assert panel.tickers == ("SYNA", "SYNB")
        assert panel.shape == (2, 24)
        assert panel.mask.all()

    def test_reference_readable_daily_detects_dialect_b(self):
        """Parity mode's universe filter: dialect-B files (the ones the
        reference's loader loses) are excluded, dialect-A and only those
        kept; missing files drop out rather than raise."""
        got = ingest.reference_readable_daily(
            self.FIXTURES, ["SYNA", "SYNB", "NOPE"]
        )
        assert got == ["SYNA"]

    def test_reference_readable_daily_quoted_and_marker_headers(self, tmp_path):
        """Detection matches what the REFERENCE's loader would do: a quoted
        '\"Price\"' header is still dialect B (excluded), and any file with
        our fetch-cache marker line is excluded outright — the reference's
        bare read_csv takes the marker as a one-field header and loses the
        file regardless of dialect."""
        (tmp_path / "QB_daily.csv").write_text(
            '"Price","Close","High","Low","Open","Volume"\n'
            "Ticker,QB,QB,QB,QB,QB\nDate,,,,,\n2020-01-03,1,1,1,1,10\n"
        )
        (tmp_path / "QA_daily.csv").write_text(
            '"Date","Adj Close","Close","High","Low","Open","Volume"\n'
            "2020-01-03,1,1,1,1,1,10\n"
        )
        (tmp_path / "MA_daily.csv").write_text(
            "# csmom-cache-v1\n"
            "Date,Adj Close,Close,High,Low,Open,Volume\n"
            "2020-01-03,1,1,1,1,1,10\n"
        )
        got = ingest.reference_readable_daily(
            str(tmp_path), ["QB", "QA", "MA"]
        )
        assert got == ["QA"]


# --------------------- ISSUE 7 satellite: duplicate-timestamp dedupe ------

DOCTORED_DUPES = """Date,Adj Close,Close,High,Low,Open,Volume
,FAKE,FAKE,FAKE,FAKE,FAKE,FAKE
2020-01-02,10.0,10.5,11.0,9.5,10.0,1000
2020-01-03,10.2,10.7,11.2,9.7,10.1,1100
2020-01-03,10.9,10.9,11.9,9.9,10.9,1900
2020-01-06,10.4,10.8,11.4,9.8,10.2,1200
"""


def _capture_ingest_warnings(caplog):
    """The csmom_tpu root logger is propagate=False (it owns its own
    handler), so caplog's root capture misses it — attach caplog's
    handler to the package logger directly."""
    import contextlib
    import logging

    @contextlib.contextmanager
    def _cm():
        lg = logging.getLogger("csmom_tpu.panel.ingest")
        lg.addHandler(caplog.handler)
        try:
            with caplog.at_level(logging.WARNING,
                                 logger="csmom_tpu.panel.ingest"):
                yield
        finally:
            lg.removeHandler(caplog.handler)

    return _cm()


def test_duplicate_timestamps_deduped_keep_last(tmp_path, caplog):
    """A vendor cache carrying a repeated date (a re-download appended a
    correction row) must dedupe keep-last with a COUNTED warning —
    silently keeping both rows let pivot_table pick one arbitrarily."""
    p = _write(tmp_path, "FAKE_daily.csv", DOCTORED_DUPES)
    with _capture_ingest_warnings(caplog):
        df = ingest.read_price_csv(p, "FAKE", kind="daily")
    assert len(df) == 3
    assert not df["date"].duplicated().any()
    # keep-LAST: the correction row (10.9) wins over the stale 10.2
    dup_day = df[df["date"] == pd.Timestamp("2020-01-03")]
    assert dup_day["adj_close"].tolist() == [10.9]
    warnings = [r for r in caplog.records
                if "duplicate" in r.getMessage()]
    assert len(warnings) == 1
    assert "1 duplicate" in warnings[0].getMessage()


def test_no_duplicate_warning_on_clean_cache(tmp_path, caplog):
    """A clean cache must not emit the dedupe warning (the counter is a
    finding, not noise)."""
    p = _write(tmp_path, "FAKE_daily.csv", DIALECT_A)
    with _capture_ingest_warnings(caplog):
        df = ingest.read_price_csv(p, "FAKE", kind="daily")
    assert len(df) == 2
    assert not [r for r in caplog.records if "duplicate" in r.getMessage()]
