"""Intraday features vs a pandas oracle of the reference's formulas."""

import numpy as np
import pandas as pd

from csmom_tpu.signals.intraday import (
    compact_minutes,
    minute_features,
    next_row_return,
    FEATURE_NAMES,
)


def oracle_features(df: pd.DataFrame, window=30) -> pd.DataFrame:
    """features.py:110-143 semantics, re-derived."""
    d = df.sort_values(["ticker", "datetime"]).reset_index(drop=True)
    g = d.groupby("ticker")
    d["price_lag"] = g["price"].shift(1)
    d["ret_1m"] = d["price"] / d["price_lag"] - 1
    d["ret_5m"] = d.groupby("ticker")["ret_1m"].rolling(5, min_periods=1).sum().reset_index(0, drop=True)
    d["tick"] = np.sign(d["price"] - d["price_lag"]).fillna(0)
    d["svol"] = d["tick"] * d["volume"]
    d["vol_roll_sum"] = d.groupby("ticker")["volume"].rolling(window, min_periods=1).sum().reset_index(0, drop=True)
    d["signed_vol_roll"] = d.groupby("ticker")["svol"].rolling(window, min_periods=1).sum().reset_index(0, drop=True)
    m60 = d.groupby("ticker")["vol_roll_sum"].rolling(60, min_periods=1).mean().reset_index(0, drop=True)
    s60 = d.groupby("ticker")["vol_roll_sum"].rolling(60, min_periods=1).std().reset_index(0, drop=True).fillna(1.0)
    d["vol_zscore"] = (d["vol_roll_sum"] - m60) / s60
    return d


def _toy_minutes(rng, n_assets=4, n_min=300, drop_frac=0.05):
    times = pd.date_range("2025-08-18 13:30", periods=n_min, freq="min")
    rows = []
    for a in range(n_assets):
        keep = rng.random(n_min) > (drop_frac * a)  # different gaps per asset
        p = 100 * np.exp(np.cumsum(rng.normal(0, 2e-4, n_min)))
        v = rng.integers(1e3, 1e6, n_min)
        for t, k, pi, vi in zip(times, keep, p, v):
            if k:
                rows.append({"datetime": t, "ticker": f"T{a}", "price": pi, "volume": float(vi)})
    return pd.DataFrame(rows)


def test_features_match_pandas_oracle(rng):
    df = _toy_minutes(rng)
    compact = compact_minutes(df)
    feats, feat_valid = minute_features(
        compact.price, compact.volume, compact.row_valid, window=30
    )
    feats = np.asarray(feats)
    want = oracle_features(df)

    for a, t in enumerate(compact.tickers):
        wt = want[want["ticker"] == t]
        n = len(wt)
        for fi, name in enumerate(FEATURE_NAMES):
            got_col = feats[a, :n, fi]
            want_col = wt[name].values
            np.testing.assert_allclose(
                got_col, want_col, rtol=1e-9, atol=1e-12, equal_nan=True,
                err_msg=f"{t}/{name}",
            )
        # dropna survivors: row 0 only casualty
        assert not feat_valid[a, 0]
        assert np.asarray(feat_valid)[a, 1:n].all()


def test_next_row_return(rng):
    df = _toy_minutes(rng, n_assets=2, n_min=50, drop_frac=0.1)
    compact = compact_minutes(df)
    feats, feat_valid = minute_features(compact.price, compact.volume, compact.row_valid)
    y, y_valid = next_row_return(jnp_arr(compact.price), feat_valid)
    y = np.asarray(y)
    for a, t in enumerate(compact.tickers):
        n = int(compact.row_valid[a].sum())
        # last surviving row invalid; inner rows = next-row simple return
        assert not np.asarray(y_valid)[a, n - 1]
        for j in range(1, n - 1):
            want = compact.price[a, j + 1] / compact.price[a, j] - 1
            assert abs(y[a, j] - want) < 1e-12


def jnp_arr(x):
    import jax.numpy as jnp

    return jnp.asarray(x)


def test_compaction_roundtrip(rng):
    df = _toy_minutes(rng, n_assets=3, n_min=100, drop_frac=0.15)
    compact = compact_minutes(df)
    # every original row appears exactly once at its global minute index
    total = int(compact.row_valid.sum())
    assert total == len(df)
    for a, t in enumerate(compact.tickers):
        n = int(compact.row_valid[a].sum())
        times_back = compact.times[compact.time_idx[a, :n]]
        want_times = np.sort(df[df["ticker"] == t]["datetime"].values)
        np.testing.assert_array_equal(times_back, want_times)
