"""The persistent-compile-cache helper honors its env contract.

The cache is what keeps tunnel windows from being spent on recompiles
(bench children, scaling/phases captures) and what makes consecutive CLI
invocations warm — so the opt-out and override paths must actually work.
jax config is process-global state; each test restores the prior value.
"""

import os

import jax
import pytest

from csmom_tpu.utils.jit_cache import enable_persistent_cache


@pytest.fixture()
def restore_cache_dir():
    prev = jax.config.jax_compilation_cache_dir
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


def test_default_path_is_uid_suffixed(monkeypatch, restore_cache_dir):
    monkeypatch.delenv("CSMOM_JIT_CACHE", raising=False)
    path = enable_persistent_cache("unittest")
    assert path is not None
    assert path.endswith(f"csmom_unittest_cache-{os.getuid()}")
    assert jax.config.jax_compilation_cache_dir == path


def test_env_zero_disables(monkeypatch, restore_cache_dir):
    monkeypatch.setenv("CSMOM_JIT_CACHE", "0")
    before = jax.config.jax_compilation_cache_dir
    assert enable_persistent_cache("unittest") is None
    assert jax.config.jax_compilation_cache_dir == before


def test_env_value_overrides_directory(monkeypatch, tmp_path, restore_cache_dir):
    monkeypatch.setenv("CSMOM_JIT_CACHE", str(tmp_path / "override"))
    path = enable_persistent_cache("unittest")
    assert path == str(tmp_path / "override")
    assert jax.config.jax_compilation_cache_dir == path
