"""Perf ledger: artifact ingestion, CI-backed verdicts, the gate CLI.

Three layers under test:

- :mod:`csmom_tpu.obs.ledger` — committed artifacts normalize into
  provenance-aware rows, and rows only compare within an identical
  (metric, platform, device, workload) key: the cpu-fallback-vs-tpu
  confusion the ledger exists to prevent is pinned here, not prose;
- :mod:`csmom_tpu.obs.regress` — raw repeat samples become block-
  bootstrap CIs (reusing analytics/bootstrap) and a regression is only
  CONFIRMED on disjoint intervals + a practically-significant delta;
- ``csmom ledger`` CLI — `gate` exits nonzero on a synthetic injected
  regression and on unexplained memory growth, zero on the committed
  artifact history (with ``BENCH_r04.json`` surfaced as the known r4
  gap, never excused into a row); `diff` prints bootstrap CIs, not bare
  deltas, for every sampled metric; malformed artifacts degrade to
  pointed messages, never tracebacks (same contract as `csmom
  timeline`, whose malformed-sidecar behavior is pinned here too).
"""

import json
import os

import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.cli.main import main as cli_main
from csmom_tpu.obs import ledger as ld
from csmom_tpu.obs import regress

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# tight/loose sample sets around two well-separated means: the injected
# regression (REF -> 3x) must CONFIRM, and REF-vs-REF_B must not
REF_SAMPLES = [0.100, 0.101, 0.099, 0.1002, 0.0998, 0.1005, 0.0995, 0.1001]
REF_SAMPLES_B = [0.1003, 0.0997, 0.1004, 0.0999, 0.1, 0.1002, 0.0996, 0.1]
BAD_SAMPLES = [3 * s for s in REF_SAMPLES_B]

_WORKLOADS = {
    "workload": "golden 20x2728 minute panel, 28020 trades (float64)",
    "grid_workload": "16 cells, 512 stocks x 3780 days (174 months)",
}


def _full_record(rank_samples, value=1.5e6, platform="cpu", **extra_over):
    mean = sum(rank_samples) / len(rank_samples)
    extra = {
        "platform": platform,
        "device_kind": platform,
        **_WORKLOADS,
        "grid16_rank_s": round(mean, 6),
        "samples": {"grid16_rank_s": list(rank_samples)},
        **extra_over,
    }
    return {
        "metric": "intraday_event_backtest_bar_groups_per_sec",
        "value": value, "unit": "bar_groups/s", "vs_baseline": 1.0,
        "extra": extra,
    }


def _telemetry(run, peak_bytes):
    return {
        "kind": "telemetry", "schema_version": 1, "run_id": run,
        "wall_s": 1.0, "t0_s": 0.0, "t1_s": 1.0,
        "phases": [{"name": "row", "dur_s": 1.0}],
        "metrics": {"memory": {"grid.jk16.rank.xla@512x3780": {
            "argument_size_in_bytes": 100, "temp_size_in_bytes": 50,
            "peak_bytes": int(peak_bytes), "platform": "cpu",
        }}},
    }


def _write(root, name, obj):
    with open(os.path.join(root, name), "w") as f:
        json.dump(obj, f)


@pytest.fixture
def clean_pair(tmp_path):
    """Two runs, statistically identical grid samples."""
    _write(tmp_path, "BENCH_FULL_r01.json", _full_record(REF_SAMPLES))
    _write(tmp_path, "BENCH_FULL_r02.json", _full_record(REF_SAMPLES_B))
    return tmp_path


@pytest.fixture
def regressed_pair(tmp_path):
    """Candidate run r02 with grid samples degraded 3x over r01."""
    _write(tmp_path, "BENCH_FULL_r01.json", _full_record(REF_SAMPLES))
    _write(tmp_path, "BENCH_FULL_r02.json", _full_record(BAD_SAMPLES))
    return tmp_path


# ------------------------------------------------------------ regress ----

def test_bootstrap_ci_brackets_the_mean():
    ci = regress.bootstrap_mean_ci(REF_SAMPLES, n_resamples=500)
    assert ci["lo"] <= ci["point"] <= ci["hi"]
    assert abs(ci["point"] - 0.1) < 0.001
    assert ci["n"] == len(REF_SAMPLES)


def test_confirmed_regression_needs_disjoint_cis_and_material_delta():
    v = regress.compare_samples(BAD_SAMPLES, REF_SAMPLES, direction="lower")
    assert v["verdict"] == "regression" and v["worse"]
    # same distribution: never confirmed, whatever the noise says
    v2 = regress.compare_samples(REF_SAMPLES_B, REF_SAMPLES,
                                 direction="lower")
    assert v2["verdict"] == "no-change"
    # higher-is-better mirror: 3x more throughput is an improvement
    v3 = regress.compare_samples(BAD_SAMPLES, REF_SAMPLES,
                                 direction="higher")
    assert v3["verdict"] == "improvement"


def test_point_comparison_is_never_a_confirmed_regression():
    v = regress.compare(30.0, 10.0, direction="lower")
    assert v["verdict"] == "suspect"          # flagged...
    assert v["verdict"] not in regress.GATE_FAILING  # ...but never gating
    assert "point-delta" in v["basis"]
    # too few samples on one side degrades to point-delta too
    v2 = regress.compare(30.0, 10.0, cand_samples=[30.0] * 2,
                         ref_samples=REF_SAMPLES, direction="lower")
    assert "point-delta" in v2["basis"]


def test_memory_compare_is_deterministic():
    assert regress.compare_memory(220, 100)["verdict"] == "memory-growth"
    assert regress.compare_memory(105, 100)["verdict"] == "no-change"
    assert regress.compare_memory(50, 100)["verdict"] == "memory-shrink"


# ------------------------------------------------------------- ledger ----

def test_ingest_separates_platforms_and_provenance(tmp_path):
    _write(tmp_path, "BENCH_FULL_r01.json", _full_record(REF_SAMPLES))
    _write(tmp_path, "BENCH_FULL_r02.json",
           _full_record(REF_SAMPLES_B, platform="tpu"))
    L = ld.load(str(tmp_path))
    rows = [r for r in L.rows if r.metric == "grid16_rank_s"]
    assert {r.platform for r in rows} == {"cpu", "tpu"}
    keys = {r.key() for r in rows}
    assert len(keys) == 2, "cpu and tpu rows must never share a ledger key"
    assert all(r.samples == tuple(r_s) for r, r_s in
               zip(sorted(rows, key=lambda r: r.run),
                   (REF_SAMPLES, REF_SAMPLES_B)))


def test_partial_smoke_and_variant_rows_are_not_gate_eligible(tmp_path):
    _write(tmp_path, "BENCH_FULL_r01.json",
           _full_record(REF_SAMPLES, partial="deadline hit"))
    _write(tmp_path, "BENCH_FULL_r02_watcher.json",
           _full_record(REF_SAMPLES_B))
    L = ld.load(str(tmp_path))
    assert L.rows and not any(r.gate_eligible() for r in L.rows)
    flags = {f for r in L.rows for f in r.flags}
    assert "partial" in flags and "variant:watcher" in flags


def test_parsed_null_driver_capture_is_a_gap_not_a_row(tmp_path):
    _write(tmp_path, "BENCH_r04.json", {"rc": 0, "tail": "truncated…",
                                        "parsed": None})
    L = ld.load(str(tmp_path))
    assert L.rows == []
    assert any("r4 failure" in p["note"] for p in L.problems)


def test_damaged_artifact_is_a_problem_never_a_raise(tmp_path):
    (tmp_path / "BENCH_FULL_r01.json").write_text('{"metric": "x", "val')
    L = ld.load(str(tmp_path))
    assert L.rows == []
    assert any("not valid JSON" in p["note"] for p in L.problems)


def test_committed_history_ingests_with_known_gaps_only():
    L = ld.load(_REPO)
    assert len(L.rows) >= 20, "committed artifacts should yield a trajectory"
    gap_sources = {p["source"] for p in L.problems}
    # the two known headline losses stay visible as gaps
    assert {"BENCH_r01.json", "BENCH_r04.json"} <= gap_sources
    # every row's provenance fields are populated enough to key on
    for r in L.rows:
        assert r.run.startswith("r") and r.metric and r.source


# ----------------------------------------------------------- gate CLI ----

def test_gate_fails_on_injected_regression(regressed_pair, capsys):
    rc = cli_main(["ledger", "gate", "--offline",
                   "--root", str(regressed_pair)])
    out = capsys.readouterr()
    assert rc == 1
    assert "regression" in out.out and "GATE FAILED" in out.err


def test_gate_passes_on_statistically_identical_runs(clean_pair, capsys):
    rc = cli_main(["ledger", "gate", "--offline", "--root", str(clean_pair)])
    assert rc == 0
    assert "gate PASSED" in capsys.readouterr().out


def test_gate_fails_on_unexplained_memory_growth(clean_pair, capsys):
    _write(clean_pair, "TELEMETRY_r01.json", _telemetry("r01", 1_000_000))
    _write(clean_pair, "TELEMETRY_r02.json", _telemetry("r02", 2_000_000))
    rc = cli_main(["ledger", "gate", "--offline", "--root", str(clean_pair)])
    assert rc == 1
    assert "memory-growth" in capsys.readouterr().out


def test_gate_tolerates_in_band_memory_drift(clean_pair, capsys):
    _write(clean_pair, "TELEMETRY_r01.json", _telemetry("r01", 1_000_000))
    _write(clean_pair, "TELEMETRY_r02.json", _telemetry("r02", 1_050_000))
    rc = cli_main(["ledger", "gate", "--offline", "--root", str(clean_pair)])
    assert rc == 0


def test_gate_warns_on_gate_pairable_rows_without_samples(clean_pair,
                                                          capsys):
    """ISSUE 15 satellite: a gate-pairable metric riding without its
    bootstrap samples array (the r18 serve_fabric_throughput_rps /
    budget-burn shape) is NAMED by the gate — its verdicts can only
    ever be point-delta, and that degradation must be said, not
    silent.  Sampled metrics stay out of the warning."""
    rc = cli_main(["ledger", "gate", "--offline", "--root",
                   str(clean_pair)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "sample-coverage gaps" in out
    gap_block = out.split("sample-coverage gaps")[1]
    # the headline metric carries no samples entry -> warned
    assert "intraday_event_backtest_bar_groups_per_sec" in gap_block
    # the sampled grid wall must NOT be named a gap
    assert "grid16_rank_s" not in gap_block


def test_gate_passes_on_the_committed_artifact_history(capsys):
    """The tier-1 wiring (ISSUE satellite): the ledger gate runs offline
    over the repo's committed artifacts in every PR.  It must pass —
    point-delta drifts may be suspect but are never confirmed without
    samples — while BENCH_r04.json stays pinned as the visible known-bad
    gap (not excused, not a row)."""
    rc = cli_main(["ledger", "gate", "--offline", "--root", _REPO])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "gate PASSED" in out
    assert "BENCH_r04.json" in out  # the known gap stays surfaced


def test_diff_reports_bootstrap_cis_not_bare_deltas(regressed_pair, capsys):
    rc = cli_main(["ledger", "diff", "r01", "r02",
                   "--root", str(regressed_pair)])
    out = capsys.readouterr().out
    assert rc == 0
    # every sampled metric shows an interval and the sample count
    line = next(ln for ln in out.splitlines() if "grid16_rank_s" in ln
                and "regression" in ln)
    assert line
    assert "[0." in out and "(n=8)" in out
    assert "bootstrap-ci" in out


def test_diff_unknown_run_is_a_pointed_error(clean_pair, capsys):
    rc = cli_main(["ledger", "diff", "r01", "r99",
                   "--root", str(clean_pair)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "r99" in err and "known runs" in err


def test_show_markdown_emits_tables(capsys):
    rc = cli_main(["ledger", "show", "--markdown", "--root", _REPO,
                   "--metric", "grid16_rank_s"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "| run | value | platform |" in out
    assert "`BENCH_FULL_r05.json`" in out
    assert "csmom ledger show --markdown" in out  # provenance comment


def test_show_empty_root_is_a_pointed_error(tmp_path, capsys):
    rc = cli_main(["ledger", "show", "--root", str(tmp_path)])
    assert rc == 2
    assert "no round artifacts" in capsys.readouterr().err


def test_bare_ledger_prints_usage(capsys):
    assert cli_main(["ledger"]) == 2
    assert "csmom ledger {show,diff,gate}" in capsys.readouterr().err


# ------------------------------------------- timeline CLI robustness ----
# (same graceful-degradation contract as the ledger: a damaged sidecar
# gets a pointed nonzero exit, never a traceback)

def test_timeline_truncated_json_sidecar(tmp_path, capsys):
    p = tmp_path / "TELEMETRY_broken.json"
    p.write_text('{"kind": "telemetry", "run_id": "x", "wall_')
    rc = cli_main(["timeline", str(p)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "unreadable sidecar" in err


def test_timeline_missing_phases_flagged_not_crashed(tmp_path, capsys):
    p = tmp_path / "TELEMETRY_nophases.json"
    p.write_text(json.dumps({"kind": "telemetry", "schema_version": 1,
                             "run_id": "x", "wall_s": 1.0}))
    rc = cli_main(["timeline", str(p)])
    cap = capsys.readouterr()
    assert rc == 1
    assert "schema violations" in cap.err
    assert "phases" in cap.err


def test_timeline_unknown_schema_version_rejected(tmp_path, capsys):
    obj = _telemetry("x", 100)
    obj["schema_version"] = 99
    p = tmp_path / "TELEMETRY_future.json"
    p.write_text(json.dumps(obj))
    rc = cli_main(["timeline", str(p)])
    err = capsys.readouterr().err
    assert rc == 1
    assert "unknown schema_version 99" in err


def test_ledger_refuses_unknown_schema_telemetry(tmp_path):
    """Closed-world schema, ledger side: a future-era sidecar must not
    be half-parsed into gate-eligible rows (its byte semantics may have
    changed) — it becomes a named problem, and other artifacts still
    ingest."""
    obj = _telemetry("r01", 100)
    obj["schema_version"] = 99
    _write(tmp_path, "TELEMETRY_r01.json", obj)
    _write(tmp_path, "BENCH_FULL_r01.json", _full_record(REF_SAMPLES))
    L = ld.load(str(tmp_path))  # must not raise
    assert not any(r.metric == "mem_peak_bytes" for r in L.rows)
    assert any("unknown telemetry schema_version 99" in p["note"]
               for p in L.problems)
    assert any(r.metric == "grid16_rank_s" for r in L.rows)


# -------------------------------------------------- schema round-trips ----

def test_samples_schema_validated_in_records():
    good = _full_record(REF_SAMPLES)
    assert inv.validate(good, "record") == []
    bad = _full_record(REF_SAMPLES)
    bad["extra"]["samples"]["grid16_rank_s"] = ["0.1", 0.2]
    assert any("samples" in v for v in inv.validate(bad, "record"))
    bad2 = _full_record(REF_SAMPLES)
    bad2["extra"]["samples"] = [0.1, 0.2]
    assert any("samples" in v for v in inv.validate(bad2, "record"))


def test_telemetry_memory_block_schema():
    good = _telemetry("r01", 1000)
    assert inv.validate(good, "telemetry") == []
    bad = _telemetry("r01", 1000)
    bad["metrics"]["memory"]["grid.jk16.rank.xla@512x3780"].pop("peak_bytes")
    assert any("peak_bytes" in v for v in inv.validate(bad, "telemetry"))
    bad2 = _telemetry("r01", 1000)
    bad2["metrics"]["memory"]["x"] = {"peak_bytes": 1,
                                      "argument_size_in_bytes": "lots"}
    assert any("argument_size_in_bytes" in v
               for v in inv.validate(bad2, "telemetry"))
    # a capture-failure reason string is a legitimate per-shape value
    ok = _telemetry("r01", 1000)
    ok["metrics"]["memory"]["y"] = "not available: backend stub"
    assert inv.validate(ok, "telemetry") == []


# -------------------------------------------- review-hardening pins ----

def test_null_in_sample_list_degrades_never_raises(tmp_path):
    """ingest_file's no-raise contract holds for damaged sample lists:
    non-numeric entries are dropped (fewer samples), the file still
    contributes rows."""
    rec = _full_record(REF_SAMPLES)
    rec["extra"]["samples"]["grid16_rank_s"] = [0.1, None, "x", 0.2, True]
    _write(tmp_path, "BENCH_FULL_r01.json", rec)
    L = ld.load(str(tmp_path))
    row = next(r for r in L.rows if r.metric == "grid16_rank_s")
    assert row.samples == (0.1, 0.2)  # null/str/bool dropped, no raise


def test_pid_suffixed_sidecar_is_a_variant_not_round_evidence(tmp_path):
    """timeline.write_sidecar's no-clobber path lands operator reruns as
    TELEMETRY_rNN-<pid>.json; those must ingest flagged (never
    gate-eligible), so a gitignored local rerun cannot inject or mask a
    memory verdict for the round."""
    assert ld.run_of("TELEMETRY_r05-1234.json") == ("r05", 5, "1234")
    assert ld.run_of("TELEMETRY_r05.json") == ("r05", 5, None)
    _write(tmp_path, "TELEMETRY_r01.json", _telemetry("r01", 1_000_000))
    _write(tmp_path, "TELEMETRY_r01-999.json", _telemetry("r01", 9_999_999))
    L = ld.load(str(tmp_path))
    mem = [r for r in L.rows if r.metric == "mem_peak_bytes"]
    eligible = [r for r in mem if r.gate_eligible()]
    assert len(eligible) == 1 and eligible[0].value == 1_000_000
    rerun = next(r for r in mem if r.value == 9_999_999)
    assert "variant:999" in rerun.flags


def test_gate_bad_candidate_id_is_a_pointed_error(clean_pair, capsys):
    rc = cli_main(["ledger", "gate", "--root", str(clean_pair),
                   "--candidate", "rx1"])
    err = capsys.readouterr().err
    assert rc == 2
    assert "not a run id" in err


def test_diff_refuses_cross_provenance_pairing(tmp_path, capsys):
    """A session/variant row never diffs against a live row of another
    run — the weaker timing discipline makes the verdict meaningless."""
    _write(tmp_path, "BENCH_TPU_r01_session.json",
           _full_record(REF_SAMPLES, platform="tpu"))
    _write(tmp_path, "BENCH_FULL_r02.json",
           _full_record(REF_SAMPLES_B, platform="tpu"))
    rc = cli_main(["ledger", "diff", "r01", "r02", "--root", str(tmp_path)])
    cap = capsys.readouterr()
    assert "[skip]" in cap.out and "incomparable provenance" in cap.out
    assert "regression" not in cap.out and "improvement" not in cap.out
    assert rc == 1  # nothing comparable survived


def test_memstats_never_fabricates_a_zero_peak():
    from csmom_tpu.obs import memstats

    class OddFields:  # plugin stubbing everything but generated-code
        generated_code_size_in_bytes = 512

    class Holder:
        def memory_analysis(self):
            return OddFields()

    got = memstats.memory_analysis_bytes(Holder())
    assert isinstance(got, str) and "not available" in got  # no fake 0


def test_diff_pairs_like_for_like_when_both_sides_share_a_flagset(tmp_path,
                                                                  capsys):
    """Cross-provenance is refused, but an identical flag-set on both
    sides IS comparable: watcher-vs-watcher diffs even when one run also
    has a live row the other lacks."""
    _write(tmp_path, "BENCH_FULL_r01.json", _full_record(REF_SAMPLES))
    _write(tmp_path, "BENCH_FULL_r01_watcher.json",
           _full_record(REF_SAMPLES))
    _write(tmp_path, "BENCH_FULL_r02_watcher.json",
           _full_record(BAD_SAMPLES))
    rc = cli_main(["ledger", "diff", "r01", "r02", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0
    line = next(ln for ln in out.splitlines()
                if "grid16_rank_s" in ln and "regression" in ln)
    assert line  # the watcher-vs-watcher 3x regression was examined


def test_gate_reports_vanished_metrics(tmp_path, capsys):
    """A leg measured in the reference but absent from the candidate
    (budget skip — or a leg that now fails, which bench records as a
    reason string and therefore no row) must be surfaced, never silently
    dropped from the gate report."""
    r1 = _full_record(REF_SAMPLES)
    r1["extra"]["grid16_qcut_s"] = 0.25
    _write(tmp_path, "BENCH_FULL_r01.json", r1)
    r2 = _full_record(REF_SAMPLES_B)
    r2["extra"]["grid16_qcut_s"] = "failed: XlaRuntimeError: boom"
    _write(tmp_path, "BENCH_FULL_r02.json", r2)
    rc = cli_main(["ledger", "gate", "--offline", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0  # not confirmable without a number...
    assert "1 vanished" in out  # ...but loudly visible
    assert "grid16_qcut_s" in out and "last measured r01" in out


def test_bool_peak_bytes_rejected_everywhere():
    """isinstance(True, int) is True: a bool smuggled into a byte field
    must fail schema validation AND never become a ledger row."""
    bad = _telemetry("r01", 1000)
    bad["metrics"]["memory"]["grid.jk16.rank.xla@512x3780"]["peak_bytes"] \
        = True
    assert any("peak_bytes" in v for v in inv.validate(bad, "telemetry"))
    rows = ld._telemetry_rows(bad, "r01", 1, None, "TELEMETRY_r01.json")
    assert not any(r.metric == "mem_peak_bytes" for r in rows)


def test_histrank_multihost_records_are_info_never_gated():
    """Record-SHAPED captures outside the BENCH family (comm ratios,
    equality claims) ride as info rows: visible, never gate-eligible,
    never the gate's default candidate.  SERVE and REPLAY rows are the
    deliberate exceptions: those families have their own schemas + known
    directions (throughput up, latency/staleness down), so their
    unflagged rows DO gate.  TRACE joins them in r17: per-stage p99s and
    budget-burn rows are first-class gate rows by design.  FLEET joins
    in r20: kill-window capacity loss and worker-ready walls gate (the
    per-class demand rps rows inside it stay info — demand is workload,
    not performance)."""
    L = ld.load(_REPO)
    other = [r for r in L.rows
             if not r.source.startswith(("BENCH", "TELEMETRY", "SERVE",
                                         "REPLAY", "TRACE", "FLEET"))]
    assert other, "committed HISTRANK/MULTIHOST should yield info rows"
    assert all("info" in r.flags and not r.gate_eligible() for r in other)
    replay = [r for r in L.rows if r.source.startswith("REPLAY")]
    assert replay, "the committed REPLAY_r12.json should yield rows"
    assert any(r.gate_eligible() for r in replay), (
        "unflagged replay rows must be gate-eligible — that is the "
        "point of ingesting them")
    serve = [r for r in L.rows if r.source.startswith("SERVE")]
    assert serve, "the committed SERVE_r10.json should yield rows"
    assert any(r.gate_eligible() for r in serve), (
        "unflagged serve rows must be gate-eligible — that is the point "
        "of ingesting them")


def test_top_level_partial_marker_flags_rows(tmp_path):
    """invariants.is_partial honors a TOP-level partial marker; the
    ledger must use the same rule, not a private extra-only variant."""
    rec = _full_record(REF_SAMPLES)
    rec["partial"] = "deadline hit before the grid legs"
    _write(tmp_path, "BENCH_FULL_r01.json", rec)
    L = ld.load(str(tmp_path))
    assert L.rows and all("partial" in r.flags for r in L.rows)
    assert not any(r.gate_eligible() for r in L.rows)


def test_modeled_and_measured_peaks_never_share_a_key(tmp_path):
    """A jax upgrade that starts reporting true peaks must open a new
    memory trajectory (first-seen), not diff measured-vs-modeled."""
    t1 = _telemetry("r01", 150)
    t1["metrics"]["memory"]["grid.jk16.rank.xla@512x3780"]["peak_source"] \
        = "model: argument+output+temp (backend reports no peak)"
    t2 = _telemetry("r02", 220)
    t2["metrics"]["memory"]["grid.jk16.rank.xla@512x3780"]["peak_source"] \
        = "peak_memory_in_bytes"
    _write(tmp_path, "TELEMETRY_r01.json", t1)
    _write(tmp_path, "TELEMETRY_r02.json", t2)
    L = ld.load(str(tmp_path))
    mem = [r for r in L.rows if r.metric == "mem_peak_bytes"]
    assert len({r.key() for r in mem}) == 2
    rc = cli_main(["ledger", "gate", "--offline", "--root", str(tmp_path)])
    assert rc == 0  # first-seen on the measured key, no spurious growth


def test_valueless_phases_artifact_is_a_named_problem():
    """Every committed file either contributes rows or a named problem —
    PHASES_CPU_r04.json (no top-level value) must not vanish silently."""
    L = ld.load(_REPO)
    assert any(p["source"] == "PHASES_CPU_r04.json"
               and "no numeric value axis" in p["note"]
               for p in L.problems)


def test_damaged_full_record_does_not_suppress_healthy_headline(tmp_path):
    """A truncated FULL record (short write / ENOSPC) must not make the
    run's intact driver-capture headline defer to it: deferral is earned
    by rows actually ingesting, not by a file name existing."""
    (tmp_path / "BENCH_FULL_r01.json").write_text('{"metric": "x", "val')
    _write(tmp_path, "BENCH_r01.json", {
        "n": 1, "cmd": "bench", "rc": 0, "tail": "{}",
        "parsed": _full_record(REF_SAMPLES),
    })
    L = ld.load(str(tmp_path))
    assert any(r.metric == "grid16_rank_s" and r.source == "BENCH_r01.json"
               for r in L.rows)
    assert any("not valid JSON" in p["note"] for p in L.problems)


def test_variant_driver_capture_survives_canonical_full_dedup(tmp_path):
    """Dedup covers the CANONICAL headline only: a watcher/rerun driver
    capture for a run that also has a canonical FULL record is distinct
    evidence and stays visible (flagged), per the module contract."""
    _write(tmp_path, "BENCH_FULL_r05.json", _full_record(REF_SAMPLES))
    _write(tmp_path, "BENCH_r05_watcher.json", {
        "n": 5, "cmd": "bench", "rc": 0, "tail": "{}",
        "parsed": _full_record(BAD_SAMPLES),
    })
    L = ld.load(str(tmp_path))
    watcher = [r for r in L.rows if r.source == "BENCH_r05_watcher.json"]
    assert watcher and all("variant:watcher" in r.flags for r in watcher)
    # the canonical headline (same run, no variant) still defers to FULL
    _write(tmp_path, "BENCH_r05.json", {
        "n": 5, "cmd": "bench", "rc": 0, "tail": "{}",
        "parsed": _full_record(REF_SAMPLES_B),
    })
    L2 = ld.load(str(tmp_path))
    assert not any(r.source == "BENCH_r05.json" for r in L2.rows)


def test_unstamped_memory_stats_never_become_rows(tmp_path):
    """Compiled bytes are per-backend: a stats dict without a platform
    stamp must be schema-flagged and never pair under a (None, None)
    key."""
    bad = _telemetry("r01", 1000)
    bad["metrics"]["memory"]["grid.jk16.rank.xla@512x3780"].pop("platform")
    assert any("platform" in v for v in inv.validate(bad, "telemetry"))
    rows = ld._telemetry_rows(bad, "r01", 1, None, "TELEMETRY_r01.json")
    assert not any(r.metric == "mem_peak_bytes" for r in rows)


def test_point_verdict_reports_true_sample_counts():
    v = regress.compare(0.24, 0.10, cand_samples=[0.24, 0.25, 0.23],
                        ref_samples=None, direction="lower")
    assert v["candidate"]["n"] == 3 and v["reference"]["n"] == 1


def test_gate_surfaces_compounding_subtolerance_drift(tmp_path, capsys):
    """Per-PR gating against the previous run lets sub-tolerance drift
    compound invisibly (memory: +9% per round under a 10% band); the
    ratchet guard reports cumulative drift vs the oldest reference as a
    suspect (visible, non-gating)."""
    for i, peak in enumerate((1_000_000, 1_090_000, 1_190_000), start=1):
        _write(tmp_path, f"TELEMETRY_r{i:02d}.json",
               _telemetry(f"r{i:02d}", peak))
    rc = cli_main(["ledger", "gate", "--offline", "--root", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0  # each step is inside --mem-tol: accepted per round
    assert "cumulative drift since r01" in out  # ...but never hidden


# ------------------------------------------------ roadmap round-update ----

def test_splice_roadmap_replaces_the_marker_span_only():
    from csmom_tpu.cli.ledger import ROADMAP_BEGIN, ROADMAP_END, \
        splice_roadmap

    doc = (f"# head\nprose stays\n\n{ROADMAP_BEGIN}\n\nOLD TABLES\n\n"
           f"{ROADMAP_END}\ntail stays\n")
    new = splice_roadmap(doc, "#### fresh table")
    assert "OLD TABLES" not in new
    assert "#### fresh table" in new
    assert new.startswith("# head\nprose stays")
    assert new.endswith(f"{ROADMAP_END}\ntail stays\n")
    # idempotent: splicing the same tables changes nothing
    assert splice_roadmap(new, "#### fresh table") == new


def test_splice_roadmap_refuses_missing_or_misordered_markers():
    from csmom_tpu.cli.ledger import ROADMAP_BEGIN, ROADMAP_END, \
        splice_roadmap

    with pytest.raises(ValueError, match="markers missing"):
        splice_roadmap("no markers here", "t")
    with pytest.raises(ValueError, match="markers missing"):
        splice_roadmap(f"{ROADMAP_END}\n{ROADMAP_BEGIN}", "t")


def test_roadmap_rows_filter_keeps_gate_pairable_metrics_only():
    from csmom_tpu.cli.ledger import roadmap_rows
    from csmom_tpu.obs.ledger import Row

    def row(metric, flags=()):
        return Row(run="r01", run_num=1, metric=metric, value=1.0,
                   unit="s", direction="lower", platform="cpu",
                   device_kind="cpu", workload="w", source="S_r01.json",
                   flags=tuple(flags))

    rows = [row("grid16_rank_s"),
            row("grid16_rank_s", ("variant:watcher",)),  # kept: metric has a live row
            row("phase.row_s", ("info",)),               # pure info: dropped
            row("mem_peak_bytes")]                       # per-shape: dropped
    kept = {(r.metric, r.flags) for r in roadmap_rows(rows)}
    assert kept == {("grid16_rank_s", ()),
                    ("grid16_rank_s", ("variant:watcher",))}


def test_repo_roadmap_tables_are_generated_and_current():
    """The round-update flow's standing gate: ROADMAP.md carries the
    trajectory markers and the span between them matches what `csmom
    ledger roadmap --write` would regenerate from the committed
    artifacts — a round that lands evidence without regenerating (or
    hand-edits inside the span, the r14 failure) goes red here."""
    from csmom_tpu.cli.ledger import _markdown_tables, roadmap_rows, \
        splice_roadmap
    from csmom_tpu.obs import ledger as ld

    path = os.path.join(_REPO, "ROADMAP.md")
    with open(path, encoding="utf-8") as f:
        text = f.read()
    tables = _markdown_tables(roadmap_rows(ld.load(_REPO).rows))
    assert splice_roadmap(text, tables) == text, (
        "ROADMAP.md trajectory tables are stale or hand-edited — run "
        "`csmom ledger roadmap --write`")


def test_observatory_armed_is_a_footnote_not_a_flag(tmp_path):
    """The r20 steady-path cost, pinned (ISSUE 20): a fabric capture
    taken with the fleet observatory armed notes its latency rows with
    ``observatory-armed`` — the rows still gate (armed is the steady
    state from r20 on; a real latency regression must still fail the
    PR), they share their comparability key with disarmed history (the
    verdict printer surfaces the asymmetry), and the throughput row is
    untouched."""
    with open(os.path.join(_REPO, "SERVE_FABRIC_r20.json")) as f:
        base = json.load(f)
    armed = json.loads(json.dumps(base))
    armed["run_id"] = "r91"
    armed["extra"]["observatory_armed"] = True
    disarmed = json.loads(json.dumps(base))
    disarmed["run_id"] = "r90"
    disarmed["extra"]["observatory_armed"] = False
    _write(tmp_path, "SERVE_FABRIC_r90.json", disarmed)
    _write(tmp_path, "SERVE_FABRIC_r91.json", armed)
    L = ld.load(str(tmp_path))
    lat = {r.run: r for r in L.rows if r.metric == "serve_fabric_p50_ms"}
    assert lat["r91"].notes == ("observatory-armed",)
    assert lat["r90"].notes == ()
    # a footnote, not a flag: gating and pairing are unaffected
    assert lat["r91"].gate_eligible() and lat["r90"].gate_eligible()
    assert lat["r91"].key() == lat["r90"].key()
    assert lat["r91"].flags == ()
    thr = [r for r in L.rows
           if r.metric == "serve_fabric_throughput_rps" and r.run == "r91"]
    assert thr and thr[0].notes == ()


def test_verdict_printer_surfaces_note_asymmetry(capsys):
    """A note on only one side of a diff means the two captures ran
    under different provenance — the printed verdict must say the delta
    includes the documented cost."""
    from csmom_tpu.cli.ledger import _print_verdict

    def row(run, num, notes):
        return ld.Row(run=run, run_num=num, metric="serve_fabric_p50_ms",
                      value=30.0 if num == 1 else 45.0, unit="ms",
                      direction="lower", platform="cpu",
                      device_kind="cpu", workload="w",
                      source=f"S_{run}.json", notes=notes)

    ref, cand = row("r01", 1, ()), row("r02", 2, ("observatory-armed",))
    v = regress.compare_points(cand.value, ref.value, direction="lower",
                               suspect_rel=0.05, reason="test")
    _print_verdict(cand, ref, v)
    out = capsys.readouterr().out
    assert "note[observatory-armed]: r02 only" in out
    assert "documented cost" in out
