"""ISSUE 11: the unified AST analysis subsystem (`csmom lint`).

Four layers:

- **the tier-1 sweep** — the committed tree is clean (zero unsuppressed
  findings; a finding here IS a test failure with file:line and rule
  id), and `csmom lint --json` emits the machine-readable report;
- **the fixture self-test harness** — every registered rule fires on
  its known-bad fixture under ``tests/fixtures/lint/`` and stays silent
  on the clean twin (the lint analogue of the registry completeness
  test: shipping a rule without proof it fires is shipping nothing);
- **pragma semantics** — a live ``lint: allow[...]`` pragma suppresses
  exactly its finding; an unused one is itself a finding; an unknown
  rule id in a pragma is a finding; clock-tier modules cannot pragma
  out of their contract;
- **registry + gate integration** — rules are kind-``lint`` registry
  citizens (a toy rule registered at runtime joins the sweep with no
  other file edited), and ``csmom rehearse`` refuses to start on a
  dirty tree.
"""

import json
import os

import pytest

from csmom_tpu.analysis import run_lint
from csmom_tpu.analysis.core import STALE_PRAGMA_RULE, LintRule
from csmom_tpu.registry import lint_rules, register_engine, unregister_engine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIX = os.path.join(_REPO, "tests", "fixtures", "lint")


def _fixture(name: str) -> str:
    return os.path.join(_FIX, name)


# ------------------------------------------------------ the tier-1 sweep ---

def test_lint_sweep_is_clean_on_the_committed_tree():
    """THE gate: zero unsuppressed findings over the package + bench.py
    + benchmarks/.  A failure here names every offender as
    path:line: [rule] message — fix it or justify it with an in-file
    pragma (which must then actually suppress something)."""
    rep = run_lint()
    assert rep.findings == [], (
        "csmom lint found defects on the committed tree:\n  "
        + "\n  ".join(str(f) for f in rep.findings))
    assert rep.files > 100, "the sweep lost its default scope"
    assert set(rep.rules) == {s.name for s in lint_rules()}
    # the justified suppressions stay visible, never silent
    assert all(f.rule == "clock-discipline" or f.rule == "lock-discipline"
               for f in rep.suppressed)


def test_cli_lint_json_is_wired_and_clean(capsys):
    """`csmom lint --json` (what CI archives) exits 0 on the committed
    tree and emits the schema_version-1 findings report."""
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["ok"] is True
    assert report["schema_version"] == 1
    assert report["findings"] == []
    assert report["files_scanned"] > 100
    assert set(report["rules"]) == {s.name for s in lint_rules()}
    # suppressed entries carry the machine-readable finding shape
    for s in report["suppressed"]:
        assert {"rule", "path", "line", "message"} <= set(s)


def test_cli_lint_reports_findings_with_file_line_and_rule(capsys):
    from csmom_tpu.cli.main import main

    bad = _fixture("lock_discipline_bad.py")
    rc = main(["lint", "--paths", bad])
    out = capsys.readouterr().out
    assert rc == 1
    assert "lock_discipline_bad.py:11" in out
    assert "[lock-discipline]" in out

    rc = main(["lint", "--json", "--paths", bad])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and report["ok"] is False
    f0 = report["findings"][0]
    assert set(f0) == {"rule", "path", "line", "message"}


def test_cli_lint_rule_filter_and_rules_listing(capsys):
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--rule", "lock-discipline",
               "--paths", _fixture("clock_discipline_bad.py")])
    capsys.readouterr()
    assert rc == 0  # the clock offenses are not lock-discipline's

    rc = main(["lint", "--rule", "no-such-rule"])
    err = capsys.readouterr().err
    assert rc == 2 and "no-such-rule" in err

    rc = main(["lint", "--rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for spec in lint_rules():
        assert spec.name in out


# ------------------------------------------- the fixture self-test harness -

@pytest.mark.parametrize("rule_id",
                         [s.name for s in lint_rules()])
def test_every_registered_rule_fires_on_bad_and_not_on_clean(rule_id):
    """The self-test harness (ISSUE 11 satellite): registration enrolls
    a rule here — each must demonstrably fire on its known-bad fixture
    and stay silent on the clean twin."""
    spec = {s.name: s for s in lint_rules()}[rule_id]
    stem = rule_id.replace("-", "_")
    bad, clean = _fixture(f"{stem}_bad.py"), _fixture(f"{stem}_clean.py")
    assert os.path.isfile(bad), (
        f"rule {rule_id} ships no known-bad fixture at {bad} — a rule "
        "without proof it fires is not a rule")
    assert os.path.isfile(clean), f"rule {rule_id} ships no clean twin"
    rep = run_lint(paths=[bad], rules=[spec.rule_cls()])
    assert [f for f in rep.findings if f.rule == rule_id], (
        f"rule {rule_id} stayed SILENT on its known-bad fixture")
    rep = run_lint(paths=[clean], rules=[spec.rule_cls()])
    assert [f for f in rep.findings if f.rule == rule_id] == [], (
        f"rule {rule_id} false-positives on its clean twin: "
        + "; ".join(str(f) for f in rep.findings))


def test_tracer_hygiene_catches_every_escape_family():
    from csmom_tpu.analysis.rules import TracerHygiene

    rep = run_lint(paths=[_fixture("tracer_hygiene_bad.py")],
                   rules=[TracerHygiene()])
    msgs = " | ".join(f.message for f in rep.findings)
    for marker in ("global", "print", "clock read", "numpy.asarray",
                   "float()", ".item()"):
        assert marker in msgs, f"escape family {marker!r} not caught"


def test_donation_safety_tracks_indices_and_rebinding():
    from csmom_tpu.analysis.rules import DonationSafety

    rep = run_lint(paths=[_fixture("donation_safety_bad.py")],
                   rules=[DonationSafety()])
    assert len(rep.findings) == 2
    assert all("read after being donated" in f.message
               for f in rep.findings)
    # undonated args and rebound names stay legal (the clean twin)
    rep = run_lint(paths=[_fixture("donation_safety_clean.py")],
                   rules=[DonationSafety()])
    assert rep.findings == []


def test_lock_discipline_accepts_try_finally_and_with():
    from csmom_tpu.analysis.rules import LockDiscipline

    rep = run_lint(paths=[_fixture("lock_discipline_clean.py")],
                   rules=[LockDiscipline()])
    assert rep.findings == []
    rep = run_lint(paths=[_fixture("lock_discipline_bad.py")],
                   rules=[LockDiscipline()])
    kinds = sorted(f.message.split("(")[0] for f in rep.findings)
    assert len(rep.findings) == 3  # bare acquire, sleep, sendall
    assert any("acquire" in k for k in kinds)


# ------------------------------------------------------- pragma semantics --

def test_live_pragma_suppresses_and_is_not_stale():
    rep = run_lint(paths=[_fixture("pragma_live.py")])
    assert rep.findings == []
    assert len(rep.suppressed) == 1
    assert rep.suppressed[0].rule == "clock-discipline"


def test_stale_pragma_is_itself_a_finding():
    """ISSUE 11 satellite pin: a pragma with no matching finding fails
    the sweep — the unused-suppression hole the count-based allowlist
    left open."""
    rep = run_lint(paths=[_fixture("stale_pragma.py")])
    assert [f.rule for f in rep.findings] == [STALE_PRAGMA_RULE]
    assert "unused suppression" in rep.findings[0].message


def test_trailing_pragma_does_not_leak_onto_the_next_line(tmp_path):
    """A pragma on an offending CODE line covers that line only — a
    second, unjustified defect directly below must still fail the
    sweep (a standalone comment/prose pragma line covers the line
    below it, which is the documented above-the-statement form)."""
    p = tmp_path / "two.py"
    p.write_text(
        "import time\n\n\n"
        "def two():\n"
        "    a = time.time()  # lint: allow[clock-discipline] this one\n"
        "    b = time.time()\n"
        "    return a + b\n")
    rep = run_lint(paths=[str(p)])
    assert [f.line for f in rep.findings] == [6], rep.findings
    assert [s.line for s in rep.suppressed] == [5]


def test_alias_map_applies_bindings_in_source_order(tmp_path):
    """A nested-function clock rebind must not shadow a LATER
    module-level rebind of the same name (ast.walk is breadth-first;
    the map sorts bindings by source position and retires aliases on
    untracked rebinds)."""
    p = tmp_path / "alias.py"
    p.write_text(
        "import time\n\n\n"
        "def other():\n"
        "    t = time.time\n"
        "    return t\n\n\n"
        "t = len\n"
        'x = t("abc")\n')
    rep = run_lint(paths=[str(p)])
    assert rep.findings == [], rep.findings


def test_unknown_rule_in_pragma_is_a_finding(tmp_path):
    p = tmp_path / "x.py"
    p.write_text("# lint: allow[no-such-rule] why not\nX = 1\n")
    rep = run_lint(paths=[str(p)])
    assert any(f.rule == STALE_PRAGMA_RULE
               and "unknown rule" in f.message for f in rep.findings)


def test_clock_tier_modules_cannot_pragma_out(tmp_path):
    """A serve/stream/ledger module carrying a clock-discipline pragma
    is itself a finding: tiers are contracts, not defaults."""
    ring = tmp_path / "csmom_tpu" / "stream" / "ring.py"
    ring.parent.mkdir(parents=True)
    ring.write_text(
        "# lint: allow[clock-discipline] please let me\n"
        "from csmom_tpu.utils.deadline import mono_now_s\n")
    rep = run_lint(paths=[str(ring)], repo=str(tmp_path))
    assert any("must not carry a clock-discipline pragma" in f.message
               for f in rep.findings), rep.findings
    # the pragma'd import finding itself is ALSO still reported via the
    # contract path or suppressed — but the contract finding cannot be
    # silenced, so the sweep fails either way
    assert rep.findings


def test_unparseable_source_is_a_finding_not_a_crash(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    rep = run_lint(paths=[str(p)])
    assert [f.rule for f in rep.findings] == ["parse-error"]


# ------------------------------------------- registry + gate integration ---

def test_builtin_rules_are_registry_citizens():
    names = [s.name for s in lint_rules()]
    assert names == ["clock-discipline", "tracer-hygiene",
                     "lock-discipline", "donation-safety",
                     "enumeration-drift"]
    for s in lint_rules():
        assert s.kind == "lint" and s.rule_cls is not None
        assert s.description


def test_toy_rule_registered_at_runtime_joins_the_sweep(tmp_path, capsys):
    """The tentpole's acceptance property, lint edition: register once,
    appear in run_lint, the CLI listing, and `csmom registry list` —
    no other file edited."""

    class NoTodo(LintRule):
        id = "no-todo-markers"
        description = "test-only toy rule: comments must not say TODO"

        def finish_file(self, ctx):
            for kind, line, text in ctx.tokens:
                if kind == "comment" and "TODO" in text:
                    ctx.report(self.id, line, "TODO marker in a comment")

    register_engine(name=NoTodo.id, kind="lint", rule_cls=NoTodo,
                    description=NoTodo.description)
    try:
        assert NoTodo.id in [s.name for s in lint_rules()]
        p = tmp_path / "t.py"
        p.write_text("x = 1  # TODO remove\n")
        rep = run_lint(paths=[str(p)])
        assert any(f.rule == NoTodo.id for f in rep.findings)
        # a pragma for the toy rule works immediately too
        p.write_text("# lint: allow[no-todo-markers] grandfathered\n"
                     "x = 1  # TODO remove\n")
        rep = run_lint(paths=[str(p)])
        assert not [f for f in rep.findings if f.rule == NoTodo.id]
        # the registry CLI lists it under kind 'lint'
        from csmom_tpu.cli.main import main

        rc = main(["registry", "list", "--kind", "lint"])
        out = capsys.readouterr().out
        assert rc == 0 and NoTodo.id in out
    finally:
        unregister_engine(NoTodo.id, kind="lint")
    assert NoTodo.id not in [s.name for s in lint_rules()]


def test_checkpoint_vocabulary_round_trips_on_the_full_sweep():
    """Both directions of the enumeration-drift vocabulary check: the
    committed tree round-trips, and a doctored dead entry is caught at
    the KNOWN_POINTS anchor."""
    from csmom_tpu.analysis.rules import EnumerationDrift

    rep = run_lint(rules=[EnumerationDrift()])
    assert rep.findings == []

    ghost = EnumerationDrift()
    ghost._vocab = ghost._vocab + ("ghost.point",)
    rep = run_lint(rules=[ghost])
    assert any("ghost.point" in f.message
               and f.path.endswith("chaos/plan.py")
               for f in rep.findings), rep.findings


def test_rehearse_refuses_to_start_on_a_dirty_tree(monkeypatch, capsys):
    """ISSUE 11 satellite: `csmom rehearse` gates on the lint sweep —
    a dirty tree must not reach a tunnel window."""
    from csmom_tpu.analysis.core import Finding
    from csmom_tpu.cli import rehearse as reh

    monkeypatch.setattr(
        reh, "_lint_gate",
        lambda: [Finding("clock-discipline", "x.py", 3, "smuggled wall "
                         "clock")])

    class Args:
        list = False
        plan = None
        fast = True
        only = None
        sandbox = None
        keep = False
        verbose = False

    rc = reh.cmd_rehearse(Args())
    err = capsys.readouterr().err
    assert rc == 1
    assert "refusing to rehearse" in err
    assert "x.py:3" in err


def test_rehearse_list_skips_the_gate(monkeypatch, capsys):
    from csmom_tpu.cli import rehearse as reh

    def boom():  # pragma: no cover - must not run
        raise AssertionError("--list must not pay the sweep")

    monkeypatch.setattr(reh, "_lint_gate", boom)

    class Args:
        list = True
        plan = None
        fast = True
        only = None
        sandbox = None
        keep = False
        verbose = False

    rc = reh.cmd_rehearse(Args())
    out = capsys.readouterr().out
    assert rc == 0 and "plan:" in out


def test_lint_is_a_device_free_subcommand():
    """The sweep must run on a box with no accelerator and no probe —
    it gates rehearse, which gates windows."""
    from csmom_tpu.cli.main import _DEVICE_FREE_COMMANDS

    assert "lint" in _DEVICE_FREE_COMMANDS
