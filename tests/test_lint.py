"""ISSUE 11 + 12: the unified AST analysis subsystem (`csmom lint`).

Five layers:

- **the tier-1 sweep** — the committed tree is clean at PROJECT scope
  (per-file rules plus lock-order / helper-hygiene / compile-surface on
  the whole-program call graph; a finding here IS a test failure with
  file:line and rule id), and `csmom lint --format json` emits the
  machine-readable schema_version-2 report;
- **the fixture self-test harness** — every registered rule fires on
  its known-bad fixture under ``tests/fixtures/lint/`` (a FILE for
  per-file rules, a multi-file PACKAGE for project rules) and stays
  silent on the clean twin;
- **pragma semantics** — a live ``lint: allow[...]`` pragma suppresses
  exactly its finding (project findings included); an unused one is
  itself a finding; an unknown rule id in a pragma is a finding;
  clock-tier modules cannot pragma out of their contract;
- **the incremental cache** — byte-identical findings on a warm
  re-sweep, >= 5x faster on an unchanged tree, invalidated by content
  changes, bypassed by ``--no-cache``;
- **registry + gate integration** — rules are kind-``lint`` registry
  citizens (a toy rule registered at runtime joins the sweep with no
  other file edited), and ``csmom rehearse`` refuses to start on a
  dirty tree — project findings included.
"""

import json
import os
import time

import pytest

from csmom_tpu.analysis import run_lint
from csmom_tpu.analysis.core import STALE_PRAGMA_RULE, LintRule
from csmom_tpu.registry import lint_rules, register_engine, unregister_engine

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIX = os.path.join(_REPO, "tests", "fixtures", "lint")


def _fixture(name: str) -> str:
    return os.path.join(_FIX, name)


def _rule_fixture_pair(rule_id: str) -> tuple:
    """(bad, clean) fixture paths: ``<stem>_bad.py`` single files for
    per-file rules, ``<stem>_bad/`` packages for project rules."""
    stem = rule_id.replace("-", "_")
    for suffix in ("", ".py"):
        bad = _fixture(f"{stem}_bad{suffix}")
        clean = _fixture(f"{stem}_clean{suffix}")
        if os.path.exists(bad) or os.path.exists(clean):
            return bad, clean
    return _fixture(f"{stem}_bad.py"), _fixture(f"{stem}_clean.py")


# ------------------------------------------------------ the tier-1 sweep ---

def test_lint_sweep_is_clean_on_the_committed_tree():
    """THE gate: zero unsuppressed findings over the package + bench.py
    + benchmarks/ at PROJECT scope — the whole-program rules (lock
    acquisition order, helper-hidden blocking/tracer escapes, compile-
    surface coverage) run here, not just the per-file set.  A failure
    names every offender as path:line: [rule] message — fix it or
    justify it with an in-file pragma (which must then actually
    suppress something)."""
    rep = run_lint(project=True)
    assert rep.findings == [], (
        "csmom lint found defects on the committed tree:\n  "
        + "\n  ".join(str(f) for f in rep.findings))
    assert rep.files > 100, "the sweep lost its default scope"
    assert rep.project is True
    assert set(rep.rules) == {s.name for s in lint_rules()}
    # the justified suppressions stay visible, never silent.  lock-order
    # joined in r19: the channel writer locks exist to serialize frame
    # writes on one socket — the one blocking call that IS the lock's
    # purpose, justified in place at the two proto.py call sites
    assert all(f.rule in ("clock-discipline", "lock-discipline",
                          "lock-order")
               for f in rep.suppressed)


def test_cli_lint_json_is_wired_and_clean(capsys):
    """`csmom lint --project --format json` (what CI archives) exits 0
    on the committed tree and emits the schema_version-2 findings
    report — which the artifact validator accepts closed-world."""
    from csmom_tpu.chaos import invariants as inv
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--project", "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["ok"] is True
    assert report["schema_version"] == 2
    assert report["project"] is True
    assert report["findings"] == []
    assert report["files_scanned"] > 100
    assert set(report["rules"]) == {s.name for s in lint_rules()}
    assert report["cache"]["enabled"] in (True, False)
    # suppressed entries carry the machine-readable finding shape
    for s in report["suppressed"]:
        assert {"rule", "path", "line", "message", "chain"} <= set(s)
    # the validator recognizes and accepts the report (closed world)
    assert inv.detect_kind(report) == "lint"
    assert inv.validate(report) == []
    # ... and rejects a key outside the v2 world or a lying ok flag
    assert any("unknown v2 keys" in v for v in inv.validate(
        {**report, "surprise": 1}))
    assert any("disagrees" in v for v in inv.validate(
        {**report, "ok": False}))


def test_cli_lint_json_alias_still_works(capsys):
    """``--json`` remains an alias for ``--format json`` (r16 callers)."""
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["schema_version"] == 2


def test_cli_lint_explicit_format_beats_the_json_alias(capsys):
    """A wrapper script still appending ``--json`` unconditionally must
    not silently suppress an explicitly requested ``--format``."""
    from csmom_tpu.cli.main import main

    bad = _fixture("clock_discipline_bad.py")
    rc = main(["lint", "--format", "github", "--json", "--paths", bad,
               "--no-cache"])
    out = capsys.readouterr().out
    assert rc == 1 and "::error file=" in out


def test_cli_lint_github_format_emits_workflow_annotations(capsys):
    """``--format github`` prints ::error annotations CI surfaces inline
    on the PR diff, one per finding, and keeps the exit contract."""
    from csmom_tpu.cli.main import main

    bad = _fixture("lock_discipline_bad.py")
    rc = main(["lint", "--format", "github", "--paths", bad])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [ln for ln in out.splitlines() if ln.startswith("::error ")]
    assert lines, out
    assert any("file=" in ln and "line=11" in ln
               and "title=lint:lock-discipline" in ln for ln in lines)

    rc = main(["lint", "--format", "github", "--paths",
               _fixture("lock_discipline_clean.py")])
    out = capsys.readouterr().out
    assert rc == 0 and "::error" not in out


def test_cli_lint_reports_findings_with_file_line_and_rule(capsys):
    from csmom_tpu.cli.main import main

    bad = _fixture("lock_discipline_bad.py")
    rc = main(["lint", "--paths", bad])
    out = capsys.readouterr().out
    assert rc == 1
    assert "lock_discipline_bad.py:11" in out
    assert "[lock-discipline]" in out

    rc = main(["lint", "--json", "--paths", bad])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1 and report["ok"] is False
    f0 = report["findings"][0]
    assert set(f0) == {"rule", "path", "line", "message", "chain"}


def test_cli_lint_rule_filter_and_rules_listing(capsys):
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--rule", "lock-discipline",
               "--paths", _fixture("clock_discipline_bad.py")])
    capsys.readouterr()
    assert rc == 0  # the clock offenses are not lock-discipline's

    rc = main(["lint", "--rule", "no-such-rule"])
    err = capsys.readouterr().err
    assert rc == 2 and "no-such-rule" in err

    rc = main(["lint", "--rules"])
    out = capsys.readouterr().out
    assert rc == 0
    for spec in lint_rules():
        assert spec.name in out


# ------------------------------------------- the fixture self-test harness -

@pytest.mark.parametrize("rule_id",
                         [s.name for s in lint_rules()])
def test_every_registered_rule_fires_on_bad_and_not_on_clean(rule_id):
    """The self-test harness (ISSUE 11 satellite, extended to project
    scope in ISSUE 12): registration enrolls a rule here — each must
    demonstrably fire on its known-bad fixture and stay silent on the
    clean twin.  Per-file rules ship a single-file pair; project rules
    ship MULTI-FILE packages (their defects are cross-file by
    definition)."""
    spec = {s.name: s for s in lint_rules()}[rule_id]
    bad, clean = _rule_fixture_pair(rule_id)
    assert os.path.exists(bad), (
        f"rule {rule_id} ships no known-bad fixture at {bad} — a rule "
        "without proof it fires is not a rule")
    assert os.path.exists(clean), f"rule {rule_id} ships no clean twin"
    if getattr(spec.rule_cls, "scope", "file") == "project":
        assert os.path.isdir(bad), (
            f"project rule {rule_id} must prove itself on a MULTI-FILE "
            "fixture package — a single file cannot demonstrate a "
            "cross-file defect")
        assert len([n for n in os.listdir(bad) if n.endswith(".py")]) >= 2
    rep = run_lint(paths=[bad], rules=[spec.rule_cls()])
    assert [f for f in rep.findings if f.rule == rule_id], (
        f"rule {rule_id} stayed SILENT on its known-bad fixture")
    rep = run_lint(paths=[clean], rules=[spec.rule_cls()])
    assert [f for f in rep.findings if f.rule == rule_id] == [], (
        f"rule {rule_id} false-positives on its clean twin: "
        + "; ".join(str(f) for f in rep.findings))


def test_tracer_hygiene_catches_every_escape_family():
    from csmom_tpu.analysis.rules import TracerHygiene

    rep = run_lint(paths=[_fixture("tracer_hygiene_bad.py")],
                   rules=[TracerHygiene()])
    msgs = " | ".join(f.message for f in rep.findings)
    for marker in ("global", "print", "clock read", "numpy.asarray",
                   "float()", ".item()"):
        assert marker in msgs, f"escape family {marker!r} not caught"


def test_donation_safety_tracks_indices_and_rebinding():
    from csmom_tpu.analysis.rules import DonationSafety

    rep = run_lint(paths=[_fixture("donation_safety_bad.py")],
                   rules=[DonationSafety()])
    assert len(rep.findings) == 2
    assert all("read after being donated" in f.message
               for f in rep.findings)
    # undonated args and rebound names stay legal (the clean twin)
    rep = run_lint(paths=[_fixture("donation_safety_clean.py")],
                   rules=[DonationSafety()])
    assert rep.findings == []


def test_dial_discipline_flags_hot_paths_and_spares_probes():
    """ISSUE 15 satellite: every one-shot dial family on the bad
    fixture fires (direct proto.request, aliased request_once import,
    a dispatch loop), the probe/stats/drain clean twin stays silent,
    and the COMMITTED serve tier sweeps clean — the pooled transport
    is pinned as the only hot-path dial."""
    from csmom_tpu.analysis.rules import DialDiscipline

    rep = run_lint(paths=[_fixture("dial_discipline_bad.py")],
                   rules=[DialDiscipline()])
    assert len(rep.findings) == 3, rep.findings
    assert all("dial-per-call" in f.message for f in rep.findings)
    rep = run_lint(paths=[_fixture("dial_discipline_clean.py")],
                   rules=[DialDiscipline()])
    assert rep.findings == []
    # the committed request path: router/fabric dispatch is pooled,
    # probes and admin ops one-shot — zero findings, zero pragmas
    serve = os.path.join(_REPO, "csmom_tpu", "serve")
    rep = run_lint(paths=[serve], rules=[DialDiscipline()])
    assert rep.findings == [], rep.findings
    assert rep.suppressed == [], (
        "dial-discipline must hold on the serve tier without pragmas")


def test_lock_discipline_accepts_try_finally_and_with():
    from csmom_tpu.analysis.rules import LockDiscipline

    rep = run_lint(paths=[_fixture("lock_discipline_clean.py")],
                   rules=[LockDiscipline()])
    assert rep.findings == []
    rep = run_lint(paths=[_fixture("lock_discipline_bad.py")],
                   rules=[LockDiscipline()])
    kinds = sorted(f.message.split("(")[0] for f in rep.findings)
    assert len(rep.findings) == 3  # bare acquire, sleep, sendall
    assert any("acquire" in k for k in kinds)


# ---------------------------------------------- the whole-program rules ---

def test_lock_order_catches_what_the_per_file_rule_cannot():
    """The tentpole's acceptance pin: the bad package's lock-order cycle
    AND its helper-hidden blocking call are invisible to the r16
    per-file lock-discipline rule (every function is locally
    disciplined) — and both are caught at project scope."""
    from csmom_tpu.analysis.project_rules import LockOrder
    from csmom_tpu.analysis.rules import LockDiscipline

    bad = _fixture("lock_order_bad")
    per_file = run_lint(paths=[bad], rules=[LockDiscipline()])
    assert per_file.findings == [], (
        "the fixture must be per-file clean (otherwise it proves "
        "nothing about whole-program scope): " + str(per_file.findings))
    rep = run_lint(paths=[bad], rules=[LockOrder()])
    msgs = " | ".join(f.message for f in rep.findings)
    assert "acquisition-order cycle" in msgs
    assert "blocking call (time.sleep)" in msgs and "slow_push" in msgs
    # findings carry the evidence chain (the schema v2 project field)
    assert any(len(f.chain) >= 2 for f in rep.findings)


def test_lock_order_flags_reacquisition_through_a_chain(tmp_path):
    """Re-acquiring a non-reentrant lock through a call chain is the
    one-lock deadlock; the same shape through an RLock is legal."""
    from csmom_tpu.analysis.project_rules import LockOrder

    p = tmp_path / "re.py"
    p.write_text(
        "import threading\n\n\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def outer(self):\n"
        "        with self._lock:\n"
        "            self.inner()\n\n"
        "    def inner(self):\n"
        "        with self._lock:\n"
        "            return 1\n")
    rep = run_lint(paths=[str(p)], rules=[LockOrder()])
    assert any("re-acquired" in f.message for f in rep.findings)

    p.write_text(p.read_text().replace("threading.Lock()",
                                       "threading.RLock()"))
    rep = run_lint(paths=[str(p)], rules=[LockOrder()])
    assert rep.findings == [], rep.findings


def test_lock_order_covers_anonymous_local_locks(tmp_path):
    """A locally-created lock (the router's per-request state-dict
    pattern) has no order-graph node, but a helper-hidden blocking call
    under it still serializes its waiters — and is still flagged."""
    from csmom_tpu.analysis.project_rules import LockOrder

    (tmp_path / "a.py").write_text(
        "import threading\n\n"
        "from .b import push\n\n\n"
        "def drive():\n"
        "    state = {'lock': threading.Lock()}\n"
        "    with state['lock']:\n"
        "        push(state)\n")
    (tmp_path / "b.py").write_text(
        "import time\n\n\ndef push(state):\n    time.sleep(0.01)\n")
    rep = run_lint(paths=[str(tmp_path)], rules=[LockOrder()])
    assert any("locally-scoped lock" in f.message
               and "time.sleep" in f.message for f in rep.findings), (
        rep.findings)


def test_lock_order_multi_item_with_orders_left_to_right(tmp_path):
    """``with a, b:`` acquires left-to-right — opposite-order nesting
    elsewhere closes the cycle, and a directly nested re-acquisition of
    the same lock is the self-deadlock (both review findings)."""
    from csmom_tpu.analysis.project_rules import LockOrder

    p = tmp_path / "multi.py"
    p.write_text(
        "import threading\n\n\n"
        "class M:\n"
        "    def __init__(self):\n"
        "        self._a_lock = threading.Lock()\n"
        "        self._b_lock = threading.Lock()\n\n"
        "    def fwd(self):\n"
        "        with self._a_lock, self._b_lock:\n"
        "            return 1\n\n"
        "    def rev(self):\n"
        "        with self._b_lock:\n"
        "            with self._a_lock:\n"
        "                return 2\n")
    rep = run_lint(paths=[str(p)], rules=[LockOrder()])
    assert any("acquisition-order cycle" in f.message
               for f in rep.findings), rep.findings

    p2 = tmp_path / "self.py"
    p2.write_text(
        "import threading\n\n\n"
        "class S:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def broken(self):\n"
        "        with self._lock:\n"
        "            with self._lock:\n"
        "                return 1\n")
    rep = run_lint(paths=[str(p2)], rules=[LockOrder()])
    assert any("re-acquired inside its own with-block" in f.message
               for f in rep.findings), rep.findings


def test_helper_hygiene_partial_decorator_must_wrap_jit(tmp_path):
    """``@partial(jax.jit, ...)`` is a traced root; ``@partial`` over an
    ordinary decorator is NOT (the review's false-positive trap: a
    non-jit partial whose helper prints must stay silent)."""
    from csmom_tpu.analysis.project_rules import HelperHygiene

    (tmp_path / "helpers.py").write_text(
        "def log_it(x):\n    print(x)\n    return x\n")
    p = tmp_path / "m.py"
    p.write_text(
        "from functools import partial\n\n"
        "from .helpers import log_it\n\n\n"
        "def retry(fn, attempts=1):\n"
        "    return fn\n\n\n"
        "@partial(retry, attempts=3)\n"
        "def ordinary(x):\n"
        "    return log_it(x)\n")
    rep = run_lint(paths=[str(tmp_path)], rules=[HelperHygiene()])
    assert rep.findings == [], rep.findings

    p.write_text(
        "from functools import partial\n\n"
        "import jax\n\n"
        "from .helpers import log_it\n\n\n"
        "@partial(jax.jit, static_argnums=1)\n"
        "def traced(x, n):\n"
        "    return log_it(x)\n")
    rep = run_lint(paths=[str(tmp_path)], rules=[HelperHygiene()])
    assert any("print" in f.message for f in rep.findings), rep.findings


def test_cache_coexists_across_rule_filtered_sweeps(tmp_path):
    """A ``--rule`` filtered sweep must not evict the full gate's warm
    entries (the review's thrash finding): full, filtered, full again —
    the third sweep still hits every file."""
    cache_dir = str(tmp_path / "c")
    run_lint(project=True, cache_dir=cache_dir)              # warm full
    run_lint(rule="clock-discipline", cache_dir=cache_dir)   # filtered
    again = run_lint(project=True, cache_dir=cache_dir)
    assert again.cache["hits"] == again.files, again.cache
    assert again.cache["project_hit"] is True


def test_compile_surface_anchor_is_identical_warm_and_cold(tmp_path):
    """The finding anchor (and so any pragma match) must not depend on
    cache temperature: a doctored feeder reports at the PROFILES line
    on a cold sweep AND on a fully warm one (CachedSlot, no parse)."""
    import dataclasses

    from csmom_tpu.registry import ensure_builtin

    reg = ensure_builtin()
    spec = reg.get("serve.buckets", kind="compile")
    orig_names = spec.manifest_names_fn
    cache_dir = str(tmp_path / "c")
    try:
        reg.register(dataclasses.replace(
            spec, manifest_names_fn=lambda p: set(
                sorted(orig_names(p))[:-1])), replace=True)
        cold = run_lint(project=True, rule="compile-surface",
                        cache_dir=cache_dir)
        warm = run_lint(project=True, rule="compile-surface",
                        cache_dir=cache_dir)
        assert warm.cache["hits"] == warm.files
        assert cold.findings and warm.findings
        assert ([(f.path, f.line) for f in cold.findings]
                == [(f.path, f.line) for f in warm.findings])
        assert cold.findings[0].line > 1   # the real PROFILES line
    finally:
        reg.register(spec, replace=True)


def test_bare_condition_is_rlock_backed_and_reentrant(tmp_path):
    """``threading.Condition()`` with no lock wraps an RLock (CPython
    default) — re-acquiring it through a chain is LEGAL and must not be
    called a self-deadlock (review finding)."""
    from csmom_tpu.analysis.project_rules import LockOrder

    p = tmp_path / "cv.py"
    p.write_text(
        "import threading\n\n\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._cv_lock = threading.Condition()\n\n"
        "    def outer(self):\n"
        "        with self._cv_lock:\n"
        "            self.inner()\n\n"
        "    def inner(self):\n"
        "        with self._cv_lock:\n"
        "            return 1\n")
    rep = run_lint(paths=[str(p)], rules=[LockOrder()])
    assert rep.findings == [], rep.findings


def test_fully_warm_project_sweep_does_not_rewrite_the_cache(tmp_path):
    """A 100%-hit sweep must be I/O-free on the cache file (the dirty
    flag's whole job — review finding)."""
    cache_dir = str(tmp_path / "c")
    run_lint(project=True, cache_dir=cache_dir)
    path = os.path.join(cache_dir, "sweep.json")
    before = os.stat(path).st_mtime_ns
    warm = run_lint(project=True, cache_dir=cache_dir)
    assert warm.cache["hits"] == warm.files
    assert os.stat(path).st_mtime_ns == before, (
        "warm sweep rewrote sweep.json")


def test_condition_aliases_the_lock_it_wraps(tmp_path):
    """``threading.Condition(self._lock)``: holding the condition IS
    holding the lock — acquiring one inside the other is flagged as
    re-acquisition, not treated as two independent locks."""
    from csmom_tpu.analysis.project_rules import LockOrder

    p = tmp_path / "cond.py"
    p.write_text(
        "import threading\n\n\n"
        "class Q:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._nonempty = threading.Condition(self._lock)\n\n"
        "    def broken(self):\n"
        "        with self._lock:\n"
        "            self.wake()\n\n"
        "    def wake(self):\n"
        "        with self._nonempty:\n"
        "            return 1\n")
    rep = run_lint(paths=[str(p)], rules=[LockOrder()])
    assert any("re-acquired" in f.message for f in rep.findings), (
        rep.findings)


def test_helper_hygiene_catches_what_tracer_hygiene_cannot():
    """The per-file tracer-hygiene rule is silent on the bad package's
    entry file (every escape hides one hop away); the project rule
    flags all three escape families at the traced call sites."""
    from csmom_tpu.analysis.project_rules import HelperHygiene
    from csmom_tpu.analysis.rules import TracerHygiene

    bad = _fixture("helper_hygiene_bad")
    per_file = run_lint(paths=[os.path.join(bad, "entry.py")],
                        rules=[TracerHygiene()])
    assert per_file.findings == [], (
        "the traced entry file must be per-file clean: "
        + str(per_file.findings))
    rep = run_lint(paths=[bad], rules=[HelperHygiene()])
    msgs = " | ".join(f.message for f in rep.findings)
    for marker in ("print", "clock read", "donated-buffer entry"):
        assert marker in msgs, f"escape family {marker!r} not caught"
    assert all(f.path.endswith("entry.py") for f in rep.findings), (
        "findings anchor at the traced CALL SITE, not the helper")


def test_compile_surface_fails_when_a_manifest_entry_is_deregistered():
    """The acceptance pin: the committed registry passes; re-registering
    the serve feeder with one entry name dropped (the static equivalent
    of deregistering one manifest entry for a registered endpoint
    bucket) fails the sweep; dropping the feeder's coverage declaration
    entirely fails with the no-feeder message."""
    import dataclasses

    from csmom_tpu.registry import ensure_builtin

    reg = ensure_builtin()
    spec = reg.get("serve.buckets", kind="compile")
    orig_names = spec.manifest_names_fn

    rep = run_lint(project=True, rule="compile-surface")
    assert rep.findings == [], rep.findings

    try:
        reg.register(dataclasses.replace(
            spec, manifest_names_fn=lambda p: set(
                sorted(orig_names(p))[:-1])), replace=True)
        rep = run_lint(project=True, rule="compile-surface")
        assert any("no warmed manifest entry" in f.message
                   and f.path == "csmom_tpu/serve/buckets.py"
                   for f in rep.findings), rep.findings

        reg.register(dataclasses.replace(spec, manifest_names_fn=None),
                     replace=True)
        rep = run_lint(project=True, rule="compile-surface")
        assert any("no registered manifest feeder" in f.message
                   for f in rep.findings), rep.findings
    finally:
        reg.register(spec, replace=True)
    rep = run_lint(project=True, rule="compile-surface")
    assert rep.findings == []


def test_compile_surface_registry_and_health_agree():
    """The two independent derivations of the warm world (the feeder's
    jax-free names declaration vs health's geometry walk) are equal on
    the committed tree — the drift either side would introduce is what
    the rule exists to catch."""
    from csmom_tpu.registry import manifest_entry_names
    from csmom_tpu.serve.health import expected_entry_names

    for profile in ("serve", "serve-smoke"):
        declared = manifest_entry_names(profile)
        expected = expected_entry_names(profile)
        assert expected <= declared, (
            f"profile {profile}: dispatchable shapes missing warm "
            f"coverage: {sorted(expected - declared)[:3]}")


def test_project_findings_respect_pragmas(tmp_path):
    """A ``lint: allow[lock-order]`` pragma suppresses the project
    finding on its line — and an unused one is stale, like any rule."""
    a = tmp_path / "a.py"
    a.write_text(
        "import threading\n\n"
        "from .b import helper\n\n\n"
        "class A:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def run(self):\n"
        "        with self._lock:\n"
        "            # lint: allow[lock-order] startup only, no traffic\n"
        "            helper()\n")
    (tmp_path / "b.py").write_text(
        "import time\n\n\ndef helper():\n    time.sleep(0.01)\n")
    from csmom_tpu.analysis.project_rules import LockOrder

    rep = run_lint(paths=[str(tmp_path)], rules=[LockOrder()])
    assert rep.findings == [], rep.findings
    assert [s.rule for s in rep.suppressed] == ["lock-order"]


def test_committed_tree_lock_audit_is_pinned():
    """The ISSUE 12 audit, mechanized: on the committed tree (a) the
    router's per-request hedging state lock and its book lock NEVER
    nest (no order edge touches Router._lock — ``_terminate`` and
    ``_conclude_attempt`` are called sequentially, never one inside the
    other), (b) the supervisor's restart path spawns/probes OUTSIDE its
    event lock, and (c) the serve tier's cross-lock acquisition orders
    are exactly AdmissionQueue._lock -> obs.metrics._LOCK (the counter
    increments inside admission) and the r21 elastic tier's backfill
    lock over its probe-path leaves — each one-directional.  A
    new edge here is not automatically a bug — but it IS a new global
    ordering constraint, and this test makes adding one a deliberate
    act."""
    import os as _os

    from csmom_tpu.analysis.callgraph import ProjectContext
    from csmom_tpu.analysis.core import FileContext, RunContext
    from csmom_tpu.analysis.core import default_sources

    run = RunContext(_REPO)
    slots = {}
    for p in default_sources():
        rel = _os.path.relpath(p, _REPO)
        with open(p, encoding="utf-8") as f:
            slots[rel] = FileContext(p, rel, f.read(), run)
    pc = ProjectContext(slots, _REPO)
    pc.run = run
    pc.build()
    edges = set()
    for info in pc.functions.values():
        for outer, inner, _line in info.order_pairs:
            edges.add((outer, inner))
        for s in info.calls:
            if s.held and s.callee in pc.functions:
                for lock in pc.acquired_closure(s.callee):
                    for h in s.held:
                        if h != lock:
                            edges.add((h, lock))
    # r21 added the elastic tier's backfill lock: it exists to
    # serialize slow spare spawns (Popen/fork + readiness probes), so
    # the probe's chaos-checkpoint and partition-table consults and the
    # controller's own state lock are reached UNDER it — each a leaf
    # (acquires nothing further) and every edge one-directional: no
    # path holds _STATE_LOCK/_PARTITION_LOCK/FleetController._lock and
    # then takes _backfill_lock (the closure proves exactly that)
    backfill = "csmom_tpu.serve.fleet.FleetController._backfill_lock"
    assert edges == {("csmom_tpu.serve.queue.AdmissionQueue._lock",
                      "csmom_tpu.obs.metrics._LOCK"),
                     (backfill, "csmom_tpu.chaos.inject._STATE_LOCK"),
                     (backfill, "csmom_tpu.serve.fleet."
                                "FleetController._lock"),
                     (backfill, "csmom_tpu.serve.proto._PARTITION_LOCK"),
                     }, sorted(edges)
    assert not any(e[1] == backfill for e in edges), (
        "a reverse edge INTO the backfill lock would complete a cycle")
    router_lock = "csmom_tpu.serve.router.Router._lock"
    assert not any(router_lock in e for e in edges)
    # the supervisor restart path: _restart/_spawn (Popen, file opens)
    # and _probe_until_ready (sleep-polling) acquire nothing and are
    # never called with the supervisor lock held
    sup = "csmom_tpu.serve.supervisor.PoolSupervisor"
    for fn in ("_restart", "_spawn", "_probe_until_ready"):
        # the path may briefly take its own event lock plus the chaos
        # checkpoint, metrics, and transport-partition locks — all leaf
        # locks that acquire nothing else (the closure proves exactly
        # that).  _PARTITION_LOCK joined at r18: the probe's readiness
        # request rides proto.request, whose score path consults the
        # chaos partition table before dialing.
        assert pc.acquired_closure(f"{sup}.{fn}").keys() <= {
            f"{sup}._lock", "csmom_tpu.chaos.inject._STATE_LOCK",
            "csmom_tpu.obs.metrics._LOCK",
            "csmom_tpu.serve.proto._PARTITION_LOCK"}
    for info in pc.functions.values():
        for s in info.calls:
            if s.callee in (f"{sup}._spawn", f"{sup}._probe_until_ready"):
                assert not s.held, (info.qname, s.line)


# ---------------------------------------------- the incremental cache ------

def test_cache_second_sweep_is_faster_and_byte_identical(tmp_path):
    """The CI satellite pin: on an unchanged tree the warm project
    sweep is >= 5x faster than the cold one (it skips every parse), and
    the reports agree finding-for-finding."""
    cache_dir = str(tmp_path / "lintcache")
    t0 = time.monotonic()
    cold = run_lint(project=True, cache_dir=cache_dir)
    t1 = time.monotonic()
    warm = run_lint(project=True, cache_dir=cache_dir)
    t2 = time.monotonic()
    assert cold.cache["misses"] > 100 and cold.cache["hits"] == 0
    assert warm.cache["hits"] == warm.files and warm.cache["misses"] == 0
    assert warm.cache["project_hit"] is True
    assert [str(f) for f in cold.findings] == [str(f)
                                               for f in warm.findings]
    assert ([str(s) for s in cold.suppressed]
            == [str(s) for s in warm.suppressed])
    cold_s, warm_s = t1 - t0, max(t2 - t1, 1e-9)
    assert cold_s / warm_s >= 5.0, (
        f"cache speedup only {cold_s / warm_s:.1f}x "
        f"({cold_s:.3f}s -> {warm_s:.3f}s)")


def test_cache_invalidates_on_content_change_and_honors_no_cache(tmp_path):
    """A content change re-sweeps exactly the changed file (findings
    change accordingly); ``cache=False`` (the --no-cache path) never
    reads or writes the cache."""
    repo = tmp_path / "repo"
    (repo / "csmom_tpu").mkdir(parents=True)
    mod = repo / "csmom_tpu" / "m.py"
    mod.write_text("X = 1\n")
    cache_dir = str(tmp_path / "c")
    r1 = run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir)
    assert r1.findings == []
    # out-of-repo rels are absolute and deliberately uncached; in-repo
    # files key by relative path + digest
    r2 = run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir)
    assert r2.cache["hits"] == 1
    mod.write_text("import time\nX = time.time()\n")
    r3 = run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir)
    assert r3.cache["hits"] == 0 and r3.cache["misses"] == 1
    assert [f.rule for f in r3.findings] == ["clock-discipline"]
    r4 = run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir,
                  cache=False)
    assert r4.cache == {"enabled": False}
    assert [f.rule for f in r4.findings] == ["clock-discipline"]


def test_cached_sweep_replays_suppressions_and_cross_file_facts():
    """A warm sweep must not lose (a) pragma suppressions or (b) the
    cross-file enumeration-drift vocabulary state — both replay from
    the cache record, and a stale cache entry can never change a
    verdict (content-digest keyed)."""
    rep = run_lint(project=True)   # warm or cold, either way
    rep2 = run_lint(project=True)
    assert len(rep2.suppressed) == len(rep.suppressed) > 0
    assert rep2.findings == rep.findings == []


def test_vocabulary_change_invalidates_cached_enumeration_verdicts(
        tmp_path, monkeypatch):
    """enumeration-drift verdicts depend on the LIVE checkpoint
    vocabulary, not just the scanned sources — changing KNOWN_POINTS
    must invalidate cached per-file verdicts in BOTH directions
    (review finding: the cache key now folds the rule's cache_salt)."""
    import csmom_tpu.chaos.plan as plan

    repo = tmp_path / "repo"
    (repo / "csmom_tpu").mkdir(parents=True)
    mod = repo / "csmom_tpu" / "m.py"
    mod.write_text('def f(checkpoint):\n    checkpoint("zzz.bogus")\n')
    cache_dir = str(tmp_path / "c")
    r1 = run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir)
    assert [f.rule for f in r1.findings] == ["enumeration-drift"]
    monkeypatch.setattr(plan, "KNOWN_POINTS",
                        tuple(plan.KNOWN_POINTS) + ("zzz.bogus",))
    r2 = run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir)
    assert r2.findings == [], (
        "a stale cached verdict replayed past a vocabulary change: "
        + str(r2.findings))


def test_compile_surface_toy_check_is_identical_warm_and_cold(tmp_path):
    """The toy LINT_SURFACE check must see parse-free warm slots too
    (review finding): the bad fixture package reports its missing entry
    on the cold sweep AND on the fully-warm repeat."""
    cache_dir = str(tmp_path / "c")
    bad = _fixture("compile_surface_bad")
    cold = run_lint(paths=[bad], project=True, cache_dir=cache_dir)
    warm = run_lint(paths=[bad], project=True, cache_dir=cache_dir)
    for rep in (cold, warm):
        assert any(f.rule == "compile-surface"
                   and "no warmed manifest entry" in f.message
                   for f in rep.findings), rep.findings
    assert ([(f.path, f.line) for f in cold.findings]
            == [(f.path, f.line) for f in warm.findings])


def test_cli_no_cache_flag_is_wired(capsys):
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--no-cache", "--format", "json",
               "--paths", _fixture("lock_discipline_clean.py")])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0 and report["cache"] == {"enabled": False}


def test_cli_records_sweep_seconds_on_the_metrics_gauge(capsys):
    """ISSUE 12 satellite: the sweep wall time lands on the
    ``lint.sweep_s`` gauge when telemetry is armed (and, per the
    zero-cost-unarmed contract, nowhere otherwise)."""
    from csmom_tpu import obs
    from csmom_tpu.cli.main import main
    from csmom_tpu.obs import metrics

    obs.arm(None, run_id="lint-unit", proc="t")
    try:
        rc = main(["lint", "--paths",
                   _fixture("lock_discipline_clean.py")])
        capsys.readouterr()
        assert rc == 0
        v = metrics.gauge("lint.sweep_s").value
        assert isinstance(v, float) and v > 0.0
    finally:
        obs.disarm()
        metrics.reset()


# ------------------------------------------------------- pragma semantics --

def test_live_pragma_suppresses_and_is_not_stale():
    rep = run_lint(paths=[_fixture("pragma_live.py")])
    assert rep.findings == []
    assert len(rep.suppressed) == 1
    assert rep.suppressed[0].rule == "clock-discipline"


def test_stale_pragma_is_itself_a_finding():
    """ISSUE 11 satellite pin: a pragma with no matching finding fails
    the sweep — the unused-suppression hole the count-based allowlist
    left open."""
    rep = run_lint(paths=[_fixture("stale_pragma.py")])
    assert [f.rule for f in rep.findings] == [STALE_PRAGMA_RULE]
    assert "unused suppression" in rep.findings[0].message


def test_trailing_pragma_does_not_leak_onto_the_next_line(tmp_path):
    """A pragma on an offending CODE line covers that line only — a
    second, unjustified defect directly below must still fail the
    sweep (a standalone comment/prose pragma line covers the line
    below it, which is the documented above-the-statement form)."""
    p = tmp_path / "two.py"
    p.write_text(
        "import time\n\n\n"
        "def two():\n"
        "    a = time.time()  # lint: allow[clock-discipline] this one\n"
        "    b = time.time()\n"
        "    return a + b\n")
    rep = run_lint(paths=[str(p)])
    assert [f.line for f in rep.findings] == [6], rep.findings
    assert [s.line for s in rep.suppressed] == [5]


def test_alias_map_applies_bindings_in_source_order(tmp_path):
    """A nested-function clock rebind must not shadow a LATER
    module-level rebind of the same name (ast.walk is breadth-first;
    the map sorts bindings by source position and retires aliases on
    untracked rebinds)."""
    p = tmp_path / "alias.py"
    p.write_text(
        "import time\n\n\n"
        "def other():\n"
        "    t = time.time\n"
        "    return t\n\n\n"
        "t = len\n"
        'x = t("abc")\n')
    rep = run_lint(paths=[str(p)])
    assert rep.findings == [], rep.findings


def test_unknown_rule_in_pragma_is_a_finding(tmp_path):
    p = tmp_path / "x.py"
    p.write_text("# lint: allow[no-such-rule] why not\nX = 1\n")
    rep = run_lint(paths=[str(p)])
    assert any(f.rule == STALE_PRAGMA_RULE
               and "unknown rule" in f.message for f in rep.findings)


def test_clock_tier_modules_cannot_pragma_out(tmp_path):
    """A serve/stream/ledger module carrying a clock-discipline pragma
    is itself a finding: tiers are contracts, not defaults."""
    ring = tmp_path / "csmom_tpu" / "stream" / "ring.py"
    ring.parent.mkdir(parents=True)
    ring.write_text(
        "# lint: allow[clock-discipline] please let me\n"
        "from csmom_tpu.utils.deadline import mono_now_s\n")
    rep = run_lint(paths=[str(ring)], repo=str(tmp_path))
    assert any("must not carry a clock-discipline pragma" in f.message
               for f in rep.findings), rep.findings
    # the pragma'd import finding itself is ALSO still reported via the
    # contract path or suppressed — but the contract finding cannot be
    # silenced, so the sweep fails either way
    assert rep.findings


def test_unparseable_source_is_a_finding_not_a_crash(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def broken(:\n")
    rep = run_lint(paths=[str(p)])
    assert [f.rule for f in rep.findings] == ["parse-error"]


def test_non_utf8_source_is_a_finding_not_a_crash(tmp_path):
    """A latin-1 byte in a scanned file must degrade to a parse-error
    finding, not abort the sweep (UnicodeDecodeError is a ValueError
    the read path has to absorb like any other unparseable source)."""
    p = tmp_path / "latin.py"
    p.write_bytes(b"# caf\xe9\nX = 1\n")
    rep = run_lint(paths=[str(p)])
    assert [f.rule for f in rep.findings] == ["parse-error"]


def test_damaged_cache_with_valid_marker_reads_as_cold(tmp_path):
    """The cache contract: a sweep.json that carries the right format
    marker but alien inner structure (truncated, hand-edited, written
    by a future version reusing the marker) is treated as EMPTY — the
    cache may only ever change the sweep's speed, never crash it."""
    import json

    from csmom_tpu.analysis.cache import SweepCache

    repo = tmp_path / "repo"
    (repo / "csmom_tpu").mkdir(parents=True)
    mod = repo / "csmom_tpu" / "m.py"
    mod.write_text("X = 1\n")
    cache_dir = tmp_path / "c"
    cache_dir.mkdir()
    for alien in (
            {"format": 2, "files": {"a.py": [1]}, "project": [1]},
            {"format": 2, "files": {"a.py": {"sig": {"digest": "d",
             "raw": [{"line": 1}], "pragmas": [], "facts": {}}}},
             "project": {}},
            {"format": 2, "files": {}, "project": {"k": {"r": [None]}}},
    ):
        (cache_dir / "sweep.json").write_text(json.dumps(alien))
        sc = SweepCache(str(repo), ["clock-discipline"],
                        directory=str(cache_dir))
        assert sc.lookup("a.py", "d") is None
        assert sc.lookup_project("k") is None
        rep = run_lint(paths=[str(mod)], repo=str(repo),
                       cache_dir=str(cache_dir))
        assert rep.findings == [] and rep.cache["hits"] == 0


def test_editing_a_plugin_rule_source_invalidates_its_cached_verdicts(
        tmp_path):
    """The invalidation signature must cover rule sources OUTSIDE the
    analysis package too: a runtime-registered plugin rule whose file
    changes is a different sweep, so its cached verdicts cannot be
    replayed."""
    import importlib.util
    import sys

    plug = tmp_path / "plug_rule.py"
    plug.write_text(
        "from csmom_tpu.analysis.core import LintRule\n\n\n"
        "class PlugRule(LintRule):\n"
        "    id = 'plug-rule'\n"
        "    description = 'test-only plugin rule'\n\n"
        "    def finish_file(self, ctx):\n"
        "        pass\n")
    spec = importlib.util.spec_from_file_location("plug_rule", str(plug))
    module = importlib.util.module_from_spec(spec)
    sys.modules["plug_rule"] = module
    spec.loader.exec_module(module)
    register_engine(name="plug-rule", kind="lint",
                    rule_cls=module.PlugRule,
                    description="test-only plugin rule")
    try:
        repo = tmp_path / "repo"
        (repo / "csmom_tpu").mkdir(parents=True)
        mod = repo / "csmom_tpu" / "m.py"
        mod.write_text("X = 1\n")
        cache_dir = str(tmp_path / "c")
        run_lint(paths=[str(mod)], repo=str(repo), cache_dir=cache_dir)
        warm = run_lint(paths=[str(mod)], repo=str(repo),
                        cache_dir=cache_dir)
        assert warm.cache["hits"] == 1
        # a behavioral edit to the plugin file (its content is what the
        # signature hashes) must read as a different sweep
        plug.write_text(plug.read_text() + "# tightened\n")
        cold = run_lint(paths=[str(mod)], repo=str(repo),
                        cache_dir=cache_dir)
        assert cold.cache["hits"] == 0 and cold.cache["misses"] == 1
    finally:
        unregister_engine("plug-rule", kind="lint")
        sys.modules.pop("plug_rule", None)


# ------------------------------------------- registry + gate integration ---

def test_builtin_rules_are_registry_citizens():
    names = [s.name for s in lint_rules()]
    assert names == ["clock-discipline", "tracer-hygiene",
                     "lock-discipline", "donation-safety",
                     "enumeration-drift", "dial-discipline",
                     "lock-order", "helper-hygiene", "compile-surface"]
    for s in lint_rules():
        assert s.kind == "lint" and s.rule_cls is not None
        assert s.description
    scopes = {s.name: getattr(s.rule_cls, "scope", "file")
              for s in lint_rules()}
    assert {n for n, sc in scopes.items() if sc == "project"} == {
        "lock-order", "helper-hygiene", "compile-surface"}


def test_project_rules_join_only_project_sweeps():
    """A plain ``run_lint()`` stays the per-file sweep (same cost as
    r16); ``project=True`` adds the whole-program set; naming a project
    rule explicitly runs it regardless of the flag."""
    plain = run_lint(paths=[_fixture("lock_discipline_clean.py")])
    assert set(plain.rules) == {"clock-discipline", "tracer-hygiene",
                                "lock-discipline", "donation-safety",
                                "enumeration-drift", "dial-discipline"}
    assert plain.project is False
    via_flag = run_lint(paths=[_fixture("lock_discipline_clean.py")],
                        project=True)
    assert "lock-order" in via_flag.rules and via_flag.project is True
    via_rule = run_lint(paths=[_fixture("lock_order_bad")],
                        rule="lock-order")
    assert via_rule.project is True
    assert [f for f in via_rule.findings if f.rule == "lock-order"]


def test_cli_rules_listing_marks_project_scope(capsys):
    from csmom_tpu.cli.main import main

    rc = main(["lint", "--rules"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "lock-order  [project]" in out
    assert "compile-surface  [project]" in out


def test_toy_rule_registered_at_runtime_joins_the_sweep(tmp_path, capsys):
    """The tentpole's acceptance property, lint edition: register once,
    appear in run_lint, the CLI listing, and `csmom registry list` —
    no other file edited."""

    class NoTodo(LintRule):
        id = "no-todo-markers"
        description = "test-only toy rule: comments must not say TODO"

        def finish_file(self, ctx):
            for kind, line, text in ctx.tokens:
                if kind == "comment" and "TODO" in text:
                    ctx.report(self.id, line, "TODO marker in a comment")

    register_engine(name=NoTodo.id, kind="lint", rule_cls=NoTodo,
                    description=NoTodo.description)
    try:
        assert NoTodo.id in [s.name for s in lint_rules()]
        p = tmp_path / "t.py"
        p.write_text("x = 1  # TODO remove\n")
        rep = run_lint(paths=[str(p)])
        assert any(f.rule == NoTodo.id for f in rep.findings)
        # a pragma for the toy rule works immediately too
        p.write_text("# lint: allow[no-todo-markers] grandfathered\n"
                     "x = 1  # TODO remove\n")
        rep = run_lint(paths=[str(p)])
        assert not [f for f in rep.findings if f.rule == NoTodo.id]
        # the registry CLI lists it under kind 'lint'
        from csmom_tpu.cli.main import main

        rc = main(["registry", "list", "--kind", "lint"])
        out = capsys.readouterr().out
        assert rc == 0 and NoTodo.id in out
    finally:
        unregister_engine(NoTodo.id, kind="lint")
    assert NoTodo.id not in [s.name for s in lint_rules()]


def test_checkpoint_vocabulary_round_trips_on_the_full_sweep():
    """Both directions of the enumeration-drift vocabulary check: the
    committed tree round-trips, and a doctored dead entry is caught at
    the KNOWN_POINTS anchor."""
    from csmom_tpu.analysis.rules import EnumerationDrift

    rep = run_lint(rules=[EnumerationDrift()])
    assert rep.findings == []

    ghost = EnumerationDrift()
    ghost._vocab = ghost._vocab + ("ghost.point",)
    rep = run_lint(rules=[ghost])
    assert any("ghost.point" in f.message
               and f.path.endswith("chaos/plan.py")
               for f in rep.findings), rep.findings


def test_rehearse_refuses_to_start_on_a_dirty_tree(monkeypatch, capsys):
    """ISSUE 11 satellite: `csmom rehearse` gates on the lint sweep —
    a dirty tree must not reach a tunnel window."""
    from csmom_tpu.analysis.core import Finding
    from csmom_tpu.cli import rehearse as reh

    monkeypatch.setattr(
        reh, "_lint_gate",
        lambda: [Finding("clock-discipline", "x.py", 3, "smuggled wall "
                         "clock")])

    class Args:
        list = False
        plan = None
        fast = True
        only = None
        sandbox = None
        keep = False
        verbose = False

    rc = reh.cmd_rehearse(Args())
    err = capsys.readouterr().err
    assert rc == 1
    assert "refusing to rehearse" in err
    assert "x.py:3" in err


def test_rehearse_gate_runs_at_project_scope(monkeypatch):
    """ISSUE 12 satellite: the rehearse refusal extends to project
    findings — the gate sweeps with project=True, so a lock-order cycle
    or an unwarmed dispatchable shape blocks the tunnel window too."""
    import csmom_tpu.analysis as analysis
    from csmom_tpu.cli import rehearse as reh

    seen = {}
    real = analysis.run_lint

    def spy(*a, **kw):
        seen.update(kw)
        return real(*a, **kw)

    monkeypatch.setattr(analysis, "run_lint", spy)
    findings = reh._lint_gate()
    assert seen.get("project") is True
    assert findings == []


def test_rehearse_list_skips_the_gate(monkeypatch, capsys):
    from csmom_tpu.cli import rehearse as reh

    def boom():  # pragma: no cover - must not run
        raise AssertionError("--list must not pay the sweep")

    monkeypatch.setattr(reh, "_lint_gate", boom)

    class Args:
        list = True
        plan = None
        fast = True
        only = None
        sandbox = None
        keep = False
        verbose = False

    rc = reh.cmd_rehearse(Args())
    out = capsys.readouterr().out
    assert rc == 0 and "plan:" in out


def test_lint_is_a_device_free_subcommand():
    """The sweep must run on a box with no accelerator and no probe —
    it gates rehearse, which gates windows."""
    from csmom_tpu.cli.main import _DEVICE_FREE_COMMANDS

    assert "lint" in _DEVICE_FREE_COMMANDS
