"""ISSUE 10: the mesh subsystem — partition rules, sharded variants,
bitwise parity, device pinning, and the mesh warmup profiles.

Parity is asserted BITWISE (``np.array_equal``), not to tolerance: the
mesh layer's placements are chosen so distribution never changes
reduction order on the tested paths (batch rows and grid cells are
independent; the asset-sharded signals are per-asset independent), and
the degenerate 1-shard path is the literal single-device program.  All
on the conftest-forced 8-device CPU host platform, f32 AND f64.
"""

import os

import numpy as np
import pytest

import jax

from csmom_tpu.mesh import (
    DEVICE_SLICE_ENV,
    parse_device_slice,
    shards_for,
    slice_for_slot,
)
from csmom_tpu.mesh.rules import (
    match_partition_rules,
    named_mesh,
    serve_axis_for,
    serve_rules,
)
from csmom_tpu.mesh.variants import sharded_serve_entry_fn
from csmom_tpu.registry import engine_specs, get_engine, serve_endpoints
from csmom_tpu.serve.engine import serve_entry_fn


@pytest.fixture(scope="module", autouse=True)
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("8 virtual CPU devices not configured")
    return jax.devices()


def _batch_panel(rng, B=4, A=16, M=24, dtype=np.float32):
    v = 100.0 * np.exp(np.cumsum(rng.normal(0, 0.03, (B, A, M)), axis=2))
    m = rng.random((B, A, M)) > 0.05
    return np.where(m, v, np.nan).astype(dtype), m


# ------------------------------------------------------------ pinning -----

def test_slice_arithmetic_round_trips():
    assert slice_for_slot(0, 2) == "0:2"
    assert slice_for_slot(3, 4) == "12:4"
    assert parse_device_slice("12:4") == (12, 4)
    for bad in ("x", "3", "-1:2", "1:0", ""):
        with pytest.raises(ValueError):
            parse_device_slice(bad)
    with pytest.raises(ValueError):
        slice_for_slot(-1, 2)


def test_shards_for_picks_largest_divisor():
    assert shards_for(8, 8) == 8
    assert shards_for(4, 8) == 4
    assert shards_for(6, 4) == 3
    assert shards_for(7, 4) == 1   # prime > cap: the degenerate path
    assert shards_for(0, 8) == 1


def test_pinned_slice_env_bounds_the_mesh(monkeypatch):
    monkeypatch.setenv(DEVICE_SLICE_ENV, "2:2")
    entry = sharded_serve_entry_fn("momentum")
    assert entry.n_devices == 2
    assert entry.devices == tuple(jax.devices()[2:4])
    monkeypatch.setenv(DEVICE_SLICE_ENV, "6:4")  # runs off the end
    with pytest.raises(ValueError, match="exceeds"):
        sharded_serve_entry_fn("momentum")


# -------------------------------------------------------------- rules -----

def test_match_partition_rules_resolves_named_leaves():
    from jax.sharding import PartitionSpec as P

    tree = {"values": jax.ShapeDtypeStruct((4, 8, 24), np.float32),
            "mask": jax.ShapeDtypeStruct((4, 8, 24), bool),
            "scale": jax.ShapeDtypeStruct((), np.float32)}
    specs = match_partition_rules(serve_rules("batch"), tree)
    assert specs["values"] == P("batch", None, None)
    assert specs["mask"] == P("batch", None, None)
    assert specs["scale"] == P()   # scalars are never partitioned
    with pytest.raises(ValueError, match="no partition rule matches"):
        match_partition_rules(serve_rules("batch"),
                              {"mystery": jax.ShapeDtypeStruct(
                                  (4, 4), np.float32)})


def test_serve_axis_placement_table():
    # per-asset-independent signals shard assets; cross-sectional
    # reducers (summary backtest, z-scored combo) stay batch-sharded
    assert serve_axis_for("momentum") == "assets"
    assert serve_axis_for("turnover") == "assets"
    assert serve_axis_for("backtest") == "batch"
    assert serve_axis_for("zscore_combo") == "batch"
    assert serve_axis_for("some_runtime_plugin") == "batch"  # safe default


def test_asset_axis_refused_for_summary_endpoints():
    with pytest.raises(ValueError, match="reduction order"):
        sharded_serve_entry_fn("backtest", axis="assets")


# ----------------------------------------------- serve entry parity -------

@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("kind", ["momentum", "turnover", "backtest"])
def test_sharded_serve_entry_bitwise_equals_single_device(rng, kind, dtype):
    v, m = _batch_panel(rng, dtype=dtype)
    single = np.asarray(serve_entry_fn(kind, 12, 1, 10, "rank")(v, m))
    entry = sharded_serve_entry_fn(kind)
    assert entry.shards_for_shape(v.shape[0], v.shape[1]) > 1, (
        "test shapes must actually shard, or parity is vacuous")
    sharded = np.asarray(entry(v, m))
    np.testing.assert_array_equal(single, sharded), (kind, dtype)


def test_degenerate_single_device_entry_is_the_unsharded_program(rng):
    # one pinned device: shards_for -> 1 and the entry is jit(vmap(one))
    entry = sharded_serve_entry_fn("momentum",
                                   devices=jax.devices()[:1])
    assert entry.n_devices == 1
    v, m = _batch_panel(rng)
    single = np.asarray(serve_entry_fn("momentum", 12, 1, 10, "rank")(v, m))
    np.testing.assert_array_equal(single, np.asarray(entry(v, m)))


def test_toy_registered_engine_gets_the_sharded_surface(rng):
    """Surface (e) for a runtime registration: the catch-all serve rule
    hands any per-request scorer the batch-axis variant with no edit
    anywhere — the r14 stub's pointed error is gone."""
    from csmom_tpu.registry import ServeSurface, register_engine, \
        unregister_engine

    def batch(params):
        import jax.numpy as jnp

        return lambda v, m: jnp.where(m[:, -1], v[:, -1], jnp.nan)

    def stub(params):
        return lambda v, m: np.where(m[:, :, -1], v[:, :, -1], np.nan)

    name = "toy_mesh_last_price"
    spec = register_engine(name=name, kind="serve",
                           serve=ServeSurface(batch_fn=batch, stub_fn=stub))
    try:
        entry = spec.sharded()
        assert entry.axis == "batch"
        v, m = _batch_panel(rng, B=8, A=4)
        single = np.asarray(serve_entry_fn(name, 12, 1, 10, "rank")(v, m))
        np.testing.assert_array_equal(single, np.asarray(entry(v, m)))
    finally:
        unregister_engine(name, kind="serve")


# ------------------------------------------------------- grid parity ------

@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_sharded_grid_bitwise_equals_single_device(rng, dtype):
    from csmom_tpu.backtest.grid import jk_grid_backtest

    A, M = 24, 48
    p = 50 * np.exp(np.cumsum(rng.normal(0.003, 0.07, (A, M)), axis=1))
    p[:4, :10] = np.nan
    p = p.astype(dtype)
    m = np.isfinite(p)
    Js, Ks = np.array([3, 6]), np.array([3, 6])
    single = jk_grid_backtest(p, m, Js, Ks, skip=1, n_bins=5, mode="rank")
    fn = get_engine("grid.jk", kind="compile").sharded(grid_shards=2,
                                                       asset_shards=2)
    sh = fn(p, m, Js, Ks, skip=1, n_bins=5, mode="rank")
    for field in ("spreads", "spread_valid", "mean_spread", "ann_sharpe",
                  "tstat", "tstat_nw"):
        np.testing.assert_array_equal(
            np.asarray(getattr(single, field)),
            np.asarray(getattr(sh, field)),
            err_msg=f"{field} diverged under grid2 x assets2 ({dtype})")


def test_sharded_stream_signals_bitwise_equal(rng):
    from csmom_tpu.signals.momentum import momentum

    A, bars = 16, 36
    p = 100.0 * np.exp(np.cumsum(rng.normal(0, 0.02, (A, bars)), axis=1))
    m = rng.random((A, bars)) > 0.04
    p = np.where(m, p, np.nan).astype(np.float32)
    fns = get_engine("stream.signals", kind="compile").sharded()
    mom_s, ok_s = fns["momentum"](p, m, lookback=6, skip=1)
    mom_1, ok_1 = momentum(p, m, lookback=6, skip=1)
    np.testing.assert_array_equal(np.asarray(mom_1), np.asarray(mom_s))
    np.testing.assert_array_equal(np.asarray(ok_1), np.asarray(ok_s))


# ------------------------------------------------ registry completeness ---

def test_sharded_surface_complete_for_serve_and_compile():
    """The r14 stub expectation, FLIPPED: every serve/compile engine now
    resolves a non-stub sharded variant (explicit sharded_fn or a mesh
    rule); only kinds with no dispatchable axis of their own (strategy
    plugin classes) keep the pointed refusal."""
    from csmom_tpu.mesh.variants import resolve_sharded
    from csmom_tpu.registry import strategies

    specs = engine_specs("serve") + engine_specs("compile")
    assert specs, "registry unexpectedly empty"
    missing = [f"{s.kind}:{s.name}" for s in specs
               if s.sharded_fn is None and resolve_sharded(s) is None]
    assert missing == [], (
        f"engines with a stubbed sharded surface: {missing} — ISSUE 10 "
        "filled every serve/compile engine")
    strategies()  # force the zoo registrations
    strat = engine_specs("strategy")
    assert strat, "strategy zoo unexpectedly empty"
    with pytest.raises(NotImplementedError, match="no sharded variant"):
        strat[0].sharded()


# ------------------------------------------------------ mesh profiles -----

def test_mesh_profiles_cover_every_endpoint_and_match_health_names():
    from csmom_tpu.compile.manifest import build_manifest
    from csmom_tpu.serve.health import expected_entry_names

    ndev = len(jax.devices())
    entries = build_manifest("serve-mesh-smoke")
    names = {e.name for e in entries}
    assert len(names) == len(entries)
    for e in entries:
        e.validate()
    # the jax-free health check derives the SAME names the jax-side
    # manifest feeder generates — the drift either side would suffer
    # alone is exactly what this cross-check refuses
    assert names == expected_entry_names("serve-smoke", mesh_devices=ndev)
    for kind in serve_endpoints():
        assert any(f".{kind}." in n for n in names), (
            f"endpoint {kind!r} missing from the serve-mesh profile")


def test_bench_mesh_profile_binds_the_sharded_grid():
    from csmom_tpu.compile.manifest import build_manifest

    entries = build_manifest("bench-mesh")
    assert len(entries) == 2  # reduced + north-star panels
    for e in entries:
        e.validate()
        assert e.name.startswith("mesh.grid.jk16.")


def test_mesh_cache_version_is_topology_keyed():
    from csmom_tpu.serve.health import aot_cache_version

    base = aot_cache_version("serve")
    assert aot_cache_version("serve") == base  # deterministic
    mesh2 = aot_cache_version("serve", engine="jax-mesh", mesh_devices=2)
    mesh8 = aot_cache_version("serve", engine="jax-mesh", mesh_devices=8)
    assert len({base, mesh2, mesh8}) == 3, (
        "a resized mesh must read as version skew, not share a token")


# ----------------------------------------------------- the mesh engine ----

def test_mesh_engine_serves_every_endpoint_with_zero_fresh_compiles():
    """The serving tier's mesh claim end-to-end: warm -> per-endpoint
    dispatch through the sharded entries -> zero in-window compiles,
    results identical to the single-device engine's."""
    from csmom_tpu.serve.service import ServeConfig, SignalService

    svc = SignalService(ServeConfig(profile="serve-smoke",
                                    engine="jax-mesh",
                                    max_wait_s=0.005)).start()
    months = svc.spec.months
    try:
        mesh = (svc.warm_report or {}).get("mesh") or {}
        assert mesh.get("devices", 0) > 1
        rng = np.random.default_rng(7)
        panels = {}
        reqs = {}
        for i, kind in enumerate(serve_endpoints()):
            v = 100.0 * np.exp(np.cumsum(
                rng.normal(0, 0.03, (5, months)), axis=1)
            ).astype(np.float32)
            m = np.ones((5, months), bool)
            panels[kind] = (v, m)
            reqs[kind] = svc.submit(kind, v, m)
        for kind, r in reqs.items():
            assert r.wait(30.0) and r.state == "served", (kind, r.state,
                                                          r.error)
    finally:
        svc.stop()
    assert svc.invariant_violations() == []
    fresh = svc.fresh_compiles()
    assert fresh == 0, f"mesh serving window compiled: {fresh}"
    # served numbers are the single-device numbers, bit for bit
    for kind, (v, m) in panels.items():
        single = np.asarray(serve_entry_fn(kind, 12, 1, 10, "rank")(
            v[None], m[None]))
        r = reqs[kind]
        if isinstance(r.result, dict):
            from csmom_tpu.serve.engine import unpack_result

            ref = unpack_result(kind, single, 0, 5)
            assert set(r.result) == set(ref)
            for f in ref:
                np.testing.assert_array_equal(r.result[f], ref[f])
        else:
            np.testing.assert_array_equal(np.asarray(r.result),
                                          single[0, :5])
