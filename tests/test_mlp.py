"""MLP score model: linear anchor vs ridge, nonlinear lift, determinism,
padding invariance."""

import numpy as np
import pytest

pytest.importorskip("optax")

from csmom_tpu.models import mlp_time_series_cv, ridge_time_series_cv

from tests.test_ridge import _padded


@pytest.mark.slow
def test_linear_anchor_matches_ridge(rng):
    """``hidden=()`` is a linear model trained by gradient descent — on a
    well-conditioned linear problem it must land near the closed-form ridge
    solution (same harness, so identical folds/scaler by construction)."""
    A, R, F = 2, 400, 5
    valid = np.ones((A, R), bool)
    X = rng.normal(size=(A, R, F))
    w_true = np.array([0.8, -0.5, 0.3, 0.0, 0.2])
    y = X @ w_true + 0.01 * rng.normal(size=(A, R))

    mlp = mlp_time_series_cv(
        X, y, valid, hidden=(), n_steps=3000, learning_rate=3e-2,
        weight_decay=0.0,
    )
    ridge = ridge_time_series_cv(X, y, valid, alpha=1e-8)

    assert int(mlp.n_train) == int(ridge.n_train)
    np.testing.assert_allclose(
        np.asarray(mlp.scale_mean), np.asarray(ridge.scale_mean), rtol=1e-12
    )
    # gradient-descent convergence tolerance, not solver equality
    v = valid.reshape(-1)
    np.testing.assert_allclose(
        np.asarray(mlp.scores).reshape(-1)[v],
        np.asarray(ridge.scores).reshape(-1)[v],
        atol=5e-3,
    )


@pytest.mark.slow
def test_nonlinear_lift_over_ridge(rng):
    """On a target no linear model can express, the MLP's held-out fold MSE
    must beat ridge's."""
    A, R, F = 2, 600, 5
    valid = np.ones((A, R), bool)
    X = rng.normal(size=(A, R, F))
    y = np.sin(2.0 * X[..., 0]) * X[..., 1] + 0.05 * rng.normal(size=(A, R))

    mlp = mlp_time_series_cv(
        X, y, valid, hidden=(32, 16), n_steps=1500, learning_rate=1e-2
    )
    ridge = ridge_time_series_cv(X, y, valid, alpha=1.0)

    assert float(mlp.cv_mse[-1]) < float(ridge.cv_mse[-1])
    assert float(mlp.train_mse) < float(ridge.cv_mse[-1])


@pytest.mark.slow
def test_deterministic_given_seed(rng):
    X, y, valid, _, _ = _padded(rng)
    a = mlp_time_series_cv(X, y, valid, n_steps=50, seed=7)
    b = mlp_time_series_cv(X, y, valid, n_steps=50, seed=7)
    np.testing.assert_array_equal(np.asarray(a.scores), np.asarray(b.scores))
    c = mlp_time_series_cv(X, y, valid, n_steps=50, seed=8)
    assert not np.array_equal(
        np.asarray(c.scores)[np.asarray(valid)],
        np.asarray(a.scores)[np.asarray(valid)],
    )


@pytest.mark.slow
def test_padding_layout_invariance(rng):
    """The fit depends on the ordered set of valid rows, not where padding
    sits: appending extra all-invalid rows must not change anything."""
    X, y, valid, _, _ = _padded(rng)
    A, R, F = X.shape
    Xp = np.concatenate([X, np.full((A, 37, F), np.nan)], axis=1)
    yp = np.concatenate([y, np.full((A, 37), np.nan)], axis=1)
    vp = np.concatenate([valid, np.zeros((A, 37), bool)], axis=1)

    a = mlp_time_series_cv(X, y, valid, n_steps=100)
    b = mlp_time_series_cv(Xp, yp, vp, n_steps=100)
    np.testing.assert_allclose(np.asarray(a.cv_mse), np.asarray(b.cv_mse),
                               rtol=1e-9)
    np.testing.assert_allclose(
        np.asarray(a.scores)[np.asarray(valid)],
        np.asarray(b.scores)[np.asarray(vp)],
        rtol=1e-9,
    )


def test_scores_masked_and_shaped(rng):
    X, y, valid, _, _ = _padded(rng)
    fit = mlp_time_series_cv(X, y, valid, n_steps=50)
    s = np.asarray(fit.scores)
    assert s.shape == y.shape
    assert np.isnan(s[~np.asarray(valid)]).all()
    assert np.isfinite(s[np.asarray(valid)]).all()
    assert np.asarray(fit.cv_mse).shape == (3,)
