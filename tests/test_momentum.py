"""Momentum kernel vs a pandas oracle implementing the reference formulas.

The oracle re-derives features.py:44-52 semantics (pct_change -> shift(skip)
-> rolling(J, min_periods=1).apply(prod(1+r)-1)) on wide frames.
"""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.signals import monthly_returns, momentum


def oracle_momentum(prices: pd.DataFrame, J: int, skip: int) -> pd.DataFrame:
    """prices: wide (months x assets). Returns wide mom_J frame."""
    ret = prices.pct_change()
    shifted = ret.shift(skip)
    return shifted.rolling(J, min_periods=1).apply(
        lambda r: np.prod(1 + r) - 1, raw=True
    )


def _panelize(wide: pd.DataFrame):
    vals = wide.values.T.astype(np.float64)  # [A, M]
    return vals, np.isfinite(vals)


@pytest.mark.parametrize("J,skip", [(12, 1), (6, 1), (3, 0), (9, 2)])
def test_momentum_matches_pandas(rng, J, skip):
    M, A = 60, 8
    prices = pd.DataFrame(
        100 * np.exp(np.cumsum(rng.normal(0, 0.05, size=(M, A)), axis=0))
    )
    vals, mask = _panelize(prices)
    got, got_valid = momentum(vals, mask, lookback=J, skip=skip)
    want = oracle_momentum(prices, J, skip).values.T
    got = np.asarray(got)
    # same NaN pattern
    np.testing.assert_array_equal(np.isnan(got), np.isnan(want))
    np.testing.assert_allclose(got, want, rtol=1e-9, atol=1e-12, equal_nan=True)


def test_warmup_is_J_plus_skip_plus_1(rng):
    """SURVEY §2.1.2: first valid mom_J at month index J+skip (0-based),
    i.e. the (J+skip+1)-th month."""
    M = 40
    prices = pd.DataFrame(100 + np.cumsum(rng.normal(size=(M, 1)), axis=0))
    vals, mask = _panelize(prices)
    _, valid = momentum(vals, mask, lookback=12, skip=1)
    first_valid = int(np.argmax(np.asarray(valid[0])))
    assert first_valid == 13  # 14th month


def test_interior_gap_poisons_windows(rng):
    """A missing month must poison exactly the windows that cover it,
    mirroring NaN propagation through np.prod."""
    M = 50
    prices = pd.DataFrame(100 * np.exp(np.cumsum(rng.normal(0, 0.03, size=(M, 1)), axis=0)))
    prices.iloc[25] = np.nan
    vals, mask = _panelize(prices)
    got, _ = momentum(vals, mask, lookback=6, skip=1)
    want = oracle_momentum(prices, 6, 1).values.T
    np.testing.assert_array_equal(np.isnan(np.asarray(got)), np.isnan(want))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-9, equal_nan=True)


def test_late_starting_asset(rng):
    """An asset entering the panel mid-history warms up J+skip+1 months after
    its own first observation (pandas compacts per ticker; masks must agree)."""
    M = 40
    prices = pd.DataFrame(100 * np.exp(np.cumsum(rng.normal(0, 0.03, size=(M, 2)), axis=0)))
    prices.iloc[:10, 1] = np.nan
    vals, mask = _panelize(prices)
    _, valid = momentum(vals, mask, lookback=6, skip=1)
    assert int(np.argmax(np.asarray(valid[1]))) == 10 + 7


def test_monthly_returns(rng):
    M, A = 30, 5
    prices = pd.DataFrame(100 * np.exp(np.cumsum(rng.normal(0, 0.04, size=(M, A)), axis=0)))
    vals, mask = _panelize(prices)
    got, _ = monthly_returns(vals, mask)
    want = prices.pct_change().values.T
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-9, equal_nan=True)
