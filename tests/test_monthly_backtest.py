"""Monthly decile backtest: pandas-oracle equivalence + BASELINE golden parity."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.backtest import monthly_spread_backtest
from tests.conftest import MEASURED_TICKERS, requires_reference, REFERENCE_DATA
from tests.test_ranking import oracle_deciles


def oracle_monthly_spread(prices: pd.DataFrame, J=12, skip=1):
    """Reference monthly_replication semantics (run_demo.py:31-73) re-derived
    on wide frames: signal -> per-date qcut deciles -> next-month return ->
    equal-weighted decile means -> 9-minus-0 spread."""
    ret = prices.pct_change()
    mom = prices.shift(skip) / prices.shift(skip + J) - 1
    # poison windows covering missing months, like rolling.apply(np.prod)
    bad = ret.isna().astype(int)
    window_bad = bad.shift(skip).rolling(J, min_periods=J).sum()
    mom = mom.where(window_bad == 0)

    labels = pd.DataFrame(
        {t: oracle_deciles(mom.loc[t].values) for t in mom.index}, index=mom.columns
    ).T  # dates x assets, -1 invalid
    next_ret = ret.shift(-1)

    spread = {}
    for t in mom.index:
        lab = labels.loc[t]
        nr = next_ret.loc[t]
        ok = (lab >= 0) & nr.notna()
        top = nr[ok & (lab == 9)]
        bot = nr[ok & (lab == 0)]
        if len(top) and len(bot):
            spread[t] = top.mean() - bot.mean()
    return pd.Series(spread)


def _run(prices_wide: pd.DataFrame, **kw):
    vals = prices_wide.values.T.astype(np.float64)
    mask = np.isfinite(vals)
    return monthly_spread_backtest(vals, mask, **kw)


def test_matches_pandas_oracle(rng):
    M, A = 72, 25
    prices = pd.DataFrame(
        50 * np.exp(np.cumsum(rng.normal(0.005, 0.08, size=(M, A)), axis=0))
    )
    res = _run(prices)
    want = oracle_monthly_spread(prices)
    got = np.asarray(res.spread)[np.asarray(res.spread_valid)]
    np.testing.assert_allclose(got, want.values, rtol=1e-9, atol=1e-12)


def test_late_entrants_and_gaps(rng):
    M, A = 60, 30
    prices = pd.DataFrame(
        50 * np.exp(np.cumsum(rng.normal(0.0, 0.06, size=(M, A)), axis=0))
    )
    prices.iloc[:20, :5] = np.nan    # late entrants
    prices.iloc[40:, 25:] = np.nan   # delistings
    res = _run(prices)
    want = oracle_monthly_spread(prices)
    got = np.asarray(res.spread)[np.asarray(res.spread_valid)]
    np.testing.assert_allclose(got, want.values, rtol=1e-9, atol=1e-12)


def test_rank_mode_runs(rng):
    M, A = 40, 50
    prices = pd.DataFrame(
        50 * np.exp(np.cumsum(rng.normal(0.0, 0.06, size=(M, A)), axis=0))
    )
    res = _run(prices, mode="rank")
    assert np.asarray(res.spread_valid).sum() > 10
    assert np.isfinite(float(res.ann_sharpe))


@requires_reference
def test_golden_parity_measured_baseline():
    """BASELINE.md measured numbers: 19-ticker panel (reference drops AAPL via
    its cache bug), J=12/skip=1 -> mean 0.003674/mo, Sharpe 0.1002, cum 0.7509
    over 70 months 2019-02..2024-11."""
    from csmom_tpu.api import monthly_price_panel

    prices, _ = monthly_price_panel(REFERENCE_DATA, MEASURED_TICKERS)
    v, m = prices.device()
    res = monthly_spread_backtest(v, m, lookback=12, skip=1)

    sv = np.asarray(res.spread_valid)
    assert int(sv.sum()) == 70
    assert str(prices.times[np.argmax(sv)])[:7] == "2019-02"

    assert abs(float(res.mean_spread) - 0.003674) < 5e-7
    assert abs(float(res.ann_sharpe) - 0.1002) < 5e-5
    cum = float(np.prod(1 + np.asarray(res.spread)[sv]))
    assert abs(cum - 0.7509) < 5e-5


@requires_reference
def test_golden_parity_f32():
    """The same measured-baseline workload in float32 — the TPU production
    dtype.  Deciles come from rank order (robust to f32), so validity is
    identical; the spread statistics agree to f32 relative error."""
    from csmom_tpu.api import monthly_price_panel

    prices, _ = monthly_price_panel(REFERENCE_DATA, MEASURED_TICKERS)
    v, m = prices.device()
    res = monthly_spread_backtest(
        np.asarray(v, dtype=np.float32), m, lookback=12, skip=1
    )
    sv = np.asarray(res.spread_valid)
    assert int(sv.sum()) == 70
    assert abs(float(res.mean_spread) - 0.003674) < 2e-6
    assert abs(float(res.ann_sharpe) - 0.1002) < 2e-3
