"""Native C++ CSV parser: build, parity with the pandas engine, speed."""

import os
import time

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.native import get_lib, parse_price_csv_native
from csmom_tpu.panel.ingest import read_price_csv
from tests.conftest import DEMO_TICKERS, REFERENCE_DATA, requires_reference

needs_native = pytest.mark.skipif(get_lib() is None, reason="no native toolchain")


@needs_native
def test_parse_simple_daily(tmp_path):
    p = tmp_path / "X_daily.csv"
    p.write_text(
        "Date,Adj Close,Close,Volume\n"
        ",X,X,X\n"                      # dialect-A junk row
        "2020-01-02,10.5,10.6,100\n"
        "2020-01-03,,10.8,200\n"        # empty adj_close -> NaN
        "2020-01-06,11.0,11.1,garbage\n"  # junk numeric -> NaN
    )
    epochs, values = parse_price_csv_native(str(p), 3)
    assert len(epochs) == 3
    assert pd.Timestamp(epochs[0]) == pd.Timestamp("2020-01-02")
    assert values[0, 0] == 10.5
    assert np.isnan(values[1, 0])
    assert np.isnan(values[2, 2])


@needs_native
def test_parse_timezone_offsets(tmp_path):
    p = tmp_path / "X_intraday.csv"
    p.write_text(
        "Datetime,Close,Volume\n"
        "2025-08-18 09:30:00-04:00,10.0,1\n"
        "2025-08-18 13:30:00+00:00,11.0,2\n"
        "2025-08-18T14:30:00.5+00:00,12.0,3\n"
    )
    epochs, _ = parse_price_csv_native(str(p), 2)
    ts = pd.to_datetime(epochs, unit="ns")
    assert ts[0] == pd.Timestamp("2025-08-18 13:30:00")  # EDT -> UTC
    assert ts[1] == pd.Timestamp("2025-08-18 13:30:00")
    assert ts[2] == pd.Timestamp("2025-08-18 14:30:00")


@needs_native
@requires_reference
def test_engine_parity_all_reference_files():
    """Native and pandas engines must emit identical frames for every
    shipped cache file — both dialects, daily and intraday."""
    for t in DEMO_TICKERS:
        for kind, suffix in (("daily", "daily"), ("intraday", "intraday")):
            path = os.path.join(REFERENCE_DATA, f"{t}_{suffix}.csv")
            if not os.path.exists(path):
                continue
            nat = read_price_csv(path, t, kind=kind, engine="native")
            pdf = read_price_csv(path, t, kind=kind, engine="pandas")
            # numeric cells may differ by 1 ulp (glibc strtod vs pandas'
            # float parser); timestamps/structure must be exact
            tcol = "date" if kind == "daily" else "datetime"
            pd.testing.assert_series_equal(nat[tcol], pdf[tcol], check_exact=True)
            pd.testing.assert_series_equal(nat["ticker"], pdf["ticker"])
            pd.testing.assert_frame_equal(nat, pdf, rtol=1e-15, atol=0)


@needs_native
@requires_reference
def test_native_engine_is_faster():
    paths = [
        os.path.join(REFERENCE_DATA, f"{t}_intraday.csv") for t in DEMO_TICKERS
    ]
    paths = [p for p in paths if os.path.exists(p)]
    read_price_csv(paths[0], "X", kind="intraday", engine="native")  # warm build

    t0 = time.perf_counter()
    for p in paths:
        read_price_csv(p, "X", kind="intraday", engine="native")
    t_nat = time.perf_counter() - t0

    t0 = time.perf_counter()
    for p in paths:
        read_price_csv(p, "X", kind="intraday", engine="pandas")
    t_pd = time.perf_counter() - t0
    # the native path should win clearly on the 20 x ~2.7k-row minute files
    assert t_nat < t_pd, f"native {t_nat:.3f}s vs pandas {t_pd:.3f}s"


@needs_native
def test_versioned_cache_header_skipped(tmp_path):
    p = tmp_path / "A_daily.csv"
    p.write_text(
        "# csmom-cache-v1\n"
        "date,open,high,low,close,adj_close,volume\n"
        "2020-01-02,1,2,0.5,1.5,1.4,100\n"
    )
    df = read_price_csv(str(p), "A", kind="daily", engine="native")
    assert len(df) == 1
    assert df.loc[0, "adj_close"] == 1.4


def test_auto_engine_always_works(tmp_path):
    """engine='auto' must produce a frame with or without a toolchain."""
    p = tmp_path / "Z_daily.csv"
    p.write_text("Date,Close,Volume\n2020-01-02,5.0,10\n")
    df = read_price_csv(str(p), "Z", kind="daily", engine="auto")
    assert len(df) == 1 and df.loc[0, "close"] == 5.0
