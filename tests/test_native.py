"""Native C++ CSV parser: build, parity with the pandas engine, speed."""

import os
import time

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.native import get_lib, parse_price_csv_native
from csmom_tpu.panel.ingest import read_price_csv
from tests.conftest import DEMO_TICKERS, REFERENCE_DATA, requires_reference

needs_native = pytest.mark.skipif(get_lib() is None, reason="no native toolchain")


@needs_native
def test_parse_simple_daily(tmp_path):
    p = tmp_path / "X_daily.csv"
    p.write_text(
        "Date,Adj Close,Close,Volume\n"
        ",X,X,X\n"                      # dialect-A junk row
        "2020-01-02,10.5,10.6,100\n"
        "2020-01-03,,10.8,200\n"        # empty adj_close -> NaN
        "2020-01-06,11.0,11.1,garbage\n"  # junk numeric -> NaN
    )
    epochs, values = parse_price_csv_native(str(p), 3)
    assert len(epochs) == 3
    assert pd.Timestamp(epochs[0]) == pd.Timestamp("2020-01-02")
    assert values[0, 0] == 10.5
    assert np.isnan(values[1, 0])
    assert np.isnan(values[2, 2])


@needs_native
def test_parse_timezone_offsets(tmp_path):
    p = tmp_path / "X_intraday.csv"
    p.write_text(
        "Datetime,Close,Volume\n"
        "2025-08-18 09:30:00-04:00,10.0,1\n"
        "2025-08-18 13:30:00+00:00,11.0,2\n"
        "2025-08-18T14:30:00.5+00:00,12.0,3\n"
    )
    epochs, _ = parse_price_csv_native(str(p), 2)
    ts = pd.to_datetime(epochs, unit="ns")
    assert ts[0] == pd.Timestamp("2025-08-18 13:30:00")  # EDT -> UTC
    assert ts[1] == pd.Timestamp("2025-08-18 13:30:00")
    assert ts[2] == pd.Timestamp("2025-08-18 14:30:00.5")  # frac kept (pandas does)


@needs_native
@requires_reference
def test_engine_parity_all_reference_files():
    """Native and pandas engines must emit identical frames for every
    shipped cache file — both dialects, daily and intraday."""
    for t in DEMO_TICKERS:
        for kind, suffix in (("daily", "daily"), ("intraday", "intraday")):
            path = os.path.join(REFERENCE_DATA, f"{t}_{suffix}.csv")
            if not os.path.exists(path):
                continue
            nat = read_price_csv(path, t, kind=kind, engine="native")
            pdf = read_price_csv(path, t, kind=kind, engine="pandas")
            # numeric cells may differ by 1 ulp (glibc strtod vs pandas'
            # float parser); timestamps/structure must be exact
            tcol = "date" if kind == "daily" else "datetime"
            pd.testing.assert_series_equal(nat[tcol], pdf[tcol], check_exact=True)
            pd.testing.assert_series_equal(nat["ticker"], pdf["ticker"])
            pd.testing.assert_frame_equal(nat, pdf, rtol=1e-15, atol=0)


@needs_native
@requires_reference
def test_native_engine_is_faster():
    paths = [
        os.path.join(REFERENCE_DATA, f"{t}_intraday.csv") for t in DEMO_TICKERS
    ]
    paths = [p for p in paths if os.path.exists(p)]
    read_price_csv(paths[0], "X", kind="intraday", engine="native")  # warm build

    t0 = time.perf_counter()
    for p in paths:
        read_price_csv(p, "X", kind="intraday", engine="native")
    t_nat = time.perf_counter() - t0

    t0 = time.perf_counter()
    for p in paths:
        read_price_csv(p, "X", kind="intraday", engine="pandas")
    t_pd = time.perf_counter() - t0
    # the native path should win clearly on the 20 x ~2.7k-row minute files
    assert t_nat < t_pd, f"native {t_nat:.3f}s vs pandas {t_pd:.3f}s"


@needs_native
def test_versioned_cache_header_skipped(tmp_path):
    p = tmp_path / "A_daily.csv"
    p.write_text(
        "# csmom-cache-v1\n"
        "date,open,high,low,close,adj_close,volume\n"
        "2020-01-02,1,2,0.5,1.5,1.4,100\n"
    )
    df = read_price_csv(str(p), "A", kind="daily", engine="native")
    assert len(df) == 1
    assert df.loc[0, "adj_close"] == 1.4


# --------------------------------------------------------------- fuzzing ----
# Property: for any CSV in the price-cache family (ISO-ish timestamps first
# column, numeric columns after, arbitrary quoting/preamble/line endings),
# the native and pandas engines emit IDENTICAL canonical frames
# (VERDICT r2 item 6; the defensive surface being matched is the
# reference's normalizer, /root/reference/src/data_io.py:23-129).

_TZ_OFFSETS = ["+00:00", "-04:00", "+05:30", "-09:00", "+09:30", "-00:30"]


def _fuzz_cell(rng):
    """One numeric-ish cell: valid floats in many spellings, quoted values,
    quoted values with embedded commas, junk, empties."""
    r = rng.random()
    if r < 0.35:
        return f"{rng.normal(100, 30):.6f}"
    if r < 0.45:
        return f"{rng.normal(0, 1):.3e}"          # scientific
    if r < 0.50:
        return f'"{rng.normal(50, 5):.4f}"'       # quoted number
    if r < 0.56:
        return f'"{rng.integers(1, 9)},{rng.integers(100, 999)}.{rng.integers(0, 99):02d}"'  # embedded comma -> NaN both
    if r < 0.62:
        return ""                                  # empty -> NaN
    if r < 0.68:
        return rng.choice(["garbage", "12abc", "N/A", "--", "0x1f", "1.2.3"])
    if r < 0.74:
        return f"  {rng.normal(10, 2):.2f}  "      # padded with spaces
    if r < 0.80:
        return f"+{rng.random():.5f}"              # explicit plus sign
    if r < 0.90:
        return str(rng.integers(0, 10**6))         # integer volume
    return "nan"


def _fuzz_timestamp(rng, kind, day):
    if rng.random() < 0.12:
        # out-of-range components: both engines must NaT-drop these rows
        # (pandas coerces; the native parser validates calendar + clock)
        return rng.choice([
            "2024-02-30", "2023-02-29", "2024-13-05", "2024-04-31",
            "2024-01-02 24:01:00", "2024-01-02 12:60:00",
            "2024-01-02 12:30:61", "2024-01-02 10:00:00+25:00",
        ])
    date = f"2024-{rng.integers(1, 13):02d}-{day:02d}"
    if kind == "daily":
        return f'"{date}"' if rng.random() < 0.15 else date
    sep = "T" if rng.random() < 0.3 else " "
    t = f"{rng.integers(0, 24):02d}:{rng.integers(0, 60):02d}"
    if rng.random() < 0.7:
        t += f":{rng.integers(0, 60):02d}"
        if rng.random() < 0.3:
            t += f".{rng.integers(0, 10**6)}"      # fractional seconds
    s = f"{date}{sep}{t}"
    if rng.random() < 0.6:
        s += rng.choice(_TZ_OFFSETS)               # exotic UTC offsets
    return f'"{s}"' if rng.random() < 0.1 else s


def _fuzz_csv(rng, kind):
    """Random cache-family CSV text + its header column count."""
    if kind == "daily":
        header_pool = [
            ["Date", "Adj Close", "Close", "High", "Low", "Open", "Volume"],
            ["Price", "Close", "High", "Low", "Open", "Volume"],   # dialect B
            ["Date", "Close", "Volume"],
        ]
    else:
        header_pool = [
            ["Datetime", "Close", "Volume"],
            ["Datetime", "Price", "Volume", "Close"],
        ]
    cols = list(header_pool[rng.integers(0, len(header_pool))])
    if rng.random() < 0.2:
        cols = [f'"{c}"' for c in cols]            # quoted header names
    lines = [",".join(cols)]
    if rng.random() < 0.5:                         # dialect preamble rows
        lines.append("," + ",".join(["XYZ"] * (len(cols) - 1)))
    if rng.random() < 0.3:
        lines.append("Ticker," + ",".join(["XYZ"] * (len(cols) - 1)))
        lines.append("Date" + "," * (len(cols) - 1))
    n_rows = int(rng.integers(3, 25))
    for i in range(n_rows):
        r = rng.random()
        if r < 0.08:
            lines.append(rng.choice(["junk,row,here", "#comment", ""]))
            continue
        ts = _fuzz_timestamp(rng, kind, day=min(28, i + 1))
        n_cells = len(cols) - 1
        if rng.random() < 0.15:                    # short (ragged) row
            n_cells = int(rng.integers(0, n_cells))
        lines.append(",".join([ts] + [_fuzz_cell(rng) for _ in range(n_cells)]))
    newline = "\r\n" if rng.random() < 0.35 else "\n"
    return newline.join(lines) + newline, len(cols)


@needs_native
@pytest.mark.parametrize("seed", range(24))
def test_fuzz_engines_identical(tmp_path, seed):
    rng = np.random.default_rng(24_000 + seed)
    kind = "daily" if seed % 2 == 0 else "intraday"
    text, _ = _fuzz_csv(rng, kind)
    p = tmp_path / f"F{seed}_{kind}.csv"
    p.write_bytes(text.encode())
    nat = read_price_csv(str(p), "F", kind=kind, engine="native")
    pdf = read_price_csv(str(p), "F", kind=kind, engine="pandas")
    tcol = "date" if kind == "daily" else "datetime"
    pd.testing.assert_series_equal(nat[tcol], pdf[tcol], check_exact=True)
    pd.testing.assert_frame_equal(nat, pdf, rtol=1e-15, atol=0)


@needs_native
def test_long_rows_loud_not_silent(tmp_path):
    """Rows with MORE fields than the header.  Long FIRST data row: both
    engines truncate to the header width identically (index_col=False —
    without it pandas silently shifts the timestamp column into the
    index).  Long LATER row: pandas raises (ParserError -> universe-level
    skip), native truncates.  Pinned so a silent divergence cannot creep
    in unnoticed."""
    import warnings

    p = tmp_path / "L_daily.csv"
    p.write_text(
        "Date,Close,Volume\n"
        "2020-01-02,1.5,100,999,888\n"   # 2 extra fields, first data row
        "2020-01-03,1.6,200\n"
    )
    nat = read_price_csv(str(p), "L", kind="daily", engine="native")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # pandas warns about the truncation
        pdf = read_price_csv(str(p), "L", kind="daily", engine="pandas")
    assert len(nat) == 2 and nat.loc[0, "close"] == 1.5
    pd.testing.assert_frame_equal(nat, pdf, rtol=1e-15, atol=0)

    p2 = tmp_path / "L2_daily.csv"
    p2.write_text(
        "Date,Close,Volume\n"
        "2020-01-02,1.5,100\n"
        "2020-01-03,1.6,200,7,8\n"       # long row later in the file
    )
    nat2 = read_price_csv(str(p2), "L2", kind="daily", engine="native")
    assert len(nat2) == 2
    with pytest.raises(Exception, match="fields"):
        read_price_csv(str(p2), "L2", kind="daily", engine="pandas")


def test_auto_engine_always_works(tmp_path):
    """engine='auto' must produce a frame with or without a toolchain."""
    p = tmp_path / "Z_daily.csv"
    p.write_text("Date,Close,Volume\n2020-01-02,5.0,10\n")
    df = read_price_csv(str(p), "Z", kind="daily", engine="auto")
    assert len(df) == 1 and df.loc[0, "close"] == 5.0
