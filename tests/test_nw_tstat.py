"""Newey–West t-stat kernel vs an independent numpy oracle.

Series lengths here stick to the suite's canonical sizes (120/240) so the
eager-op executable cache is shared across the stats-family modules —
every one-off length re-pays ~80 tiny XLA CPU compiles (~4 s).

The replicated paper quotes NW t-stats (LeSw00.pdf Tables I–II); the
reference framework has no t-stats at all (``src/utils.py:8-16``).  These
tests pin the HAC conventions documented in
:func:`csmom_tpu.analytics.stats.nw_t_stat` against the clean-room numpy
implementation in :mod:`csmom_tpu.backends.pandas_engine`.
"""

import numpy as np
import pytest

from csmom_tpu.analytics.stats import nw_t_stat, t_stat
from csmom_tpu.backends.pandas_engine import _nw_tstat_1d


def oracle(x, lags=None):
    return _nw_tstat_1d(np.asarray(x, float), lags)


@pytest.mark.parametrize("lags", [None, 0, 1, 3, 6, 12])
def test_dense_matches_oracle(rng, lags):
    x = rng.normal(0.004, 0.02, size=240)
    valid = np.ones(240, bool)
    got = float(nw_t_stat(x, valid, lags=lags))
    np.testing.assert_allclose(got, oracle(x, lags), rtol=1e-10)


def test_prefix_suffix_mask_equals_compacted(rng):
    """The engines' only invalidity is warmup (prefix) and horizon tail
    (suffix); there the masked kernel must equal the dropna'd series."""
    x = rng.normal(0.002, 0.03, size=120)
    valid = np.ones(120, bool)
    valid[:14] = False   # JT warmup
    valid[-3:] = False   # horizon tail
    for lags in (None, 4):
        got = float(nw_t_stat(x, valid, lags=lags))
        np.testing.assert_allclose(got, oracle(x[valid], lags), rtol=1e-10)


def test_max_lag_invariance(rng):
    """Weights beyond L are exactly zero, so any max_lag >= L is identical."""
    x = rng.normal(0.0, 1.0, size=120)
    v = np.ones(120, bool)
    a = float(nw_t_stat(x, v, lags=5, max_lag=8))
    b = float(nw_t_stat(x, v, lags=5, max_lag=24))
    np.testing.assert_allclose(a, b, rtol=1e-12)


def test_lag_zero_vs_iid():
    """L=0 reduces to the iid t up to the n vs n-1 variance normalization."""
    x = np.sin(np.arange(120)) + 0.3
    v = np.ones(120, bool)
    t0 = float(nw_t_stat(x, v, lags=0))
    ti = float(t_stat(x, v))
    np.testing.assert_allclose(t0, ti * np.sqrt(120 / 119), rtol=1e-10)


@pytest.mark.slow
def test_positive_autocorrelation_shrinks_t(rng):
    """Overlapping K-month holding induces positive serial correlation; NW
    must report smaller |t| than iid there (the whole point of the fix)."""
    e = rng.normal(0, 0.01, size=400)
    # MA(5): the structure K-overlap creates by construction
    x = 0.003 + np.convolve(e, np.ones(6) / 6.0, mode="same")
    v = np.ones_like(x, bool)
    assert abs(float(nw_t_stat(x, v, lags=6))) < abs(float(t_stat(x, v)))


@pytest.mark.slow
def test_broadcast_per_cell_lags(rng):
    """A [nJ, nK, M] grid with per-K lags equals per-cell scalar calls."""
    nJ, nK, M = 2, 3, 150
    x = rng.normal(0.003, 0.02, size=(nJ, nK, M))
    v = rng.random((nJ, nK, M)) > 0.05
    Ks = np.array([1, 3, 6])
    got = np.asarray(nw_t_stat(x, v, lags=Ks[None, :], max_lag=12))
    assert got.shape == (nJ, nK)
    for i in range(nJ):
        for j in range(nK):
            want = float(nw_t_stat(x[i, j], v[i, j], lags=int(Ks[j]), max_lag=12))
            np.testing.assert_allclose(got[i, j], want, rtol=1e-10)


def test_hand_computed_fixtures():
    """Closed-form NW t-stats worked out by hand in exact arithmetic —
    an oracle that shares no code (or author conventions) with either
    implementation, so the kernel and the numpy oracle cannot both hide
    one bug (VERDICT r2 weak #6).

    x=[1,2,3,4], L=1: mean 5/2, u=[-3/2,-1/2,1/2,3/2],
      g0 = 5/4, g1 = 5/16, w1 = 1/2 -> lrv = 25/16,
      se = sqrt(25/64) = 5/8, t = (5/2)/(5/8) = 4 exactly.
    x=[1,-1,1,-1,1], L=1: mean 1/5, u=[4/5,-6/5,...],
      g0 = 24/25, g1 = -96/125 -> lrv = 24/125,
      se = 2*sqrt(6)/25, t = 5/(2*sqrt(6)) = 5*sqrt(6)/12.
    x=[2,1,3,1,2,4,1,2] with the automatic bandwidth (n=8 ->
      L = floor(4*(8/100)^(2/9)) = 2): mean 2, u=[0,-1,1,-1,0,2,-1,0],
      g0 = 1, g1 = -1/2, g2 = -1/8, w = (2/3, 1/3)
      -> lrv = 1 - 2/3 - 1/12 = 1/4, se = 1/(4*sqrt(2)), t = 8*sqrt(2).
    """
    cases = [
        (np.array([1.0, 2.0, 3.0, 4.0]), 1, 4.0),
        (np.array([1.0, -1.0, 1.0, -1.0, 1.0]), 1, 5.0 * np.sqrt(6.0) / 12.0),
        (np.array([2.0, 1.0, 3.0, 1.0, 2.0, 4.0, 1.0, 2.0]), None,
         8.0 * np.sqrt(2.0)),
    ]
    for x, lags, want in cases:
        v = np.ones(len(x), bool)
        np.testing.assert_allclose(float(nw_t_stat(x, v, lags=lags)), want,
                                   rtol=1e-12)
        # the numpy oracle must reproduce the same closed forms
        np.testing.assert_allclose(oracle(x, lags), want, rtol=1e-12)


def test_hand_fixture_with_mask_prefix():
    """Fixture 1 behind an invalid warmup prefix: masked == compacted."""
    x = np.array([9.0, 9.0, 1.0, 2.0, 3.0, 4.0])
    v = np.array([False, False, True, True, True, True])
    np.testing.assert_allclose(float(nw_t_stat(x, v, lags=1)), 4.0, rtol=1e-12)


def test_degenerate_cases():
    assert np.isnan(float(nw_t_stat(np.zeros(10), np.zeros(10, bool))))
    assert np.isnan(float(nw_t_stat(np.zeros(10), np.ones(10, bool))))
    one = np.ones(1)
    assert np.isnan(float(nw_t_stat(one, np.ones(1, bool))))


def test_grid_reports_nw(rng):
    """GridResult carries both stats; NW shrinks |t| on the overlap-built
    series and uses lag = K per cell."""
    from csmom_tpu.backtest.grid import jk_grid_backtest

    A, T = 40, 120
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, T)), axis=1))
    mask = np.ones((A, T), bool)
    Js = np.array([6, 12])
    Ks = np.array([1, 6])
    res = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode="rank")
    tn = np.asarray(res.tstat_nw)
    assert tn.shape == (2, 2)
    for i in range(2):
        for j in range(2):
            want = float(
                nw_t_stat(res.spreads[i, j], res.spread_valid[i, j],
                          lags=int(Ks[j]), max_lag=int(Ks.max()))
            )
            np.testing.assert_allclose(tn[i, j], want, rtol=1e-9)
