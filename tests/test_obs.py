"""Run-telemetry subsystem: spans, metrics registry, timeline assembly.

The zero-cost-unarmed contract mirrors the chaos checkpoint's: with no
collector armed, ``span()`` / ``point()`` / ``metric.inc()`` must do no
allocation-visible work per call — the measurement path never pays for
observability it did not ask for.  Armed behavior: spans nest per thread,
record monotonic walls, and land in the event stream; the timeline
assembler partitions the run's wall into phases that sum to the wall by
construction (the invariant the ``telemetry`` schema validator pins).
"""

import gc
import json
import os
import sys
import threading
import time
import types

import pytest

from csmom_tpu import obs
from csmom_tpu.chaos import invariants as inv
from csmom_tpu.obs import metrics, timeline as tl

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    """Every case starts and ends disarmed with an empty registry, and the
    env contract never leaks into other tests' subprocesses."""
    monkeypatch.delenv("CSMOM_TELEMETRY", raising=False)
    monkeypatch.delenv("CSMOM_TELEMETRY_RUN", raising=False)
    obs.disarm()
    metrics.reset()
    yield
    obs.disarm()
    metrics.reset()


# ------------------------------------------------- disarmed = zero cost ----

def test_disarmed_span_is_a_shared_noop_singleton():
    s1, s2 = obs.span("a"), obs.span("b")
    assert s1 is s2  # no per-call object
    with obs.span("c") as sp:
        assert sp is s1
        sp.set(x=1).event("e", y=2)  # all no-ops, all chainable
    assert obs.point("d") is None
    assert not obs.armed()


def test_disarmed_calls_do_no_allocation_visible_work():
    c = metrics.counter("overhead.count")   # registration allocates, once
    g = metrics.gauge("overhead.gauge")
    h = metrics.histogram("overhead.hist")
    for _ in range(2000):  # warm every code path / cache first
        obs.span("x")
        obs.point("x")
        c.inc()
        g.set(1.0)
        h.observe(1.0)
    gc.collect()
    before = sys.getallocatedblocks()
    for _ in range(5000):
        obs.span("x")
        obs.point("x")
        c.inc()
        g.set(1.0)
        h.observe(1.0)
    gc.collect()
    grown = sys.getallocatedblocks() - before
    assert grown < 50, (
        f"disarmed telemetry calls allocated {grown} blocks over 5000 "
        "iterations — the unarmed fast path must be allocation-free"
    )
    # and nothing was recorded: the registry only accumulates while armed
    assert c.value == 0
    assert g.value is None
    assert h.count == 0


# --------------------------------------------------------- armed spans ----

def test_armed_spans_record_nesting_attrs_and_device_time():
    col = obs.arm(None, run_id="unit", proc="t")
    with obs.span("outer", kind="root") as so:
        with obs.span("inner", leg="x") as si:
            time.sleep(0.01)
            si.set(extra_attr=3)
        so.event("mark", at="after-inner")
    by_name = {e["name"]: e for e in col.events}
    inner, outer = by_name["inner"], by_name["outer"]
    assert inner["parent"] == outer["seq"]
    assert outer["parent"] is None
    assert inner["dur_s"] >= 0.01
    assert inner["attrs"] == {"leg": "x", "extra_attr": 3}
    assert by_name["mark"]["kind"] == "point"
    assert by_name["mark"]["parent"] == outer["seq"]
    assert all(e["run"] == "unit" and e["proc"] == "t" for e in col.events)


def test_armed_span_records_exceptions_and_unwinds_stack():
    col = obs.arm(None, run_id="unit", proc="t")
    with pytest.raises(ValueError, match="boom"):
        with obs.span("failing"):
            raise ValueError("boom")
    (ev,) = col.events
    assert ev["error"].startswith("ValueError")
    # the stack unwound: a new span parents to nothing, not to the corpse
    with obs.span("after"):
        pass
    assert col.events[-1]["parent"] is None


def test_spans_nest_independently_across_threads():
    col = obs.arm(None, run_id="unit", proc="t")
    with obs.span("main.root"):
        def worker(n):
            with obs.span(f"w{n}.outer"):
                with obs.span(f"w{n}.inner"):
                    time.sleep(0.005)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    by_name = {e["name"]: e for e in col.events}
    for n in range(3):
        # each thread's outer span has NO parent: thread-local stacks mean
        # a worker never parents into main's (or a sibling's) open span
        assert by_name[f"w{n}.outer"]["parent"] is None
        assert (by_name[f"w{n}.inner"]["parent"]
                == by_name[f"w{n}.outer"]["seq"])


def test_arm_with_unwritable_stream_degrades_to_memory(tmp_path, capsys):
    """An unopenable stream path must not cost the run: the collector
    drops to in-memory with a stderr note instead of raising."""
    col = obs.arm(str(tmp_path / "no-such-dir" / "events.jsonl"),
                  run_id="u", proc="t")
    assert col.path is None
    with obs.span("bench.row"):
        pass
    assert [e["name"] for e in col.events] == ["bench.row"]
    assert "continuing in-memory" in capsys.readouterr().err


def test_finish_and_write_lands_disarms_and_reports_failures(tmp_path):
    obs.arm(None, run_id="fw", proc="t")
    with obs.span("run.root", root=True):
        pass
    name = tl.finish_and_write(str(tmp_path))
    assert name == "TELEMETRY_fw.json"
    assert not obs.armed()
    assert inv.validate_file(str(tmp_path / name)) == []
    # disarmed: a reason, not a crash
    assert "disarmed" in tl.finish_and_write(str(tmp_path))
    # unwritable out_dir: the REASON comes back (for the record to carry)
    # and the collector still disarms
    obs.arm(None, run_id="fw2", proc="t")
    with obs.span("x"):
        pass
    reason = tl.finish_and_write(str(tmp_path / "missing" / "dir"))
    assert "unwritable" in reason
    assert not obs.armed()


def test_write_sidecar_no_overwrite_protects_existing_name(tmp_path):
    """An operator-supplied run id (e.g. a round id like r05) must not
    replace an existing sidecar of that name — the new run lands
    pid-suffixed instead."""
    existing = tmp_path / "TELEMETRY_r99.json"
    existing.write_text("{}")
    name = tl.write_sidecar(str(tmp_path), "r99", events=[],
                            overwrite=False)
    assert name == f"TELEMETRY_r99-{os.getpid()}.json"
    assert existing.read_text() == "{}"  # untouched
    # default (our own name): overwrite freely
    assert tl.write_sidecar(str(tmp_path), "r99", events=[]) == \
        "TELEMETRY_r99.json"
    assert existing.read_text() != "{}"


def test_arm_exports_the_actual_stream_not_the_requested_one(tmp_path):
    """If the stream open fails and the collector degrades to in-memory,
    children must not be pointed at a path the assembler never reads."""
    obs.arm(str(tmp_path / "gone" / "e.jsonl"), run_id="u", proc="t")
    assert os.environ["CSMOM_TELEMETRY"] == "1"  # degraded: in-memory


def test_finish_and_write_run_scopes_the_stream_metrics_check(tmp_path):
    """A stale metrics event from an older run in a reused (append-mode)
    stream must not suppress the live fallback snapshot."""
    stream = tmp_path / "s.jsonl"
    stream.write_text(json.dumps(
        {"kind": "metrics", "run": "old-run", "t_s": 0.0,
         "data": {"counters": {"stale": 9}}}) + "\n")
    obs.arm(str(stream), run_id="new-run", proc="t")
    with obs.span("run.root", root=True):
        pass
    name = tl.finish_and_write(str(tmp_path),
                               fallback_metrics={"counters": {"live": 1}})
    obj = tl.load_sidecar(str(tmp_path / name))
    assert obj["metrics"] == {"counters": {"live": 1}}


def test_event_stream_file_appends_parseable_lines(tmp_path):
    stream = tmp_path / "events.jsonl"
    obs.arm(str(stream), run_id="filerun", proc="t")
    with obs.span("bench.row", row=1):
        pass
    obs.point("chaos.bench.finish")
    events = tl.read_events(str(stream))
    assert [e["name"] for e in events] == ["bench.row", "chaos.bench.finish"]
    assert os.environ["CSMOM_TELEMETRY"] == str(stream)  # exported for kids
    obs.disarm()
    assert "CSMOM_TELEMETRY" not in os.environ  # and retracted


# ------------------------------------------------------------- metrics ----

def test_metrics_accumulate_only_while_armed():
    obs.arm(None, run_id="unit", proc="t")
    c = metrics.counter("bench.rows_landed")
    c.inc()
    c.inc(2)
    metrics.gauge("bench.deadline_margin_s").set(17.5)
    metrics.histogram("row.wall_s").observe(0.5)
    metrics.histogram("row.wall_s").observe(1.5)
    snap = metrics.snapshot(include_compile=False)
    assert snap["counters"]["bench.rows_landed"] == 3
    assert snap["gauges"]["bench.deadline_margin_s"] == 17.5
    h = snap["histograms"]["row.wall_s"]
    assert (h["count"], h["min"], h["max"], h["mean"]) == (2, 0.5, 1.5, 1.0)
    with pytest.raises(TypeError, match="already registered"):
        metrics.gauge("bench.rows_landed")


def test_metrics_snapshot_folds_compile_stats_and_listener_state():
    obs.arm(None, run_id="unit", proc="t")
    snap = metrics.snapshot()  # jax is imported in the test process
    assert isinstance(snap["compile"], dict)
    assert {"cache_hits", "cache_misses", "traces",
            "backend_compiles"} <= set(snap["compile"])
    assert snap["profiling_listeners_installed"] in (True, False)


# -------------------------------------------- checkpoints double as events --

def test_chaos_checkpoint_doubles_as_telemetry_point(monkeypatch):
    monkeypatch.delenv("CSMOM_FAULT_PLAN", raising=False)
    from csmom_tpu.chaos import inject

    inject.reset()
    col = obs.arm(None, run_id="unit", proc="t")
    assert inject.checkpoint("bench.row", row=3) is None  # no fault fired
    (ev,) = col.events
    assert ev["name"] == "chaos.bench.row"
    assert ev["kind"] == "point"
    assert ev["attrs"] == {"row": 3}


def test_chaos_checkpoint_stays_silent_disarmed(monkeypatch):
    monkeypatch.delenv("CSMOM_FAULT_PLAN", raising=False)
    from csmom_tpu.chaos import inject

    inject.reset()
    assert inject.checkpoint("bench.row") is None  # and no collector to hit


# ------------------------------------------------------------ timeline ----

def _span_ev(name, t0, t1, seq, parent=None, attrs=None):
    return {"kind": "span", "name": name, "seq": seq, "parent": parent,
            "thread": 1, "t0_s": t0, "t1_s": t1, "dur_s": t1 - t0,
            "attrs": attrs or {}, "run": "synt", "proc": "t", "pid": 1}


def test_timeline_phase_partition_priority_and_exact_sum():
    events = [
        _span_ev("root", 0.0, 5.0, 1, attrs={"root": True}),
        _span_ev("bench.probe", 0.0, 2.0, 2),
        _span_ev("bench.compile", 1.0, 3.0, 3),   # overlaps probe: wins 1..2
        _span_ev("bench.row", 2.5, 4.0, 4),       # overlaps compile: wins
    ]
    obj = tl.assemble(events, run_id="synt")
    durs = {p["name"]: p["dur_s"] for p in obj["phases"]}
    assert durs == pytest.approx({
        "warmup": 0.0, "probe": 1.0, "compile": 1.5, "row": 1.5,
        "land": 0.0, "other": 1.0,
    })
    assert sum(durs.values()) == pytest.approx(obj["wall_s"])
    assert obj["wall_s"] == pytest.approx(5.0)
    assert inv.detect_kind(obj) == "telemetry"
    assert inv.validate(obj) == []


def test_timeline_envelope_fallback_without_root_span():
    events = [_span_ev("bench.row", 1.0, 2.0, 1)]
    obj = tl.assemble(events, run_id="synt")
    assert obj["wall_s"] == pytest.approx(1.0)
    assert "no root span" in obj["root"]
    assert inv.validate(obj) == []


def test_assemble_filters_foreign_run_events():
    """An env-armed stream file opens append, so a reused path can carry
    an older run; with an explicit run_id those events must be dropped,
    not blended into a timeline that corresponds to no single run."""
    events = [
        _span_ev("root", 0.0, 1.0, 1, attrs={"root": True}),
        dict(_span_ev("bench.row", 0.0, 0.5, 2), run="yesterdays-run"),
    ]
    obj = tl.assemble(events, run_id="synt")
    assert obj["n_spans"] == 1
    durs = {p["name"]: p["dur_s"] for p in obj["phases"]}
    assert durs["row"] == 0.0


def test_cli_timeline_damaged_sidecar_still_reports_violations(tmp_path,
                                                               capsys):
    bad = {"kind": "telemetry", "schema_version": 1, "run_id": "x",
           "wall_s": 1.0,
           "phases": [{"dur_s": 1.0}, "not-a-dict"], "spans": ["junk"]}
    p = tmp_path / "TELEMETRY_bad.json"
    p.write_text(json.dumps(bad))
    from csmom_tpu.cli.timeline import cmd_timeline

    args = types.SimpleNamespace(run=str(p), top=5, json=False)
    assert cmd_timeline(args) == 1  # render survives, violations reported
    assert "schema violations" in capsys.readouterr().err


def test_telemetry_validator_rejects_unaccounted_wall():
    obj = {"kind": "telemetry", "schema_version": 1, "run_id": "x",
           "wall_s": 10.0,
           "phases": [{"name": "row", "dur_s": 1.0}]}
    assert any("5%" in v for v in inv.validate(obj))
    obj["phases"].append({"name": "other", "dur_s": 9.0})
    assert inv.validate(obj) == []
    obj["phases"].append({"name": "other", "dur_s": 0.0})
    assert any("duplicate" in v for v in inv.validate(obj))


def test_sidecar_write_validate_render_and_cli(tmp_path, capsys):
    col = obs.arm(None, run_id="unit-run", proc="t")
    with obs.span("run.root", root=True):
        with obs.span("bench.row", row="leg0"):
            time.sleep(0.005)
        metrics.counter("bench.rows_landed").inc()
    name = tl.write_sidecar(str(tmp_path), "unit-run",
                            events=list(col.events),
                            metrics=metrics.snapshot(include_compile=False))
    path = tmp_path / name
    assert name == "TELEMETRY_unit-run.json"
    assert inv.validate_file(str(path)) == []

    rendered = tl.render(tl.load_sidecar(str(path)))
    assert "unit-run" in rendered and "row" in rendered

    from csmom_tpu.cli.timeline import cmd_timeline

    args = types.SimpleNamespace(run=str(path), top=5, json=False)
    assert cmd_timeline(args) == 0
    out = capsys.readouterr().out
    assert "phase" in out and "bench.rows_landed=1" in out
    # --json dumps the assembled object verbatim
    args = types.SimpleNamespace(run=str(path), top=5, json=True)
    assert cmd_timeline(args) == 0
    assert json.loads(capsys.readouterr().out)["run_id"] == "unit-run"


def test_cli_timeline_missing_run_fails_cleanly(tmp_path, capsys,
                                                monkeypatch):
    from csmom_tpu.cli.timeline import cmd_timeline

    monkeypatch.chdir(tmp_path)
    args = types.SimpleNamespace(run="no-such-run-id", top=5, json=False)
    assert cmd_timeline(args) == 2
    assert "no TELEMETRY sidecar" in capsys.readouterr().err


# --------------------------------------- profiling listener idempotency ----

def test_install_listeners_idempotent_under_recall_and_reimport():
    import importlib

    from jax._src import monitoring

    from csmom_tpu.utils import profiling

    profiling._install_listeners()
    n_ev = len(monitoring._event_listeners)
    n_dur = len(monitoring._event_duration_secs_listeners)
    profiling._install_listeners()  # re-call: no growth
    reloaded = importlib.reload(profiling)  # re-import: the r7 hazard
    reloaded._install_listeners()
    assert len(monitoring._event_listeners) == n_ev
    assert len(monitoring._event_duration_secs_listeners) == n_dur
    assert reloaded.listeners_installed() is True
    # the reloaded module ADOPTED the live counter dict instead of
    # registering fresh closures over a zeroed one (no double counting,
    # no dead counters)
    assert reloaded._COUNTERS is getattr(monitoring,
                                         reloaded._LISTENER_TAG)


# ------------------------------------------ skew-safe wall-clock helpers ----

def test_marker_fresh_is_skew_resistant(tmp_path, monkeypatch):
    from csmom_tpu.utils import deadline as dl

    p = tmp_path / "marker"
    p.write_text("ok")
    assert dl.marker_fresh(str(p), 60) is True
    assert dl.marker_fresh(str(p), 0) is False       # TTL disabled
    assert dl.marker_fresh(str(tmp_path / "absent"), 60) is False

    # the chaos clock_skew fault monkeypatches time.time (+1h); the
    # helpers read CLOCK_REALTIME and must not flinch
    real = time.clock_gettime(time.CLOCK_REALTIME)
    monkeypatch.setattr(time, "time", lambda: real + 3600.0)
    assert dl.marker_fresh(str(p), 60) is True

    # an mtime in the future (backwards wall step, copied file) must read
    # STALE — an unknowable age can never be "fresh forever"
    future = time.clock_gettime(time.CLOCK_REALTIME) + 3600
    os.utime(p, (future, future))
    assert dl.file_age_s(str(p)) == float("inf")
    assert dl.marker_fresh(str(p), 1e9) is False
