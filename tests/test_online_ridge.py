"""Online ridge: sequential-oracle parity, batch parity, causality, masking.

The scan implementation must equal a plain Python replay of the same
recursions (implementation parity), and — with the causal scaler off —
its one-step-ahead prediction must equal the batch closed form fit on
exactly the prior rows (algorithmic correctness of the Sherman–Morrison
update).  Causality is pinned adversarially: perturbing any future row
must not move an earlier score.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from csmom_tpu.models.online_ridge import online_ridge_scores


def _panel(A=3, R=40, F=4, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(A, R, F))
    y = rng.normal(scale=1e-2, size=(A, R))
    valid = rng.random((A, R)) > 0.15
    return feats, y, valid


def _oracle(feats, y, valid, alpha, burn_in, standardize):
    """Sequential replay of the documented row-blocked recursions: score the
    whole row with the prior state, THEN apply the row's updates."""
    A, R, F = feats.shape
    P = np.eye(F + 1) / alpha
    b = np.zeros(F + 1)
    cnt, mean, M2 = 0.0, np.zeros(F), np.zeros(F)
    scores = np.full((A, R), np.nan)
    for r in range(R):
        # score every asset's row r with the state from rows < r
        if cnt >= burn_in:
            for a in range(A):
                if not valid[a, r]:
                    continue
                x = feats[a, r]
                if standardize:
                    std = np.sqrt(np.maximum(M2 / max(cnt, 1.0), 1e-24))
                    std = np.where(std > 1e-12, std, 1.0)
                    xs = (x - mean) / std
                else:
                    xs = x
                scores[a, r] = np.concatenate([xs, [1.0]]) @ (P @ b)
        # then apply the row's updates (scaling still by the PRIOR moments)
        if standardize:
            std = np.sqrt(np.maximum(M2 / max(cnt, 1.0), 1e-24))
            std = np.where(std > 1e-12, std, 1.0)
        for a in range(A):
            if not valid[a, r]:
                continue
            x = feats[a, r]
            xs = (x - mean) / std if standardize else x
            xa = np.concatenate([xs, [1.0]])
            Px = P @ xa
            P = P - np.outer(Px, Px) / (1.0 + xa @ Px)
            b = b + xa * y[a, r]
        for a in range(A):
            if not valid[a, r]:
                continue
            x = feats[a, r]
            cnt += 1.0
            delta = x - mean
            mean = mean + delta / cnt
            M2 = M2 + delta * (x - mean)
    return scores


@pytest.mark.parametrize("standardize", [True, False])
def test_matches_sequential_oracle(standardize):
    feats, y, valid, = _panel()
    fit = online_ridge_scores(
        jnp.asarray(feats), jnp.asarray(y), jnp.asarray(valid),
        alpha=0.5, burn_in=10, standardize=standardize,
    )
    want = _oracle(feats, y, valid, alpha=0.5, burn_in=10,
                   standardize=standardize)
    np.testing.assert_allclose(np.asarray(fit.scores), want,
                               rtol=1e-9, atol=1e-12)


def test_prediction_equals_batch_closed_form():
    """With the causal scaler off, the score at any row equals ridge fit on
    the augmented prior rows: (Xa'Xa + aI)^-1 Xa'y — Sherman-Morrison is
    exactly the batch inverse, not an approximation of it."""
    feats, y, valid = _panel(A=2, R=30, F=3, seed=1)
    alpha, burn_in = 2.0, 8
    fit = online_ridge_scores(
        jnp.asarray(feats), jnp.asarray(y), jnp.asarray(valid),
        alpha=alpha, burn_in=burn_in, standardize=False,
    )
    # the state behind scores at row r holds exactly the rows of r' < r
    A, R, F = feats.shape
    for r in (12, 20, R - 1):
        prior = np.array([np.concatenate([feats[pa, pr], [1.0]])
                          for pr in range(r) for pa in range(A)
                          if valid[pa, pr]])
        if len(prior) < burn_in:
            continue
        ypri = np.array([y[pa, pr] for pr in range(r) for pa in range(A)
                         if valid[pa, pr]])
        w = np.linalg.solve(prior.T @ prior + alpha * np.eye(F + 1),
                            prior.T @ ypri)
        for a in range(A):
            if not valid[a, r]:
                continue
            want = np.concatenate([feats[a, r], [1.0]]) @ w
            np.testing.assert_allclose(float(fit.scores[a, r]), want,
                                       rtol=1e-8, atol=1e-12)


def test_scores_are_strictly_causal():
    feats, y, valid = _panel(seed=2)
    base = online_ridge_scores(jnp.asarray(feats), jnp.asarray(y),
                               jnp.asarray(valid), burn_in=5)
    # nuke everything at row >= 25: earlier scores must not move at all
    y2, f2 = y.copy(), feats.copy()
    y2[:, 25:] += 100.0
    f2[:, 25:] *= -3.0
    pert = online_ridge_scores(jnp.asarray(f2), jnp.asarray(y2),
                               jnp.asarray(valid), burn_in=5)
    np.testing.assert_array_equal(np.asarray(base.scores)[:, :25],
                                  np.asarray(pert.scores)[:, :25])


def test_no_same_row_cross_asset_label_leak():
    """y[0, r] is the r -> r+1 return — unknown at decision time r.  The
    scores of OTHER assets at row r must not move when it changes (the
    asset-sequential formulation this replaced failed exactly here:
    asset 0's row-r label updated the state before asset 1's row r was
    scored, leaking the contemporaneous future through the market
    factor)."""
    feats, y, valid = _panel(seed=5)
    valid[:, :] = True  # every asset present at the probed row
    r = 25
    base = online_ridge_scores(jnp.asarray(feats), jnp.asarray(y),
                               jnp.asarray(valid), burn_in=5)
    y2 = y.copy()
    y2[0, r] += 1e3
    f2 = feats.copy()
    f2[0, r] *= -7.0
    pert = online_ridge_scores(jnp.asarray(f2), jnp.asarray(y2),
                               jnp.asarray(valid), burn_in=5)
    # other assets' same-row scores: bit-identical
    np.testing.assert_array_equal(np.asarray(base.scores)[1:, r],
                                  np.asarray(pert.scores)[1:, r])
    # and everything strictly earlier too
    np.testing.assert_array_equal(np.asarray(base.scores)[:, :r],
                                  np.asarray(pert.scores)[:, :r])


def test_invalid_rows_are_noops_and_unscored():
    feats, y, valid = _panel(seed=3)
    fit = online_ridge_scores(jnp.asarray(feats), jnp.asarray(y),
                              jnp.asarray(valid), burn_in=5)
    assert np.all(np.isnan(np.asarray(fit.scores)[~valid]))
    # garbage on invalid rows must not change anything
    f2 = feats.copy()
    y2 = y.copy()
    f2[~valid] = 1e6
    y2[~valid] = -1e6
    fit2 = online_ridge_scores(jnp.asarray(f2), jnp.asarray(y2),
                               jnp.asarray(valid), burn_in=5)
    np.testing.assert_array_equal(
        np.nan_to_num(np.asarray(fit.scores)),
        np.nan_to_num(np.asarray(fit2.scores)),
    )
    assert int(fit.n_train) == int(valid.sum())


def test_prequential_blocks_cover_scored_rows():
    feats, y, valid = _panel(seed=4)
    fit = online_ridge_scores(jnp.asarray(feats), jnp.asarray(y),
                              jnp.asarray(valid), n_splits=3, burn_in=5)
    mses = np.asarray(fit.cv_mse)
    assert mses.shape == (3,)
    assert np.all(np.isfinite(mses)) and np.all(mses >= 0)
    # overall prequential MSE equals the weighted combination of blocks
    s = np.asarray(fit.scores)
    scored = np.isfinite(s)
    total = np.mean((s[scored] - y[scored]) ** 2)
    # blocks are near-equal-sized: their mean ~= the overall MSE
    assert abs(np.mean(mses) - total) < 0.5 * total + 1e-12
