"""Time-sharded online ridge equals the single-device scan on a CPU mesh.

The sequence-parallel decomposition (exclusive Chan/Gram carries + local
Sherman-Morrison scans) is mathematically identical to the sequential
recursion; these tests pin that across shard counts, padding, and
standardization modes, plus the strict-causality property surviving the
sharding.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from csmom_tpu.models.online_ridge import online_ridge_scores
from csmom_tpu.parallel.mesh import make_mesh
from csmom_tpu.parallel.online_ridge import time_sharded_online_ridge_scores

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


def _panel(A=4, R=90, F=3, seed=0):
    rng = np.random.default_rng(seed)
    feats = rng.normal(size=(A, R, F))
    y = rng.normal(scale=1e-2, size=(A, R))
    valid = rng.random((A, R)) > 0.15
    return feats, y, valid


def _mesh(n):
    return make_mesh(grid_axis=8 // n, axis_names=("grid", "time"))


def _assert_fit_equal(got, ref, rtol=1e-8):
    np.testing.assert_allclose(np.asarray(got.scores), np.asarray(ref.scores),
                               rtol=rtol, atol=1e-12, equal_nan=True)
    np.testing.assert_allclose(np.asarray(got.cv_mse), np.asarray(ref.cv_mse),
                               rtol=rtol)
    np.testing.assert_allclose(np.asarray(got.coef), np.asarray(ref.coef),
                               rtol=1e-7, atol=1e-12)
    np.testing.assert_allclose(float(got.intercept), float(ref.intercept),
                               rtol=1e-6, atol=1e-12)
    np.testing.assert_allclose(np.asarray(got.scale_mean),
                               np.asarray(ref.scale_mean), rtol=1e-8)
    np.testing.assert_allclose(np.asarray(got.scale_std),
                               np.asarray(ref.scale_std), rtol=1e-8)
    assert int(got.n_train) == int(ref.n_train)


@pytest.mark.parametrize("standardize", [True, False])
@pytest.mark.parametrize("n_shards", [2, 8])
def test_sharded_equals_single_device(standardize, n_shards):
    feats, y, valid = _panel()
    ref = online_ridge_scores(jnp.asarray(feats), jnp.asarray(y),
                              jnp.asarray(valid), alpha=0.7, burn_in=12,
                              standardize=standardize)
    mesh = _mesh(n_shards)
    got = time_sharded_online_ridge_scores(
        feats, y, valid, mesh=mesh, time_axis="time",
        alpha=0.7, burn_in=12, standardize=standardize,
    )
    _assert_fit_equal(got, ref)


def test_sharded_with_row_padding():
    """R not divisible by the shard count: padded no-op rows change nothing."""
    feats, y, valid = _panel(R=85, seed=1)  # 85 % 8 != 0
    ref = online_ridge_scores(jnp.asarray(feats), jnp.asarray(y),
                              jnp.asarray(valid), burn_in=10)
    mesh = _mesh(8)
    got = time_sharded_online_ridge_scores(
        feats, y, valid, mesh=mesh, burn_in=10,
    )
    assert got.scores.shape == ref.scores.shape
    _assert_fit_equal(got, ref)


def test_sharded_is_still_strictly_causal():
    """Perturbing a late row moves no earlier (or same-row other-asset)
    score — the carries must not smuggle future labels backwards."""
    feats, y, valid = _panel(seed=2)
    valid[:, :] = True
    mesh = _mesh(8)
    base = time_sharded_online_ridge_scores(feats, y, valid, mesh=mesh,
                                            burn_in=5)
    r = 60  # inside a late shard
    y2 = y.copy()
    y2[0, r] += 1e3
    pert = time_sharded_online_ridge_scores(feats, y2, valid, mesh=mesh,
                                            burn_in=5)
    np.testing.assert_array_equal(np.asarray(base.scores)[1:, r],
                                  np.asarray(pert.scores)[1:, r])
    np.testing.assert_array_equal(np.asarray(base.scores)[:, :r],
                                  np.asarray(pert.scores)[:, :r])


def test_gather_outputs_mode_equals_sharded_default():
    """gather_outputs=True (the multi-process readable form) returns the
    same predictions as the default sharded path, replicated."""
    import numpy as np

    from csmom_tpu.parallel.online_ridge import _compiled

    feats, y, valid = _panel(R=88, seed=3)  # 88 % 8 == 0: no padding
    A, R, F = feats.shape
    mesh = _mesh(8)
    ref = time_sharded_online_ridge_scores(feats, y, valid, mesh=mesh,
                                           burn_in=9)

    Xr = np.ascontiguousarray(np.swapaxes(feats, 0, 1))
    yr = np.ascontiguousarray(np.swapaxes(y, 0, 1))
    wr = np.ascontiguousarray(np.swapaxes(valid, 0, 1)).astype(feats.dtype)
    fn = _compiled(mesh, "time", A, F, feats.dtype, 1.0, 9, True,
                   gather_outputs=True)
    with mesh:
        preds, seen, G_tot, b_tot, (cnt_f, mean_f, M2_f) = fn(
            jnp.asarray(Xr), jnp.asarray(yr), jnp.asarray(wr)
        )
    got = np.where((wr > 0) & np.asarray(seen), np.asarray(preds), np.nan).T
    np.testing.assert_array_equal(got, np.asarray(ref.scores))
    # the gathered moments are the full history's (drive the scaler state)
    np.testing.assert_allclose(float(cnt_f), float(valid.sum()), rtol=0)
    np.testing.assert_allclose(np.asarray(mean_f), np.asarray(ref.scale_mean),
                               rtol=1e-9)
