"""Packed binary panel cache: roundtrip exactness, memmapped loads, version
loudness, CSV-cache conversion (the at-scale analogue of the reference's
per-ticker CSV persistence, /root/reference/src/data_io.py:131-159)."""

import json
import os

import numpy as np
import pytest

from csmom_tpu.panel import Panel, load_packed, save_packed
from csmom_tpu.panel.panel import PanelBundle
from csmom_tpu.panel.synthetic import synthetic_daily_panel


def _panel(rng, A=7, T=40):
    vals = rng.normal(100, 10, size=(A, T))
    vals[rng.random((A, T)) < 0.2] = np.nan
    return Panel.from_dense(
        vals,
        tickers=[f"T{i}" for i in range(A)],
        times=np.arange("2020-01-01", 40, dtype="datetime64[D]")[:T].astype(
            "datetime64[ns]"
        ),
        name="adj_close",
    )


def test_roundtrip_exact(tmp_path, rng):
    p = _panel(rng)
    out = save_packed(p, str(tmp_path / "pack"))
    q = load_packed(out)
    assert isinstance(q, Panel)
    np.testing.assert_array_equal(np.asarray(q.values), p.values)
    np.testing.assert_array_equal(np.asarray(q.mask), p.mask)
    assert q.tickers == p.tickers
    np.testing.assert_array_equal(q.times, p.times)
    assert q.name == "adj_close"


def test_load_is_memmapped(tmp_path, rng):
    """mmap=True must return lazily-paged views, not RAM copies — the whole
    point of the flat-.npy layout over the .npz snapshot."""
    p = _panel(rng)
    save_packed(p, str(tmp_path / "pack"))
    q = load_packed(str(tmp_path / "pack"))
    assert isinstance(q.values, np.memmap)
    assert isinstance(q.mask, np.memmap)
    eager = load_packed(str(tmp_path / "pack"), mmap=False)
    assert not isinstance(eager.values, np.memmap)


def test_bundle_roundtrip_and_calendar_guard(tmp_path, rng):
    px = _panel(rng)
    vol = Panel.from_dense(
        np.abs(rng.normal(1e6, 1e5, size=px.shape)),
        tickers=px.tickers, times=px.times, name="volume",
    )
    b = PanelBundle(panels={"adj_close": px, "volume": vol},
                    tickers=px.tickers, times=px.times)
    out = save_packed(b, str(tmp_path / "bundle"))
    q = load_packed(out)
    assert isinstance(q, PanelBundle)
    assert set(q.fields) == {"adj_close", "volume"}
    np.testing.assert_array_equal(
        np.asarray(q["volume"].values), vol.values
    )

    # mismatched calendars must refuse to pack
    other = Panel.from_dense(
        px.values[:, :-1], tickers=px.tickers, times=px.times[:-1],
        name="close",
    )
    bad = PanelBundle(panels={"adj_close": px, "close": other},
                      tickers=px.tickers, times=px.times)
    with pytest.raises(ValueError, match="shared calendar"):
        save_packed(bad, str(tmp_path / "bad"))


def test_unknown_version_is_loud(tmp_path, rng):
    p = _panel(rng)
    out = save_packed(p, str(tmp_path / "pack"))
    meta = json.load(open(os.path.join(out, "meta.json")))
    meta["version"] = 99
    json.dump(meta, open(os.path.join(out, "meta.json"), "w"))
    with pytest.raises(ValueError, match="version 99"):
        load_packed(out)


def test_packed_feeds_kernels(tmp_path):
    """A packed synthetic panel drives the compiled path end-to-end and
    matches the in-memory panel bit-for-bit (the bench's data path)."""
    from csmom_tpu.backtest.grid import jk_grid_backtest
    from csmom_tpu.panel.calendar import month_end_aggregate, month_end_segments

    p = synthetic_daily_panel(24, 500, seed=3, listing_gaps=True)
    save_packed(p, str(tmp_path / "ns"))
    q = load_packed(str(tmp_path / "ns"))

    def run(panel):
        seg, ends = month_end_segments(panel.times)
        v, m = panel.device()
        pm, mm = month_end_aggregate(v, m, seg, len(ends))
        Js, Ks = np.array([3, 6]), np.array([1, 3])
        return jk_grid_backtest(pm, mm, Js, Ks, skip=1, n_bins=5, mode="rank")

    a, b = run(p), run(q)
    np.testing.assert_array_equal(np.asarray(a.mean_spread),
                                  np.asarray(b.mean_spread))


@pytest.mark.reference_data
def test_pack_csv_cache_cli(tmp_path):
    """csmom fetch --pack converts the CSV caches; the pack re-opens with
    the full universe and the dense values match the ingest pivot."""
    from tests.conftest import DEMO_TICKERS, REFERENCE_DATA

    from csmom_tpu.cli.main import main
    from csmom_tpu.panel.ingest import load_daily, long_to_panel

    out = tmp_path / "packed"
    rc = main(["fetch", "--data-dir", REFERENCE_DATA,
               "--tickers", "AAPL,AMD,NVDA", "--kind", "daily",
               "--pack", str(out)])
    assert rc == 0
    b = load_packed(str(out))
    assert set(b.fields) == {"adj_close", "volume"}
    assert len(b.tickers) == 3  # AAPL included: the dialect-B file reads

    df = load_daily(REFERENCE_DATA, ["AAPL", "AMD", "NVDA"])
    want = long_to_panel(df, "adj_close")
    np.testing.assert_array_equal(
        np.asarray(b["adj_close"].values), want.values
    )


@pytest.mark.reference_data
def test_monthly_pipeline_reads_pack_directly(tmp_path):
    """monthly_price_panel on a packed dir must equal the CSV path exactly
    (same tickers, same month-ends, bit-equal panels) — the pack is a
    drop-in --data-dir for every CLI subcommand."""
    from tests.conftest import REFERENCE_DATA

    from csmom_tpu.api import monthly_price_panel
    from csmom_tpu.panel.pack import pack_csv_cache

    tk = ["AAPL", "AMD", "NVDA", "MSFT"]
    out = str(tmp_path / "pack")
    pack_csv_cache(REFERENCE_DATA, tk, out)

    p_csv, v_csv = monthly_price_panel(REFERENCE_DATA, tk)
    p_pack, v_pack = monthly_price_panel(out, tk)
    assert p_pack.tickers == p_csv.tickers
    np.testing.assert_array_equal(p_pack.times, p_csv.times)
    np.testing.assert_array_equal(p_pack.values, p_csv.values)
    np.testing.assert_array_equal(v_pack.values, v_csv.values)
    np.testing.assert_array_equal(v_pack.mask, v_csv.mask)

    # subset selection + loud failure on missing tickers
    p_sub, _ = monthly_price_panel(out, ["AMD", "NVDA"])
    assert p_sub.tickers == ("AMD", "NVDA")
    with pytest.raises(ValueError, match="lacks 1 requested"):
        monthly_price_panel(out, ["AMD", "ZZZNOPE"])


@pytest.mark.reference_data
def test_cli_replicate_on_pack(tmp_path, capsys):
    """A packed --data-dir drives replicate end-to-end: default = whole
    pack, --tickers = explicit subset, and the universe line names the
    packed source."""
    from tests.conftest import REFERENCE_DATA

    from csmom_tpu.cli.main import main
    from csmom_tpu.panel.pack import pack_csv_cache

    out = str(tmp_path / "pack")
    pack_csv_cache(REFERENCE_DATA, ["AAPL", "AMD", "NVDA", "MSFT"], out)

    assert main(["replicate", "--data-dir", out, "--out",
                 str(tmp_path / "r1")]) == 0
    text = capsys.readouterr().out
    assert "Universe: 4 tickers" in text and "packed panel" in text

    assert main(["replicate", "--data-dir", out, "--tickers", "AMD,NVDA",
                 "--out", str(tmp_path / "r2")]) == 0
    assert "Universe: 2 tickers" in capsys.readouterr().out


def test_cli_pack_info(tmp_path, capsys, rng):
    from csmom_tpu.cli.main import main

    px = _panel(rng, A=5, T=30)
    save_packed(px, str(tmp_path / "p"))
    assert main(["pack-info", str(tmp_path / "p")]) == 0
    out = capsys.readouterr().out
    assert "5 tickers" in out and "30 dates" in out and "adj_close" in out

    assert main(["pack-info", str(tmp_path / "nope")]) == 2
    assert "not a packed panel" in capsys.readouterr().err


@pytest.mark.reference_data
def test_pack_f32_dtype(tmp_path, capsys):
    from tests.conftest import REFERENCE_DATA

    from csmom_tpu.cli.main import main

    out = tmp_path / "p32"
    rc = main(["fetch", "--data-dir", REFERENCE_DATA,
               "--tickers", "AMD,NVDA", "--kind", "daily",
               "--pack", str(out), "--pack-f32"])
    assert rc == 0
    b = load_packed(str(out))
    assert np.asarray(b["adj_close"].values).dtype == np.float32
    assert main(["pack-info", str(out)]) == 0
    assert "float32" in capsys.readouterr().out
