"""Pallas decile-aggregation kernel vs the XLA implementation (interpret
mode on the CPU mesh; the compiled path is exercised by bench.py on TPU)."""

import numpy as np
import jax.numpy as jnp
import pytest

from csmom_tpu.backtest.monthly import decile_partial_sums
from csmom_tpu.ops.pallas_kernels import decile_partial_sums_pallas

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


def _case(rng, a, m, n_bins):
    labels = rng.integers(-1, n_bins, size=(a, m)).astype(np.int32)
    valid = rng.random((a, m)) > 0.2
    ret = rng.normal(size=(a, m))
    labels = np.where(valid, labels, -1)
    ret_z = np.where(labels >= 0, ret, 0.0)
    return labels, ret_z, valid


def _xla(labels, ret_z, n_bins):
    valid = labels >= 0
    sums, counts = decile_partial_sums(
        jnp.asarray(ret_z), jnp.asarray(valid), jnp.asarray(labels), n_bins
    )
    return np.asarray(sums), np.asarray(counts, dtype=np.float64)


@pytest.mark.parametrize("a,m", [(16, 24), (256, 128), (300, 130), (37, 7)])
def test_matches_xla(rng, a, m):
    n_bins = 10
    labels, ret_z, _ = _case(rng, a, m, n_bins)
    sums, counts = decile_partial_sums_pallas(
        jnp.asarray(ret_z), jnp.asarray(labels), n_bins=n_bins, interpret=True
    )
    ws, wc = _xla(labels, ret_z, n_bins)
    # Pallas and XLA reduce in different orders, so f64 sums can differ by
    # ~1 ulp — near-zero bin sums then breach a pure relative tolerance
    np.testing.assert_allclose(np.asarray(sums), ws, rtol=1e-10, atol=1e-13)
    np.testing.assert_allclose(np.asarray(counts), wc)


def test_small_bins(rng):
    labels, ret_z, _ = _case(rng, 50, 40, 3)
    sums, counts = decile_partial_sums_pallas(
        jnp.asarray(ret_z), jnp.asarray(labels), n_bins=3, interpret=True
    )
    ws, wc = _xla(labels, ret_z, 3)
    np.testing.assert_allclose(np.asarray(sums), ws, rtol=1e-10, atol=1e-13)
    np.testing.assert_allclose(np.asarray(counts), wc)


def test_all_invalid(rng):
    labels = np.full((20, 16), -1, dtype=np.int32)
    ret_z = np.zeros((20, 16))
    sums, counts = decile_partial_sums_pallas(
        jnp.asarray(ret_z), jnp.asarray(labels), n_bins=5, interpret=True
    )
    assert (np.asarray(counts) == 0).all()
    assert (np.asarray(sums) == 0).all()


def test_monthly_backtest_pallas_impl(rng):
    """monthly_spread_backtest(impl='pallas') == impl='xla' end to end
    (interpret mode on CPU; f64 here so reduction order is immaterial)."""
    from csmom_tpu.backtest import monthly_spread_backtest

    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(24, 36)), axis=1))
    prices[rng.random(prices.shape) < 0.05] = np.nan
    mask = np.isfinite(prices)
    a = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5, impl="xla")
    b = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5, impl="pallas")
    np.testing.assert_allclose(
        np.asarray(a.spread), np.asarray(b.spread), rtol=1e-12, equal_nan=True
    )
    np.testing.assert_array_equal(np.asarray(a.decile_counts), np.asarray(b.decile_counts))
    np.testing.assert_allclose(float(a.ann_sharpe), float(b.ann_sharpe), rtol=1e-12)


@pytest.mark.parametrize("a,m,h", [(37, 50, 6), (130, 300, 12), (64, 20, 12)])
def test_cohort_kernel_matches_xla(rng, a, m, h):
    """The grid engine's cohort x horizon aggregation: fused kernel vs the
    XLA roll-based form, all horizons, ragged shapes."""
    from csmom_tpu.backtest.grid import _cohort_partial_sums

    n_bins = 5
    labels = rng.integers(-1, n_bins, size=(a, m)).astype(np.int32)
    valid = rng.random((a, m)) > 0.25
    ret = np.where(valid, rng.normal(0, 0.02, size=(a, m)), np.nan)
    sx, cx = _cohort_partial_sums(
        jnp.asarray(labels), jnp.asarray(ret), jnp.asarray(valid), n_bins, h
    )
    sp, cp = _cohort_partial_sums(
        jnp.asarray(labels), jnp.asarray(ret), jnp.asarray(valid), n_bins, h,
        impl="pallas",
    )
    np.testing.assert_allclose(np.asarray(sp), np.asarray(sx), rtol=1e-10,
                               atol=1e-13)
    np.testing.assert_allclose(np.asarray(cp, dtype=np.float64),
                               np.asarray(cx, dtype=np.float64))


@pytest.mark.parametrize("a,m,h", [(37, 50, 6), (130, 300, 12), (64, 20, 12),
                                   (24, 5, 8)])
def test_cohort_matmul_impl_matches_xla(rng, a, m, h):
    """The MXU formulation (membership^T @ returns cross table + band
    gather) equals the rolled-panel XLA form, including horizons past the
    panel end (h > m exercises the in-range mask)."""
    from csmom_tpu.backtest.grid import _cohort_partial_sums

    n_bins = 5
    labels = rng.integers(-1, n_bins, size=(a, m)).astype(np.int32)
    valid = rng.random((a, m)) > 0.25
    ret = np.where(valid, rng.normal(0, 0.02, size=(a, m)), np.nan)
    sx, cx = _cohort_partial_sums(
        jnp.asarray(labels), jnp.asarray(ret), jnp.asarray(valid), n_bins, h
    )
    sm, cm = _cohort_partial_sums(
        jnp.asarray(labels), jnp.asarray(ret), jnp.asarray(valid), n_bins, h,
        impl="matmul",
    )
    np.testing.assert_allclose(np.asarray(sm), np.asarray(sx), rtol=1e-10,
                               atol=1e-13)
    np.testing.assert_allclose(np.asarray(cm, dtype=np.float64),
                               np.asarray(cx, dtype=np.float64))


def test_grid_backtest_matmul_impl(rng):
    """jk_grid_backtest(impl='matmul') == 'xla' end to end."""
    from csmom_tpu.backtest.grid import jk_grid_backtest

    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(40, 90)), axis=1))
    mask = np.ones((40, 90), bool)
    mask[:8, :20] = False
    Js = np.array([3, 6])
    Ks = np.array([1, 6])
    r1 = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode="rank")
    r2 = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode="rank",
                          impl="matmul")
    np.testing.assert_array_equal(np.asarray(r1.spread_valid),
                                  np.asarray(r2.spread_valid))
    np.testing.assert_allclose(np.asarray(r1.spreads), np.asarray(r2.spreads),
                               rtol=1e-9, equal_nan=True)
    np.testing.assert_allclose(np.asarray(r1.tstat_nw), np.asarray(r2.tstat_nw),
                               rtol=1e-8, equal_nan=True)


def test_grid_backtest_pallas_impl(rng):
    """jk_grid_backtest(impl='pallas') == 'xla' end to end, vmapped over J."""
    from csmom_tpu.backtest.grid import jk_grid_backtest

    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(40, 120)), axis=1))
    mask = np.ones((40, 120), bool)
    mask[:5, :30] = False  # late listings
    Js = np.array([3, 6])
    Ks = np.array([1, 6])
    for mode in ("rank", "qcut"):
        r1 = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode=mode)
        r2 = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode=mode,
                              impl="pallas")
        np.testing.assert_allclose(np.asarray(r1.spreads), np.asarray(r2.spreads),
                                   rtol=1e-12, equal_nan=True)
        np.testing.assert_array_equal(np.asarray(r1.spread_valid),
                                      np.asarray(r2.spread_valid))
        np.testing.assert_allclose(np.asarray(r1.tstat_nw), np.asarray(r2.tstat_nw),
                                   rtol=1e-12, equal_nan=True)


def test_cohort_kernel_rejects_horizon_beyond_tile():
    from csmom_tpu.ops.pallas_kernels import cohort_partial_sums_pallas

    with pytest.raises(ValueError, match="max_hold"):
        cohort_partial_sums_pallas(
            jnp.zeros((8, 16)), jnp.ones((8, 16), bool),
            jnp.zeros((8, 16), jnp.int32), max_hold=200, block_t=128,
        )


def test_custom_tiling(rng):
    labels, ret_z, _ = _case(rng, 511, 257, 10)
    sums, counts = decile_partial_sums_pallas(
        jnp.asarray(ret_z), jnp.asarray(labels),
        n_bins=10, block_a=128, block_t=128, interpret=True,
    )
    ws, wc = _xla(labels, ret_z, 10)
    # blocked accumulation reorders the sum vs XLA: tolerance, not equality
    np.testing.assert_allclose(np.asarray(sums), ws, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(counts), wc)


def test_cohort_matmul_bf16_counts_exact_sums_close(rng):
    """bf16 operands with f32 accumulation: the count cross table must be
    EXACT (0/1 operands are bf16-representable), and the return sums within
    bf16 input-rounding tolerance of the f64 XLA form."""
    from csmom_tpu.backtest.grid import _cohort_partial_sums

    a, m, h, n_bins = 130, 60, 6, 5
    labels = rng.integers(-1, n_bins, size=(a, m)).astype(np.int32)
    valid = rng.random((a, m)) > 0.25
    ret = np.where(valid, rng.normal(0, 0.02, size=(a, m)), np.nan)
    sx, cx = _cohort_partial_sums(
        jnp.asarray(labels), jnp.asarray(ret), jnp.asarray(valid), n_bins, h
    )
    sb, cb = _cohort_partial_sums(
        jnp.asarray(labels), jnp.asarray(ret), jnp.asarray(valid), n_bins, h,
        impl="matmul_bf16",
    )
    np.testing.assert_array_equal(
        np.asarray(cb, dtype=np.float64), np.asarray(cx, dtype=np.float64)
    )
    # bf16 has ~8 mantissa bits: per-element relative error <= 2^-8; sums of
    # ~a/n_bins same-sign-ish terms keep roughly that relative scale
    np.testing.assert_allclose(np.asarray(sb), np.asarray(sx),
                               rtol=2e-2, atol=5e-4)


def test_grid_backtest_matmul_bf16_close(rng):
    """End to end the bf16 grid tracks the exact grid: identical validity,
    mean spreads within bf16 tolerance."""
    from csmom_tpu.backtest.grid import jk_grid_backtest

    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(60, 90)), axis=1))
    mask = np.ones((60, 90), bool)
    mask[:8, :20] = False
    Js = np.array([3, 6])
    Ks = np.array([1, 6])
    r1 = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode="rank")
    r2 = jk_grid_backtest(prices, mask, Js, Ks, skip=1, n_bins=5, mode="rank",
                          impl="matmul_bf16")
    np.testing.assert_array_equal(np.asarray(r1.spread_valid),
                                  np.asarray(r2.spread_valid))
    v = np.asarray(r1.spread_valid)
    np.testing.assert_allclose(np.asarray(r2.spreads)[v],
                               np.asarray(r1.spreads)[v],
                               rtol=0, atol=2e-3)
    np.testing.assert_allclose(np.asarray(r2.mean_spread),
                               np.asarray(r1.mean_spread),
                               rtol=0, atol=5e-4)
