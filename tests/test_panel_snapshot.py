"""Panel snapshot save/load (checkpoint/resume, SURVEY §5)."""

import numpy as np
import pytest

from csmom_tpu.panel.panel import Panel


def _panel(rng):
    v = rng.normal(size=(5, 8))
    v[0, :3] = np.nan
    times = np.array([np.datetime64("2020-01-31") + 31 * i for i in range(8)])
    return Panel.from_dense(v, [f"T{i}" for i in range(5)], times, name="px")


def test_roundtrip_exact(tmp_path, rng):
    p = _panel(rng)
    path = str(tmp_path / "snap.npz")
    p.save(path)
    q = Panel.load(path)
    np.testing.assert_array_equal(p.values, q.values)
    np.testing.assert_array_equal(p.mask, q.mask)
    assert p.tickers == q.tickers
    np.testing.assert_array_equal(p.times, q.times)
    assert p.name == q.name


def test_future_version_is_loud(tmp_path, rng):
    p = _panel(rng)
    path = str(tmp_path / "snap.npz")
    p.save(path)
    with np.load(path, allow_pickle=True) as z:
        data = {k: z[k] for k in z.files}
    data["__version__"] = np.int64(99)
    np.savez_compressed(path, **data)
    with pytest.raises(ValueError, match="version 99"):
        Panel.load(path)
