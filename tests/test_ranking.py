"""Decile assignment vs the reference's exact pandas semantics.

Oracle = pd.qcut(labels=False, duplicates='drop') with the ordinal-rank
fallback, i.e. the behaviour of assign_deciles_per_date (run_demo.py:18-29),
re-derived here independently.
"""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.ops import decile_assign, decile_assign_panel


def oracle_deciles(values: np.ndarray, n: int = 10) -> np.ndarray:
    """Reference semantics on one cross-section; -1 where input is NaN."""
    s = pd.Series(values)
    sv = s.dropna()
    if sv.empty:
        return np.full(len(s), -1)
    try:
        labels = pd.qcut(sv, q=n, labels=False, duplicates="drop")
        out = labels.reindex(s.index)
    except ValueError:
        ranks = s.rank(method="first", pct=True)
        bins = np.floor(ranks * n)
        bins[bins == n] = n - 1
        out = bins
    return np.where(np.isnan(out.values.astype(float)), -1, out.values).astype(int)


def _check(values, n=10, mode="qcut"):
    valid = np.isfinite(values)
    got, n_eff = decile_assign(values, valid, n_bins=n, mode=mode)
    want = oracle_deciles(values, n)
    np.testing.assert_array_equal(np.asarray(got), want)
    return int(n_eff)


def test_clean_cross_section(rng):
    for a in (10, 20, 37, 100):
        vals = rng.normal(size=a)
        n_eff = _check(vals)
        assert n_eff == 10


def test_with_nans(rng):
    vals = rng.normal(size=40)
    vals[rng.random(40) < 0.3] = np.nan
    _check(vals)


def test_heavy_ties():
    """Duplicate values collapse qcut edges -> fewer bins (duplicates='drop')."""
    vals = np.array([1.0] * 8 + [2.0] * 8 + [3.0] * 4)
    n_eff = _check(vals)
    assert n_eff < 10


def test_all_identical_yields_all_invalid():
    """duplicates='drop' on an all-identical cross-section emits NaN labels
    (it does not raise, so the reference's rank fallback never fires)."""
    vals = np.full(20, 7.0)
    valid = np.isfinite(vals)
    got, n_eff = decile_assign(vals, valid)
    assert (np.asarray(got) == -1).all()
    assert int(n_eff) == 0
    _check(vals)


def test_tiny_cross_sections(rng):
    for a in (1, 2, 3, 9, 11):
        vals = rng.normal(size=a)
        _check(vals)


def test_values_on_edges():
    """A value exactly equal to an interior quantile edge must land in the
    lower (right-closed) bin, and the minimum in bin 0."""
    vals = np.arange(20, dtype=float)  # edges land exactly on data points
    _check(vals)


def test_rank_mode_matches_reference_fallback(rng):
    """mode='rank' must equal the reference's fallback formula on any input."""
    vals = rng.normal(size=50)
    valid = np.isfinite(vals)
    got, _ = decile_assign(vals, valid, n_bins=10, mode="rank")
    ranks = pd.Series(vals).rank(method="first", pct=True)
    bins = np.floor(ranks * 10)
    bins[bins == 10] = 9
    np.testing.assert_array_equal(np.asarray(got), bins.astype(int).values)


@pytest.mark.slow
def test_rank_mode_fuzz_ties_masks_small_n(rng):
    """Rank mode vs the pandas fallback formula under heavy ties, masked
    lanes, and tiny/degenerate cross-sections (exercises the boundary-pair
    formulation's tie-breaks)."""
    for trial in range(200):
        a = int(rng.integers(1, 60))
        vals = rng.choice(
            [np.nan, 0.0, 0.0, 1.0, 1.0, -2.5, *rng.normal(size=3)], size=a
        )
        valid = np.isfinite(vals)
        for n_bins in (3, 10):
            got, _ = decile_assign(vals, valid, n_bins=n_bins, mode="rank")
            got = np.asarray(got)
            if not valid.any():
                assert (got == -1).all()
                continue
            ranks = pd.Series(vals).rank(method="first", pct=True)
            bins = np.floor(ranks * n_bins)
            bins[bins == n_bins] = n_bins - 1
            np.testing.assert_array_equal(got[valid], bins[valid].astype(int))
            assert (got[~valid] == -1).all()


def test_panel_vmap(rng):
    x = rng.normal(size=(20, 15))
    x[rng.random(x.shape) < 0.2] = np.nan
    valid = np.isfinite(x)
    labels, n_eff = decile_assign_panel(x, valid)
    assert labels.shape == x.shape
    assert n_eff.shape == (15,)
    for t in range(15):
        np.testing.assert_array_equal(
            np.asarray(labels[:, t]), oracle_deciles(x[:, t])
        )


@pytest.mark.slow
def test_random_fuzz_vs_oracle(rng):
    """Fuzz: many random cross-sections incl. ties, NaNs, tiny N."""
    for trial in range(200):
        a = int(rng.integers(1, 40))
        vals = rng.choice([np.nan, 0.0, 1.0, 1.0 + 1e-9, *rng.normal(size=5)], size=a)
        _check(vals)
