"""Decile assignment vs the reference's exact pandas semantics.

Oracle = pd.qcut(labels=False, duplicates='drop') with the ordinal-rank
fallback, i.e. the behaviour of assign_deciles_per_date (run_demo.py:18-29),
re-derived here independently.
"""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.ops import decile_assign, decile_assign_panel


def oracle_deciles(values: np.ndarray, n: int = 10) -> np.ndarray:
    """Reference semantics on one cross-section; -1 where input is NaN."""
    s = pd.Series(values)
    sv = s.dropna()
    if sv.empty:
        return np.full(len(s), -1)
    try:
        labels = pd.qcut(sv, q=n, labels=False, duplicates="drop")
        out = labels.reindex(s.index)
    except ValueError:
        ranks = s.rank(method="first", pct=True)
        bins = np.floor(ranks * n)
        bins[bins == n] = n - 1
        out = bins
    return np.where(np.isnan(out.values.astype(float)), -1, out.values).astype(int)


def _check(values, n=10, mode="qcut"):
    valid = np.isfinite(values)
    got, n_eff = decile_assign(values, valid, n_bins=n, mode=mode)
    want = oracle_deciles(values, n)
    np.testing.assert_array_equal(np.asarray(got), want)
    return int(n_eff)


def test_clean_cross_section(rng):
    for a in (10, 20, 37, 100):
        vals = rng.normal(size=a)
        n_eff = _check(vals)
        assert n_eff == 10


def test_with_nans(rng):
    vals = rng.normal(size=40)
    vals[rng.random(40) < 0.3] = np.nan
    _check(vals)


def test_heavy_ties():
    """Duplicate values collapse qcut edges -> fewer bins (duplicates='drop')."""
    vals = np.array([1.0] * 8 + [2.0] * 8 + [3.0] * 4)
    n_eff = _check(vals)
    assert n_eff < 10


def test_all_identical_yields_all_invalid():
    """duplicates='drop' on an all-identical cross-section emits NaN labels
    (it does not raise, so the reference's rank fallback never fires)."""
    vals = np.full(20, 7.0)
    valid = np.isfinite(vals)
    got, n_eff = decile_assign(vals, valid)
    assert (np.asarray(got) == -1).all()
    assert int(n_eff) == 0
    _check(vals)


def test_tiny_cross_sections(rng):
    for a in (1, 2, 3, 9, 11):
        vals = rng.normal(size=a)
        _check(vals)


def test_values_on_edges():
    """A value exactly equal to an interior quantile edge must land in the
    lower (right-closed) bin, and the minimum in bin 0."""
    vals = np.arange(20, dtype=float)  # edges land exactly on data points
    _check(vals)


def test_rank_mode_matches_reference_fallback(rng):
    """mode='rank' must equal the reference's fallback formula on any input."""
    vals = rng.normal(size=50)
    valid = np.isfinite(vals)
    got, _ = decile_assign(vals, valid, n_bins=10, mode="rank")
    ranks = pd.Series(vals).rank(method="first", pct=True)
    bins = np.floor(ranks * 10)
    bins[bins == 10] = 9
    np.testing.assert_array_equal(np.asarray(got), bins.astype(int).values)


@pytest.mark.slow
def test_rank_mode_fuzz_ties_masks_small_n(rng):
    """Rank mode vs the pandas fallback formula under heavy ties, masked
    lanes, and tiny/degenerate cross-sections (exercises the boundary-pair
    formulation's tie-breaks)."""
    for trial in range(200):
        a = int(rng.integers(1, 60))
        vals = rng.choice(
            [np.nan, 0.0, 0.0, 1.0, 1.0, -2.5, *rng.normal(size=3)], size=a
        )
        valid = np.isfinite(vals)
        for n_bins in (3, 10):
            got, _ = decile_assign(vals, valid, n_bins=n_bins, mode="rank")
            got = np.asarray(got)
            if not valid.any():
                assert (got == -1).all()
                continue
            ranks = pd.Series(vals).rank(method="first", pct=True)
            bins = np.floor(ranks * n_bins)
            bins[bins == n_bins] = n_bins - 1
            np.testing.assert_array_equal(got[valid], bins[valid].astype(int))
            assert (got[~valid] == -1).all()


def test_panel_vmap(rng):
    x = rng.normal(size=(20, 15))
    x[rng.random(x.shape) < 0.2] = np.nan
    valid = np.isfinite(x)
    labels, n_eff = decile_assign_panel(x, valid)
    assert labels.shape == x.shape
    assert n_eff.shape == (15,)
    for t in range(15):
        np.testing.assert_array_equal(
            np.asarray(labels[:, t]), oracle_deciles(x[:, t])
        )


@pytest.mark.slow
def test_random_fuzz_vs_oracle(rng):
    """Fuzz: many random cross-sections incl. ties, NaNs, tiny N."""
    for trial in range(200):
        a = int(rng.integers(1, 40))
        vals = rng.choice([np.nan, 0.0, 1.0, 1.0 + 1e-9, *rng.normal(size=5)], size=a)
        _check(vals)


class TestHistMode:
    """mode='hist' (sort-free radix binning) must be label-identical to
    mode='rank' — same order statistics, same stable tie rule — including
    the adversarial cases that broke round 2's distributed version.

    Mostly slow-tier: the f64 hist kernel's compile (16 unrolled radix
    rounds) costs ~30 s per (shape, B) on this single-core image, so the
    fast tier keeps one cheap f32 representative and the full tier runs
    the adversarial battery."""

    @pytest.mark.slow
    def test_matches_rank_random_with_holes(self, rng):
        x = rng.normal(size=(57, 9))
        valid = rng.random((57, 9)) > 0.25
        x = np.where(valid, x, np.nan)
        lr, nr = decile_assign_panel(x, valid, 10, mode="rank")
        lh, nh = decile_assign_panel(x, valid, 10, mode="hist")
        np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))
        np.testing.assert_array_equal(np.asarray(nr), np.asarray(nh))

    @pytest.mark.slow
    def test_heavy_ties_and_signed_zero(self, rng):
        x = rng.choice([0.0, -0.0, 1.5, -1.5, 2.0], size=(40, 6))
        valid = rng.random((40, 6)) > 0.2
        x = np.where(valid, x, np.nan)
        for B in (3, 5, 10):
            lr, _ = decile_assign_panel(x, valid, B, mode="rank")
            lh, _ = decile_assign_panel(x, valid, B, mode="hist")
            np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))

    @pytest.mark.slow
    def test_fewer_valid_than_bins_and_empty_dates(self, rng):
        x = rng.normal(size=(4, 5))
        valid = np.zeros((4, 5), bool)
        valid[:2, 0] = True   # 2 valid < 10 bins
        valid[:, 2] = True    # full date
        x = np.where(valid, x, np.nan)
        lr, nr = decile_assign_panel(x, valid, 10, mode="rank")
        lh, nh = decile_assign_panel(x, valid, 10, mode="hist")
        np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))
        np.testing.assert_array_equal(np.asarray(nr), np.asarray(nh))

    @pytest.mark.slow
    def test_single_date_form(self, rng):
        x = rng.normal(size=37)
        valid = rng.random(37) > 0.3
        x = np.where(valid, x, np.nan)
        lr, nr = decile_assign(x, valid, 10, mode="rank")
        lh, nh = decile_assign(x, valid, 10, mode="hist")
        np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))
        assert int(nr) == int(nh)

    def test_f32_keys_adversarial(self, rng):
        """The fast-tier representative: f32 (8 cheap radix rounds) but
        fully adversarial — ties, masked holes, +/-inf against invalid
        lanes (the shipped sentinel-collision regression), signed zeros —
        so the default tier keeps real coverage of the tie/sentinel logic
        while the f64 battery stays in the full tier."""
        x = rng.normal(size=(48, 4)).astype(np.float32)
        x[rng.random((48, 4)) < 0.25] = 0.0
        x[rng.random((48, 4)) < 0.1] = np.inf
        x[rng.random((48, 4)) < 0.1] = -np.inf
        x[rng.random((48, 4)) < 0.15] = -0.0
        valid = rng.random((48, 4)) > 0.3
        x = np.where(valid, x, np.float32(np.nan))
        for B in (3, 10):
            lr, nr = decile_assign_panel(x, valid, B, mode="rank")
            lh, nh = decile_assign_panel(x, valid, B, mode="hist")
            np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))
            np.testing.assert_array_equal(np.asarray(nr), np.asarray(nh))

    @pytest.mark.slow
    def test_grid_engine_hist_mode_matches_rank(self, rng):
        from csmom_tpu.backtest.grid import jk_grid_backtest

        prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(30, 60)), axis=1))
        mask = np.ones((30, 60), bool)
        mask[:4, :12] = False
        Js, Ks = np.array([3, 6]), np.array([1, 3])
        a = jk_grid_backtest(prices, mask, Js, Ks, n_bins=5, mode="rank")
        b = jk_grid_backtest(prices, mask, Js, Ks, n_bins=5, mode="hist")
        np.testing.assert_array_equal(np.asarray(a.spread_valid),
                                      np.asarray(b.spread_valid))
        np.testing.assert_allclose(np.asarray(a.mean_spread),
                                   np.asarray(b.mean_spread), rtol=1e-12)

    @pytest.mark.slow
    def test_valid_inf_with_invalid_lanes(self):
        """A valid +inf must not tie with the invalid-lane sentinel: rank
        and hist agree, and no boundary slot lands on an invalid lane
        (regression: the float-inf sentinel let stable-sort position decide
        and mislabeled real +inf momentum, e.g. a zero formation price)."""
        x = np.array([[np.nan], [np.inf], [np.inf], [np.inf], [0.0], [1.0]])
        valid = np.array([[False], [True], [True], [True], [True], [True]])
        for B in (3, 5, 10):
            lr, nr = decile_assign_panel(x, valid, B, mode="rank")
            lh, nh = decile_assign_panel(x, valid, B, mode="hist")
            np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))
            np.testing.assert_array_equal(np.asarray(nr), np.asarray(nh))
            assert np.asarray(lr)[0, 0] == -1
        # B=3 over 5 valid, ordinal ranks [3,4,5,1,2] (ties by position,
        # rank(method='first')): labels floor(pct*3) = [1,2,2,0,1] — the
        # first +inf lands in bin 1, exactly as the pandas formula says
        lr, _ = decile_assign_panel(x, valid, 3, mode="rank")
        np.testing.assert_array_equal(np.asarray(lr)[:, 0],
                                      [-1, 1, 2, 2, 0, 1])

    @pytest.mark.slow
    def test_fuzz_matches_rank_with_inf_injection(self, rng):
        """Randomized panels with ties, holes, +/-inf and signed zeros:
        hist and rank must agree bin-for-bin on every draw.

        STATIC shapes: every draw uses one [80, 8] panel with the drawn
        (A_eff, M_eff) realized as masked-out lanes/dates, so the whole
        fuzz compiles 8 executables (4 bin counts x 2 modes), not 24 —
        varying data through a fixed shape is both the framework's own
        discipline and what keeps a compile-heavy suite inside the
        process's executable budget (a fresh-shape-per-draw version of
        this test segfaulted XLA CPU late in the full tier)."""
        A, M = 80, 8
        for _ in range(12):
            a_eff = int(rng.integers(3, A + 1))
            m_eff = int(rng.integers(1, M + 1))
            B = int(rng.choice([3, 4, 5, 10]))
            x = rng.normal(size=(A, M))
            x[rng.random((A, M)) < 0.25] = 0.0
            x[rng.random((A, M)) < 0.1] = np.inf
            x[rng.random((A, M)) < 0.1] = -np.inf
            x[rng.random((A, M)) < 0.15] = -0.0
            valid = rng.random((A, M)) > 0.3
            valid[a_eff:, :] = False
            valid[:, m_eff:] = False
            x = np.where(valid, x, np.nan)
            lr, nr = decile_assign_panel(x, valid, B, mode="rank")
            lh, nh = decile_assign_panel(x, valid, B, mode="hist")
            np.testing.assert_array_equal(np.asarray(lr), np.asarray(lh))
            np.testing.assert_array_equal(np.asarray(nr), np.asarray(nh))
