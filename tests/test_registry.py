"""ISSUE 9: the engine registry — completeness, the toy-engine contract,
and the enumeration-drift lint.

Three layers:

- **completeness** (tier-1): every registered serve engine round-trips
  through manifest -> AOT warm -> serve dispatch on BOTH engines (stub
  and jax-on-CPU), with zero in-window fresh compiles — registration IS
  the production surface, there is no second list to also be on;
- **the toy-engine contract**: registering a throwaway engine in-test
  yields all five surfaces (manifest entries, donated variant, serve
  dispatch, loadgen leg + ledger rows, sharded hook) with NO other file
  edited;
- **the lint**: no module outside ``csmom_tpu/registry/`` may define an
  endpoint/entry/workload enumeration (grep-style AST walk, like the
  time-discipline lint) — the registry cannot silently fork back into
  parallel tables.
"""

import ast
import json
import os
import random

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.registry import (
    EngineSpec,
    ServeSurface,
    engine_specs,
    get_engine,
    register_engine,
    serve_endpoints,
    serve_surface,
    unregister_engine,
    workload_kinds,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _panel(n_assets: int, months: int, seed: int = 0):
    r = np.random.default_rng(seed)
    v = 100.0 * np.exp(np.cumsum(r.normal(0, 0.03, (n_assets, months)),
                                 axis=1)).astype(np.float32)
    return v, np.ones((n_assets, months), bool)


# ------------------------------------------------------------ registry -----

def test_builtin_endpoint_set_is_the_five_engine_registry():
    kinds = serve_endpoints()
    # the three r10 endpoints plus the two previously research-only
    # strategies ISSUE 9 ships as live endpoints
    assert set(kinds) >= {"momentum", "turnover", "backtest",
                          "low_volatility", "zscore_combo"}
    assert tuple(workload_kinds()) == tuple(kinds)


def test_every_surface_declares_its_panel_family_and_output():
    for kind in serve_endpoints():
        s = serve_surface(kind)
        assert s.panel_family in ("price", "volume")
        if s.output == "summary":
            assert s.summary_fields


def test_duplicate_registration_within_a_kind_refuses():
    spec = get_engine("momentum", kind="serve")
    clone = EngineSpec(name="momentum", kind="serve", serve=spec.serve,
                       description="not the same spec")
    with pytest.raises(ValueError, match="already registered"):
        register_engine(clone)
    # same name in ANOTHER kind is fine (namespaced): the strategy zoo
    # holds a 'momentum' row next to the serve endpoint
    from csmom_tpu.registry import strategies

    assert "momentum" in strategies()  # importing the zoo registers it
    assert get_engine("momentum", kind="strategy").strategy_cls is not None


def test_sharded_hook_is_filled_for_every_serve_and_compile_engine():
    """The r14 stubbed-hook pin, FLIPPED at r15: every serve/compile
    engine resolves a non-stub sharded variant through the mesh rule
    table (tests/test_mesh.py pins completeness + bitwise parity); the
    pointed refusal remains only for kinds with no mesh placement."""
    from csmom_tpu.mesh.variants import resolve_sharded

    for spec in engine_specs("serve") + engine_specs("compile"):
        assert spec.sharded_fn is not None \
            or resolve_sharded(spec) is not None, (
            f"{spec.kind}:{spec.name} still has a stubbed sharded hook")


# -------------------------------------------------- completeness (tier-1) --

def test_manifest_covers_every_registered_endpoint():
    from csmom_tpu.compile.manifest import build_manifest
    from csmom_tpu.serve.buckets import bucket_spec

    for profile in ("serve", "serve-smoke"):
        spec = bucket_spec(profile)
        entries = build_manifest(profile)
        names = {e.name for e in entries}
        assert len(names) == len(entries)
        for kind in serve_endpoints():
            for B, A, M in spec.shapes():
                assert f"serve.{kind}.b{B}@{A}x{M}" in names, (
                    f"endpoint {kind!r} missing its {B}x{A}x{M} manifest "
                    "entry: registration did not buy surface (a)")
        for e in entries:
            e.validate()


@pytest.mark.parametrize("engine", ["stub", "jax"])
def test_every_registered_endpoint_round_trips_through_dispatch(engine):
    """manifest -> warm -> dispatch, driven only by the registry: the
    loop body never names an endpoint."""
    from csmom_tpu.serve.service import ServeConfig, SignalService

    svc = SignalService(ServeConfig(profile="serve-smoke", engine=engine,
                                    max_wait_s=0.005)).start()
    months = svc.spec.months
    try:
        warm = svc.warm_report
        assert list(warm["endpoints"]) == list(serve_endpoints())
        reqs = {k: svc.submit(k, *_panel(5, months, seed=i))
                for i, k in enumerate(serve_endpoints())}
        for kind, r in reqs.items():
            assert r.wait(30.0) and r.state == "served", (
                kind, r.state, r.error)
            s = serve_surface(kind)
            if s.output == "summary":
                assert set(r.result) == set(s.summary_fields)
            else:
                assert np.asarray(r.result).shape == (5,)
    finally:
        svc.stop()
    assert svc.invariant_violations() == []
    fresh = svc.fresh_compiles()
    assert fresh == 0, f"in-window fresh compiles: {fresh}"


# ------------------------------------------------------- the toy engine ----

def _toy_batch(params):
    import jax.numpy as jnp

    def one(values, mask):
        return jnp.where(mask[:, -1], values[:, -1], jnp.nan)

    return one


def _toy_stub(params):
    def fn(values, mask):
        return np.where(mask[:, :, -1], values[:, :, -1], np.nan)

    return fn


@pytest.fixture
def toy_engine():
    name = "toy_last_price"
    spec = register_engine(
        name=name, kind="serve",
        description="last observed price (test-only toy engine)",
        axes="values f[B,A,M], mask bool[B,A,M] -> f[B,A]",
        serve=ServeSurface(batch_fn=_toy_batch, stub_fn=_toy_stub,
                           panel_family="price"),
    )
    try:
        yield spec
    finally:
        unregister_engine(name, kind="serve")


def test_toy_engine_gets_all_five_surfaces(toy_engine, tmp_path):
    """Register once in-test; every production surface appears with no
    other file edited — the tentpole's acceptance property."""
    from csmom_tpu.compile.manifest import build_manifest
    from csmom_tpu.serve.buckets import bucket_spec
    from csmom_tpu.serve.loadgen import (
        LoadConfig,
        run_loadgen,
        write_artifact,
    )
    from csmom_tpu.serve.service import ServeConfig, SignalService

    name = toy_engine.name
    spec = bucket_spec("serve-smoke")

    # (a) manifest entries, bound against the live jitted signature
    entries = [e for e in build_manifest("serve-smoke")
               if e.name.startswith(f"serve.{name}.")]
    assert len(entries) == len(spec.shapes())
    for e in entries:
        e.validate()

    # (b) a donated-buffer jit variant that computes the same thing
    v = np.asarray(_panel(3, spec.months, seed=7)[0])
    m = np.ones((3, spec.months), bool)
    plain = np.asarray(toy_engine.serve.batch_fn(
        dict(lookback=12, skip=1, n_bins=10, mode="rank"))(v, m))
    donated = toy_engine.donated(lookback=12, skip=1, n_bins=10,
                                 mode="rank")
    out = np.asarray(donated(v[None].copy(), m[None].copy()))
    np.testing.assert_allclose(out[0], plain)

    # (c) a live serve endpoint (stub engine keeps this test fast; the
    # jax dispatch path is pinned by the round-trip test above)
    svc = SignalService(ServeConfig(profile="serve-smoke", engine="stub",
                                    max_wait_s=0.005)).start()
    try:
        req = svc.submit(name, *_panel(4, spec.months))
        assert req.wait(5.0) and req.state == "served", (req.state,
                                                         req.error)
        assert np.asarray(req.result).shape == (4,)
    finally:
        svc.stop()

    # (d) a loadgen workload leg that lands per-endpoint ledger rows
    assert name in workload_kinds()
    svc = SignalService(ServeConfig(profile="serve-smoke", engine="stub",
                                    max_wait_s=0.005)).start()
    art = run_loadgen(svc, LoadConfig(schedule="0.4x80", seed=3,
                                      run_id="r98"))
    assert inv.validate(art, "serve") == []
    assert name in art["endpoints"]
    assert art["endpoints"][name]["submitted"] > 0, (
        "the toy engine never entered the load mix")
    path = write_artifact(str(tmp_path), art)
    from csmom_tpu.obs import ledger

    rows = ledger.load(str(tmp_path)).rows
    assert any(r.metric == f"serve_ep_{name}_p99_ms" for r in rows), (
        "no per-endpoint ledger row for the toy engine")
    assert os.path.basename(path) == "SERVE_r98.json"

    # (e) the sharded surface resolves via the mesh rule table — the
    # catch-all serve rule gives ANY per-request scorer the batch-axis
    # variant (parity pinned in tests/test_mesh.py)
    entry = toy_engine.sharded()
    assert entry.axis == "batch" and callable(entry)


def test_unregistered_endpoint_rejected_at_every_door():
    from csmom_tpu.serve.service import ServeConfig, SignalService

    svc = SignalService(ServeConfig(profile="serve-smoke", engine="stub",
                                    max_wait_s=0.005)).start()
    try:
        req = svc.submit("no_such_engine", *_panel(3, svc.spec.months))
        assert req.state == "rejected"
        assert "unknown endpoint" in (req.error or "")
    finally:
        svc.stop()


# ------------------------------------------- schema v3 registry validation -

def _v3_artifact(**over):
    """A minimal well-formed serve v3 artifact to doctor."""
    from csmom_tpu.serve.loadgen import LoadConfig, run_loadgen
    from csmom_tpu.serve.service import ServeConfig, SignalService

    svc = SignalService(ServeConfig(profile="serve-smoke", engine="stub",
                                    max_wait_s=0.005)).start()
    art = run_loadgen(svc, LoadConfig(schedule="0.3x60", seed=1,
                                      run_id="doctored"))
    art.update(over)
    return art


def test_serve_v3_artifact_validates_and_is_registry_checked():
    from csmom_tpu.serve.loadgen import SCHEMA_VERSION

    art = _v3_artifact()
    # v4 (ISSUE 13) is a superset of v3: the registry rules under test
    # here apply to every version >= 3
    assert art["schema_version"] == SCHEMA_VERSION >= 3
    assert inv.validate(art, "serve") == []

    # an endpoint name no registered engine implements is invalid
    bad = json.loads(json.dumps(art))
    bad["endpoints"]["phantom_engine"] = {
        "submitted": 0, "served": 0, "rejected": 0, "expired": 0,
        "latency_ms": {"p50": None, "p95": None, "p99": None}}
    viols = inv.validate(bad, "serve")
    assert any("not a registered engine" in v for v in viols), viols

    # an offered mix naming an unregistered endpoint is invalid
    bad2 = json.loads(json.dumps(art))
    bad2["offered"]["kinds"] = list(bad2["offered"]["kinds"]) + ["phantom"]
    viols = inv.validate(bad2, "serve")
    assert any("unregistered endpoints" in v for v in viols), viols

    # endpoint books must sum to the global served book
    bad3 = json.loads(json.dumps(art))
    k = next(iter(bad3["endpoints"]))
    bad3["endpoints"][k]["served"] += 1
    bad3["endpoints"][k]["submitted"] += 1
    viols = inv.validate(bad3, "serve")
    assert any("endpoint books do not sum" in v for v in viols), viols


def test_loadgen_default_mix_is_the_registry():
    from csmom_tpu.serve.loadgen import LoadConfig, synth_panel

    assert LoadConfig().resolved_kinds() == workload_kinds()
    # the synthetic panel family is the surface's declaration
    rng = random.Random(0)
    v, m = synth_panel(rng, 4, 24, "turnover")
    assert np.nanmax(v) > 1e3  # volume-family magnitudes
    v, m = synth_panel(rng, 4, 24, "low_volatility")
    assert np.nanmax(v) < 1e3  # price-family random walk


# ---------------------------------------------------- enumeration lint -----

# r14's inline AST walk became the registered ``enumeration-drift`` rule
# (ISSUE 11): the tree sweep lives in tests/test_lint.py / `csmom lint`;
# what stays here are the thin regression pins on the migrated behavior.

def test_enumeration_lint_is_a_registered_rule_covering_the_tree():
    """The registry lint is now itself a registry citizen (kind 'lint'),
    and the committed tree stays clean under it — including the new
    checkpoint-vocabulary coverage both ways."""
    from csmom_tpu.analysis import run_lint
    from csmom_tpu.registry import lint_rules

    specs = {s.name: s for s in lint_rules()}
    assert "enumeration-drift" in specs
    rep = run_lint(rules=[specs["enumeration-drift"].rule_cls()])
    assert rep.findings == [], [str(f) for f in rep.findings]


def test_lint_actually_catches_an_enumeration():
    """The lint's own regression test: the pre-ISSUE-9 buckets.py line
    (kept verbatim in the known-bad fixture) is flagged by the rule."""
    from csmom_tpu.analysis import run_lint
    from csmom_tpu.analysis.rules import (
        EnumerationDrift,
        banned_enumeration_name,
    )

    src = 'ENDPOINTS = ("momentum", "turnover", "backtest")\n'
    node = ast.parse(src).body[0]
    assert isinstance(node, ast.Assign)
    assert banned_enumeration_name(node.targets[0].id)
    # and the allowed spellings stay allowed
    for ok in ("GRID_JS", "NAMED_SCHEDULES", "PROFILES", "OUTCOMES",
               "KNOWN_POINTS"):
        assert not banned_enumeration_name(ok)
    fixture = os.path.join(_REPO, "tests", "fixtures", "lint",
                           "enumeration_drift_bad.py")
    rep = run_lint(paths=[fixture], rules=[EnumerationDrift()])
    msgs = [f.message for f in rep.findings]
    assert any("'ENDPOINTS'" in m for m in msgs), msgs
    assert any("serve.not_a_point" in m for m in msgs), msgs


def test_reregistration_rebuilds_the_jitted_scorer():
    """The jit cache keys on the SURFACE, not the endpoint name: a name
    re-registered with a new surface must serve the new scorer, never a
    stale compiled one (the runtime-registration flow's correctness)."""
    from csmom_tpu.serve.engine import serve_entry_fn

    name = "toy_reregister"

    def batch_v1(params):
        import jax.numpy as jnp

        return lambda v, m: jnp.where(m[:, -1], v[:, -1], jnp.nan)

    def batch_v2(params):
        import jax.numpy as jnp

        return lambda v, m: jnp.where(m[:, -1], 2.0 * v[:, -1], jnp.nan)

    stub = _toy_stub
    v = np.ones((1, 2, 4), np.float32) * 3.0
    m = np.ones((1, 2, 4), bool)
    try:
        register_engine(name=name, kind="serve",
                        serve=ServeSurface(batch_fn=batch_v1, stub_fn=stub))
        out1 = np.asarray(serve_entry_fn(name, 12, 1, 10, "rank")(v, m))
        unregister_engine(name, kind="serve")
        register_engine(name=name, kind="serve",
                        serve=ServeSurface(batch_fn=batch_v2, stub_fn=stub))
        out2 = np.asarray(serve_entry_fn(name, 12, 1, 10, "rank")(v, m))
        np.testing.assert_allclose(out1, 3.0)
        np.testing.assert_allclose(out2, 6.0), (
            "re-registered endpoint served the STALE compiled scorer")
    finally:
        unregister_engine(name, kind="serve")
