"""Rehearsal driver: the fast tier is a tier-1 gate, the full matrix slow.

``csmom rehearse --fast`` is what the watcher scripts gate on before a
tunnel window: <=3 capture-path faults, no jax in the rehearsed
processes, well under 30 s.  The slow test runs the complete built-in
matrix — the real bench.py supervisor/child in smoke mode — which is the
acceptance bar: every fault lands a schema-valid (possibly partial)
artifact with zero lost measured rows, including the r5 failure mode
reproduced and shown fixed.
"""

import os
import subprocess
import sys
import time

import pytest

from csmom_tpu.cli.rehearse import builtin_matrix

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_cli(args, timeout):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
    env.pop("CSMOM_FAULT_PLAN", None)
    return subprocess.run(
        [sys.executable, "-m", "csmom_tpu.cli.main", "rehearse", *args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=_REPO,
    )


def test_fast_tier_is_small_and_capture_path_only():
    fast = builtin_matrix(fast=True)
    # 16 since r21 (spare-promote-on-kill joined) — raise deliberately
    assert 1 <= len(fast) <= 16, "the fast tier must stay <= 16 faults"
    # mini/shell run as jax-free subprocesses; serve and replay run
    # IN-PROCESS on the stub engine; serve-pool spawns stub-engine
    # worker PROCESSES — none may need a jax-importing rehearsed pipeline
    assert all(s.pipeline in ("mini", "shell", "serve", "serve-pool",
                              "replay")
               for s in fast), (
        "fast-tier scenarios must not need jax-importing pipelines"
    )
    # the r4/r5 family (deadline loses measured rows) must be represented
    assert any("deadline" in s.name and s.pipeline == "mini" for s in fast)
    # ISSUE 5: both serve degradation scenarios ride in the fast tier
    serve = [s.name for s in fast if s.pipeline == "serve"]
    assert any("worker-kill" in n for n in serve), serve
    assert any("deadline-storm" in n for n in serve), serve
    # ISSUE 6: the three pool scenarios ride in the fast tier — a real
    # worker-process kill, a rolling restart under load, and the
    # AOT-cache version-skew refusal
    pool = [s.name for s in fast if s.pipeline == "serve-pool"]
    assert any("worker-kill" in n for n in pool), pool
    assert any("rolling-restart" in n for n in pool), pool
    assert any("version-skew" in n for n in pool), pool
    # ISSUE 10: the mesh path's kill — a DEVICE-PINNED worker dies
    # mid-batch and its replacement re-pins the same slice
    assert any("mesh-pinned" in n for n in pool), pool
    # ISSUE 19: the fleet observatory's capture-under-kill rehearsal —
    # a SIGKILLed emitter must land as a severed stream book feeding a
    # kill-window capacity account, never silent truncation
    assert any("fleet-capture" in n for n in pool), pool
    # ISSUE 7: both replay degradation scenarios ride in the fast tier —
    # the tick storm (late/ooo/dup/gap) and the ingest-serve skew gate
    replay = [s.name for s in fast if s.pipeline == "replay"]
    assert any("tick-storm" in n for n in replay), replay
    assert any("skew" in n for n in replay), replay
    # ISSUE 8: the adaptive-dispatch scenarios ride in the fast tier —
    # the bulk burst storm (quota holds, per-class books close) and the
    # cache-poisoning rehearsal (version floor refuses stale entries)
    assert any("burst-storm" in n for n in serve), serve
    assert any("cache-poison" in n for n in serve), serve


def test_rehearse_fast_runs_green_and_quick():
    # the wall gate gets ONE retry: the <30s claim is about the CODE
    # (the watcher budget), and this box has measured multi-second
    # noisy-neighbor windows (r19: worker warm 9.8s vs the usual ~5)
    # that overrun any wall assertion regardless of the tier's cost —
    # two consecutive overruns is a real regression, one is weather
    walls = []
    for _ in range(2):
        t0 = time.monotonic()
        p = _run_cli(["--fast"], timeout=120)
        walls.append(time.monotonic() - t0)
        assert p.returncode == 0, p.stdout + p.stderr
        assert "scenarios green" in p.stdout
        if walls[-1] < 30:
            break
    assert min(walls) < 30, (
        f"--fast took {', then '.join(f'{w:.1f}s' for w in walls)}; "
        "the watcher gate needs <30s")


def test_rehearse_exits_nonzero_on_violation(tmp_path):
    """A plan that kills the mini pipeline outright cannot satisfy the
    full-record invariants — rehearse must fail loudly, not shrug."""
    plan = tmp_path / "kill.toml"
    plan.write_text(
        'name = "kill-now"\n\n[[fault]]\npoint = "mini.start"\n'
        'action = "kill"\n'
    )
    p = _run_cli(["--plan", str(plan), "--pipeline", "mini"], timeout=120)
    assert p.returncode == 1
    assert "FAIL" in p.stdout


def test_rehearse_list_names_whole_matrix():
    p = _run_cli(["--list"], timeout=60)
    assert p.returncode == 0
    for scenario in builtin_matrix():
        assert scenario.name in p.stdout


@pytest.mark.slow
def test_rehearse_full_matrix_green():
    """Acceptance: the complete built-in fault matrix — including the r5
    reproduction against the real bench child — runs green on a CPU-only
    machine."""
    p = _run_cli(["--verbose"], timeout=3000)
    assert p.returncode == 0, p.stdout + p.stderr
    n = len(builtin_matrix())
    assert f"{n}/{n} scenarios green" in p.stdout


def test_fabric_scenarios_ride_the_full_matrix():
    """ISSUE 14: the partition-mid-burst, induced-straggler, and
    router-kill scenarios exist in the matrix on the serve-fabric
    pipeline (they spawn two process tiers, so they ride the FULL
    matrix — the fast tier stays <= 14 and < 30 s)."""
    mats = builtin_matrix()
    fabric = {s.name: s for s in mats if s.pipeline == "serve-fabric"}
    assert {"fabric-partition-mid-burst", "fabric-induced-straggler",
            "fabric-router-kill-mid-burst"} <= set(fabric)
    part = fabric["fabric-partition-mid-burst"]
    assert any(f.point == "serve.transport" and f.action == "partition"
               for f in part.plan.faults)
    strag = fabric["fabric-induced-straggler"]
    assert any(f.point == "serve.transport" and f.action == "net_delay"
               for f in strag.plan.faults)
    assert fabric["fabric-router-kill-mid-burst"].env.get("kill"), (
        "the double-kill scenario must SIGKILL by plan, not by accident")
