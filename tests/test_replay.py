"""Replay harness tests: artifact contract, chaos outcomes, CLI, ledger.

The replay artifact's schema (kind ``replay``) carries the two closed
books and the version reconciliation as RULES — these tests pin both
directions: a healthy run validates, and a doctored artifact (vanished
tick, impossible serve version, unbalanced serve book) is refused.
"""

import copy
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.chaos.plan import PLAN_ENV
from csmom_tpu.stream.replay import (
    ReplayConfig,
    builtin_fault_plan,
    run_replay,
    synth_tick_log,
)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def clean_art():
    """One fault-free stub replay, shared read-only across the module."""
    return run_replay(ReplayConfig(run_id="t_clean", engine="stub",
                                   profile="serve-smoke"))


@pytest.fixture(scope="module")
def chaos_art():
    """One builtin-fault-plan stub replay (late/ooo/dup/gap + skew)."""
    cfg = ReplayConfig(run_id="t_chaos", engine="stub",
                       profile="serve-smoke")
    from csmom_tpu.chaos import inject

    saved = {k: os.environ.get(k) for k in (PLAN_ENV, "CSMOM_FAULT_STATE")}
    os.environ[PLAN_ENV] = builtin_fault_plan(cfg).to_toml()
    os.environ.pop("CSMOM_FAULT_STATE", None)
    inject.reset()
    try:
        return run_replay(cfg)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        inject.reset()


def test_synth_log_is_deterministic():
    cfg = ReplayConfig()
    a = synth_tick_log(cfg)
    b = synth_tick_log(cfg)
    assert [(t.asset, t.bar_time, t.price) for t in a] \
        == [(t.asset, t.bar_time, t.price) for t in b]
    assert len(a) == cfg.n_assets * cfg.bars


def test_clean_replay_validates_and_books_close(clean_art):
    art = clean_art
    assert inv.detect_kind(art) == "replay"
    assert inv.validate(art) == []
    t = art["ticks"]
    assert t["offered"] == t["generated"]
    assert t["applied"] == t["offered"]
    assert art["panel"]["unfilled_cells"] == 0
    assert art["reconcile"]["drift_events"] == 0
    assert art["serve"]["requests"]["served"] > 0
    assert art["compile"]["in_window_fresh_compiles"] == 0


def test_chaos_replay_exercises_every_degradation(chaos_art):
    art = chaos_art
    assert inv.validate(art) == []
    t = art["ticks"]
    assert t["merged_late"] > 0, "no late tick merged"
    assert t["quarantined"] > 0, "no tick quarantined past the watermark"
    assert t["deduped"] > 0, "no duplicate deduplicated"
    assert t["dropped_gap"] > 0, "no tick dropped into a gap"
    assert art["panel"]["gap_bars"] >= 1, "the whole-bar gap vanished"
    assert art["panel"]["stale_bars"] >= 1, "gap bar not marked stale"
    v = art["versions"]
    assert v["skew_events"] == 1
    assert v["skew_refusals"] > 0, "the version gate did not refuse"
    assert v["skew_refusals"] <= v["skew_attempts"]
    assert v["serve_max"] <= v["ingest_final"]
    # drift-free even under the storm; the merges forced rebuilds
    assert art["reconcile"]["drift_events"] == 0
    assert art["reconcile"]["rebuilds"] > 0
    # the skew refusals are IN the closed serve book
    req = art["serve"]["requests"]
    assert req["rejected_version_skew"] == v["skew_refusals"]
    assert (req["served"] + req["rejected"] + req["expired"]
            == req["admitted"])


class TestReplaySchemaRefusesDoctoredBooks:
    def _doctor(self, art, fn):
        bad = copy.deepcopy(art)
        fn(bad)
        return inv.validate(bad, "replay")

    def test_vanished_tick_refused(self, clean_art):
        out = self._doctor(clean_art,
                           lambda a: a["ticks"].__setitem__(
                               "applied", a["ticks"]["applied"] - 1))
        assert any("tick accounting broken" in v for v in out)

    def test_feed_ledger_mismatch_refused(self, clean_art):
        out = self._doctor(clean_art,
                           lambda a: a["ticks"].__setitem__(
                               "dropped_gap", 7))
        assert any("feed accounting broken" in v for v in out)

    def test_impossible_serve_version_refused(self, clean_art):
        out = self._doctor(
            clean_art,
            lambda a: a["versions"].__setitem__(
                "serve_max", a["versions"]["ingest_final"] + 5))
        assert any("version reconciliation broken" in v for v in out)

    def test_unbalanced_serve_book_refused(self, clean_art):
        out = self._doctor(
            clean_art,
            lambda a: a["serve"]["requests"].__setitem__(
                "served", a["serve"]["requests"]["served"] + 1))
        assert any("request accounting broken" in v for v in out)

    def test_skew_counter_mismatch_refused(self, clean_art):
        out = self._doctor(
            clean_art,
            lambda a: a["versions"].__setitem__("skew_refusals", 3))
        assert any("skew_refusals" in v for v in out)

    def test_unknown_schema_version_refused(self, clean_art):
        out = self._doctor(
            clean_art, lambda a: a.__setitem__("schema_version", 99))
        assert any("unknown schema_version" in v for v in out)


def test_late_tick_on_final_bar_does_not_read_as_drift():
    """A tick of the LAST bar held late lands at the end-of-log flush as
    'applied' into an already-consumed bar — that must dirty the
    updaters like a merge, not surface as reconcile drift (the
    regression a first cut of the flush had)."""
    from csmom_tpu.chaos import inject
    from csmom_tpu.chaos.plan import Fault, FaultPlan

    cfg = ReplayConfig(run_id="t_lastlate", engine="stub",
                       profile="serve-smoke")
    total = cfg.n_assets * cfg.bars
    plan = FaultPlan("late-on-final-bar", seed=1, faults=(
        Fault(point="stream.tick", action="tick_late", after=total - 2,
              max_fires=1),
    ))
    saved = {k: os.environ.get(k) for k in (PLAN_ENV, "CSMOM_FAULT_STATE")}
    os.environ[PLAN_ENV] = plan.to_toml()
    os.environ.pop("CSMOM_FAULT_STATE", None)
    inject.reset()
    try:
        art = run_replay(cfg)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        inject.reset()
    assert inv.validate(art) == []
    assert art["reconcile"]["drift_events"] == 0
    assert art["reconcile"]["rebuilds"] >= 1
    assert art["ticks"]["offered"] == art["ticks"]["generated"]


def test_replay_sidecar_committable_rule():
    """Only round REPLAY artifacts may be committed (the TELEMETRY/SERVE
    rule, extended)."""
    assert inv.committable_sidecar("REPLAY_r12.json")
    assert not inv.committable_sidecar("REPLAY_smoke.json")
    assert not inv.committable_sidecar("REPLAY_rehearse_tick-storm.json")
    assert not inv.committable_sidecar("REPLAY_r12-999.json")


def test_replay_pattern_in_tier1_sweep_and_ledger():
    import inspect

    from csmom_tpu.obs import ledger

    sig = inspect.signature(inv.validate_tree)
    assert "REPLAY_*.json" in sig.parameters["patterns"].default
    assert "REPLAY_*.json" in ledger.DEFAULT_PATTERNS


def test_ledger_ingests_replay_rows(tmp_path, clean_art):
    from csmom_tpu.obs import ledger
    from csmom_tpu.serve.loadgen import write_artifact

    art = dict(clean_art, run_id="r99")
    write_artifact(str(tmp_path), art, prefix="REPLAY")
    led = ledger.load(str(tmp_path))
    metrics = {r.metric for r in led.rows}
    assert "replay_ticks_per_s" in metrics
    assert "replay_staleness_p99_ms" in metrics
    assert "replay_in_window_fresh_compiles" in metrics
    # smoke-bucket replays are flagged, never gate-eligible
    smoke_rows = [r for r in led.rows if r.metric == "replay_ticks_per_s"]
    assert smoke_rows and not any(r.gate_eligible() for r in smoke_rows)


def test_ledger_refuses_unknown_replay_schema(tmp_path, clean_art):
    from csmom_tpu.obs import ledger

    art = dict(clean_art, run_id="r98", schema_version=42)
    path = tmp_path / "REPLAY_r98.json"
    path.write_text(json.dumps(art))
    led = ledger.load(str(tmp_path))
    assert led.rows == []
    assert any("unknown replay schema_version" in p["note"]
               for p in led.problems)


def test_service_version_skew_gate_direct():
    """The serve-side gate in isolation: a stale panel_version is
    refused at the door and counted; a fresh one passes."""
    from csmom_tpu.serve.service import ServeConfig, SignalService

    svc = SignalService(ServeConfig(profile="serve-smoke", engine="stub",
                                    default_deadline_s=2.0)).start()
    try:
        live = {"v": 10}
        svc.attach_live_version(lambda: live["v"], max_skew=0)
        values = np.full((4, svc.spec.months), 100.0, np.float32)
        mask = np.ones_like(values, bool)
        stale = svc.submit("momentum", values, mask, panel_version=7)
        assert stale.state == "rejected"
        assert "skew" in (stale.error or "")
        fresh = svc.submit("momentum", values, mask, panel_version=10)
        fresh.wait(5.0)
        assert fresh.state == "served"
        assert fresh.panel_version == 10
        acct = svc.accounting()
        assert acct["rejected_version_skew"] == 1
        assert svc.invariant_violations() == []
    finally:
        svc.stop(drain=True)


def test_cli_replay_smoke_lands_valid_artifact(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=_REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop(PLAN_ENV, None)
    p = subprocess.run(
        [sys.executable, "-m", "csmom_tpu.cli.main", "replay", "--smoke",
         "--stub", "--chaos", "builtin", "--out-dir", str(tmp_path)],
        capture_output=True, text=True, timeout=120, env=env, cwd=_REPO)
    assert p.returncode == 0, p.stdout + p.stderr
    path = tmp_path / "REPLAY_smoke.json"
    assert path.exists()
    assert inv.validate_file(str(path)) == []
    assert "stale request(s) refused" in p.stdout


def test_manifest_stream_profiles_validate():
    """The stream reconcile entries bind against the live signal
    signatures and enumerate the canonical replay shapes."""
    from csmom_tpu.compile.manifest import build_manifest
    from csmom_tpu.stream.replay import REPLAY_BARS, REPLAY_SMOKE_BARS

    for profile, bars in (("stream", REPLAY_BARS),
                          ("stream-smoke", REPLAY_SMOKE_BARS)):
        entries = build_manifest(profile)
        assert entries, profile
        for e in entries:
            e.validate()
            assert f"x{bars}" in e.name
        kinds = {e.name.split(".")[1].split("@")[0] for e in entries}
        assert kinds == {"momentum", "turn_avg"}


def test_replay_default_capacity_wraps_the_ring(clean_art):
    """ISSUE 9 satellite: run_replay no longer masks the wrap-around
    reconcile defect by pinning capacity == bars — the default ring is
    smaller than the log, so every replay evicts, re-anchors, and must
    still report zero drift."""
    cfg = ReplayConfig()
    assert cfg.resolved_capacity() < cfg.bars
    panel = clean_art["panel"]
    assert panel["capacity"] < panel["bars_appended"]
    assert panel["evictions"] > 0
    rec = clean_art["reconcile"]
    assert rec["reanchors"] > 0, (
        "the window never slid past the prefix anchor — the wrap path "
        "went unexercised")
    assert rec["drift_events"] == 0


def test_replay_capacity_must_hold_a_serve_window():
    with pytest.raises(ValueError, match="capacity"):
        ReplayConfig(capacity=8).validate()
    # explicit capacity == bars restores the r12 non-evicting behavior
    cfg = ReplayConfig(capacity=ReplayConfig().bars)
    cfg.validate()
    assert cfg.resolved_capacity() == cfg.bars
