"""Residual momentum kernel vs a per-(asset, month) OLS loop oracle."""

import numpy as np
import pytest

from csmom_tpu.signals.residual import residual_momentum
from csmom_tpu.strategy import make_strategy, strategy_backtest


def _panel(rng, A=8, M=80, hole_frac=0.06):
    """Random-walk price panel with staggered listings and interior holes."""
    rets = rng.normal(0.005, 0.05, size=(A, M))
    prices = 100.0 * np.exp(np.cumsum(rets, axis=1))
    start = rng.integers(0, 6, size=A)
    mask = np.arange(M)[None, :] >= start[:, None]
    mask &= rng.random((A, M)) > hole_frac
    prices = np.where(mask, prices, np.nan)
    return prices, mask


def _oracle(prices, mask, lookback, skip, est_window, scale_by_vol):
    """Straight-line reimplementation: explicit returns, market mean, OLS
    per (asset, formation month), residual mean/std over the formation
    tail.  Mirrors the kernel's masked-month semantics (a missing month
    drops out of that asset's windows; full windows required)."""
    A, M = prices.shape
    r = np.full((A, M), np.nan)
    for i in range(A):
        for t in range(1, M):
            if mask[i, t] and mask[i, t - 1] and prices[i, t - 1] != 0:
                r[i, t] = prices[i, t] / prices[i, t - 1] - 1.0
    rv = np.isfinite(r)
    m = np.array([
        r[rv[:, t], t].mean() if rv[:, t].any() else np.nan for t in range(M)
    ])

    score = np.full((A, M), np.nan)
    for i in range(A):
        for t in range(M):
            tp = t - skip
            if tp < 0 or not mask[i, t]:
                continue
            ew = np.arange(tp - est_window + 1, tp + 1)
            fw = np.arange(tp - lookback + 1, tp + 1)
            if ew[0] < 0 or not rv[i, ew].all():
                continue
            X = np.stack([np.ones(est_window), m[ew]], axis=1)
            coef, *_ = np.linalg.lstsq(X, r[i, ew], rcond=None)
            a, b = coef
            e = r[i, fw] - a - b * m[fw]
            mu, sd = e.mean(), e.std()  # population std, matching var_e
            if scale_by_vol:
                if sd > 0:
                    score[i, t] = mu / sd
            else:
                score[i, t] = mu
    return score


@pytest.mark.parametrize("scale_by_vol", [True, False])
def test_matches_ols_loop_oracle(rng, scale_by_vol):
    prices, mask = _panel(rng)
    J, skip, W = 6, 1, 18
    score, valid = residual_momentum(
        prices, mask, lookback=J, skip=skip, est_window=W,
        scale_by_vol=scale_by_vol,
    )
    want = _oracle(prices, mask, J, skip, W, scale_by_vol)

    got = np.asarray(score)
    v = np.asarray(valid)
    assert v.any(), "no valid scores in the test panel"
    np.testing.assert_array_equal(v, np.isfinite(want))
    np.testing.assert_allclose(got[v], want[v], rtol=1e-8, atol=1e-12)
    assert np.isnan(got[~v]).all()


def test_warmup_and_validity(rng):
    """Warmup is est_window + skip + 1 months (1-indexed, like the momentum
    kernel's J+skip+1 — SURVEY 2.1.2): the return lost to differencing
    delays the first full window to index est_window, plus the skip.
    Degenerate regressions are masked out."""
    A, M, W, skip = 4, 60, 24, 1
    rets = rng.normal(0.0, 0.04, size=(A, M))
    prices = 100.0 * np.exp(np.cumsum(rets, axis=1))
    mask = np.ones((A, M), bool)
    _, valid = residual_momentum(prices, mask, lookback=6, skip=skip,
                                 est_window=W)
    v = np.asarray(valid)
    first = np.argmax(v.any(axis=0))
    assert first == W + skip  # 0-indexed == (W + skip + 1)-th month
    assert v[:, first:].all()

    # a flat market (zero variance) has no regression: nothing valid
    flat = np.full((A, M), 100.0)
    _, v2 = residual_momentum(flat, mask, lookback=6, est_window=W)
    assert not np.asarray(v2).any()


def test_est_window_guard():
    with pytest.raises(ValueError, match="est_window"):
        residual_momentum(np.ones((2, 40)), np.ones((2, 40), bool),
                          lookback=12, est_window=6)


@pytest.mark.slow
def test_plugin_runs_through_engine(rng):
    """The registered strategy runs the shared engine end-to-end and its
    spread differs from raw momentum's (it is a genuinely different sort)."""
    prices, mask = _panel(rng, A=12, M=90, hole_frac=0.0)
    s = make_strategy("residual_momentum", lookback=6, skip=1, est_window=18)
    res = strategy_backtest(prices, mask, s, n_bins=3)
    assert np.asarray(res.spread_valid).any()

    raw = strategy_backtest(
        prices, mask, make_strategy("momentum", lookback=6, skip=1), n_bins=3
    )
    both = np.asarray(res.spread_valid) & np.asarray(raw.spread_valid)
    assert both.any()
    assert not np.allclose(
        np.asarray(res.spread)[both], np.asarray(raw.spread)[both]
    )


def test_sweep_matches_per_cell_calls(rng):
    """Each (J, W) sweep cell is bit-identical to the static single call."""
    from csmom_tpu.signals.residual import residual_momentum_sweep

    prices, mask = _panel(rng, A=10, M=70)
    Js = np.array([3, 6])
    Ws = np.array([12, 18])
    scores, valid = residual_momentum_sweep(prices, mask, Js, Ws, skip=1)
    assert scores.shape == (2, 2, 10, 70)
    for i, J in enumerate(Js):
        for j, W in enumerate(Ws):
            s1, v1 = residual_momentum(prices, mask, lookback=int(J),
                                       skip=1, est_window=int(W))
            np.testing.assert_array_equal(np.asarray(valid)[i, j],
                                          np.asarray(v1))
            np.testing.assert_allclose(
                np.asarray(scores)[i, j][np.asarray(v1)],
                np.asarray(s1)[np.asarray(v1)], rtol=1e-12,
            )


def test_sweep_misconfigured_cell_is_invalid_not_fatal(rng):
    """A cell with est_window < lookback comes back all-invalid while the
    well-formed cells are untouched."""
    from csmom_tpu.signals.residual import residual_momentum_sweep

    prices, mask = _panel(rng, A=8, M=60)
    scores, valid = residual_momentum_sweep(
        prices, mask, np.array([6, 12]), np.array([9, 18]), skip=1
    )
    v = np.asarray(valid)
    assert not v[1, 0].any()   # J=12, W=9 < J: structurally invalid
    assert v[0, 0].any() and v[0, 1].any() and v[1, 1].any()


@pytest.mark.slow
def test_sweep_backtest_matches_strategy_engine(rng):
    """residual_sweep_backtest's per-cell spreads equal the strategy engine
    run at the same parameters."""
    from csmom_tpu.signals.residual import residual_sweep_backtest
    from csmom_tpu.strategy import ResidualMomentum

    prices, mask = _panel(rng, A=12, M=80, hole_frac=0.0)
    Js = np.array([3, 6])
    Ws = np.array([12, 18])
    grid = residual_sweep_backtest(prices, mask, Js, Ws, n_bins=3,
                                   mode="rank")
    for i, J in enumerate(Js):
        for j, W in enumerate(Ws):
            one = strategy_backtest(
                prices, mask,
                ResidualMomentum(lookback=int(J), skip=1, est_window=int(W)),
                n_bins=3, mode="rank",
            )
            np.testing.assert_array_equal(
                np.asarray(grid.spread_valid)[i, j],
                np.asarray(one.spread_valid),
            )
            v = np.asarray(one.spread_valid)
            np.testing.assert_allclose(
                np.asarray(grid.spreads)[i, j][v],
                np.asarray(one.spread)[v], rtol=1e-11,
            )
