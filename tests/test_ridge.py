"""Closed-form ridge + TimeSeriesSplit CV vs sklearn (the reference's stack)."""

import numpy as np
import pytest

sklearn = pytest.importorskip("sklearn")
from sklearn.linear_model import Ridge
from sklearn.metrics import mean_squared_error
from sklearn.model_selection import TimeSeriesSplit
from sklearn.preprocessing import StandardScaler

from csmom_tpu.models import ridge_time_series_cv


def reference_train(X, y, n_splits=3, alpha=1.0):
    """models.py:8-22 re-derived with sklearn."""
    scaler = StandardScaler()
    Xs = scaler.fit_transform(X)
    mses = []
    for tr, te in TimeSeriesSplit(n_splits=n_splits).split(Xs):
        m = Ridge(alpha=alpha).fit(Xs[tr], y[tr])
        mses.append(mean_squared_error(y[te], m.predict(Xs[te])))
    final = Ridge(alpha=alpha).fit(Xs, y)
    return final, scaler, mses


def _padded(rng, A=3, R=400, F=5, hole_frac=0.1):
    """Build a padded [A, R, F] tensor + the flat (asset-major) row view."""
    valid = rng.random((A, R)) > hole_frac
    valid[:, -1] = False
    X = rng.normal(size=(A, R, F)) * rng.uniform(0.5, 3, size=F)
    y = rng.normal(scale=1e-3, size=(A, R))
    X[~valid] = np.nan
    y[~valid] = np.nan
    flatX = X.reshape(-1, F)[valid.reshape(-1)]
    flaty = y.reshape(-1)[valid.reshape(-1)]
    return X, y, valid, flatX, flaty


def test_matches_sklearn_end_to_end(rng):
    X, y, valid, flatX, flaty = _padded(rng)
    n = len(flatX)
    split = int(n * 0.7)

    fit = ridge_time_series_cv(X, y, valid, n_splits=3, alpha=1.0)
    final, scaler, mses = reference_train(flatX[:split], flaty[:split])

    assert int(fit.n_train) == split
    np.testing.assert_allclose(np.asarray(fit.cv_mse), mses, rtol=1e-9)
    np.testing.assert_allclose(np.asarray(fit.scale_mean), scaler.mean_, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(fit.scale_std), scaler.scale_, rtol=1e-10)
    np.testing.assert_allclose(np.asarray(fit.coef), final.coef_, rtol=1e-8)
    assert abs(float(fit.intercept) - final.intercept_) < 1e-12

    # full-history scoring (incl. training span, run_demo.py:144-147)
    want_scores = final.predict(scaler.transform(flatX))
    got_scores = np.asarray(fit.scores).reshape(-1)[valid.reshape(-1)]
    np.testing.assert_allclose(got_scores, want_scores, rtol=1e-8, atol=1e-14)


def test_small_sample_uses_60_percent(rng):
    X, y, valid, flatX, _ = _padded(rng, A=1, R=90, hole_frac=0.0)
    # mark only 80 rows valid -> n <= 100 -> 60% train frac
    valid[:, 80:] = False
    X[:, 80:] = np.nan
    fit = ridge_time_series_cv(X, y, valid, n_splits=3)
    assert int(fit.n_train) == int(80 * 0.6)


def test_zero_variance_feature(rng):
    X, y, valid, _, _ = _padded(rng)
    X[..., 2] = 1.234  # constant feature -> sklearn scale_=1, coef ~ 0
    X[~valid] = np.nan
    fit = ridge_time_series_cv(X, y, valid)
    assert float(np.asarray(fit.scale_std)[2]) == 1.0
    assert np.isfinite(np.asarray(fit.coef)).all()
