"""Masked rolling kernels vs pandas rolling oracles (NaN-skipping semantics)."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.ops import rolling_sum, rolling_mean, rolling_std, rolling_count


@pytest.fixture
def noisy_panel(rng):
    x = rng.normal(size=(7, 200)) * 10
    # punch holes: leading NaNs, interior gaps
    holes = rng.random((7, 200)) < 0.15
    x[holes] = np.nan
    x[:, :3] = np.nan
    return x


def _pandas_roll(x, window, min_periods, op):
    out = np.empty_like(x)
    for i in range(x.shape[0]):
        s = pd.Series(x[i]).rolling(window, min_periods=min_periods)
        out[i] = getattr(s, op)().values
    return out


@pytest.mark.parametrize("window,min_periods", [(5, 1), (30, 1), (60, 2), (3, 3)])
def test_rolling_sum_mean(noisy_panel, window, min_periods):
    x = noisy_panel
    valid = np.isfinite(x)
    got_sum, _ = rolling_sum(x, valid, window, min_periods)
    got_mean, _ = rolling_mean(x, valid, window, min_periods)
    np.testing.assert_allclose(
        np.asarray(got_sum), _pandas_roll(x, window, min_periods, "sum"),
        rtol=1e-10, atol=1e-10,
    )
    np.testing.assert_allclose(
        np.asarray(got_mean), _pandas_roll(x, window, min_periods, "mean"),
        rtol=1e-10, atol=1e-10,
    )


@pytest.mark.parametrize("window,min_periods", [(5, 1), (60, 1), (10, 4)])
def test_rolling_std(noisy_panel, window, min_periods):
    x = noisy_panel
    valid = np.isfinite(x)
    got, _ = rolling_std(x, valid, window, min_periods)
    want = _pandas_roll(x, window, min_periods, "std")
    # pandas emits 0-count/1-count windows as NaN with ddof=1; ours must agree
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-8, atol=1e-10)


def test_rolling_std_large_magnitude(rng):
    """Volume-scale inputs (~1e8): the centered formulation must stay accurate."""
    x = rng.uniform(5e7, 2e8, size=(3, 500))
    valid = np.ones_like(x, dtype=bool)
    got, _ = rolling_std(x, valid, 60, 1)
    want = _pandas_roll(x, 60, 1, "std")
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)


def test_rolling_count(noisy_panel):
    valid = np.isfinite(noisy_panel)
    got = rolling_count(valid, 5)
    want = _pandas_roll(valid.astype(float), 5, 1, "sum")
    np.testing.assert_array_equal(np.asarray(got), want)
