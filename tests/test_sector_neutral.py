"""Sector-neutral ranking & backtest vs a pandas groupby-qcut oracle.

The oracle is what a pandas user would write for BASELINE config 3:
``df.groupby(['date', 'sector'])['mom'].transform(qcut)`` with the
reference's qcut semantics (duplicates='drop'), then pooled decile means.
"""

import pytest

import numpy as np
import pandas as pd

from csmom_tpu.backtest import monthly_spread_backtest, sector_neutral_backtest
from csmom_tpu.ops import sector_decile_assign, sector_decile_assign_panel

from tests.test_ranking import oracle_deciles


def oracle_sector_deciles(values, sector_ids, n_sectors, n=10):
    out = np.full(len(values), -1, dtype=int)
    for s in range(n_sectors):
        sel = (sector_ids == s) & np.isfinite(values)
        if not sel.any():
            continue
        sub = np.where(sel, values, np.nan)
        out[sel] = oracle_deciles(sub, n)[sel]
    return out


@pytest.mark.slow
def test_single_date_vs_oracle(rng):
    for trial in range(50):
        a = int(rng.integers(6, 60))
        n_sectors = int(rng.integers(1, 5))
        vals = rng.choice([np.nan, 0.0, 1.0, *rng.normal(size=6)], size=a)
        sectors = rng.integers(-1, n_sectors, size=a).astype(np.int32)
        valid = np.isfinite(vals)
        got, n_eff = sector_decile_assign(vals, valid, sectors, n_sectors)
        want = oracle_sector_deciles(
            np.where(sectors >= 0, vals, np.nan), sectors, n_sectors
        )
        np.testing.assert_array_equal(np.asarray(got), want)
        assert n_eff.shape == (n_sectors,)


def test_unclassified_assets_excluded(rng):
    vals = rng.normal(size=30)
    sectors = np.full(30, -1, dtype=np.int32)
    got, _ = sector_decile_assign(vals, np.isfinite(vals), sectors, 3)
    assert (np.asarray(got) == -1).all()


def test_one_sector_equals_plain_deciles(rng):
    """With a single sector covering everything, sector-neutral == plain."""
    vals = rng.normal(size=40)
    vals[rng.random(40) < 0.2] = np.nan
    valid = np.isfinite(vals)
    sectors = np.zeros(40, dtype=np.int32)
    got, _ = sector_decile_assign(vals, valid, sectors, 1)
    np.testing.assert_array_equal(np.asarray(got), oracle_deciles(vals))


def test_panel_shapes(rng):
    x = rng.normal(size=(24, 10))
    x[rng.random(x.shape) < 0.2] = np.nan
    valid = np.isfinite(x)
    sectors = rng.integers(0, 3, size=24).astype(np.int32)
    labels, n_eff = sector_decile_assign_panel(x, valid, sectors, 3, n_bins=5)
    assert labels.shape == (24, 10)
    assert n_eff.shape == (3, 10)
    for t in range(10):
        want = oracle_sector_deciles(x[:, t], sectors, 3, n=5)
        np.testing.assert_array_equal(np.asarray(labels[:, t]), want)


def _toy_prices(rng, a=30, m=40):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.005, 0.06, size=(a, m)), axis=1))
    prices[rng.random((a, m)) < 0.05] = np.nan
    return prices, np.isfinite(prices)


def test_backtest_one_sector_matches_plain(rng):
    prices, mask = _toy_prices(rng)
    sectors = np.zeros(prices.shape[0], dtype=np.int32)
    plain = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    neut = sector_neutral_backtest(prices, mask, sectors, 1, lookback=6, skip=1, n_bins=5)
    np.testing.assert_allclose(
        np.asarray(plain.spread)[np.asarray(plain.spread_valid)],
        np.asarray(neut.spread)[np.asarray(neut.spread_valid)],
        rtol=1e-12,
    )


def test_backtest_sector_neutral_oracle(rng):
    """Full sector-neutral spread vs a hand-rolled pandas-style oracle."""
    prices, mask = _toy_prices(rng, a=36, m=30)
    sectors = (np.arange(36) % 3).astype(np.int32)
    n_bins = 3
    res = sector_neutral_backtest(
        prices, mask, sectors, 3, lookback=4, skip=1, n_bins=n_bins
    )

    # oracle: monthly returns, momentum, per-sector qcut labels, pooled means
    from csmom_tpu.signals.momentum import momentum, monthly_returns

    ret, ret_valid = monthly_returns(prices, mask)
    mom, mom_valid = momentum(prices, mask, lookback=4, skip=1)
    ret, ret_valid = np.asarray(ret), np.asarray(ret_valid)
    mom, mom_valid = np.asarray(mom), np.asarray(mom_valid)
    A, M = prices.shape
    for t in range(M - 1):
        vals = np.where(mom_valid[:, t], mom[:, t], np.nan)
        labels = oracle_sector_deciles(vals, sectors, 3, n=n_bins)
        nxt_ok = ret_valid[:, t + 1] & (labels >= 0)
        top = nxt_ok & (labels == n_bins - 1)
        bot = nxt_ok & (labels == 0)
        if top.any() and bot.any():
            want = ret[top, t + 1].mean() - ret[bot, t + 1].mean()
            assert bool(np.asarray(res.spread_valid)[t])
            np.testing.assert_allclose(np.asarray(res.spread)[t], want, rtol=1e-10)
        else:
            assert not bool(np.asarray(res.spread_valid)[t])


def test_sector_neutrality_property(rng):
    """Long and short legs hold equal counts of each sector's local extreme
    bins when sectors are balanced and fully valid (no net sector tilt)."""
    a, m = 40, 24
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.05, size=(a, m)), axis=1))
    mask = np.isfinite(prices)
    sectors = (np.arange(a) % 4).astype(np.int32)
    res = sector_neutral_backtest(prices, mask, sectors, 4, lookback=3, skip=1, n_bins=2)
    labels = np.asarray(res.labels)
    for t in range(m):
        if not np.asarray(res.spread_valid)[t]:
            continue
        for s in range(4):
            in_s = sectors == s
            n_top = ((labels[:, t] == 1) & in_s).sum()
            n_bot = ((labels[:, t] == 0) & in_s).sum()
            assert abs(int(n_top) - int(n_bot)) <= 1
