"""Sequence parallelism: the time-sharded event engine equals the
single-device engine field-for-field on the virtual CPU mesh — 1D time
sharding, 2D (assets x time), empty leading blocks, and multi-block mark
carries."""

import numpy as np
import pytest

from csmom_tpu.backtest.event import event_backtest
from csmom_tpu.parallel.event_time import pad_time, time_sharded_event_backtest
from csmom_tpu.parallel.mesh import make_mesh

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


def _scenario(rng, A=6, T=120):
    price = 100 * np.exp(np.cumsum(rng.normal(0, 1e-3, size=(A, T)), axis=1))
    valid = rng.random((A, T)) > 0.2
    score = rng.normal(0, 1e-4, size=(A, T))
    score[np.abs(score) < 2e-5] = 0.0
    adv = np.linspace(5e4, 2e6, A)
    vol = np.linspace(0.01, 0.4, A)
    price[~valid] = np.nan
    return price, valid, score, adv, vol


def _assert_equal(got, ref):
    np.testing.assert_allclose(
        np.asarray(got.pnl), np.asarray(ref.pnl), rtol=1e-9, atol=1e-7
    )
    np.testing.assert_array_equal(np.asarray(got.bar_mask), np.asarray(ref.bar_mask))
    np.testing.assert_allclose(
        np.asarray(got.portfolio_value), np.asarray(ref.portfolio_value), rtol=1e-12
    )
    np.testing.assert_allclose(np.asarray(got.cash), np.asarray(ref.cash), rtol=1e-12)
    np.testing.assert_array_equal(np.asarray(got.positions), np.asarray(ref.positions))
    np.testing.assert_array_equal(np.asarray(got.trade_side), np.asarray(ref.trade_side))
    np.testing.assert_allclose(
        np.asarray(got.exec_price), np.asarray(ref.exec_price), rtol=1e-12
    )
    for f in ("total_pnl", "net_notional"):
        assert abs(float(getattr(got, f)) - float(getattr(ref, f))) < 1e-6
    for f in ("n_trades", "n_buys", "n_sells"):
        assert int(getattr(got, f)) == int(getattr(ref, f))


def test_time_sharded_matches_single_device(rng):
    price, valid, score, adv, vol = _scenario(rng)
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))  # 1 x 8
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol)
    _assert_equal(got, ref)


def test_2d_assets_x_time_mesh(rng):
    price, valid, score, adv, vol = _scenario(rng)
    mesh = make_mesh(grid_axis=2, axis_names=("assets", "time"))  # 2 x 4
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh, asset_axis="assets"
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol)
    _assert_equal(got, ref)


def test_cross_block_carries(rng):
    """Leading empty blocks (PV carry absent -> first bar PnL 0), an asset
    observed only in one early block (mark carried across many blocks), and
    an asset never observed (marks at 0)."""
    price, valid, score, adv, vol = _scenario(rng, A=4, T=80)
    valid[:, :20] = False           # blocks 0-1 of 8 globally empty
    valid[2, :] = False
    valid[2, 25:30] = True          # asset 2 lives only in block 2
    valid[3, :] = False             # asset 3 never observed
    price[~valid] = np.nan
    score[2, 25:30] = 5e-4          # force trades that must be marked later
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol)
    _assert_equal(got, ref)
    assert int(got.n_trades) > 0


def test_pad_time_roundtrip(rng):
    price, valid, score, adv, vol = _scenario(rng, A=4, T=75)
    pp, vp, sp, T0 = pad_time(price, valid, np.nan_to_num(score), 8)
    assert pp.shape[1] == 80 and T0 == 75
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))
    got = time_sharded_event_backtest(pp, vp, sp, adv, vol, mesh)
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol)
    np.testing.assert_allclose(
        np.asarray(got.pnl)[:T0], np.asarray(ref.pnl), rtol=1e-9, atol=1e-7
    )
    np.testing.assert_array_equal(
        np.asarray(got.positions)[:, :T0], np.asarray(ref.positions)
    )
    assert not np.asarray(got.bar_mask)[T0:].any()
    assert int(got.n_trades) == int(ref.n_trades)
    assert abs(float(got.total_pnl) - float(ref.total_pnl)) < 1e-6


def test_unsupported_modes_raise(rng):
    price, valid, score, adv, vol = _scenario(rng, A=4, T=80)
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))
    with pytest.raises(ValueError, match="latency_bars"):
        # block length is 80/8 = 10; a fill target would skip the halo
        time_sharded_event_backtest(
            price, valid, score, adv, vol, mesh, latency_bars=11
        )
    with pytest.raises(ValueError, match="fill_key"):
        time_sharded_event_backtest(
            price, valid, score, adv, vol, mesh, order_type="limit"
        )
    with pytest.raises(ValueError, match="order_type"):
        time_sharded_event_backtest(
            price, valid, score, adv, vol, mesh, order_type="iceberg"
        )
    with pytest.raises(ValueError):
        time_sharded_event_backtest(
            price[:, :77], valid[:, :77], score[:, :77], adv, vol, mesh
        )


@pytest.mark.parametrize("latency", [1, 3, 10])
def test_latency_matches_single_device(rng, latency):
    """Halo-exchange latency fills == single-device latency engine, for
    fills landing in-block, next-block (halo), and blocks-ahead
    (aggregated carry).  latency=10 == the block length (80/8), the
    supported bound."""
    price, valid, score, adv, vol = _scenario(rng, A=6, T=80)
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh, latency_bars=latency
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         latency_bars=latency)
    _assert_equal(got, ref)


def test_latency_sparse_assets_cross_many_blocks(rng):
    """Assets with whole empty blocks: fills must hop 2+ blocks via the
    aggregated all_gather path, or drop exactly when the single-device
    engine drops them."""
    price, valid, score, adv, vol = _scenario(rng, A=5, T=96)
    # asset 0: no events in blocks 3..6 (cols 36..84); asset 1: nothing
    # after col 30 (its late orders must drop unfilled)
    valid[0, 36:84] = False
    valid[1, 30:] = False
    price[~valid] = np.nan
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh, latency_bars=5
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         latency_bars=5)
    _assert_equal(got, ref)


def test_latency_2d_mesh(rng):
    price, valid, score, adv, vol = _scenario(rng, A=6, T=64)
    mesh = make_mesh(grid_axis=2, axis_names=("assets", "time"))  # 2 x 4
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh,
        asset_axis="assets", latency_bars=4,
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         latency_bars=4)
    _assert_equal(got, ref)


def test_limit_mode_time_sharded(rng):
    """Counter-keyed limit draws reproduce the single-device fills when the
    *time* axis is split (each block draws its global-bar counters)."""
    import jax

    price, valid, score, adv, vol = _scenario(rng, A=6, T=80)
    key = jax.random.PRNGKey(11)
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))  # 1 x 8
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh,
        order_type="limit", aggressiveness=0.4, fill_key=key,
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         order_type="limit", aggressiveness=0.4, fill_key=key)
    _assert_equal(got, ref)
    assert int(ref.n_trades) > 0


def test_limit_mode_padding_invariant(rng):
    """pad_time must not change limit fills on the original columns: draws
    are keyed by nested (asset, bar) folds, not an a*T+t counter whose
    stride would bake in the padded length."""
    import jax

    price, valid, score, adv, vol = _scenario(rng, A=5, T=75)
    key = jax.random.PRNGKey(11)
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         order_type="limit", fill_key=key)
    pp, vp, sp, T0 = pad_time(price, valid, np.nan_to_num(score), 8)
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))
    got = time_sharded_event_backtest(pp, vp, sp, adv, vol, mesh,
                                      order_type="limit", fill_key=key)
    assert T0 == 75
    np.testing.assert_array_equal(
        np.asarray(got.trade_side)[:, :T0], np.asarray(ref.trade_side)
    )
    np.testing.assert_array_equal(
        np.asarray(got.positions)[:, :T0], np.asarray(ref.positions)
    )
    assert int(got.n_trades) == int(ref.n_trades) > 0


def test_limit_mode_2d_mesh_with_latency(rng):
    """Limit filter + halo-exchange latency fills on the 2D (assets x time)
    layout == the single-device composition."""
    import jax

    price, valid, score, adv, vol = _scenario(rng, A=6, T=64)
    key = jax.random.PRNGKey(13)
    mesh = make_mesh(grid_axis=2, axis_names=("assets", "time"))  # 2 x 4
    got = time_sharded_event_backtest(
        price, valid, np.nan_to_num(score), adv, vol, mesh,
        asset_axis="assets", order_type="limit", fill_key=key, latency_bars=3,
    )
    ref = event_backtest(price, valid, np.nan_to_num(score), adv, vol,
                         order_type="limit", fill_key=key, latency_bars=3)
    _assert_equal(got, ref)


def test_hysteresis_time_sharded_matches_single(rng):
    """The Schmitt-trigger engine under time sharding: state entering each
    block is resolved from the per-event-type carries, and every field
    equals the single-device engine — including flips (±2-unit sides)
    crossing block boundaries."""
    from csmom_tpu.backtest import hysteresis_event_backtest
    from csmom_tpu.parallel.event_time import time_sharded_hysteresis_backtest

    price, valid, score, adv, vol = _scenario(rng, A=5, T=160)
    hi, lo = 1.2e-4, 4e-5
    ref = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=hi, threshold_lo=lo)
    mesh = make_mesh(grid_axis=1, axis_names=("assets", "time"))  # 1 x 8
    got = time_sharded_hysteresis_backtest(
        price, valid, score, adv, vol, mesh,
        threshold_hi=hi, threshold_lo=lo)
    _assert_equal(got, ref)
    # the scenario must actually exercise cross-block holds and a flip,
    # or this test proves nothing about the carries
    side = np.asarray(ref.trade_side)
    assert (np.abs(side) == 2).any(), "no flip in scenario — reseed"
    assert int(ref.n_trades) > 4


def test_hysteresis_2d_mesh_and_validation(rng):
    from csmom_tpu.backtest import hysteresis_event_backtest
    from csmom_tpu.parallel.event_time import time_sharded_hysteresis_backtest

    price, valid, score, adv, vol = _scenario(rng, A=6, T=120)
    mesh = make_mesh(grid_axis=2, axis_names=("assets", "time"))  # 2 x 4
    ref = hysteresis_event_backtest(price, valid, score, adv, vol,
                                    threshold_hi=1e-4, threshold_lo=2e-5)
    got = time_sharded_hysteresis_backtest(
        price, valid, score, adv, vol, mesh, asset_axis="assets",
        threshold_hi=1e-4, threshold_lo=2e-5)
    _assert_equal(got, ref)

    with pytest.raises(ValueError, match="must not exceed"):
        time_sharded_hysteresis_backtest(
            price, valid, score, adv, vol, mesh,
            threshold_hi=1e-5, threshold_lo=1e-4)
