"""The serve subsystem: admission, coalescing, degradation, acceptance.

Three layers of coverage, mirroring how the subsystem is built:

- plumbing (stub engine, no jax compiles): queue backpressure with a
  retry-after hint, expiry-while-queued never dispatching, worker-crash
  termination + drainability, bucket padding, loadgen determinism — all
  under the accounting invariant served + rejected + expired == admitted;
- contracts: the ``serve`` artifact kind in chaos.invariants (closed
  schema, balanced books), the SERVE committable-name rule, ledger
  ingestion of serve rows and their gate eligibility;
- acceptance (ISSUE 5): ``csmom loadgen --smoke`` against the in-process
  service with the REAL jax engine on CPU — schema-valid SERVE artifact,
  p50/p95/p99 + batch histogram present, and
  ``in_window_fresh_compiles == 0`` (every dispatch hit a warmed bucket).
"""

import json
import os

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.registry import serve_endpoints
from csmom_tpu.serve.buckets import bucket_spec

# the registry-era endpoint set (the old buckets.ENDPOINTS literal)
ENDPOINTS = serve_endpoints()
from csmom_tpu.serve.queue import AdmissionQueue, Request
from csmom_tpu.serve.service import ServeConfig, SignalService
from csmom_tpu.utils.deadline import mono_now_s

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _stub_service(**over) -> SignalService:
    kw = dict(profile="serve-smoke", engine="stub", max_wait_s=0.005)
    kw.update(over)
    return SignalService(ServeConfig(**kw)).start()


def _panel(n_assets: int, months: int, seed: int = 0):
    r = np.random.default_rng(seed)
    v = 100.0 * np.exp(np.cumsum(r.normal(0, 0.03, (n_assets, months)),
                                 axis=1)).astype(np.float32)
    return v, np.ones((n_assets, months), bool)


def _accounting_closed(svc: SignalService):
    assert svc.invariant_violations() == [], svc.accounting()


# ------------------------------------------------------------- plumbing ----

def test_served_request_roundtrip_and_accounting():
    svc = _stub_service()
    months = svc.spec.months
    reqs = [svc.submit(k, *_panel(5, months, i))
            for i, k in enumerate(ENDPOINTS)]
    for r in reqs:
        assert r.wait(5.0), r.state
        assert r.state == "served", (r.state, r.error)
    mom = reqs[0].result
    assert mom.shape == (5,)  # unpadded: exactly the request's assets
    assert set(reqs[2].result) == {"mean_spread", "ann_sharpe"}
    # the registry-shipped strategy endpoints serve per-asset vectors too
    assert reqs[ENDPOINTS.index("low_volatility")].result.shape == (5,)
    assert reqs[ENDPOINTS.index("zscore_combo")].result.shape == (5,)
    svc.stop()
    _accounting_closed(svc)
    a = svc.accounting()
    assert (a["admitted"], a["served"]) == (len(ENDPOINTS), len(ENDPOINTS))


def test_queue_full_rejects_with_retry_after_hint():
    # no worker: submissions pile into the bounded queue untouched
    q = AdmissionQueue(capacity=3)
    months = 24

    def mk():
        v, m = _panel(2, months)
        return Request(kind="momentum", values=v, mask=m, n_assets=2)

    admitted = [q.submit(mk()) for _ in range(3)]
    assert all(r.state == "queued" for r in admitted)
    r = q.submit(mk())
    assert r.state == "rejected"
    assert r.retry_after_s is not None and r.retry_after_s > 0, (
        "a queue-full rejection must carry an actionable retry-after hint")
    assert "retry after" in (r.error or "")
    a = q.accounting()
    assert a["admitted"] == 4 and a["rejected_queue_full"] == 1


def test_retry_after_cold_start_is_bounded():
    """ISSUE 6 satellite: before ANYTHING has been served the EMA drain
    rate is undefined — the very first overload rejection must still
    carry a bounded float hint (never None), and however pathological
    the drain estimate gets, the hint is capped."""
    from csmom_tpu.serve.queue import (
        RETRY_AFTER_MAX_S,
        RETRY_AFTER_MIN_S,
    )

    months = 24

    def mk():
        v, m = _panel(2, months)
        return Request(kind="momentum", values=v, mask=m, n_assets=2)

    # cold queue (nothing ever served): fill to capacity, then reject
    q = AdmissionQueue(capacity=2)
    for _ in range(2):
        q.submit(mk())
    r = q.submit(mk())
    assert r.state == "rejected"
    assert isinstance(r.retry_after_s, float), (
        f"cold-start retry-after must be a float, got {r.retry_after_s!r}")
    assert RETRY_AFTER_MIN_S <= r.retry_after_s <= RETRY_AFTER_MAX_S
    # degenerate EMA (0.0 is falsy): still bounded, still a float
    q2 = AdmissionQueue(capacity=1)
    q2._ema_per_req_s = 0.0
    q2.submit(mk())
    r2 = q2.submit(mk())
    assert RETRY_AFTER_MIN_S <= r2.retry_after_s <= RETRY_AFTER_MAX_S
    # pathological drain estimate: the cap holds (a bounded queue never
    # advises a retry further out than RETRY_AFTER_MAX_S)
    q3 = AdmissionQueue(capacity=64)
    q3._ema_per_req_s = 30.0
    for _ in range(64):
        q3.submit(mk())
    r3 = q3.submit(mk())
    assert r3.retry_after_s == RETRY_AFTER_MAX_S


def test_expired_while_queued_is_never_dispatched():
    svc = _stub_service()
    months = svc.spec.months
    v, m = _panel(3, months)
    # deadline strictly in the past: the collect pass must cancel it
    # before any batch can include it
    r = svc.submit("momentum", v, m, deadline_s=-0.001)
    assert r.wait(5.0)
    assert r.state == "expired"
    assert r.t_dispatch_s is None, "an expired request was dispatched"
    svc.stop()
    _accounting_closed(svc)
    a = svc.accounting()
    assert a["expired"] == 1 and a["expired_dispatched"] == 0


def test_unserveable_requests_reject_at_the_door():
    svc = _stub_service()
    months = svc.spec.months
    v, m = _panel(svc.spec.max_assets + 1, months)   # oversize universe
    r1 = svc.submit("momentum", v, m)
    r2 = svc.submit("nope", *_panel(2, months))      # unknown endpoint
    r3 = svc.submit("momentum", *_panel(2, months + 1))  # wrong months
    for r in (r1, r2, r3):
        assert r.state == "rejected", (r.state, r.error)
        assert r.error
    svc.stop()
    _accounting_closed(svc)
    assert svc.accounting()["rejected_unserveable"] == 3


def test_worker_crash_mid_batch_rejects_batch_and_queue_drains(
        tmp_path, monkeypatch):
    from csmom_tpu.chaos import inject
    from csmom_tpu.chaos.plan import Fault, FaultPlan

    plan = FaultPlan("crash", seed=1, faults=(
        Fault(point="serve.dispatch", action="fail", after=0, max_fires=1),
    ))
    p = tmp_path / "plan.toml"
    p.write_text(plan.to_toml())
    monkeypatch.setenv("CSMOM_FAULT_PLAN", str(p))
    monkeypatch.setenv("CSMOM_FAULT_STATE", str(tmp_path / "state"))
    inject.reset()
    try:
        svc = _stub_service()
        months = svc.spec.months
        first = svc.submit("momentum", *_panel(3, months), deadline_s=5.0)
        assert first.wait(5.0)
        assert first.state == "rejected"
        assert "worker crashed mid-batch" in (first.error or "")
        # the crash consumed the fault: the queue must still drain
        second = svc.submit("momentum", *_panel(3, months), deadline_s=5.0)
        assert second.wait(5.0)
        assert second.state == "served", (second.state, second.error)
        svc.stop()
        _accounting_closed(svc)
        a = svc.accounting()
        assert a["rejected_worker_crash"] == 1 and a["served"] == 1
    finally:
        inject.reset()


def test_idle_service_stops_promptly_without_leaking_the_worker():
    """Code-review regression (lost wakeup): an IDLE worker blocks on an
    untimed condition wait; stop() must wake it deterministically — no
    30 s join timeout, no leaked daemon thread."""
    svc = _stub_service()
    t0 = mono_now_s()
    svc.stop(timeout_s=5.0)
    assert mono_now_s() - t0 < 2.0, "stop() stalled on an idle worker"
    assert not svc._worker.is_alive(), "worker thread leaked past stop()"


def test_malformed_mask_cannot_kill_the_worker():
    """Code-review regression: a mask whose shape disagrees with the
    values panel must reject at the door; and even a request that
    somehow reaches the batcher malformed terminates rejected (padding
    failure is contained) instead of killing the worker thread with the
    request stuck 'queued' forever."""
    svc = _stub_service()
    months = svc.spec.months
    v, _ = _panel(5, months)
    r = svc.submit("momentum", v, np.ones(5, bool))   # 1-D mask
    assert r.state == "rejected" and "mask shape" in (r.error or "")
    # smuggle a malformed request past the door straight into the queue:
    # the pad containment must terminate it and keep the worker alive
    bad = Request(kind="momentum", values=v, mask=np.ones((5,), bool),
                  n_assets=5, deadline_s=None)
    svc.queue.submit(bad)
    assert bad.wait(5.0), "pad containment failed: request never terminal"
    assert bad.state == "rejected" and "could not pad" in (bad.error or "")
    after = svc.submit("momentum", *_panel(3, months), deadline_s=5.0)
    assert after.wait(5.0) and after.state == "served", (
        "worker did not survive the malformed batch")
    svc.stop()
    _accounting_closed(svc)


def test_percentiles_are_nearest_rank():
    from csmom_tpu.serve.loadgen import _percentiles

    # N=2: p50 is the FIRST sample under nearest-rank (ceil(0.5*2)-1 = 0)
    assert _percentiles([0.001, 0.100])["p50"] == 1.0
    # N=100: p99 is the 99th value, not the maximum
    s = [i / 1000.0 for i in range(1, 101)]
    got = _percentiles(s)
    assert got["p99"] == 99.0 and got["p50"] == 50.0 and got["p95"] == 95.0
    assert _percentiles([])["p99"] is None


def test_batcher_pads_to_nearest_bucket():
    from csmom_tpu.serve.batcher import Batcher

    spec = bucket_spec("serve")
    b = Batcher(spec)
    months = spec.months

    def req(n):
        v, m = _panel(n, months)
        return Request(kind="momentum", values=v, mask=m, n_assets=n)

    mb = b.pad([req(3), req(40)])
    assert (mb.batch_bucket, mb.asset_bucket) == (4, 128)
    assert mb.values.shape == (4, 128, months)
    assert mb.values.dtype == np.float32
    # padded lanes are masked out
    assert not mb.mask[0, 3:].any() and not mb.mask[2:].any()
    assert 0.0 < mb.pad_fraction < 1.0
    # every padded dispatch shape is in the closed manifest world
    assert (mb.batch_bucket, mb.asset_bucket, months) in spec.shapes()


def test_bucket_spec_selection_rules():
    spec = bucket_spec("serve")
    assert spec.asset_bucket_for(1) == 32
    assert spec.asset_bucket_for(32) == 32
    assert spec.asset_bucket_for(33) == 128
    assert spec.asset_bucket_for(129) is None
    assert spec.batch_bucket_for(1) == 1
    assert spec.batch_bucket_for(5) == 8
    with pytest.raises(ValueError, match="unknown serve bucket profile"):
        bucket_spec("nope")


def test_priorities_interactive_dispatches_first():
    # stall the worker behind a long coalescing window so both classes
    # queue, then check dispatch order through t_dispatch_s
    svc = _stub_service(max_wait_s=0.15)
    months = svc.spec.months
    batch = svc.submit("momentum", *_panel(2, months), priority="batch",
                       deadline_s=5.0)
    inter = svc.submit("momentum", *_panel(2, months),
                       priority="interactive", deadline_s=5.0)
    assert batch.wait(5.0) and inter.wait(5.0)
    assert batch.state == inter.state == "served"
    # same batch or interactive first — never interactive behind batch
    assert inter.t_dispatch_s <= batch.t_dispatch_s
    svc.stop()
    _accounting_closed(svc)


# -------------------------------------------------------------- loadgen ----

def test_loadgen_is_deterministic_per_seed():
    import random

    from csmom_tpu.serve.loadgen import arrival_offsets, parse_schedule

    segs = parse_schedule("1x50,0.5x200")
    a = arrival_offsets(segs, random.Random(7))
    b = arrival_offsets(segs, random.Random(7))
    c = arrival_offsets(segs, random.Random(8))
    assert a == b, "same seed must replay the same arrival stream"
    assert a != c
    assert all(t0 <= t1 for t0, t1 in zip(a, a[1:]))
    assert a[-1] < 1.5
    with pytest.raises(ValueError, match="bad schedule segment"):
        parse_schedule("2q25")


def test_loadgen_artifact_validates_and_accounts(tmp_path):
    from csmom_tpu.serve.loadgen import LoadConfig, run_loadgen, write_artifact

    svc = _stub_service()
    art = run_loadgen(svc, LoadConfig(schedule="0.3x80", seed=5,
                                      run_id="rehearse_unit"))
    assert inv.detect_kind(art) == "serve"
    assert inv.validate(art) == []
    req = art["requests"]
    assert req["served"] + req["rejected"] + req["expired"] == req["admitted"]
    assert req["admitted"] > 0
    path = write_artifact(str(tmp_path), art)
    assert os.path.basename(path) == "SERVE_rehearse_unit.json"
    assert inv.validate_file(path) == []


def test_serve_validator_rejects_broken_books_and_unknown_schema():
    base = {
        "kind": "serve", "schema_version": 1, "run_id": "x",
        "metric": "serve_throughput_rps", "value": 1.0, "unit": "req/s",
        "vs_baseline": 1.0, "wall_s": 1.0,
        "requests": {"admitted": 3, "served": 2, "rejected": 1,
                     "expired": 0, "expired_dispatched": 0},
        "latency_ms": {
            "queue": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "service": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "total": {"p50": 2.0, "p95": 4.0, "p99": 6.0},
        },
        "batches": {"count": 2, "size_hist": {"1": 2}, "mean_size": 1.0,
                    "pad_fraction": 0.0},
    }
    assert inv.validate(base) == []
    bad = json.loads(json.dumps(base))
    bad["requests"]["served"] = 3
    assert any("accounting broken" in v for v in inv.validate(bad))
    bad = json.loads(json.dumps(base))
    bad["requests"]["expired_dispatched"] = 1
    assert any("never be dispatched" in v or "never" in v
               for v in inv.validate(bad))
    bad = json.loads(json.dumps(base))
    bad["schema_version"] = 99
    assert any("unknown schema_version" in v for v in inv.validate(bad))
    bad = json.loads(json.dumps(base))
    bad["latency_ms"]["total"]["p95"] = 99.0
    assert any("non-decreasing" in v for v in inv.validate(bad))
    bad = json.loads(json.dumps(base))
    bad["batches"]["size_hist"] = {"1": 1}
    assert any("size_hist" in v for v in inv.validate(bad))


# --------------------------------------------------------------- ledger ----

def _artifact(run_id, value=50.0, p99=20.0, smoke=False):
    extra = {"platform": "cpu", "engine": "jax", "workload": "w"}
    if smoke:
        extra["smoke"] = "smoke run"
    return {
        "kind": "serve", "schema_version": 1, "run_id": run_id,
        "metric": "serve_throughput_rps", "value": value, "unit": "req/s",
        "vs_baseline": 1.0, "wall_s": 1.0,
        "requests": {"admitted": 10, "served": 10, "rejected": 0,
                     "expired": 0, "expired_dispatched": 0},
        "latency_ms": {
            "queue": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "service": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "total": {"p50": 5.0, "p95": 10.0, "p99": p99},
        },
        "batches": {"count": 5, "size_hist": {"2": 5}, "mean_size": 2.0,
                    "pad_fraction": 0.1},
        "compile": {"in_window_fresh_compiles": 0},
        "extra": extra,
    }


def test_ledger_ingests_serve_rows(tmp_path):
    from csmom_tpu.obs import ledger as ld

    for run, val, p99 in (("r01", 40.0, 30.0), ("r02", 50.0, 20.0)):
        with open(tmp_path / f"SERVE_{run}.json", "w") as f:
            json.dump(_artifact(run, val, p99), f)
    # a smoke run stays visible but never gates
    with open(tmp_path / "SERVE_r02_smoke.json", "w") as f:
        json.dump(_artifact("r02", 99.0, 1.0, smoke=True), f)
    L = ld.load(str(tmp_path))
    metrics = {r.metric for r in L.rows}
    assert {"serve_throughput_rps", "serve_p50_ms", "serve_p95_ms",
            "serve_p99_ms", "serve_in_window_fresh_compiles"} <= metrics
    thr = [r for r in L.rows if r.metric == "serve_throughput_rps"]
    assert {r.run for r in thr} == {"r01", "r02"}
    live = [r for r in thr if r.gate_eligible()]
    assert len(live) == 2 and all(r.platform == "cpu" for r in live)
    flagged = [r for r in thr if not r.gate_eligible()]
    assert len(flagged) == 1 and "smoke" in flagged[0].flags
    p99s = [r for r in L.rows
            if r.metric == "serve_p99_ms" and r.gate_eligible()]
    assert [r.value for r in sorted(p99s, key=lambda r: r.run_num)] == [
        30.0, 20.0]


def test_ledger_refuses_unknown_serve_schema(tmp_path):
    from csmom_tpu.obs import ledger as ld

    art = _artifact("r03")
    art["schema_version"] = 42
    with open(tmp_path / "SERVE_r03.json", "w") as f:
        json.dump(art, f)
    L = ld.load(str(tmp_path))
    assert L.rows == []
    assert any("unknown serve schema_version" in p["note"]
               for p in L.problems)


def test_serve_manifest_profile_covers_every_bucket_shape():
    """The manifest's serve profiles enumerate exactly the closed shape
    world the batcher can produce — endpoint x batch bucket x asset
    bucket — bound against the live jitted signatures."""
    from csmom_tpu.compile.manifest import build_manifest

    for profile in ("serve", "serve-smoke"):
        spec = bucket_spec(profile)
        entries = build_manifest(profile)
        assert len(entries) == len(ENDPOINTS) * len(spec.shapes())
        names = [e.name for e in entries]
        assert len(set(names)) == len(names)
        for e in entries:
            e.validate()
            assert e.args[0].shape[2] == spec.months


# ------------------------------------------------------------------ cli ----

def test_cli_epilog_is_generated_from_the_registry():
    """ISSUE 5 small fix: the subcommand table is generated from the live
    subparser registry, so it CANNOT drift — every registered subcommand
    (serve and loadgen included) appears, and the advertised count is the
    registry's size."""
    import argparse
    import re

    from csmom_tpu.cli.main import build_parser

    p = build_parser()
    sub = next(a for a in p._actions
               if isinstance(a, argparse._SubParsersAction))
    names = set(sub.choices)
    assert {"serve", "loadgen", "rehearse", "ledger", "warmup"} <= names
    epilog = p.epilog or ""
    m = re.match(r"subcommands \((\d+)\):", epilog)
    assert m, f"epilog not generated: {epilog[:80]!r}"
    assert int(m.group(1)) == len(names)
    for n in names:
        assert re.search(rf"^  {re.escape(n)}\b", epilog, re.M), (
            f"subcommand {n} missing from the generated epilog")
    # and it actually reaches --help output
    assert "subcommands (" in p.format_help()


# ----------------------------------------------------------- acceptance ----

def test_loadgen_smoke_acceptance(tmp_path, monkeypatch):
    """ISSUE 5 acceptance: `csmom loadgen --smoke` against the in-process
    service on CPU — schema-valid SERVE artifact, latency percentiles +
    batch histogram present, request accounting closed, and ZERO
    in-window fresh compiles (every dispatch hit a warmed bucket)."""
    from csmom_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    rc = main(["loadgen", "--smoke", "--seed", "3"])
    assert rc == 0
    path = tmp_path / "SERVE_smoke.json"
    assert path.exists()
    assert inv.validate_file(str(path)) == []
    art = json.loads(path.read_text())
    assert art["compile"]["in_window_fresh_compiles"] == 0, (
        "a dispatch compiled inside the serving window — the bucket "
        "padding/warmup contract broke")
    req = art["requests"]
    assert req["admitted"] > 0
    assert req["served"] + req["rejected"] + req["expired"] == req["admitted"]
    assert req["expired_dispatched"] == 0
    lat = art["latency_ms"]
    for leg in ("queue", "service", "total"):
        for q in ("p50", "p95", "p99"):
            assert isinstance(lat[leg][q], (int, float)), (leg, q, lat)
    assert sum(art["batches"]["size_hist"].values()) == art["batches"]["count"]
    assert art["extra"]["platform"] == "cpu"
    # smoke runs are flagged: visible in the ledger, never gate-eligible
    assert "smoke" in art["extra"]


def test_committed_serve_artifacts_validate():
    import glob

    for p in sorted(glob.glob(os.path.join(_REPO, "SERVE_*.json"))):
        base = os.path.basename(p)
        if not inv.committable_sidecar(base):
            continue  # scratch files regenerated by local runs
        assert inv.validate_file(p) == [], (base, inv.validate_file(p))
