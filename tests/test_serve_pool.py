"""The multi-process serving tier: protocol, health, router, supervisor.

Coverage mirrors the tier's layers (ISSUE 6):

- wire protocol: framed JSON + raw arrays round-trip; malformed frames
  are refused, never half-parsed into a panel;
- health: the AOT cache version token, the cold-cache honesty check
  (scratch cache dir -> not ready, with the `csmom warmup` pointer), and
  the worker's version-skew refusal (exit code + pointed message);
- degradation paths: supervisor backoff CAPS (a crash-looping worker is
  parked, not hot-spun), hedged duplicate suppression (exactly one
  terminal state when both workers answer), and drain-on-stop across
  processes (no request stranded in a worker queue at shutdown);
- contracts: the ``serve_pool`` artifact kind (closed cross-process
  books, hedge arithmetic, availability reconciliation), its committable
  name rule, and ledger ingestion of the pool metric rows.

Everything here runs stub-engine workers (no jax in any spawned
process); the real-engine pool evidence is the committed
``SERVE_POOL_r11.json``, validated at the bottom like every artifact.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.serve import health, proto
from csmom_tpu.serve.router import Router, RouterConfig
from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMOKE_POOL = dict(profile="serve-smoke", engine="stub",
                   ready_timeout_s=30.0, poll_interval_s=0.05)


def _panel(n_assets: int, months: int, seed: int = 0):
    r = np.random.default_rng(seed)
    v = 100.0 * np.exp(np.cumsum(r.normal(0, 0.03, (n_assets, months)),
                                 axis=1)).astype(np.float32)
    return v, np.ones((n_assets, months), bool)


# ------------------------------------------------------------- protocol ----

def test_proto_roundtrips_json_and_arrays():
    a, b = socket.socketpair()
    try:
        values = np.arange(12, dtype=np.float32).reshape(3, 4)
        mask = values > 4
        proto.send_msg(a, {"op": "score", "kind": "momentum"},
                       {"values": values, "mask": mask})
        obj, arrays = proto.recv_msg(b)
        assert obj == {"op": "score", "kind": "momentum"}
        np.testing.assert_array_equal(arrays["values"], values)
        np.testing.assert_array_equal(arrays["mask"], mask)
        assert arrays["values"].dtype == np.float32
    finally:
        a.close()
        b.close()


def test_proto_refuses_malformed_frames():
    import struct

    a, b = socket.socketpair()
    try:
        # a garbage length prefix larger than the bound must be refused
        # before any allocation, not best-effort read
        a.sendall(struct.pack("!I", proto.MAX_FRAME_BYTES + 1))
        with pytest.raises(proto.ProtocolError, match="MAX_FRAME_BYTES"):
            proto.recv_msg(b)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        # an array spec whose byte count disagrees with its shape must
        # refuse the frame — half a panel never scores
        hdr = json.dumps({"op": "score", "_arrays": [
            {"name": "values", "dtype": "float32", "shape": [2, 2],
             "nbytes": 999}]}).encode()
        payload = struct.pack("!I", len(hdr)) + hdr + b"\x00" * 16
        a.sendall(struct.pack("!I", len(payload)) + payload)
        with pytest.raises(proto.ProtocolError, match="inconsistent"):
            proto.recv_msg(b)
    finally:
        a.close()
        b.close()


# --------------------------------------------------------------- health ----

def test_cache_version_fingerprints_the_compiled_world():
    v1 = health.aot_cache_version("serve")
    assert v1 == health.aot_cache_version("serve"), "must be deterministic"
    assert v1 != health.aot_cache_version("serve-smoke"), (
        "a different bucket grid is a different compiled world")
    assert v1 != health.aot_cache_version("serve", lookback=6), (
        "different engine params compile different HLO")


def test_expected_entry_names_match_the_manifest_scheme():
    from csmom_tpu.registry import serve_endpoints

    names = health.expected_entry_names("serve-smoke")
    # every REGISTERED endpoint x 2 batch buckets x 1 asset bucket —
    # sized by the registry, so a newly registered endpoint widens the
    # warm contract automatically (ISSUE 9)
    assert len(names) == len(serve_endpoints()) * 2
    assert "serve.momentum.b1@8x24" in names
    assert "serve.low_volatility.b1@8x24" in names
    assert "serve.zscore_combo.b4@8x24" in names


def test_cache_readiness_cold_dir_points_at_warmup(tmp_path, monkeypatch):
    monkeypatch.setenv("CSMOM_JIT_CACHE", str(tmp_path / "scratch"))
    ready, reason = health.cache_readiness("serve")
    assert not ready
    assert "csmom warmup --profiles serve" in reason


def test_cache_readiness_disabled_cache_is_not_ready(monkeypatch):
    monkeypatch.setenv("CSMOM_JIT_CACHE", "0")
    ready, reason = health.cache_readiness("serve")
    assert not ready and "CSMOM_JIT_CACHE=0" in reason


def test_cold_cache_makes_csmom_serve_exit_nonzero(tmp_path, monkeypatch,
                                                   capsys):
    """ISSUE 6 satellite: `csmom serve` with the jax engine and a
    scratch (cold) cache dir must exit nonzero with the warmup pointer
    BEFORE any warm — not silently compile inside the ready probe."""
    from csmom_tpu.cli.main import main

    monkeypatch.setenv("CSMOM_JIT_CACHE", str(tmp_path / "scratch"))
    rc = main(["serve", "--duration", "0.1"])
    assert rc == 3
    err = capsys.readouterr().err
    assert "csmom warmup --profiles serve" in err
    assert "NOT READY" in err


def test_worker_refuses_version_skew_with_pointed_message(tmp_path):
    """The deploy-skew gate, at the worker itself: a mismatched
    --expect-cache-version exits RC_VERSION_SKEW naming the skew and the
    remedy, before any warm/compile."""
    from csmom_tpu.serve.worker import RC_VERSION_SKEW

    p = subprocess.run(
        [sys.executable, "-m", "csmom_tpu.serve.worker",
         "--socket", str(tmp_path / "w.sock"), "--engine", "stub",
         "--profile", "serve-smoke",
         "--expect-cache-version", "deadbeef0000"],
        capture_output=True, text=True, timeout=60, cwd=_REPO,
    )
    assert p.returncode == RC_VERSION_SKEW, p.stderr
    assert "skew" in p.stderr
    assert "csmom warmup" in p.stderr


# ------------------------------------------------- supervisor degradation ---

def test_supervisor_backoff_caps_a_crash_looping_worker(tmp_path,
                                                        monkeypatch):
    """ISSUE 6 satellite: a worker that dies at every spawn is restarted
    with growing backoff and PARKED after max_restarts — the supervisor
    must not hot-spin a broken binary."""
    monkeypatch.setenv("CSMOM_SERVE_WORKER_FAULT", "exit:1")
    cfg = PoolConfig(n_workers=1, backoff_base_s=0.02, backoff_cap_s=0.2,
                     max_restarts=2, min_uptime_s=5.0, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path))
    sup.start(require_ready=False)
    try:
        h = sup.handles[0]
        deadline = time.monotonic() + 20.0
        while h.state != "failed" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert h.state == "failed", (h.state, h.reason)
        assert "crash loop" in (h.reason or "")
        events = sup.summary()["events"]
        spawns = [e for e in events if e["event"] == "spawn"]
        # initial spawn + exactly max_restarts restarts, then parked
        assert len(spawns) == 1 + cfg.max_restarts, events
        scheduled = [e for e in events if e["event"] == "restart_scheduled"]
        bases = [e["backoff_base_s"] for e in scheduled]
        assert bases == sorted(bases) and len(bases) == cfg.max_restarts, (
            "backoff must grow monotonically up to the park")
        assert any(e["event"] == "crash_loop_parked" for e in events)
    finally:
        sup.stop()


class _FakeWorker:
    """A hand-rolled protocol speaker: answers ready/score with a
    configurable delay — the controllable peer the hedging tests need
    (a real worker's timing is the thing under test, not controllable)."""

    def __init__(self, tmp, worker_id: str, delay_s: float):
        self.worker_id = worker_id
        self.socket_path = os.path.join(tmp, f"{worker_id}.sock")
        self.delay_s = delay_s
        self.scores = 0
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.socket_path)
        self._srv.listen(8)
        self._srv.settimeout(0.1)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            obj, arrays = proto.recv_msg(conn)
            if obj.get("op") == "score":
                self.scores += 1
                time.sleep(self.delay_s)
                n = arrays["values"].shape[0]
                proto.send_msg(conn, {"state": "served",
                                      "worker_id": self.worker_id},
                               {"result": np.zeros(n, np.float32)})
            else:
                proto.send_msg(conn, {"ok": True,
                                      "worker_id": self.worker_id})
        except (OSError, proto.ProtocolError):
            pass
        finally:
            conn.close()

    def close(self):
        self._stop.set()
        self._srv.close()


def test_hedged_duplicate_suppression_exactly_one_terminal(tmp_path):
    """ISSUE 6 satellite: slow primary, fast hedge — BOTH answer, the
    request reaches exactly one terminal state, and the loser is counted
    duplicates_suppressed (never double-served, never lost)."""
    slow = _FakeWorker(str(tmp_path), "slow", delay_s=0.8)
    fast = _FakeWorker(str(tmp_path), "fast", delay_s=0.05)
    try:
        router = Router(lambda: [slow, fast], RouterConfig(
            profile="serve-smoke", default_deadline_s=3.0,
            hedge_fraction=0.1, hedge_floor_s=0.05))
        v, m = _panel(4, 24)
        req = router.submit("momentum", v, m)
        assert req.wait(5.0)
        assert req.state == "served"
        assert req.worker_id == "fast", "the hedge should have won"
        assert req.hedged
        # wait out the slow primary so its duplicate answer lands
        deadline = time.monotonic() + 3.0
        while (router.accounting()["duplicates_suppressed"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        a = router.accounting()
        assert a["admitted"] == 1 and a["served"] == 1
        assert a["hedged"] == 1 and a["hedge_wins"] == 1
        assert a["duplicates_suppressed"] == 1, a
        assert slow.scores == 1 and fast.scores == 1, (
            "both workers must actually have answered")
        assert router.invariant_violations() == []
    finally:
        slow.close()
        fast.close()


def test_router_rejects_unserveable_at_the_door(tmp_path):
    fake = _FakeWorker(str(tmp_path), "w", delay_s=0.0)
    try:
        router = Router(lambda: [fake], RouterConfig(profile="serve-smoke"))
        v, m = _panel(3, 24)
        r1 = router.submit("nope", v, m)
        r2 = router.submit("momentum", v, np.ones(3, bool))
        for r in (r1, r2):
            assert r.wait(2.0) and r.state == "rejected", (r.state, r.error)
        a = router.accounting()
        assert a["rejected_unserveable"] == 2
        assert fake.scores == 0, "door rejections must not burn dispatches"
        assert router.invariant_violations() == []
        assert router.availability() == 1.0, (
            "a client-fault rejection is an honest answer, not downtime")
    finally:
        fake.close()


def test_router_with_no_workers_rejects_infra():
    router = Router(lambda: [], RouterConfig(profile="serve-smoke"))
    v, m = _panel(3, 24)
    r = router.submit("momentum", v, m)
    assert r.wait(2.0) and r.state == "rejected"
    assert "no ready worker" in (r.error or "")
    a = router.accounting()
    assert a["rejected_infra"] == 1
    assert router.availability() == 0.0
    assert router.invariant_violations() == []


def test_drain_on_stop_strands_no_request_across_processes(tmp_path):
    """ISSUE 6 satellite: a burst is in flight (some queued inside
    worker-process admission queues) when the fleet stops — every
    request still reaches exactly one terminal state and the router's
    cross-process books balance."""
    cfg = PoolConfig(n_workers=2, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    try:
        router = Router(sup.ready_workers, RouterConfig(
            profile="serve-smoke", default_deadline_s=5.0))
        months = router.spec.months
        reqs = []
        for i in range(30):
            v, m = _panel(3, months, seed=i)
            reqs.append(router.submit("momentum", v, m))
        sup.stop()  # drain-stop mid-burst
        for r in reqs:
            assert r.wait(10.0), f"request {r.req_id} stranded: {r.state}"
            assert r.state in ("served", "rejected", "expired")
        assert router.invariant_violations() == [], router.accounting()
        a = router.accounting()
        assert a["admitted"] == 30
        assert a["served"] > 0, "the drain must finish accepted work"
    finally:
        sup.stop()


# ------------------------------------------------------------ contracts ----

def _pool_artifact(run_id="r99", value=50.0, availability=1.0,
                   infra=0, hedged=2, wins=1, suppressed=1, smoke=False):
    extra = {"platform": "cpu", "engine": "jax", "workload": "w"}
    if smoke:
        extra["smoke"] = "smoke run"
    admitted = 20
    return {
        "kind": "serve_pool", "schema_version": 1, "run_id": run_id,
        "metric": "serve_pool_throughput_rps", "value": value,
        "unit": "req/s", "vs_baseline": 1.0, "wall_s": 1.0,
        "requests": {"admitted": admitted, "served": admitted - infra,
                     "rejected": infra, "expired": 0,
                     "rejected_infra": infra, "rejected_unserveable": 0,
                     "hedged": hedged, "hedge_wins": wins,
                     "duplicates_suppressed": suppressed, "retries": 0,
                     "worker_conn_failures": 0},
        "availability": availability,
        "hedge": {"hedged": hedged, "rate": round(hedged / admitted, 4),
                  "wins": wins, "suppressed": suppressed},
        "latency_ms": {"total": {"p50": 5.0, "p95": 10.0, "p99": 20.0}},
        "pool": {"n_workers": 3, "ready_workers_end": 3, "kills": 1,
                 "restarts": 1, "rolls_completed": 0, "events": []},
        "workers": [{"worker_id": f"w{i}", "state": "ready",
                     "fresh_compiles": 0} for i in range(3)],
        "compile": {"in_window_fresh_compiles": 0},
        "extra": extra,
    }


def test_serve_pool_validator_accepts_and_detects():
    art = _pool_artifact()
    assert inv.detect_kind(art) == "serve_pool"
    assert inv.validate(art) == []


def test_serve_pool_validator_rejects_broken_books():
    art = _pool_artifact()
    art["requests"]["served"] += 1
    assert any("accounting broken" in v for v in inv.validate(art))

    art = _pool_artifact()
    art["requests"]["duplicates_suppressed"] = 99
    assert any("exactly-once" in v for v in inv.validate(art))

    art = _pool_artifact(infra=2, availability=1.0)
    assert any("reconcile" in v for v in inv.validate(art))

    art = _pool_artifact()
    art["schema_version"] = 77
    assert any("unknown schema_version" in v for v in inv.validate(art))

    art = _pool_artifact()
    art["latency_ms"]["total"]["p95"] = 99.0
    assert any("non-decreasing" in v for v in inv.validate(art))


def test_ledger_ingests_serve_pool_rows(tmp_path):
    from csmom_tpu.obs import ledger as ld

    with open(tmp_path / "SERVE_POOL_r11.json", "w") as f:
        json.dump(_pool_artifact("r11", availability=0.995, infra=0), f)
    # reconcile availability with the books for this fixture
    art = _pool_artifact("r12", infra=1)
    art["availability"] = round(1 - 1 / 20, 6)
    with open(tmp_path / "SERVE_POOL_r12.json", "w") as f:
        json.dump(art, f)
    with open(tmp_path / "SERVE_POOL_smoke.json", "w") as f:
        json.dump(_pool_artifact("smoke", smoke=True), f)
    L = ld.load(str(tmp_path))
    metrics = {r.metric for r in L.rows}
    assert {"serve_pool_throughput_rps", "serve_pool_p99_ms",
            "serve_pool_availability", "serve_pool_hedge_rate",
            "serve_pool_in_window_fresh_compiles"} <= metrics
    avail = [r for r in L.rows if r.metric == "serve_pool_availability"]
    assert {r.run for r in avail} == {"r11", "r12"}
    assert all(r.direction == "higher" for r in avail)
    hedge = [r for r in L.rows if r.metric == "serve_pool_hedge_rate"]
    assert all(r.direction == "lower" for r in hedge)
    # the smoke artifact has no round id -> scratch, skipped with a note
    assert any("scratch" in p["note"] for p in L.problems)


def test_ledger_refuses_unknown_serve_pool_schema(tmp_path):
    from csmom_tpu.obs import ledger as ld

    art = _pool_artifact("r13")
    art["schema_version"] = 42
    with open(tmp_path / "SERVE_POOL_r13.json", "w") as f:
        json.dump(art, f)
    L = ld.load(str(tmp_path))
    assert L.rows == []
    assert any("unknown serve_pool schema_version" in p["note"]
               for p in L.problems)


# ----------------------------------------------------------- acceptance ----

def test_pool_smoke_acceptance_end_to_end(tmp_path, monkeypatch):
    """`csmom loadgen --pool --smoke` with stub workers: the whole tier
    (supervisor spawn -> demonstrated ready -> hedging router -> closed
    books -> schema-valid SERVE_POOL artifact) on CPU, no jax."""
    from csmom_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    rc = main(["loadgen", "--pool", "--smoke", "--stub", "--workers", "2",
               "--schedule", "0.5x50", "--seed", "6"])
    assert rc == 0
    path = tmp_path / "SERVE_POOL_smoke.json"
    assert path.exists()
    assert inv.validate_file(str(path)) == []
    art = json.loads(path.read_text())
    req = art["requests"]
    assert req["admitted"] > 0
    assert req["served"] + req["rejected"] + req["expired"] == req["admitted"]
    assert art["availability"] == 1.0
    assert art["compile"]["in_window_fresh_compiles"] == 0
    assert art["pool"]["n_workers"] == 2
    assert art["extra"]["platform"] == "stub"
    assert "smoke" in art["extra"]


def test_sigkilled_worker_mid_burst_loses_no_request(tmp_path):
    """The tentpole's core claim, in-process form: SIGKILL one worker
    PROCESS while its queue holds work — the router's books still close
    and the pool keeps serving on the survivor + the restart."""
    cfg = PoolConfig(n_workers=2, backoff_base_s=0.05, backoff_cap_s=0.2,
                     **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    try:
        router = Router(sup.ready_workers, RouterConfig(
            profile="serve-smoke", default_deadline_s=5.0))
        months = router.spec.months
        reqs = []
        for i in range(10):
            v, m = _panel(3, months, seed=i)
            reqs.append(router.submit("momentum", v, m))
        assert sup.kill_worker("w0", signal.SIGKILL)
        for i in range(10, 24):
            v, m = _panel(3, months, seed=i)
            reqs.append(router.submit("momentum", v, m))
        for r in reqs:
            assert r.wait(10.0), f"request {r.req_id} never terminal"
        assert router.invariant_violations() == [], router.accounting()
        a = router.accounting()
        assert a["admitted"] == 24
        assert a["served"] >= 20, a  # the pool kept serving
        assert router.availability() >= 0.99, a
    finally:
        sup.stop()


def test_committed_serve_pool_artifacts_validate():
    import glob

    for p in sorted(glob.glob(os.path.join(_REPO, "SERVE_POOL_*.json"))):
        base = os.path.basename(p)
        if not inv.committable_sidecar(base):
            continue  # scratch files regenerated by local runs
        assert inv.validate_file(p) == [], (base, inv.validate_file(p))
        art = json.loads(open(p).read())
        # the r11 acceptance floor: balanced books is schema; the
        # committed round evidence must ALSO show the kill survived
        assert art["availability"] >= 0.99, base
        assert art["compile"]["in_window_fresh_compiles"] == 0, base
        assert art["pool"]["kills"] >= 1, base
