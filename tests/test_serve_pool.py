"""The multi-process serving tier: protocol, health, router, supervisor.

Coverage mirrors the tier's layers (ISSUE 6):

- wire protocol: framed JSON + raw arrays round-trip; malformed frames
  are refused, never half-parsed into a panel;
- health: the AOT cache version token, the cold-cache honesty check
  (scratch cache dir -> not ready, with the `csmom warmup` pointer), and
  the worker's version-skew refusal (exit code + pointed message);
- degradation paths: supervisor backoff CAPS (a crash-looping worker is
  parked, not hot-spun), hedged duplicate suppression (exactly one
  terminal state when both workers answer), and drain-on-stop across
  processes (no request stranded in a worker queue at shutdown);
- contracts: the ``serve_pool`` artifact kind (closed cross-process
  books, hedge arithmetic, availability reconciliation), its committable
  name rule, and ledger ingestion of the pool metric rows.

Everything here runs stub-engine workers (no jax in any spawned
process); the real-engine pool evidence is the committed
``SERVE_POOL_r11.json``, validated at the bottom like every artifact.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.serve import health, proto
from csmom_tpu.serve.router import Router, RouterConfig
from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_SMOKE_POOL = dict(profile="serve-smoke", engine="stub",
                   ready_timeout_s=30.0, poll_interval_s=0.05)


def _panel(n_assets: int, months: int, seed: int = 0):
    r = np.random.default_rng(seed)
    v = 100.0 * np.exp(np.cumsum(r.normal(0, 0.03, (n_assets, months)),
                                 axis=1)).astype(np.float32)
    return v, np.ones((n_assets, months), bool)


# ------------------------------------------------------------- protocol ----

def test_proto_roundtrips_json_and_arrays():
    a, b = socket.socketpair()
    try:
        values = np.arange(12, dtype=np.float32).reshape(3, 4)
        mask = values > 4
        proto.send_msg(a, {"op": "score", "kind": "momentum"},
                       {"values": values, "mask": mask})
        obj, arrays = proto.recv_msg(b)
        assert obj == {"op": "score", "kind": "momentum"}
        np.testing.assert_array_equal(arrays["values"], values)
        np.testing.assert_array_equal(arrays["mask"], mask)
        assert arrays["values"].dtype == np.float32
    finally:
        a.close()
        b.close()


def test_proto_refuses_malformed_frames():
    import struct

    a, b = socket.socketpair()
    try:
        # a garbage length prefix larger than the bound must be refused
        # before any allocation, not best-effort read
        a.sendall(struct.pack("!I", proto.MAX_FRAME_BYTES + 1))
        with pytest.raises(proto.ProtocolError, match="MAX_FRAME_BYTES"):
            proto.recv_msg(b)
    finally:
        a.close()
        b.close()
    a, b = socket.socketpair()
    try:
        # an array spec whose byte count disagrees with its shape must
        # refuse the frame — half a panel never scores
        hdr = json.dumps({"op": "score", "_arrays": [
            {"name": "values", "dtype": "float32", "shape": [2, 2],
             "nbytes": 999}]}).encode()
        payload = struct.pack("!I", len(hdr)) + hdr + b"\x00" * 16
        a.sendall(struct.pack("!I", len(payload)) + payload)
        with pytest.raises(proto.ProtocolError, match="inconsistent"):
            proto.recv_msg(b)
    finally:
        a.close()
        b.close()


# --------------------------------------------------------------- health ----

def test_cache_version_fingerprints_the_compiled_world():
    v1 = health.aot_cache_version("serve")
    assert v1 == health.aot_cache_version("serve"), "must be deterministic"
    assert v1 != health.aot_cache_version("serve-smoke"), (
        "a different bucket grid is a different compiled world")
    assert v1 != health.aot_cache_version("serve", lookback=6), (
        "different engine params compile different HLO")


def test_expected_entry_names_match_the_manifest_scheme():
    from csmom_tpu.registry import serve_endpoints

    names = health.expected_entry_names("serve-smoke")
    # every REGISTERED endpoint x 2 batch buckets x 1 asset bucket —
    # sized by the registry, so a newly registered endpoint widens the
    # warm contract automatically (ISSUE 9)
    assert len(names) == len(serve_endpoints()) * 2
    assert "serve.momentum.b1@8x24" in names
    assert "serve.low_volatility.b1@8x24" in names
    assert "serve.zscore_combo.b4@8x24" in names


def test_cache_readiness_cold_dir_points_at_warmup(tmp_path, monkeypatch):
    monkeypatch.setenv("CSMOM_JIT_CACHE", str(tmp_path / "scratch"))
    ready, reason = health.cache_readiness("serve")
    assert not ready
    assert "csmom warmup --profiles serve" in reason


def test_cache_readiness_disabled_cache_is_not_ready(monkeypatch):
    monkeypatch.setenv("CSMOM_JIT_CACHE", "0")
    ready, reason = health.cache_readiness("serve")
    assert not ready and "CSMOM_JIT_CACHE=0" in reason


def test_cold_cache_makes_csmom_serve_exit_nonzero(tmp_path, monkeypatch,
                                                   capsys):
    """ISSUE 6 satellite: `csmom serve` with the jax engine and a
    scratch (cold) cache dir must exit nonzero with the warmup pointer
    BEFORE any warm — not silently compile inside the ready probe."""
    from csmom_tpu.cli.main import main

    monkeypatch.setenv("CSMOM_JIT_CACHE", str(tmp_path / "scratch"))
    rc = main(["serve", "--duration", "0.1"])
    assert rc == 3
    err = capsys.readouterr().err
    assert "csmom warmup --profiles serve" in err
    assert "NOT READY" in err


def test_worker_refuses_version_skew_with_pointed_message(tmp_path):
    """The deploy-skew gate, at the worker itself: a mismatched
    --expect-cache-version exits RC_VERSION_SKEW naming the skew and the
    remedy, before any warm/compile."""
    from csmom_tpu.serve.worker import RC_VERSION_SKEW

    p = subprocess.run(
        [sys.executable, "-m", "csmom_tpu.serve.worker",
         "--socket", str(tmp_path / "w.sock"), "--engine", "stub",
         "--profile", "serve-smoke",
         "--expect-cache-version", "deadbeef0000"],
        capture_output=True, text=True, timeout=60, cwd=_REPO,
    )
    assert p.returncode == RC_VERSION_SKEW, p.stderr
    assert "skew" in p.stderr
    assert "csmom warmup" in p.stderr


# ------------------------------------------------- supervisor degradation ---

def test_supervisor_backoff_caps_a_crash_looping_worker(tmp_path,
                                                        monkeypatch):
    """ISSUE 6 satellite: a worker that dies at every spawn is restarted
    with growing backoff and PARKED after max_restarts — the supervisor
    must not hot-spin a broken binary."""
    monkeypatch.setenv("CSMOM_SERVE_WORKER_FAULT", "exit:1")
    cfg = PoolConfig(n_workers=1, backoff_base_s=0.02, backoff_cap_s=0.2,
                     max_restarts=2, min_uptime_s=5.0, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path))
    sup.start(require_ready=False)
    try:
        h = sup.handles[0]
        deadline = time.monotonic() + 20.0
        while h.state != "failed" and time.monotonic() < deadline:
            time.sleep(0.05)
        assert h.state == "failed", (h.state, h.reason)
        assert "crash loop" in (h.reason or "")
        events = sup.summary()["events"]
        spawns = [e for e in events if e["event"] == "spawn"]
        # initial spawn + exactly max_restarts restarts, then parked
        assert len(spawns) == 1 + cfg.max_restarts, events
        scheduled = [e for e in events if e["event"] == "restart_scheduled"]
        bases = [e["backoff_base_s"] for e in scheduled]
        assert bases == sorted(bases) and len(bases) == cfg.max_restarts, (
            "backoff must grow monotonically up to the park")
        assert any(e["event"] == "crash_loop_parked" for e in events)
    finally:
        sup.stop()


class _FakeWorker:
    """A hand-rolled protocol speaker on the persistent-channel serve
    loop: answers ready/score with a configurable delay — the
    controllable peer the hedging tests need (a real worker's timing is
    the thing under test, not controllable)."""

    def __init__(self, tmp, worker_id: str, delay_s: float):
        self.worker_id = worker_id
        self.socket_path = os.path.join(tmp, f"{worker_id}.sock")
        self.delay_s = delay_s
        self.scores = 0
        self._stop = threading.Event()
        self._srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._srv.bind(self.socket_path)
        self._srv.listen(8)
        self._srv.settimeout(0.1)
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=proto.serve_connection,
                             args=(conn, self._handle),
                             daemon=True).start()

    def _handle(self, obj, arrays):
        if obj.get("op") == "score":
            self.scores += 1
            time.sleep(self.delay_s)
            n = arrays["values"].shape[0]
            return ({"state": "served", "worker_id": self.worker_id},
                    {"result": np.zeros(n, np.float32)})
        return {"ok": True, "worker_id": self.worker_id}, None

    def close(self):
        self._stop.set()
        self._srv.close()


def test_hedged_duplicate_suppression_exactly_one_terminal(tmp_path):
    """ISSUE 6 satellite: slow primary, fast hedge — BOTH answer, the
    request reaches exactly one terminal state, and the loser is counted
    duplicates_suppressed (never double-served, never lost)."""
    slow = _FakeWorker(str(tmp_path), "slow", delay_s=0.8)
    fast = _FakeWorker(str(tmp_path), "fast", delay_s=0.05)
    try:
        router = Router(lambda: [slow, fast], RouterConfig(
            profile="serve-smoke", default_deadline_s=3.0,
            hedge_fraction=0.1, hedge_floor_s=0.05))
        v, m = _panel(4, 24)
        req = router.submit("momentum", v, m)
        assert req.wait(5.0)
        assert req.state == "served"
        assert req.worker_id == "fast", "the hedge should have won"
        assert req.hedged
        # wait out the slow primary so its duplicate answer lands
        deadline = time.monotonic() + 3.0
        while (router.accounting()["duplicates_suppressed"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.05)
        a = router.accounting()
        assert a["admitted"] == 1 and a["served"] == 1
        assert a["hedged"] == 1 and a["hedge_wins"] == 1
        assert a["duplicates_suppressed"] == 1, a
        assert slow.scores == 1 and fast.scores == 1, (
            "both workers must actually have answered")
        assert router.invariant_violations() == []
    finally:
        slow.close()
        fast.close()


def test_router_rejects_unserveable_at_the_door(tmp_path):
    fake = _FakeWorker(str(tmp_path), "w", delay_s=0.0)
    try:
        router = Router(lambda: [fake], RouterConfig(profile="serve-smoke"))
        v, m = _panel(3, 24)
        r1 = router.submit("nope", v, m)
        r2 = router.submit("momentum", v, np.ones(3, bool))
        for r in (r1, r2):
            assert r.wait(2.0) and r.state == "rejected", (r.state, r.error)
        a = router.accounting()
        assert a["rejected_unserveable"] == 2
        assert fake.scores == 0, "door rejections must not burn dispatches"
        assert router.invariant_violations() == []
        assert router.availability() == 1.0, (
            "a client-fault rejection is an honest answer, not downtime")
    finally:
        fake.close()


def test_router_with_no_workers_rejects_infra():
    router = Router(lambda: [], RouterConfig(profile="serve-smoke"))
    v, m = _panel(3, 24)
    r = router.submit("momentum", v, m)
    assert r.wait(2.0) and r.state == "rejected"
    assert "no ready worker" in (r.error or "")
    a = router.accounting()
    assert a["rejected_infra"] == 1
    assert router.availability() == 0.0
    assert router.invariant_violations() == []


def test_drain_on_stop_strands_no_request_across_processes(tmp_path):
    """ISSUE 6 satellite: a burst is in flight (some queued inside
    worker-process admission queues) when the fleet stops — every
    request still reaches exactly one terminal state and the router's
    cross-process books balance."""
    cfg = PoolConfig(n_workers=2, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    try:
        router = Router(sup.ready_workers, RouterConfig(
            profile="serve-smoke", default_deadline_s=5.0))
        months = router.spec.months
        reqs = []
        for i in range(30):
            v, m = _panel(3, months, seed=i)
            reqs.append(router.submit("momentum", v, m))
        sup.stop()  # drain-stop mid-burst
        for r in reqs:
            assert r.wait(10.0), f"request {r.req_id} stranded: {r.state}"
            assert r.state in ("served", "rejected", "expired")
        assert router.invariant_violations() == [], router.accounting()
        a = router.accounting()
        assert a["admitted"] == 30
        assert a["served"] > 0, "the drain must finish accepted work"
    finally:
        sup.stop()


# ------------------------------------------------------------ contracts ----

def _pool_artifact(run_id="r99", value=50.0, availability=1.0,
                   infra=0, hedged=2, wins=1, suppressed=1, smoke=False):
    extra = {"platform": "cpu", "engine": "jax", "workload": "w"}
    if smoke:
        extra["smoke"] = "smoke run"
    admitted = 20
    return {
        "kind": "serve_pool", "schema_version": 1, "run_id": run_id,
        "metric": "serve_pool_throughput_rps", "value": value,
        "unit": "req/s", "vs_baseline": 1.0, "wall_s": 1.0,
        "requests": {"admitted": admitted, "served": admitted - infra,
                     "rejected": infra, "expired": 0,
                     "rejected_infra": infra, "rejected_unserveable": 0,
                     "hedged": hedged, "hedge_wins": wins,
                     "duplicates_suppressed": suppressed, "retries": 0,
                     "worker_conn_failures": 0},
        "availability": availability,
        "hedge": {"hedged": hedged, "rate": round(hedged / admitted, 4),
                  "wins": wins, "suppressed": suppressed},
        "latency_ms": {"total": {"p50": 5.0, "p95": 10.0, "p99": 20.0}},
        "pool": {"n_workers": 3, "ready_workers_end": 3, "kills": 1,
                 "restarts": 1, "rolls_completed": 0, "events": []},
        "workers": [{"worker_id": f"w{i}", "state": "ready",
                     "fresh_compiles": 0} for i in range(3)],
        "compile": {"in_window_fresh_compiles": 0},
        "extra": extra,
    }


def test_serve_pool_validator_accepts_and_detects():
    art = _pool_artifact()
    assert inv.detect_kind(art) == "serve_pool"
    assert inv.validate(art) == []


def test_serve_pool_validator_rejects_broken_books():
    art = _pool_artifact()
    art["requests"]["served"] += 1
    assert any("accounting broken" in v for v in inv.validate(art))

    art = _pool_artifact()
    art["requests"]["duplicates_suppressed"] = 99
    assert any("exactly-once" in v for v in inv.validate(art))

    art = _pool_artifact(infra=2, availability=1.0)
    assert any("reconcile" in v for v in inv.validate(art))

    art = _pool_artifact()
    art["schema_version"] = 77
    assert any("unknown schema_version" in v for v in inv.validate(art))

    art = _pool_artifact()
    art["latency_ms"]["total"]["p95"] = 99.0
    assert any("non-decreasing" in v for v in inv.validate(art))


def test_ledger_ingests_serve_pool_rows(tmp_path):
    from csmom_tpu.obs import ledger as ld

    with open(tmp_path / "SERVE_POOL_r11.json", "w") as f:
        json.dump(_pool_artifact("r11", availability=0.995, infra=0), f)
    # reconcile availability with the books for this fixture
    art = _pool_artifact("r12", infra=1)
    art["availability"] = round(1 - 1 / 20, 6)
    with open(tmp_path / "SERVE_POOL_r12.json", "w") as f:
        json.dump(art, f)
    with open(tmp_path / "SERVE_POOL_smoke.json", "w") as f:
        json.dump(_pool_artifact("smoke", smoke=True), f)
    L = ld.load(str(tmp_path))
    metrics = {r.metric for r in L.rows}
    assert {"serve_pool_throughput_rps", "serve_pool_p99_ms",
            "serve_pool_availability", "serve_pool_hedge_rate",
            "serve_pool_in_window_fresh_compiles"} <= metrics
    avail = [r for r in L.rows if r.metric == "serve_pool_availability"]
    assert {r.run for r in avail} == {"r11", "r12"}
    assert all(r.direction == "higher" for r in avail)
    hedge = [r for r in L.rows if r.metric == "serve_pool_hedge_rate"]
    assert all(r.direction == "lower" for r in hedge)
    # the smoke artifact has no round id -> scratch, skipped with a note
    assert any("scratch" in p["note"] for p in L.problems)


def test_ledger_refuses_unknown_serve_pool_schema(tmp_path):
    from csmom_tpu.obs import ledger as ld

    art = _pool_artifact("r13")
    art["schema_version"] = 42
    with open(tmp_path / "SERVE_POOL_r13.json", "w") as f:
        json.dump(art, f)
    L = ld.load(str(tmp_path))
    assert L.rows == []
    assert any("unknown serve_pool schema_version" in p["note"]
               for p in L.problems)


# ----------------------------------------------------------- acceptance ----

def test_pool_smoke_acceptance_end_to_end(tmp_path, monkeypatch):
    """`csmom loadgen --pool --smoke` with stub workers: the whole tier
    (supervisor spawn -> demonstrated ready -> hedging router -> closed
    books -> schema-valid SERVE_POOL artifact) on CPU, no jax."""
    from csmom_tpu.cli.main import main

    monkeypatch.chdir(tmp_path)
    rc = main(["loadgen", "--pool", "--smoke", "--stub", "--workers", "2",
               "--schedule", "0.5x50", "--seed", "6"])
    assert rc == 0
    path = tmp_path / "SERVE_POOL_smoke.json"
    assert path.exists()
    assert inv.validate_file(str(path)) == []
    art = json.loads(path.read_text())
    req = art["requests"]
    assert req["admitted"] > 0
    assert req["served"] + req["rejected"] + req["expired"] == req["admitted"]
    assert art["availability"] == 1.0
    assert art["compile"]["in_window_fresh_compiles"] == 0
    assert art["pool"]["n_workers"] == 2
    assert art["extra"]["platform"] == "stub"
    assert "smoke" in art["extra"]


def test_sigkilled_worker_mid_burst_loses_no_request(tmp_path):
    """The tentpole's core claim, in-process form: SIGKILL one worker
    PROCESS while its queue holds work — the router's books still close
    and the pool keeps serving on the survivor + the restart."""
    cfg = PoolConfig(n_workers=2, backoff_base_s=0.05, backoff_cap_s=0.2,
                     **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path)).start()
    try:
        router = Router(sup.ready_workers, RouterConfig(
            profile="serve-smoke", default_deadline_s=5.0))
        months = router.spec.months
        reqs = []
        for i in range(10):
            v, m = _panel(3, months, seed=i)
            reqs.append(router.submit("momentum", v, m))
        assert sup.kill_worker("w0", signal.SIGKILL)
        for i in range(10, 24):
            v, m = _panel(3, months, seed=i)
            reqs.append(router.submit("momentum", v, m))
        for r in reqs:
            assert r.wait(10.0), f"request {r.req_id} never terminal"
        assert router.invariant_violations() == [], router.accounting()
        a = router.accounting()
        assert a["admitted"] == 24
        assert a["served"] >= 20, a  # the pool kept serving
        assert router.availability() >= 0.99, a
    finally:
        sup.stop()


def test_committed_serve_pool_artifacts_validate():
    import glob

    for p in sorted(glob.glob(os.path.join(_REPO, "SERVE_POOL_*.json"))):
        base = os.path.basename(p)
        if not inv.committable_sidecar(base):
            continue  # scratch files regenerated by local runs
        assert inv.validate_file(p) == [], (base, inv.validate_file(p))
        art = json.loads(open(p).read())
        # the r11 acceptance floor: balanced books is schema; the
        # committed round evidence must ALSO show the kill survived
        assert art["availability"] >= 0.99, base
        assert art["compile"]["in_window_fresh_compiles"] == 0, base
        assert art["pool"]["kills"] >= 1, base


# ------------------------------------------------- r18 transport bounds ----

def test_proto_recv_deadline_bounds_a_stalled_peer():
    """ISSUE 14 satellite: a peer that opens a frame and then stalls (or
    trickles) must cost the reader a pointed ProtocolError within the
    receive deadline — the r11 _recv_exact blocked for as long as the
    peer kept the socket alive, wedging a router thread forever."""
    a, b = socket.socketpair()
    try:
        import struct

        a.sendall(struct.pack("!I", 64))  # declares 64 bytes, sends none
        t0 = time.monotonic()
        with pytest.raises(proto.ProtocolError, match="deadline"):
            proto.recv_msg(b, deadline_s=0.4)
        assert time.monotonic() - t0 < 2.0, (
            "the receive deadline did not bound the stall")
    finally:
        a.close()
        b.close()


def test_proto_recv_deadline_bounds_a_trickling_peer():
    """A peer trickling one byte per timeout window used to reset the
    clock forever; the deadline is TOTAL, so the trickle is refused."""
    import struct

    a, b = socket.socketpair()
    stop = threading.Event()

    def trickle():
        a.sendall(struct.pack("!I", 1 << 20))
        while not stop.is_set():
            try:
                a.sendall(b"\x00")
            except OSError:
                return
            stop.wait(0.05)

    t = threading.Thread(target=trickle, daemon=True)
    t.start()
    try:
        t0 = time.monotonic()
        with pytest.raises(proto.ProtocolError, match="deadline"):
            proto.recv_msg(b, deadline_s=0.4)
        assert time.monotonic() - t0 < 2.0
    finally:
        stop.set()
        a.close()
        b.close()
        t.join(timeout=1.0)


def test_chaos_env_duration_defaults_on_malformed_value(monkeypatch):
    """A typo'd chaos duration knob (\"250ms\") must degrade to the
    default fault, not raise an unhandled ValueError through the
    dispatch thread and strand its request non-terminal."""
    monkeypatch.setenv(proto.NET_DELAY_ENV, "250ms")
    assert proto._chaos_env_s(proto.NET_DELAY_ENV, 1.5) == 1.5
    monkeypatch.setenv(proto.NET_DELAY_ENV, "0.25")
    assert proto._chaos_env_s(proto.NET_DELAY_ENV, 1.5) == 0.25
    monkeypatch.setenv(proto.NET_DELAY_ENV, "")
    assert proto._chaos_env_s(proto.NET_DELAY_ENV, 1.5) == 1.5
    monkeypatch.delenv(proto.NET_DELAY_ENV)
    assert proto._chaos_env_s(proto.NET_DELAY_ENV, 1.5) == 1.5


def test_tcp_crash_restart_probes_a_fresh_port(tmp_path):
    """A tcp slot's crash restart must probe a FRESH port (like a
    rolling replacement does) — re-spawning onto the dead port every
    backoff cycle turns a one-off port race into a crash-loop park."""
    from csmom_tpu.serve.supervisor import (
        PoolConfig,
        PoolSupervisor,
        WorkerHandle,
    )

    sup = PoolSupervisor(PoolConfig(n_workers=1, transport="tcp",
                                    engine="stub", profile="serve-smoke"),
                         str(tmp_path))
    spawned = []
    sup._spawn = lambda h: spawned.append(h.socket_path)
    sup._probe_until_ready = lambda *a, **k: None
    h = WorkerHandle(slot=0, worker_id="w0",
                     socket_path="tcp:127.0.0.1:1")
    sup.handles.append(h)
    sup._restart(h)
    assert h.generation == 1
    assert spawned == [h.socket_path]
    assert h.socket_path != "tcp:127.0.0.1:1", (
        "the replacement re-spawned onto the dead port")
    assert h.socket_path.startswith("tcp:127.0.0.1:")


def test_proto_recv_restores_caller_socket_timeout():
    """_recv_exact re-arms the socket timeout downward per read; the
    caller's timeout must come back afterwards — a reply send on the
    same connection inheriting a near-zero residual budget would
    spuriously time out and drop an already-computed answer."""
    a, b = socket.socketpair()
    try:
        b.settimeout(60.0)
        proto.send_msg(a, {"op": "ping"})
        obj, _ = proto.recv_msg(b, deadline_s=5.0)
        assert obj == {"op": "ping"}
        assert b.gettimeout() == 60.0, (
            "recv_msg leaked its dwindling receive budget into the "
            "caller's socket timeout")
        # the error path restores it too
        b.settimeout(60.0)
        import struct

        a.sendall(struct.pack("!I", 64))
        with pytest.raises(proto.ProtocolError, match="deadline"):
            proto.recv_msg(b, deadline_s=0.2)
        assert b.gettimeout() == 60.0
    finally:
        a.close()
        b.close()


def test_proto_frame_bound_refuses_before_allocating():
    """The refusal must happen on the LENGTH PREFIX, before the payload
    allocation a hostile prefix names (the pointed-refusal satellite)."""
    import struct

    a, b = socket.socketpair()
    try:
        a.sendall(struct.pack("!I", 0xFFFFFFFF))  # a 4 GiB claim
        with pytest.raises(proto.ProtocolError,
                           match="Refusing before allocating"):
            proto.recv_msg(b)
    finally:
        a.close()
        b.close()


def test_parse_address_schemes_and_errors():
    assert proto.parse_address("/tmp/w0.sock") == ("unix", "/tmp/w0.sock")
    assert proto.parse_address("unix:/tmp/w0.sock") == ("unix",
                                                        "/tmp/w0.sock")
    assert proto.parse_address("tcp:127.0.0.1:9001") == (
        "tcp", ("127.0.0.1", 9001))
    for bad in ("unix:", "tcp:nohost", "tcp:h:notaport", "tcp:h:70000"):
        with pytest.raises(ValueError):
            proto.parse_address(bad)


def test_proto_tcp_roundtrip_with_arrays():
    """The same framed protocol over AF_INET: one listen + request
    round trip carrying arrays — the r18 cross-host spelling."""
    addr = f"tcp:127.0.0.1:{proto.free_tcp_port()}"
    srv = proto.listen(addr)
    srv.settimeout(2.0)

    def serve_one():
        conn, _ = srv.accept()
        try:
            obj, arrays = proto.recv_msg(conn)
            proto.send_msg(conn, {"echo": obj["op"]},
                           {"values": arrays["values"] * 2})
        finally:
            conn.close()

    t = threading.Thread(target=serve_one, daemon=True)
    t.start()
    try:
        v = np.arange(6, dtype=np.float32).reshape(2, 3)
        obj, arrays = proto.request(addr, {"op": "probe"},
                                    arrays={"values": v}, timeout_s=5.0)
        assert obj == {"echo": "probe"}
        np.testing.assert_array_equal(arrays["values"], v * 2)
    finally:
        srv.close()
        t.join(timeout=2.0)


# ----------------------------------------- r18 parked-fleet degradation ----

def test_router_parked_fleet_rejects_fast_with_retry_after():
    """ISSUE 14 satellite: when ALL workers are parked/unreachable the
    router rejects AT THE DOOR with a retry-after hint derived from
    supervisor backoff state, instead of burning the caller's full
    deadline per request."""
    router = Router(lambda: [], RouterConfig(profile="serve-smoke",
                                             default_deadline_s=5.0),
                    retry_after_fn=lambda: 1.7)
    v, m = _panel(3, 24)
    t0 = time.monotonic()
    r = router.submit("momentum", v, m)
    assert r.wait(2.0) and r.state == "rejected"
    assert time.monotonic() - t0 < 1.0, (
        "a parked-fleet rejection burned the deadline instead of "
        "failing fast at the door")
    assert r.retry_after_s == 1.7
    assert "retry after 1.7s" in (r.error or "")
    a = router.accounting()
    assert a["rejected_no_worker"] == 1 and a["rejected_infra"] == 1
    assert router.invariant_violations() == []


def test_supervisor_retry_after_reflects_backoff_state(tmp_path):
    """The hint is the NEXT plausible restart's floor: None while any
    worker is ready, the soonest backoff otherwise, and None again when
    every slot is parked (retrying cannot help a parked fleet)."""
    from csmom_tpu.serve.supervisor import WorkerHandle
    from csmom_tpu.utils.deadline import mono_now_s

    cfg = PoolConfig(n_workers=2, **_SMOKE_POOL)
    sup = PoolSupervisor(cfg, str(tmp_path))
    h0 = WorkerHandle(slot=0, worker_id="w0", socket_path="x")
    h1 = WorkerHandle(slot=1, worker_id="w1", socket_path="y")
    sup.handles = [h0, h1]
    h0.state, h1.state = "ready", "dead"
    assert sup.retry_after_s() is None, "a ready worker needs no hint"
    h0.state = "dead"
    h0.next_restart_at = mono_now_s() + 3.0
    h1.next_restart_at = mono_now_s() + 1.2
    hint = sup.retry_after_s()
    assert hint is not None and 0.9 <= hint <= 1.3, hint
    h0.state = h1.state = "failed"
    h0.next_restart_at = h1.next_restart_at = None
    assert sup.retry_after_s() is None, (
        "a fully parked fleet must not promise a retry that cannot come")


# ---------------------------------------------- r18 ring and fair gate ----

def test_hash_ring_is_stable_and_moves_minimally():
    from csmom_tpu.serve.router import HashRing

    ids = ["w0", "w1", "w2", "w3"]
    ring = HashRing(ids)
    keys = [f"req-{i}" for i in range(400)]
    before = {k: ring.pick(k) for k in keys}
    # deterministic: the same ring answers the same
    again = HashRing(ids)
    assert before == {k: again.pick(k) for k in keys}
    # removing one member moves ONLY that member's keys
    ring3 = HashRing([i for i in ids if i != "w2"])
    moved = sum(1 for k in keys
                if before[k] != "w2" and ring3.pick(k) != before[k])
    assert moved == 0, (
        f"{moved} keys moved off SURVIVING workers after one death — "
        "consistent hashing must only redistribute the dead arcs")
    # the dead member's keys all land somewhere real
    assert all(ring3.pick(k) in ("w0", "w1", "w3")
               for k in keys if before[k] == "w2")
    assert HashRing([]).pick("anything") is None


def test_affinity_routes_identical_requests_to_one_worker(tmp_path):
    """Byte-identical requests share a cache identity and must land on
    the SAME worker — the pool-level cache property."""
    fakes = [_FakeWorker(str(tmp_path), f"w{i}", delay_s=0.0)
             for i in range(3)]
    try:
        router = Router(lambda: fakes, RouterConfig(
            profile="serve-smoke", default_deadline_s=5.0))
        v, m = _panel(7, 24, seed=3)
        reqs = []
        for _ in range(6):
            r = router.submit("momentum", v, m)
            assert r.wait(3.0) and r.state == "served", (r.state, r.error)
            reqs.append(r)
        assert len({r.worker_id for r in reqs}) == 1, (
            [r.worker_id for r in reqs])
        assert router.accounting()["affinity_routed"] >= 6
        # a DIFFERENT panel may land elsewhere, same panel sticks
        v2, m2 = _panel(5, 24, seed=4)
        r2 = router.submit("momentum", v2, m2)
        assert r2.wait(3.0) and r2.state == "served"
    finally:
        for f in fakes:
            f.close()


def test_weighted_fair_gate_enforces_rank_and_bounds():
    from csmom_tpu.serve.router import WeightedFairGate
    from csmom_tpu.serve.slo import default_policy

    gate = WeightedFairGate(default_policy(), slots=1)
    assert gate.acquire("interactive", 0.5), "an empty gate grants"
    got = []

    def waiter(cls):
        if gate.acquire(cls, 2.0):
            got.append(cls)
            gate.release()

    # bulk queues first, interactive second — the slot must go to
    # interactive when it frees (rank order, not FIFO)
    tb = threading.Thread(target=waiter, args=("bulk",), daemon=True)
    tb.start()
    time.sleep(0.05)
    ti = threading.Thread(target=waiter, args=("interactive",), daemon=True)
    ti.start()
    time.sleep(0.05)
    gate.release()
    ti.join(3.0)
    tb.join(3.0)
    assert got == ["interactive", "bulk"], got
    s = gate.stats()
    assert s["slots"] == 1 and s["in_use"] == 0
    assert s["granted"]["interactive"] >= 2


def test_weighted_fair_gate_timeout_is_honest_backpressure():
    from csmom_tpu.serve.router import WeightedFairGate
    from csmom_tpu.serve.slo import default_policy

    gate = WeightedFairGate(default_policy(), slots=1)
    assert gate.acquire("interactive", 0.5)
    t0 = time.monotonic()
    assert not gate.acquire("bulk", 0.2), "a full gate must time out"
    assert 0.15 <= time.monotonic() - t0 < 1.0
    assert gate.stats()["timeouts"]["bulk"] == 1
    gate.release()
    assert gate.acquire("bulk", 0.5), (
        "the timed-out class must not poison later acquires")
    gate.release()
