"""Adaptive dispatch (ISSUE 8): SLO classes, result cache, coalescing.

Four properties this file exists to pin, per the issue's test satellite:

- **cache correctness**: hit -> ``panel_version`` bump -> miss, with the
  bumped-over entries invalidated and ZERO stale hits (the floor refuses
  a stale entry even when one is planted under a live key);
- **in-flight coalescing**: identical concurrent requests share ONE
  dispatch and every waiter gets the result exactly once, counted;
- **bounded memory**: the cache evicts LRU under both the entry cap and
  the byte cap, and eviction is counted;
- **starvation-proofness**: bulk saturation (over-quota burst) with
  interactive p99 still inside its class budget and every class book
  closed.

Everything runs on the stub engine (no jax), like the rest of the serve
plumbing tier.
"""

import json
import threading

import numpy as np
import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.serve.cache import CacheKey, ResultCache, panel_fingerprint
from csmom_tpu.serve.service import ServeConfig, SignalService
from csmom_tpu.serve.slo import (
    SLOClass,
    SLOPolicy,
    TokenBucket,
    default_policy,
)

def _panel(n_assets: int, months: int, seed: int = 0):
    r = np.random.default_rng(seed)
    v = 100.0 * np.exp(np.cumsum(r.normal(0, 0.03, (n_assets, months)),
                                 axis=1)).astype(np.float32)
    return v, np.ones((n_assets, months), bool)


def _stub_service(**over) -> SignalService:
    kw = dict(profile="serve-smoke", engine="stub", max_wait_s=0.005)
    kw.update(over)
    return SignalService(ServeConfig(**kw)).start()


# ------------------------------------------------------------------ slo ----

def test_token_bucket_rate_and_burst():
    b = TokenBucket(rate=10.0, burst=3.0)
    # burst credit: 3 immediate takes, then dry
    assert [b.try_take(0.0) for _ in range(4)] == [True, True, True, False]
    # 0.1 s at 10 rps refills exactly one token
    assert b.try_take(0.1) is True
    assert b.try_take(0.1) is False
    # refill never exceeds burst
    assert [b.try_take(100.0) for _ in range(4)] == [True, True, True,
                                                     False]


def test_policy_resolves_aliases_and_rejects_unknown():
    p = default_policy()
    assert p.names() == ("interactive", "standard", "bulk")
    assert p.resolve_name("batch") == "bulk"   # the r10 legacy name
    assert p.resolve("interactive").rank == 0
    with pytest.raises(ValueError, match="unknown SLO class"):
        p.resolve("vip")
    with pytest.raises(ValueError, match="duplicate SLO class"):
        SLOPolicy((SLOClass("a", 0, 1.0), SLOClass("a", 1, 1.0)))


def test_unknown_class_rejects_at_the_door_and_books_close():
    svc = _stub_service()
    r = svc.submit("momentum", *_panel(3, svc.spec.months), priority="vip")
    assert r.state == "rejected" and "unknown SLO class" in (r.error or "")
    svc.stop()
    assert svc.invariant_violations() == []


def test_class_deadline_budget_is_the_default_deadline():
    svc = _stub_service()
    months = svc.spec.months
    reqs = {cls: svc.submit("momentum", *_panel(3, months, seed=i),
                            priority=cls, cacheable=False)
            for i, cls in enumerate(("interactive", "standard", "bulk"))}
    for r in reqs.values():
        assert r.wait(5.0) and r.state == "served"
    budgets = {c.name: c.deadline_s for c in svc.policy.classes}
    for cls, r in reqs.items():
        want = r.t_submit_s + budgets[cls]
        # the absolute deadline was anchored slightly before t_submit_s
        assert abs(r.deadline_s - want) < 0.2, (cls, r.deadline_s, want)
    svc.stop()
    assert svc.invariant_violations() == []


# ---------------------------------------------------------------- cache ----

def _key(n=3, months=24, version=None, seed=0, kind="momentum"):
    v, m = _panel(n, months, seed)
    return CacheKey(kind=kind, params=("stub", 12, 1, 10, "rank"),
                    months=months, n_assets=n,
                    fingerprint=panel_fingerprint(v, m),
                    panel_version=version)


def test_cache_hit_then_version_bump_then_miss():
    """The issue's named sequence: a versioned hit, a panel_version bump,
    then the SAME panel misses (old entries invalidated, new version is
    a new key) — with zero stale hits throughout."""
    c = ResultCache()
    k1 = _key(version=1)
    assert c.get(k1) == (False, None)
    assert c.put(k1, np.arange(3.0))
    hit, res = c.get(k1)
    assert hit and list(res) == [0.0, 1.0, 2.0]
    # the bump: ingestion moved to panel version 2
    assert c.set_version_floor(2) == 1          # the v1 entry dropped
    assert c.get(k1) == (False, None)            # and can never hit again
    k2 = _key(version=2)
    assert c.get(k2) == (False, None)            # new version = new key
    # a result computed from the OLD panel arriving late is refused
    assert not c.put(k1, np.arange(3.0))
    s = c.stats()
    assert s["stale_hits"] == 0
    assert s["invalidated"] == 1
    assert s["stale_put_refused"] == 1
    assert s["version_floor"] == 2


def test_cache_version_floor_blocks_planted_stale_entry():
    """Defense in depth: even an entry that EXISTS under a live key but
    is stamped below the floor (the cache_poison chaos shape) is refused
    by the get path and counted stale_blocked, never returned."""
    c = ResultCache()
    c.set_version_floor(5)
    k = _key(version=5)
    with c._lock:
        from csmom_tpu.serve.cache import _Entry

        c._entries[k] = _Entry(result="POISON", version=3, nbytes=8)
    hit, res = c.get(k)
    assert not hit and res is None
    s = c.stats()
    assert s["stale_blocked"] == 1 and s["stale_hits"] == 0
    assert s["entries"] == 0  # the poisoned entry was evicted on sight


def test_cache_bounded_by_entries_and_bytes():
    c = ResultCache(max_entries=3, max_bytes=1 << 30)
    keys = [_key(seed=i, version=None) for i in range(5)]
    for k in keys:
        c.put(k, np.zeros(4))
    s = c.stats()
    assert s["entries"] == 3 and s["evictions"] == 2
    # LRU: the two oldest are gone, the three newest hit
    assert c.get(keys[0]) == (False, None)
    assert c.get(keys[1]) == (False, None)
    assert all(c.get(k)[0] for k in keys[2:])
    # byte bound: each entry is 800 bytes, cap at ~2 entries
    c2 = ResultCache(max_entries=100, max_bytes=1600)
    for i in range(4):
        c2.put(_key(seed=10 + i), np.zeros(100))
    s2 = c2.stats()
    assert s2["entries"] <= 2 and s2["evictions"] >= 2
    assert s2["size_bytes"] <= 1600


def test_service_cache_hit_roundtrip_and_readonly_result():
    svc = _stub_service()
    months = svc.spec.months
    v, m = _panel(4, months)
    a = svc.submit("momentum", v, m)
    assert a.wait(5.0) and a.state == "served" and not a.cache_hit
    b = svc.submit("momentum", v, m)
    assert b.wait(5.0) and b.state == "served" and b.cache_hit
    assert np.allclose(np.asarray(a.result), np.asarray(b.result),
                       equal_nan=True)
    # a cached payload goes out read-only: no caller can poison the cache
    with pytest.raises(ValueError):
        np.asarray(b.result)[0] = 1.0
    svc.stop()
    assert svc.invariant_violations() == []
    assert svc.accounting()["served_cache_hits"] == 1
    assert svc.cache_stats()["hit_rate"] > 0


def test_service_version_bump_invalidates_between_submissions():
    svc = _stub_service()
    months = svc.spec.months
    v, m = _panel(4, months)
    a = svc.submit("momentum", v, m, panel_version=1)
    assert a.wait(5.0) and a.state == "served"
    b = svc.submit("momentum", v, m, panel_version=1)
    assert b.wait(5.0) and b.cache_hit
    assert svc.notify_panel_version(2) == 1     # the v1 entry invalidated
    c = svc.submit("momentum", v, m, panel_version=2)
    assert c.wait(5.0) and c.state == "served" and not c.cache_hit
    svc.stop()
    s = svc.cache_stats()
    assert s["stale_hits"] == 0 and s["invalidated"] == 1
    assert svc.invariant_violations() == []


# ------------------------------------------------------------ coalescing ----

def test_inflight_coalescing_shares_one_dispatch_exactly_once():
    """Identical concurrent requests: one leader dispatch, every waiter
    served exactly once with the shared result, books count them all."""
    # a long coalescing window stalls the leader in the queue so the
    # followers provably attach while it is in flight
    svc = _stub_service(max_wait_s=0.25)
    months = svc.spec.months
    v, m = _panel(4, months)
    lead = svc.submit("momentum", v, m, deadline_s=5.0)
    followers = [svc.submit("momentum", v, m, deadline_s=5.0)
                 for _ in range(3)]
    for r in [lead] + followers:
        assert r.wait(5.0), r.state
        assert r.state == "served", (r.state, r.error)
    assert not lead.coalesced
    assert all(f.coalesced for f in followers)
    for f in followers:
        assert np.allclose(np.asarray(f.result), np.asarray(lead.result),
                           equal_nan=True)
        # the follower's timeline shares the leader's dispatch instant
        assert f.t_dispatch_s == lead.t_dispatch_s
    svc.stop()
    a = svc.accounting()
    assert a["served_coalesced"] == 3
    assert a["admitted"] == 4 and a["served"] == 4
    # ONE dispatch for the four of them
    assert svc.batch_stats()["count"] == 1
    assert svc.invariant_violations() == []


def test_coalesced_followers_ride_a_crashed_leader_to_terminal(
        tmp_path, monkeypatch):
    """A leader that dies mid-batch takes its followers to a TERMINAL
    state (rejected, with the leader's fate as the reason) — coalescing
    must never strand a waiter."""
    from csmom_tpu.chaos import inject
    from csmom_tpu.chaos.plan import Fault, FaultPlan

    plan = FaultPlan("crash", seed=1, faults=(
        Fault(point="serve.dispatch", action="fail", after=0, max_fires=1),
    ))
    p = tmp_path / "plan.toml"
    p.write_text(plan.to_toml())
    monkeypatch.setenv("CSMOM_FAULT_PLAN", str(p))
    monkeypatch.setenv("CSMOM_FAULT_STATE", str(tmp_path / "state"))
    inject.reset()
    try:
        svc = _stub_service(max_wait_s=0.25)
        months = svc.spec.months
        v, m = _panel(4, months)
        lead = svc.submit("momentum", v, m, deadline_s=5.0)
        follower = svc.submit("momentum", v, m, deadline_s=5.0)
        assert lead.wait(5.0) and follower.wait(5.0)
        assert lead.state == "rejected"
        assert follower.state == "rejected"
        assert "coalesced onto request" in (follower.error or "")
        svc.stop()
        assert svc.invariant_violations() == []
        assert svc.accounting()["rejected_coalesced"] == 1
    finally:
        inject.reset()


def test_coalesced_follower_expires_when_dispatch_begins_too_late():
    """Coalescing must not void the deadline contract: a follower whose
    own deadline passed BEFORE the shared dispatch began expires (never
    'served late'), while followers whose dispatch began in time ride
    the leader — same rule the deques enforce for queued requests."""
    # stall the worker in a long coalescing window so the leader is in
    # flight long past the tight follower's deadline
    svc = _stub_service(max_wait_s=0.3)
    months = svc.spec.months
    v, m = _panel(4, months)
    lead = svc.submit("momentum", v, m, deadline_s=5.0)
    tight = svc.submit("momentum", v, m, deadline_s=0.02)   # follower
    loose = svc.submit("momentum", v, m, deadline_s=5.0)    # follower
    for r in (lead, tight, loose):
        assert r.wait(5.0), r.state
    assert lead.state == "served"
    assert loose.state == "served" and loose.coalesced
    assert tight.state == "expired", (tight.state, tight.error)
    assert "before the coalesced dispatch" in (tight.error or "")
    svc.stop()
    assert svc.invariant_violations() == []


def test_coalesced_backtest_followers_get_their_own_dict():
    """A shared mutable dict result would let one coalesced caller edit
    what another reads; every waiter must get its own copy."""
    svc = _stub_service(max_wait_s=0.25)
    months = svc.spec.months
    v, m = _panel(4, months)
    lead = svc.submit("backtest", v, m, deadline_s=5.0)
    follower = svc.submit("backtest", v, m, deadline_s=5.0)
    assert lead.wait(5.0) and follower.wait(5.0)
    assert lead.state == follower.state == "served"
    assert follower.result == lead.result
    assert follower.result is not lead.result
    follower.result["ann_sharpe"] = 99.0
    assert lead.result["ann_sharpe"] != 99.0
    # and a later cache hit is untouched by either caller's edits
    hit = svc.submit("backtest", v, m, deadline_s=5.0)
    assert hit.wait(5.0) and hit.cache_hit
    assert hit.result["ann_sharpe"] != 99.0
    svc.stop()
    assert svc.invariant_violations() == []


# ------------------------------------------------------------ starvation ----

def test_bulk_saturation_cannot_starve_interactive():
    """THE starvation test: a bulk flood (way over quota) concurrent with
    an interactive stream — every interactive request is served inside
    its class budget, bulk absorbs the rejections, and every book
    closes."""
    policy = SLOPolicy((
        SLOClass("interactive", rank=0, deadline_s=0.5),
        SLOClass("standard", rank=1, deadline_s=1.0, queue_share=0.75),
        SLOClass("bulk", rank=2, deadline_s=3.0,
                 quota_rps=20.0, quota_burst=5.0, queue_share=0.5),
    ))
    svc = _stub_service(policy=policy, capacity=16)
    months = svc.spec.months
    stop = threading.Event()
    bulk_reqs: list = []

    def _flood():
        i = 0
        while not stop.is_set() and i < 400:
            v, m = _panel(4, months, seed=1000 + i)
            bulk_reqs.append(svc.submit("momentum", v, m, priority="bulk",
                                        cacheable=False))
            i += 1

    flood = threading.Thread(target=_flood, daemon=True)
    flood.start()
    inter = []
    for i in range(20):
        v, m = _panel(4, months, seed=i)
        inter.append(svc.submit("momentum", v, m, priority="interactive",
                                cacheable=False))
        # an interactive STREAM, not an interactive flood: arrivals are
        # paced like a client, the bulk side is the saturating tenant
        threading.Event().wait(0.003)
    stop.set()
    flood.join(timeout=10.0)
    for r in inter:
        assert r.wait(5.0), r.state
    for r in bulk_reqs:
        assert r.wait(5.0), r.state
    svc.stop()
    assert svc.invariant_violations() == []
    books = svc.queue.class_accounting()
    # the flood provably hit the quota
    assert books["bulk"]["rejected_quota"] > 0, books["bulk"]
    # every interactive request was served, inside the class budget
    assert all(r.state == "served" for r in inter), (
        [(r.state, r.error) for r in inter if r.state != "served"])
    budget_s = policy.resolve("interactive").deadline_s
    walls = sorted(r.total_s for r in inter)
    # judge all-but-one against the budget: the property under test is
    # scheduling (interactive never queues behind bulk), and a single
    # straggler on a contended test machine is machine noise, not a
    # starvation signal — but the p95 busting a 0.5 s budget when stub
    # dispatches take microseconds could only be bulk in the way
    assert walls[-2] <= budget_s, (
        f"interactive p95 {walls[-2] * 1e3:.1f} ms busted the "
        f"{budget_s * 1e3:.0f} ms class budget under bulk saturation "
        f"(walls ms: {[round(w * 1e3, 1) for w in walls]})")


def test_queue_share_bounds_bulk_occupancy():
    """Even inside its rate quota, bulk can never occupy more than its
    share of the queue slots — interactive admission capacity survives a
    bulk pile-up by construction."""
    from csmom_tpu.serve.queue import AdmissionQueue, Request

    policy = SLOPolicy((
        SLOClass("interactive", rank=0, deadline_s=0.5),
        SLOClass("bulk", rank=1, deadline_s=3.0, queue_share=0.5),
    ))
    q = AdmissionQueue(capacity=8, policy=policy)  # bulk may hold 4

    def mk(prio):
        v, m = _panel(2, 24)
        return Request(kind="momentum", values=v, mask=m, n_assets=2,
                       priority=prio)

    outcomes = [q.submit(mk("bulk")).state for _ in range(6)]
    assert outcomes == ["queued"] * 4 + ["rejected"] * 2
    assert q.rejected_quota == 2
    # the other half of the queue is still open for interactive
    assert all(q.submit(mk("interactive")).state == "queued"
               for _ in range(4))


# ------------------------------------------------- artifact + validator ----

def _v2_artifact(**over):
    art = {
        "kind": "serve", "schema_version": 2, "run_id": "x",
        "metric": "serve_throughput_rps", "value": 10.0, "unit": "req/s",
        "vs_baseline": 1.0, "wall_s": 1.0, "offered_limited": False,
        "requests": {"admitted": 6, "served": 4, "rejected": 2,
                     "expired": 0, "expired_dispatched": 0},
        "classes": {
            "interactive": {"admitted": 4, "served": 4, "rejected": 0,
                            "expired": 0, "rejected_quota": 0,
                            "latency_ms": {"p50": 1.0, "p95": 2.0,
                                           "p99": 3.0},
                            "budget_ms": 500.0, "within_budget": True},
            "bulk": {"admitted": 2, "served": 0, "rejected": 2,
                     "expired": 0, "rejected_quota": 2,
                     "latency_ms": {"p50": None, "p95": None, "p99": None},
                     "budget_ms": 3000.0, "within_budget": None},
        },
        "cache": {"enabled": True, "hits": 2, "misses": 3,
                  "stale_blocked": 1, "stale_hits": 0, "lookups": 6,
                  "hit_rate": round(2 / 6, 4), "inserts": 3,
                  "evictions": 0},
        "latency_ms": {
            "queue": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "service": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
            "total": {"p50": 2.0, "p95": 4.0, "p99": 6.0},
        },
        "batches": {"count": 4, "size_hist": {"1": 4}, "mean_size": 1.0,
                    "pad_fraction": 0.0,
                    "fire_reasons": {"window": 3, "deadline_risk": 1}},
        "compile": {"in_window_fresh_compiles": 0},
        "offered": {"schedule": "1x10", "schedule_kind": "bursty",
                    "offered_rps": 10.0, "n_arrivals": 10},
        "extra": {"platform": "cpu", "engine": "stub", "workload": "w"},
    }
    art.update(over)
    return art


def test_serve_v2_validator_enforces_class_and_cache_books():
    assert inv.validate(_v2_artifact()) == []
    # broken per-class book
    bad = json.loads(json.dumps(_v2_artifact()))
    bad["classes"]["interactive"]["served"] = 3
    assert any("class 'interactive' book broken" in v
               for v in inv.validate(bad))
    # class books that do not sum to the global book
    bad = json.loads(json.dumps(_v2_artifact()))
    bad["classes"].pop("bulk")
    bad["requests"]["admitted"] = 4
    assert any("accounting broken" in v or "do not sum" in v
               for v in inv.validate(bad))
    # a stale cache hit is invalid evidence, full stop
    bad = json.loads(json.dumps(_v2_artifact()))
    bad["cache"]["stale_hits"] = 1
    assert any("stale" in v for v in inv.validate(bad))
    # hit_rate must reconcile with its own counters
    bad = json.loads(json.dumps(_v2_artifact()))
    bad["cache"]["hit_rate"] = 0.9
    assert any("hit_rate" in v for v in inv.validate(bad))
    # offered_rps is required in v2 (the r11 footnote, made mechanical)
    bad = json.loads(json.dumps(_v2_artifact()))
    del bad["offered"]["offered_rps"]
    assert any("offered_rps" in v for v in inv.validate(bad))
    # v1 artifacts (SERVE_r10.json's era) validate WITHOUT the v2 blocks
    v1 = json.loads(json.dumps(_v2_artifact()))
    v1["schema_version"] = 1
    for k in ("classes", "cache", "offered_limited"):
        v1.pop(k, None)
    assert inv.validate(v1) == []


def test_ledger_ingests_v2_rows_and_flags_offered_limited(tmp_path):
    from csmom_tpu.obs import ledger as ld

    sat = _v2_artifact()                        # rejects: saturation
    lim = _v2_artifact(offered_limited=True)    # fully kept up
    lim["requests"] = {"admitted": 6, "served": 6, "rejected": 0,
                       "expired": 0, "expired_dispatched": 0}
    lim["classes"]["bulk"] = {
        "admitted": 2, "served": 2, "rejected": 0, "expired": 0,
        "rejected_quota": 0,
        "latency_ms": {"p50": 1.0, "p95": 2.0, "p99": 3.0},
        "budget_ms": 3000.0, "within_budget": True}
    with open(tmp_path / "SERVE_r01.json", "w") as f:
        json.dump(sat, f)
    with open(tmp_path / "SERVE_r02.json", "w") as f:
        json.dump(lim, f)
    L = ld.load(str(tmp_path))
    metrics = {r.metric for r in L.rows}
    assert {"serve_throughput_rps", "serve_offered_rps",
            "serve_cache_hit_rate", "serve_interactive_p99_ms",
            "serve_p99_under_burst_ms"} <= metrics
    thr = {r.run: r for r in L.rows if r.metric == "serve_throughput_rps"}
    # the saturated run's throughput gates; the offered-limited one is
    # visible but flagged — it measured the load, not the ceiling
    assert thr["r01"].gate_eligible()
    assert not thr["r02"].gate_eligible()
    assert "offered-limited" in thr["r02"].flags
    # latency rows still gate on both runs
    p99 = [r for r in L.rows if r.metric == "serve_p99_ms"]
    assert all(r.gate_eligible() for r in p99) and len(p99) == 2
    # offered rows are informational, never gating
    off = [r for r in L.rows if r.metric == "serve_offered_rps"]
    assert all(not r.gate_eligible() for r in off)


# ------------------------------------------------------ adaptive batcher ----

def test_deadline_risk_fires_before_the_window_expires_the_request():
    """A tight deadline inside a LONG coalescing window: the adaptive
    batcher must fire early (the request is served), where the r10
    fixed-window batcher would have let it expire in the queue."""
    svc = _stub_service(max_wait_s=0.4)
    months = svc.spec.months
    # train the service EMA with one dispatch
    w = svc.submit("momentum", *_panel(3, months, seed=9), deadline_s=5.0)
    assert w.wait(5.0) and w.state == "served"
    r = svc.submit("momentum", *_panel(3, months, seed=10),
                   deadline_s=0.08, cacheable=False)
    assert r.wait(5.0)
    assert r.state == "served", (r.state, r.error)
    assert r.total_s < 0.4, "the window was waited out, not cut short"
    svc.stop()
    reasons = svc.batch_stats()["fire_reasons"]
    assert reasons.get("deadline_risk", 0) >= 1, reasons
    assert svc.invariant_violations() == []


def test_refill_fires_immediately_under_backlog():
    """Continuous batching: with a backlog waiting when the engine frees,
    the next batch collects with a zero window (fire reason refill) —
    sustained load never pays the idle coalescing wait."""
    svc = _stub_service(max_wait_s=0.2)
    months = svc.spec.months
    # stall the engine so a real backlog builds while a batch is in
    # flight — the refill decision needs work WAITING when it frees
    real_score = svc.engine.score

    def slow_score(kind, values, mask):
        threading.Event().wait(0.05)
        return real_score(kind, values, mask)

    svc.engine.score = slow_score
    reqs = [svc.submit("momentum", *_panel(3, months, seed=i),
                       deadline_s=5.0, cacheable=False)
            for i in range(10)]
    for r in reqs:
        assert r.wait(5.0) and r.state == "served", (r.state, r.error)
    svc.stop()
    reasons = svc.batch_stats()["fire_reasons"]
    # under backlog the engine-freed path fires with a zero window:
    # either a grown full batch or an immediate refill — never only the
    # idle window
    assert (reasons.get("refill", 0) + reasons.get("full", 0) >= 1
            and reasons.get("refill", 0) >= 1), reasons
    assert svc.invariant_violations() == []
