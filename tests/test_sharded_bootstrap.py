"""Sharded bootstrap == single-device bootstrap, on the 8-device CPU mesh."""

import numpy as np
import jax
import pytest

from csmom_tpu.analytics import block_bootstrap
from csmom_tpu.parallel import make_mesh, sharded_block_bootstrap

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices()[:8], grid_axis=1)


def test_matches_single_device(rng, mesh):
    T = 72
    x = rng.normal(0.005, 0.04, size=T)
    v = rng.random(T) > 0.1
    x = np.where(v, x, np.nan)
    key = jax.random.PRNGKey(11)
    local = block_bootstrap(x, v, key, n_samples=64, block_len=5)
    dist = sharded_block_bootstrap(x, v, key, mesh, n_samples=64, block_len=5)
    np.testing.assert_allclose(
        np.asarray(dist.mean_samples), np.asarray(local.mean_samples), rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(dist.sharpe_samples), np.asarray(local.sharpe_samples), rtol=1e-12
    )
    np.testing.assert_allclose(np.asarray(dist.mean_ci), np.asarray(local.mean_ci), rtol=1e-12)


def test_indivisible_samples_raise(rng, mesh):
    x = rng.normal(size=24)
    v = np.ones(24, dtype=bool)
    with pytest.raises(ValueError, match="not divisible"):
        sharded_block_bootstrap(x, v, jax.random.PRNGKey(0), mesh, n_samples=13)
