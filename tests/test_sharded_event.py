"""Sharded event engine == single-device engine, on the 8-device CPU mesh."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from csmom_tpu.backtest.event import event_backtest
from csmom_tpu.parallel import make_mesh, sharded_event_backtest
from csmom_tpu.parallel.mesh import pad_assets

from tests.test_event_latency import _workload

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(jax.devices()[:8], grid_axis=1)


def _compare(res_d, res_l, A):
    np.testing.assert_allclose(np.asarray(res_d.cash), np.asarray(res_l.cash), rtol=1e-12)
    np.testing.assert_allclose(
        np.asarray(res_d.portfolio_value), np.asarray(res_l.portfolio_value), rtol=1e-12
    )
    np.testing.assert_allclose(np.asarray(res_d.pnl), np.asarray(res_l.pnl), rtol=1e-9, atol=1e-9)
    np.testing.assert_array_equal(
        np.asarray(res_d.positions)[:A], np.asarray(res_l.positions)
    )
    np.testing.assert_array_equal(np.asarray(res_d.bar_mask), np.asarray(res_l.bar_mask))
    assert int(res_d.n_trades) == int(res_l.n_trades)
    assert int(res_d.n_buys) == int(res_l.n_buys)
    np.testing.assert_allclose(
        float(res_d.net_notional), float(res_l.net_notional), rtol=1e-12
    )


def test_matches_single_device(rng, mesh):
    price, valid, score, adv, vol = _workload(rng, a=12, t=50)
    local = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                           jnp.asarray(adv), jnp.asarray(vol))
    pv, mv, A = pad_assets(price, valid, 8)
    sc = np.zeros_like(pv)
    sc[:12] = score
    advp = np.concatenate([adv, np.full(pv.shape[0] - 12, 1e5)])
    volp = np.concatenate([vol, np.full(pv.shape[0] - 12, 0.02)])
    dist = sharded_event_backtest(
        jnp.asarray(pv), jnp.asarray(mv), jnp.asarray(sc),
        jnp.asarray(advp), jnp.asarray(volp), mesh,
    )
    _compare(dist, local, 12)


def test_matches_with_latency(rng, mesh):
    price, valid, score, adv, vol = _workload(rng, a=16, t=40)
    local = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                           jnp.asarray(adv), jnp.asarray(vol), latency_bars=3)
    dist = sharded_event_backtest(
        jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
        jnp.asarray(adv), jnp.asarray(vol), mesh, latency_bars=3,
    )
    _compare(dist, local, 16)


@pytest.mark.parametrize("n_shards", [1, 2, 4, 8])
def test_limit_mode_matches_single_device(rng, n_shards):
    """Limit fills are counter-keyed by global (asset, bar): any asset-shard
    count reproduces the single-device draws exactly (VERDICT r2 missing #4)."""
    price, valid, score, adv, vol = _workload(rng, a=16, t=40)
    key = jax.random.PRNGKey(7)
    local = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                           jnp.asarray(adv), jnp.asarray(vol),
                           order_type="limit", aggressiveness=0.6, fill_key=key)
    shard_mesh = make_mesh(jax.devices()[:n_shards], grid_axis=1)
    dist = sharded_event_backtest(
        jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
        jnp.asarray(adv), jnp.asarray(vol), shard_mesh,
        order_type="limit", aggressiveness=0.6, fill_key=key,
    )
    _compare(dist, local, 16)
    np.testing.assert_array_equal(np.asarray(dist.trade_side),
                                  np.asarray(local.trade_side))
    assert int(local.n_trades) > 0


def test_limit_with_latency_sharded(rng, mesh):
    """Limit filter composes with delayed fills under asset sharding."""
    price, valid, score, adv, vol = _workload(rng, a=16, t=40)
    key = jax.random.PRNGKey(3)
    local = event_backtest(jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
                           jnp.asarray(adv), jnp.asarray(vol),
                           order_type="limit", fill_key=key, latency_bars=2)
    dist = sharded_event_backtest(
        jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
        jnp.asarray(adv), jnp.asarray(vol), mesh,
        order_type="limit", fill_key=key, latency_bars=2,
    )
    _compare(dist, local, 16)
    np.testing.assert_array_equal(np.asarray(dist.trade_side),
                                  np.asarray(local.trade_side))


def test_indivisible_assets_raise(rng, mesh):
    price, valid, score, adv, vol = _workload(rng, a=9, t=20)
    with pytest.raises(ValueError, match="pad_assets"):
        sharded_event_backtest(
            jnp.asarray(price), jnp.asarray(valid), jnp.asarray(score),
            jnp.asarray(adv), jnp.asarray(vol), mesh,
        )
