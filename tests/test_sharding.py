"""Sharded engines on the mocked 8-device CPU mesh: exact equality with the
single-device engines (distribution must not change a single bit of logic)."""

import numpy as np
import pytest

import jax

from csmom_tpu.backtest import monthly_spread_backtest, jk_grid_backtest
from csmom_tpu.parallel import (
    make_mesh,
    auto_mesh,
    sharded_monthly_spread_backtest,
    sharded_jk_grid_backtest,
)
from csmom_tpu.parallel.mesh import pad_assets

# 8-device-mesh / compile-heavy: excluded from the default fast tier
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def eight_devices():
    if len(jax.devices()) < 8:
        pytest.skip("8 virtual CPU devices not configured")
    return jax.devices()[:8]


def _panel(rng, A=37, M=60):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.003, 0.07, size=(A, M)), axis=1))
    prices[:5, :12] = np.nan  # late entrants
    mask = np.isfinite(prices)
    return prices, mask


def test_sharded_monthly_matches_single(rng, eight_devices):
    prices, mask = _panel(rng)
    mesh = make_mesh(eight_devices, grid_axis=1)
    pv, mv, A = pad_assets(prices, mask, mesh.shape["assets"])

    spread, valid, mean, sh, ts = sharded_monthly_spread_backtest(pv, mv, mesh)
    single = monthly_spread_backtest(prices, mask)

    np.testing.assert_array_equal(np.asarray(valid), np.asarray(single.spread_valid))
    np.testing.assert_allclose(
        np.asarray(spread)[np.asarray(valid)],
        np.asarray(single.spread)[np.asarray(single.spread_valid)],
        rtol=1e-12,
    )
    assert abs(float(mean) - float(single.mean_spread)) < 1e-12
    assert abs(float(sh) - float(single.ann_sharpe)) < 1e-12


def test_sharded_grid_matches_single(rng, eight_devices):
    prices, mask = _panel(rng, A=29, M=72)
    mesh = make_mesh(eight_devices, grid_axis=2)  # 2 grid x 4 asset shards
    pv, mv, A = pad_assets(prices, mask, mesh.shape["assets"])

    Js = np.array([6, 12])  # one J per grid shard
    Ks = np.array([1, 3, 6])
    res = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh)
    single = jk_grid_backtest(prices, mask, Js, Ks)

    live = np.asarray(res.spread_valid)
    np.testing.assert_array_equal(live, np.asarray(single.spread_valid))
    got = np.asarray(res.spreads)
    want = np.asarray(single.spreads)
    np.testing.assert_allclose(
        got[live], want[np.asarray(single.spread_valid)], rtol=1e-11
    )
    np.testing.assert_allclose(np.asarray(res.mean_spread),
                               np.asarray(single.mean_spread),
                               rtol=1e-10, equal_nan=True)
    # the sharded engine must report the same corrected inference as the
    # single-device GridResult (VERDICT r2 weak #3)
    np.testing.assert_allclose(np.asarray(res.tstat_nw),
                               np.asarray(single.tstat_nw),
                               rtol=1e-10, equal_nan=True)
    np.testing.assert_allclose(np.asarray(res.tstat), np.asarray(single.tstat),
                               rtol=1e-10, equal_nan=True)


def test_sharded_grid_pallas_impl_matches_xla(rng, eight_devices):
    """impl='pallas' plumbed through the sharded path (VERDICT r2 weak #4)."""
    prices, mask = _panel(rng, A=29, M=72)
    mesh = make_mesh(eight_devices, grid_axis=2)
    pv, mv, _ = pad_assets(prices, mask, mesh.shape["assets"])

    Js = np.array([6, 12])
    Ks = np.array([1, 3])
    res_p = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, impl="pallas")
    res_x = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, impl="xla")
    np.testing.assert_array_equal(np.asarray(res_p.spread_valid),
                                  np.asarray(res_x.spread_valid))
    np.testing.assert_allclose(np.asarray(res_p.spreads),
                               np.asarray(res_x.spreads),
                               rtol=1e-11, equal_nan=True)
    np.testing.assert_allclose(np.asarray(res_p.tstat_nw),
                               np.asarray(res_x.tstat_nw),
                               rtol=1e-10, equal_nan=True)


def test_sharded_rank_mode(rng, eight_devices):
    prices, mask = _panel(rng, A=40, M=48)
    mesh = make_mesh(eight_devices, grid_axis=1)
    pv, mv, _ = pad_assets(prices, mask, 8)
    spread, valid, *_ = sharded_monthly_spread_backtest(pv, mv, mesh, mode="rank")
    single = monthly_spread_backtest(prices, mask, mode="rank")
    np.testing.assert_allclose(
        np.asarray(spread)[np.asarray(valid)],
        np.asarray(single.spread)[np.asarray(single.spread_valid)],
        rtol=1e-12,
    )


def test_auto_mesh_and_padding(rng):
    mesh = auto_mesh(4)
    assert mesh.shape["assets"] == 4
    prices, mask = _panel(rng, A=10, M=20)
    pv, mv, A = pad_assets(prices, mask, 4)
    assert pv.shape[0] == 12 and A == 10
    assert not mv[10:].any()


def test_sharded_grid_bf16_impl_close_counts_exact(rng, eight_devices):
    """impl='matmul_bf16' through the sharded path: validity (from the
    exact f32-accumulated counts) is bit-identical to xla; spreads are
    within bf16 input-rounding tolerance."""
    prices, mask = _panel(rng, A=29, M=72)
    mesh = make_mesh(eight_devices, grid_axis=2)
    pv, mv, _ = pad_assets(prices, mask, mesh.shape["assets"])

    Js = np.array([6, 12])
    Ks = np.array([1, 3])
    res_b = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, impl="matmul_bf16")
    res_x = sharded_jk_grid_backtest(pv, mv, Js, Ks, mesh, impl="xla")
    np.testing.assert_array_equal(np.asarray(res_b.spread_valid),
                                  np.asarray(res_x.spread_valid))
    v = np.asarray(res_x.spread_valid)
    np.testing.assert_allclose(np.asarray(res_b.spreads)[v],
                               np.asarray(res_x.spreads)[v],
                               rtol=0, atol=2e-3)


def test_sharded_banded_matches_single(rng, eight_devices):
    """The band recursion is per-asset, so sharding it must be exact: the
    8-device banded engine reproduces banded_from_labels bit-for-bit
    (padded lanes have no signal, so they never enter a book)."""
    from csmom_tpu.backtest import banded_monthly_backtest
    from csmom_tpu.parallel import sharded_banded_backtest

    prices, mask = _panel(rng)
    mesh = make_mesh(eight_devices, grid_axis=1)
    pv, mv, A = pad_assets(prices, mask, mesh.shape["assets"])

    for band in (0, 1):
        spread, valid, mean, sh, tnw = sharded_banded_backtest(
            pv, mv, mesh, lookback=12, skip=1, n_bins=5, band=band)
        single = banded_monthly_backtest(prices, mask, lookback=12, skip=1,
                                         n_bins=5, band=band)
        np.testing.assert_array_equal(np.asarray(valid),
                                      np.asarray(single.spread_valid))
        np.testing.assert_allclose(
            np.asarray(spread)[np.asarray(valid)],
            np.asarray(single.spread)[np.asarray(single.spread_valid)],
            rtol=1e-12)
        assert abs(float(mean) - float(single.mean_spread)) < 1e-12
        assert abs(float(tnw) - float(single.tstat_nw)) < 1e-11
