"""Analytics stats vs the reference's sharpe() and scipy-free t-stat oracle."""

import numpy as np

from csmom_tpu.analytics import sharpe, masked_mean, masked_std, t_stat
from csmom_tpu.analytics.stats import cumulative_growth


def reference_sharpe(returns, freq_per_year=252):
    """utils.py:8-16 semantics, re-derived."""
    rs = np.asarray(returns)
    if len(rs) == 0:
        return float("nan")
    sd = rs.std(ddof=1) * freq_per_year**0.5
    if sd == 0:
        return float("nan")
    return rs.mean() * freq_per_year / sd


def test_sharpe_matches_reference(rng):
    r = rng.normal(0.001, 0.02, size=120)
    valid = np.ones_like(r, dtype=bool)
    got = float(sharpe(r, valid, freq_per_year=12))
    assert abs(got - reference_sharpe(r, 12)) < 1e-12


def test_sharpe_nan_cases():
    r = np.zeros(10)
    assert np.isnan(float(sharpe(r, np.ones(10, bool), freq_per_year=12)))
    assert np.isnan(float(sharpe(r, np.zeros(10, bool), freq_per_year=12)))


def test_masked_moments(rng):
    x = rng.normal(size=50)
    valid = rng.random(50) > 0.3
    assert abs(float(masked_mean(x, valid)) - x[valid].mean()) < 1e-12
    assert abs(float(masked_std(x, valid)) - x[valid].std(ddof=1)) < 1e-12


def test_t_stat(rng):
    x = rng.normal(0.5, 1.0, size=200)
    valid = np.ones(200, bool)
    want = x.mean() / (x.std(ddof=1) / np.sqrt(200))
    assert abs(float(t_stat(x, valid)) - want) < 1e-10


def test_cumulative_growth(rng):
    r = rng.normal(0, 0.02, size=30)
    valid = rng.random(30) > 0.2
    got = np.asarray(cumulative_growth(r, valid))
    want = np.cumprod(np.where(valid, 1 + r, 1.0))
    np.testing.assert_allclose(got, want, rtol=1e-12)


class TestRollingSharpe:
    def test_matches_pandas_rolling_oracle(self, rng):
        """Trailing-window Sharpe equals pandas rolling mean/std (ddof=1)
        annualized, with NaN-skipping window counts."""
        import pandas as pd

        from csmom_tpu.analytics import rolling_sharpe

        T, W = 120, 24
        r = rng.normal(0.004, 0.05, size=T)
        valid = rng.random(T) > 0.15
        r = np.where(valid, r, np.nan)

        got, ok = rolling_sharpe(r, valid, W, freq_per_year=12)
        s = pd.Series(r)
        m = s.rolling(W, min_periods=W).mean()
        sd = s.rolling(W, min_periods=W).std(ddof=1)
        want = (m * 12) / (sd * np.sqrt(12))
        wv = want.notna().values
        np.testing.assert_array_equal(np.asarray(ok), wv)
        np.testing.assert_allclose(np.asarray(got)[wv], want.values[wv],
                                   rtol=1e-9)

    def test_batched_and_full_window_matches_sharpe(self, rng):
        """A window covering the whole valid history reproduces the scalar
        sharpe() at the last position; leading axes broadcast."""
        from csmom_tpu.analytics import rolling_sharpe, sharpe

        G, T = 3, 60
        r = rng.normal(0.002, 0.04, size=(G, T))
        valid = np.ones((G, T), bool)
        got, ok = rolling_sharpe(r, valid, T, freq_per_year=12)
        assert ok[:, -1].all()
        np.testing.assert_allclose(
            np.asarray(got[:, -1]),
            np.asarray(sharpe(r, valid, freq_per_year=12)), rtol=1e-9)
