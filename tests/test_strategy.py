"""Strategy plugin boundary tests.

Pins the north-star constraint (BASELINE.json): plugged-in strategies run
through the unmodified ranking/portfolio engines on both backends, and the
built-in ``Momentum`` strategy is bit-identical to the dedicated momentum
engine.
"""

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from csmom_tpu.backends import run_monthly
from csmom_tpu.backtest import monthly_spread_backtest
from csmom_tpu.ops.ranking import decile_assign_panel
from csmom_tpu.panel.panel import Panel
from csmom_tpu.signals.momentum import momentum
from csmom_tpu.strategy import (
    Momentum,
    Reversal,
    Strategy,
    VolumeZMomentum,
    ZScoreCombo,
    available_strategies,
    make_strategy,
    register_strategy,
    strategy_backtest,
    strategy_backtest_pandas,
)


def _toy(rng, a=30, m=48, gaps=False):
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(a, m)), axis=1))
    prices[: a // 5, : m // 4] = np.nan  # late listings
    if gaps:
        prices[rng.random((a, m)) < 0.03] = np.nan
    mask = np.isfinite(prices)
    return prices, mask


def _panel(prices):
    a, m = prices.shape
    times = np.array([np.datetime64("2000-01-31") + 31 * i for i in range(m)])
    return Panel.from_dense(prices, [f"T{i:03d}" for i in range(a)], times)


def test_momentum_strategy_matches_dedicated_engine(rng):
    prices, mask = _toy(rng)
    ded = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    via = strategy_backtest(prices, mask, Momentum(lookback=6, skip=1), n_bins=5)
    np.testing.assert_array_equal(np.asarray(ded.labels), np.asarray(via.labels))
    np.testing.assert_allclose(
        np.asarray(ded.spread), np.asarray(via.spread), equal_nan=True
    )
    assert float(ded.ann_sharpe) == float(via.ann_sharpe)


def test_momentum_strategy_matches_engine_with_delistings(rng):
    """The parity contract must hold on DELISTING panels too: the pad
    semantics carry a delisted asset's signal forward, and both paths must
    apply the same formation_listed_mask drop rule (a latent divergence
    here survived every late-entrant-only fixture)."""
    prices, mask = _toy(rng)
    prices[-4:, 30:] = np.nan  # four delistings mid-sample
    mask = np.isfinite(prices)
    ded = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    via = strategy_backtest(prices, mask, Momentum(lookback=6, skip=1), n_bins=5)
    np.testing.assert_array_equal(np.asarray(ded.labels), np.asarray(via.labels))
    np.testing.assert_allclose(
        np.asarray(ded.spread), np.asarray(via.spread), equal_nan=True
    )
    assert float(ded.ann_sharpe) == float(via.ann_sharpe)


def test_reversal_is_negated_momentum_ranks(rng):
    prices, mask = _toy(rng)
    res = strategy_backtest(prices, mask, Reversal(lookback=1, skip=0), n_bins=5)
    mom, valid = momentum(prices, mask, lookback=1, skip=0)
    labels, _ = decile_assign_panel(
        jnp.where(valid, -mom, jnp.nan), valid, n_bins=5
    )
    np.testing.assert_array_equal(np.asarray(res.labels), np.asarray(labels))


def test_zscore_combo_single_component_same_deciles(rng):
    """z-scoring is monotone per date -> identical decile labels."""
    prices, mask = _toy(rng, gaps=True)
    combo = ZScoreCombo(components=((Momentum(lookback=6, skip=1), 1.0),))
    via = strategy_backtest(prices, mask, combo, n_bins=5)
    ded = monthly_spread_backtest(prices, mask, lookback=6, skip=1, n_bins=5)
    np.testing.assert_array_equal(np.asarray(via.labels), np.asarray(ded.labels))


@pytest.mark.slow
def test_volume_z_momentum_gamma_zero_matches_momentum(rng):
    prices, mask = _toy(rng)
    volumes = rng.lognormal(10, 1, size=prices.shape)
    vm = mask.copy()
    strat = VolumeZMomentum(lookback=6, skip=1, vol_lookback=3, gamma=0.0)
    via = strategy_backtest(
        prices, mask, strat, n_bins=5, volumes=volumes, volumes_mask=vm
    )
    # gamma=0 leaves the z-scored momentum, monotone per date; but validity
    # additionally requires a full 3-month volume window
    mom, valid = momentum(prices, mask, lookback=6, skip=1)
    score, svalid = strat.signal(
        jnp.asarray(prices), jnp.asarray(mask),
        volumes=jnp.asarray(volumes), volumes_mask=jnp.asarray(vm),
    )
    labels, _ = decile_assign_panel(score, svalid, n_bins=5)
    np.testing.assert_array_equal(np.asarray(via.labels), np.asarray(labels))
    # on fully observed volume, the extra requirement only trims the first
    # vol_lookback months
    sv = np.asarray(svalid)
    np.testing.assert_array_equal(sv[:, 3:], np.asarray(valid)[:, 3:])


def test_volume_z_momentum_requires_volumes(rng):
    prices, mask = _toy(rng)
    with pytest.raises(ValueError, match="volumes"):
        VolumeZMomentum().signal(jnp.asarray(prices), jnp.asarray(mask))


def test_cross_backend_parity_custom_strategy(rng):
    """The same plugged-in strategy gives identical deciles/spreads through
    the TPU engine and the pandas tail."""
    prices, mask = _toy(rng)
    panel = _panel(prices)
    strat = Reversal(lookback=3, skip=1)
    tpu = run_monthly(panel, n_bins=5, backend="tpu", strategy=strat)
    pdr = run_monthly(panel, n_bins=5, backend="pandas", strategy=strat)
    np.testing.assert_array_equal(tpu.labels, pdr.labels)
    np.testing.assert_allclose(tpu.spread, pdr.spread, rtol=1e-9, equal_nan=True)
    np.testing.assert_allclose(tpu.ann_sharpe, pdr.ann_sharpe, rtol=1e-9)


def test_run_monthly_rejects_stray_kwargs_without_strategy(rng):
    """Typos must not be silently swallowed by the panels pass-through."""
    prices, _ = _toy(rng)
    with pytest.raises(TypeError, match="lokback"):
        run_monthly(_panel(prices), lokback=6)


def test_cli_momentum_params_flow_into_strategy():
    """An *explicitly set* --lookback/--skip reaches a --strategy instance
    unless --strategy-arg overrides it; built-in MomentumConfig defaults do
    NOT override a strategy's own defaults (ADVICE r1 #1)."""
    import argparse
    import dataclasses as dc

    from csmom_tpu.cli.main import _load_cfg, _parse_strategy
    from csmom_tpu.config import RunConfig

    ns = argparse.Namespace(strategy="momentum", strategy_arg=None,
                            lookback=6, skip=2, config=None)
    cfg6 = _load_cfg(ns)
    assert _parse_strategy(ns, cfg6) == Momentum(lookback=6, skip=2)
    ns2 = argparse.Namespace(strategy="momentum", strategy_arg=["lookback=9"],
                             lookback=6, skip=2, config=None)
    assert _parse_strategy(ns2, _load_cfg(ns2)) == Momentum(lookback=9, skip=2)
    # no explicit flags/config: the strategy's own defaults stand, even when
    # cfg.momentum carries non-default (but not user-set) values
    ns3 = argparse.Namespace(strategy="momentum", strategy_arg=None,
                             lookback=None, skip=None, config=None)
    cfg_stale = dc.replace(
        RunConfig(),
        momentum=dc.replace(RunConfig().momentum, lookback=6, skip=2),
    )
    assert _parse_strategy(ns3, cfg_stale) == Momentum()
    assert _parse_strategy(argparse.Namespace(strategy=None), cfg_stale) is None


def test_volume_fallback_mask_excludes_phantom_zeros(rng):
    """segment-summed volume panels store 0.0 at unobserved slots; the
    fallback mask must not rank those months."""
    prices, mask = _toy(rng, a=20, m=30)
    volumes = rng.lognormal(10, 1, size=prices.shape)
    volumes[:, :10] = 0.0  # phantom pre-listing zeros, no mask given
    strat = VolumeZMomentum(lookback=3, skip=1, vol_lookback=3)
    _, valid = strat.signal(
        jnp.asarray(prices), jnp.asarray(mask), volumes=jnp.asarray(volumes)
    )
    # windows overlapping the phantom region are invalid
    assert not np.asarray(valid)[:, :12].any()


def test_registry_roundtrip_and_unknown():
    s = make_strategy("momentum", lookback=9, skip=2)
    assert s == Momentum(lookback=9, skip=2)
    assert "reversal" in available_strategies()
    with pytest.raises(KeyError, match="unknown strategy"):
        make_strategy("nope")


def test_intermediate_momentum_registered(rng):
    """NM-2012 intermediate momentum is a first-class zoo member: the
    registry constructs it by name, `csmom strategies` lists it, and its
    signal equals the plain momentum signal at (lookback=6, skip=7) — it
    IS that parametrization, owned by the registry rather than a CLI row
    (VERDICT r4 #7)."""
    s = make_strategy("intermediate_momentum")
    assert (s.lookback, s.skip) == (6, 7)
    assert "intermediate_momentum" in available_strategies()

    prices, mask = _toy(rng, m=40)
    got, gv = s.signal(jnp.asarray(prices), jnp.asarray(mask))
    want, wv = Momentum(lookback=6, skip=7).signal(
        jnp.asarray(prices), jnp.asarray(mask)
    )
    np.testing.assert_array_equal(np.asarray(gv), np.asarray(wv))
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=0, atol=0, equal_nan=True
    )

    from csmom_tpu.cli.main import main as cli_main
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        assert cli_main(["strategies"]) == 0
    assert "intermediate_momentum" in buf.getvalue()


def test_low_volatility_matches_pandas_rolling_std_oracle(rng):
    """Blitz-van Vliet low-vol: the signal is the NEGATED pandas
    ``pct_change().rolling(window, min_periods).std(ddof=1)`` per asset,
    and it runs through the unmodified engine by registry name."""
    import pandas as pd

    prices, mask = _toy(rng, m=60, gaps=True)
    s = make_strategy("low_volatility", window=12, min_obs=6)
    got, gv = s.signal(jnp.asarray(prices), jnp.asarray(mask))

    want = np.full_like(prices, np.nan)
    for a in range(prices.shape[0]):
        ser = pd.Series(prices[a])
        # adjacent-months return (NaN unless both ends exist — the
        # monthly_returns contract), then a CALENDAR-axis rolling window:
        # pandas rolling skips NaNs inside the window and counts only
        # non-NaN toward min_periods, matching the masked kernel
        ret = ser / ser.shift(1) - 1.0
        vol = ret.rolling(12, min_periods=6).std(ddof=1)
        want[a] = -vol.to_numpy()
    got_np = np.asarray(got)
    ok = np.isfinite(want)
    np.testing.assert_allclose(got_np[ok], want[ok], rtol=1e-5, atol=1e-9)
    # invalid slots carry no signal
    assert np.all(np.isnan(got_np[~np.asarray(gv)]))

    # and the strategy runs end-to-end through the engine by name
    res = strategy_backtest(prices, mask, s, n_bins=5)
    assert np.isfinite(np.asarray(res.spread)).any()
    assert "low_volatility" in available_strategies()


def test_user_registered_strategy_runs_through_engine(rng):
    @register_strategy("test_price_level")
    @dataclasses.dataclass(frozen=True)
    class PriceLevel(Strategy):
        """Rank directly on price level (a deliberately silly plugin)."""

        def signal(self, prices, mask, **panels):
            return jnp.where(mask, prices, jnp.nan), mask

    prices, mask = _toy(rng)
    res = strategy_backtest(prices, mask, make_strategy("test_price_level"), n_bins=5)
    # every observed month ranks (no warmup for this signal)
    labels = np.asarray(res.labels)
    assert (labels[mask] >= 0).all()
    pdr = strategy_backtest_pandas(_panel(prices).to_dataframe(), PriceLevel(), n_bins=5)
    np.testing.assert_array_equal(labels, pdr.labels.to_numpy())


def test_cross_backend_parity_residual_momentum(rng):
    """Residual momentum through both backends: one JAX signal definition,
    identical deciles and spreads from the TPU engine and the pandas tail."""
    from csmom_tpu.strategy import ResidualMomentum

    prices, mask = _toy(rng, m=90)
    panel = _panel(prices)
    strat = ResidualMomentum(lookback=6, skip=1, est_window=18)
    tpu = run_monthly(panel, n_bins=5, backend="tpu", strategy=strat)
    pdr = run_monthly(panel, n_bins=5, backend="pandas", strategy=strat)
    np.testing.assert_array_equal(tpu.labels, pdr.labels)
    np.testing.assert_allclose(tpu.spread, pdr.spread, rtol=1e-9, equal_nan=True)


def test_zscore_combo_string_spec(rng):
    """The CLI-friendly "name:weight,..." spec builds the same combo as the
    tuple API, and bad specs fail loudly."""
    from csmom_tpu.strategy import Momentum, Reversal, ZScoreCombo
    from csmom_tpu.strategy.builtin import parse_combo_spec

    prices, mask = _toy(rng)
    by_str = ZScoreCombo(components="momentum:0.6, reversal:0.4")
    by_tup = ZScoreCombo(components=((Momentum(), 0.6), (Reversal(), 0.4)))
    a = strategy_backtest(prices, mask, by_str, n_bins=5)
    b = strategy_backtest(prices, mask, by_tup, n_bins=5)
    np.testing.assert_array_equal(np.asarray(a.labels), np.asarray(b.labels))

    assert parse_combo_spec("momentum")[0][1] == 1.0
    with pytest.raises(ValueError, match="not a number"):
        parse_combo_spec("momentum:abc")
    with pytest.raises(KeyError, match="unknown strategy"):
        parse_combo_spec("nope:1.0")
    with pytest.raises(ValueError, match="empty"):
        parse_combo_spec(" , ")


def test_zscore_combo_string_spec_via_cli_parsing(rng):
    """--strategy zscore_combo --strategy-arg components=momentum:1 works
    through the REAL CLI channel: _parse_strategy's literal_eval fallback
    must deliver the spec to __post_init__ as a string."""
    import argparse

    from csmom_tpu.cli.main import _load_cfg, _parse_strategy

    ns = argparse.Namespace(
        strategy="zscore_combo",
        strategy_arg=["components=momentum:0.5,reversal:0.5"],
        lookback=None, skip=None, config=None,
    )
    s = _parse_strategy(ns, _load_cfg(ns))
    assert len(s.components) == 2
    prices, mask = _toy(rng)
    res = strategy_backtest(prices, mask, s, n_bins=5)
    assert np.asarray(res.spread_valid).any()


class TestFiftyTwoWeekHigh:
    def test_matches_pandas_rolling_max_oracle(self, rng):
        """score = P.shift(skip) / P.shift(skip).rolling(W).max(), full
        window required (min_periods=W), exactly the GH nearness ratio."""
        import pandas as pd

        from csmom_tpu.strategy import make_strategy

        A, M, W, skip = 12, 60, 12, 1
        prices = 50 * np.exp(np.cumsum(rng.normal(0.003, 0.08, size=(A, M)), axis=1))
        mask = rng.random((A, M)) > 0.15
        pv = np.where(mask, prices, np.nan)

        strat = make_strategy("high_52w", lookback=W, skip=skip)
        score, valid = strat.signal(pv, mask)

        df = pd.DataFrame(pv.T)  # time-major for pandas rolling
        shifted = df.shift(skip)
        want = shifted / shifted.rolling(W, min_periods=W).max()
        want_v = want.notna().values.T
        np.testing.assert_array_equal(np.asarray(valid), want_v)
        np.testing.assert_allclose(
            np.asarray(score)[want_v], want.values.T[want_v], rtol=1e-12
        )

    def test_runs_through_engine_and_cli_listing(self, rng):
        from csmom_tpu.backends import run_monthly
        from csmom_tpu.panel.panel import Panel
        from csmom_tpu.strategy import available_strategies, make_strategy

        assert "high_52w" in available_strategies()
        A, M = 20, 70
        prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.07, size=(A, M)), axis=1))
        panel = Panel.from_dense(
            prices, tickers=[f"T{i}" for i in range(A)],
            times=np.arange("2015-01", "2020-11", dtype="datetime64[M]")[:M],
        )
        rep = run_monthly(panel, n_bins=5, mode="rank",
                          strategy=make_strategy("high_52w"))
        assert np.isfinite(float(rep.mean_spread))
