"""Stream vertical tests: ring, watermark ingest, incremental exactness.

The load-bearing claims, in test form:

- the ring's snapshots are immutable and versioned (a consumer holding
  version v still sees version v after a million more ticks);
- the ingestor's tick ledger is closed under every arrival disorder
  (late / out-of-order / duplicate / gap), with the watermark policy
  deciding merge-vs-quarantine in event time;
- **the property tests**: after ANY seeded interleaving of in-order,
  late (merged), duplicate, and dropped ticks, the incremental
  momentum/turnover state equals the full-panel recompute BIT-FOR-BIT
  under the NaN/listing masks — in float32 AND float64;
- the numpy mirrors themselves equal the jitted ``signals`` engines
  (momentum exactly — same elementwise IEEE ops; turnover to
  float-association tolerance, XLA's cumsum may reassociate).
"""

import random

import numpy as np
import pytest

from csmom_tpu.stream.incremental import (
    IncrementalMomentum,
    IncrementalTurnover,
    full_momentum_np,
    full_turnover_np,
    nan_equal,
)
from csmom_tpu.stream.ingest import StreamIngestor, Tick, WatermarkPolicy
from csmom_tpu.stream.ring import LiveRing

PERIOD = 60 * 10**9  # one-minute bars in ns


def _bar(i: int) -> int:
    return 1_700_000_000_000_000_000 + i * PERIOD


# -------------------------------------------------------------------- ring --

class TestLiveRing:
    def test_append_write_version_monotone(self):
        ring = LiveRing(["a", "b"], capacity=4, fields=("price",))
        v0 = ring.version
        i = ring.append_bar(_bar(0))
        assert ring.version > v0
        v1 = ring.version
        ring.write("price", "a", i, 10.0)
        assert ring.version > v1
        assert ring.cell_written("price", "a", i)
        assert not ring.cell_written("price", "b", i)

    def test_snapshot_is_immutable_and_pinned(self):
        ring = LiveRing(["a", "b"], capacity=4, fields=("price",))
        i = ring.append_bar(_bar(0))
        ring.write("price", "a", i, 10.0)
        snap = ring.snapshot()
        v = snap.version
        # later mutations must not reach the snapshot
        j = ring.append_bar(_bar(1))
        ring.write("price", "a", j, 11.0)
        assert snap.version == v
        assert snap.n_bars == 1
        assert snap.values["price"][0, 0] == 10.0
        with pytest.raises(ValueError):
            snap.values["price"][0, 0] = 99.0  # read-only

    def test_ring_wraps_and_counts_evictions(self):
        ring = LiveRing(["a"], capacity=3, fields=("price",))
        for b in range(5):
            i = ring.append_bar(_bar(b))
            ring.write("price", "a", i, float(b))
        assert ring.n_bars == 3
        assert ring.evictions == 2
        assert ring.first_bar_index == 2
        snap = ring.snapshot()
        assert snap.values["price"][0].tolist() == [2.0, 3.0, 4.0]
        assert snap.bar_times.tolist() == [_bar(2), _bar(3), _bar(4)]
        assert not ring.in_window(1)

    def test_bars_must_ascend(self):
        ring = LiveRing(["a"], capacity=4, fields=("price",))
        ring.append_bar(_bar(1))
        with pytest.raises(ValueError):
            ring.append_bar(_bar(0))

    def test_stale_gap_bar_clears_on_real_write(self):
        ring = LiveRing(["a"], capacity=4, fields=("price",))
        ring.append_bar(_bar(0))
        g = ring.append_bar(_bar(1), stale=True)
        assert ring.stats()["stale_bars"] == 1
        ring.write("price", "a", g, 5.0)
        assert ring.stats()["stale_bars"] == 0


# ------------------------------------------------------------------ ingest --

def _mk(A=3, capacity=32, lateness=2):
    tickers = [f"a{i}" for i in range(A)]
    ring = LiveRing(tickers, capacity=capacity, fields=("price", "volume"))
    ing = StreamIngestor(ring, WatermarkPolicy(
        bar_period_ns=PERIOD, allowed_lateness_bars=lateness))
    return ring, ing


class TestIngest:
    def test_in_order_applies(self):
        ring, ing = _mk()
        assert ing.offer(Tick("a0", _bar(0), 10.0, 100.0)) == "applied"
        assert ing.offer(Tick("a1", _bar(0), 11.0, 110.0)) == "applied"
        assert ing.offer(Tick("a0", _bar(1), 10.5, 105.0)) == "applied"
        assert ing.invariant_violations() == []

    def test_duplicate_is_idempotent(self):
        ring, ing = _mk()
        ing.offer(Tick("a0", _bar(0), 10.0))
        v = ring.version
        assert ing.offer(Tick("a0", _bar(0), 99.0)) == "deduped"
        assert ring.version == v          # first write wins, no bump
        snap = ring.snapshot()
        assert snap.values["price"][0, 0] == 10.0
        assert ing.deduped == 1
        assert ing.invariant_violations() == []

    def test_late_within_allowance_merges_and_bumps_version(self):
        ring, ing = _mk(lateness=3)
        ing.offer(Tick("a0", _bar(0), 10.0))
        ing.offer(Tick("a0", _bar(2), 12.0))   # a1's bar-0/1 never arrived
        v = ring.version
        assert ing.offer(Tick("a1", _bar(1), 11.0)) == "merged_late"
        assert ring.version > v
        assert ing.merged_late == 1
        snap = ring.snapshot()
        assert snap.values["price"][1, 1] == 11.0
        assert ing.invariant_violations() == []

    def test_late_beyond_watermark_quarantines(self):
        ring, ing = _mk(lateness=1)
        ing.offer(Tick("a0", _bar(0), 10.0))
        ing.offer(Tick("a0", _bar(5), 15.0))
        v = ring.version
        assert ing.offer(Tick("a1", _bar(1), 11.0)) == "quarantined"
        assert ring.version == v              # nothing written
        assert ing.quarantined == 1
        assert not ring.cell_written("price", "a1", 1)
        q = list(ing.quarantine)
        assert q and "below watermark" in q[-1]["reason"]
        assert ing.invariant_violations() == []

    def test_gap_bars_materialize_stale_never_carry(self):
        ring, ing = _mk()
        ing.offer(Tick("a0", _bar(0), 10.0))
        ing.offer(Tick("a0", _bar(3), 13.0))  # bars 1, 2 skipped
        assert ing.gap_bars == 2
        snap = ring.snapshot()
        assert snap.n_bars == 4
        assert snap.stale.tolist() == [False, True, True, False]
        # the hole is masked NaN — the last price was NOT carried
        assert not snap.mask["price"][0, 1]
        assert not snap.mask["price"][0, 2]
        assert np.isnan(snap.values["price"][0, 1])

    def test_closed_accounting_equation(self):
        ring, ing = _mk(lateness=1)
        ing.offer(Tick("a0", _bar(0), 10.0))
        ing.offer(Tick("a0", _bar(0), 10.0))   # dup
        ing.offer(Tick("a0", _bar(4), 14.0))
        ing.offer(Tick("a1", _bar(3), 13.0))   # late, within
        ing.offer(Tick("a1", _bar(0), 10.0))   # late, beyond -> quarantine
        a = ing.accounting()
        assert (a["applied"] + a["merged_late"] + a["quarantined"]
                + a["deduped"]) == a["offered"] == 5
        assert ing.invariant_violations() == []


# ---------------------------------------------- incremental property tests --

def _drive_interleaved(seed: int, dtype, A=5, B=40, lateness=2,
                       lookback=6, skip=1, turn_lookback=3):
    """One seeded disordered feed: per-tick chances of being dropped
    (cell gap), delayed within the allowance (merged late), delayed past
    it (quarantined), or duplicated; whole bars occasionally skipped.
    After EVERY closed bar, assert the incremental state equals the
    full-panel mirror bit-for-bit."""
    rng = random.Random(seed)
    r = np.random.default_rng(seed)
    prices = (100.0 * np.exp(np.cumsum(r.normal(0, 0.02, (A, B)),
                                       axis=1))).astype(dtype)
    vols = r.lognormal(8.0, 0.5, (A, B)).astype(dtype)
    tickers = [f"a{i}" for i in range(A)]
    ring = LiveRing(tickers, capacity=B, fields=("price", "volume"),
                    dtype=dtype)
    ing = StreamIngestor(ring, WatermarkPolicy(
        bar_period_ns=PERIOD, allowed_lateness_bars=lateness))
    mom = IncrementalMomentum(A, lookback=lookback, skip=skip, dtype=dtype)
    turn = IncrementalTurnover(A, shares=np.ones(A), lookback=turn_lookback,
                               dtype=dtype)
    held = []
    checks = 0
    outcomes = {"merged_late": 0, "quarantined": 0, "deduped": 0,
                "dropped": 0}

    def _offer(t):
        out = ing.offer(t)
        if out == "merged_late":
            mom.mark_dirty()
            turn.mark_dirty()
        outcomes[out] = outcomes.get(out, 0) + 1

    for b in range(B):
        if rng.random() < 0.05 and 0 < b < B - 1:
            outcomes["dropped"] += A
            continue  # whole-bar gap
        for a in rng.sample(range(A), A):
            t = Tick(tickers[a], _bar(b), float(prices[a, b]),
                     float(vols[a, b]))
            u = rng.random()
            if u < 0.05:
                outcomes["dropped"] += 1
                continue                      # cell gap
            if u < 0.20:
                held.append((b + rng.randint(1, lateness + 2), t))
                continue                      # late / out-of-order
            _offer(t)
            if u < 0.28:
                _offer(t)                     # duplicate
        for h in list(held):
            if h[0] <= b:
                _offer(h[1])
                held.remove(h)
        if ring.next_bar_index == 0:
            continue
        snap = ring.snapshot()
        mom.sync(snap)
        turn.sync(snap)
        ref_m, ref_mok = full_momentum_np(
            np.asarray(snap.values["price"], dtype), snap.mask["price"],
            lookback, skip)
        cur_m, cur_mok = mom.current()
        assert nan_equal(cur_m, ref_m[:, -1]), (seed, dtype, b, "momentum")
        assert np.array_equal(cur_mok, ref_mok[:, -1])
        ref_t, ref_tok = full_turnover_np(
            np.asarray(snap.values["volume"], dtype), snap.mask["volume"],
            np.ones(A), turn_lookback)
        cur_t, cur_tok = turn.current()
        assert nan_equal(cur_t, ref_t[:, -1]), (seed, dtype, b, "turnover")
        assert np.array_equal(cur_tok, ref_tok[:, -1])
        checks += 1
    assert checks > 10
    assert ing.invariant_violations() == []
    return outcomes, mom, turn


@pytest.mark.parametrize("dtype", [np.float32, np.float64],
                         ids=["f32", "f64"])
@pytest.mark.parametrize("seed", [0, 1, 2, 7])
def test_incremental_equals_full_recompute_bit_for_bit(seed, dtype):
    outcomes, mom, turn = _drive_interleaved(seed, dtype)
    # the interleaving actually exercised the disorder paths
    assert outcomes["merged_late"] > 0
    assert outcomes["deduped"] > 0
    assert outcomes["dropped"] > 0
    # late merges forced rebuilds; none of them drifted
    assert mom.rebuilds > 0
    assert mom.drift_events == 0
    assert turn.drift_events == 0


def test_sync_rebuilds_when_ring_window_moves_past_consumed():
    """A long session wraps the ring: bars evicted before the updater
    saw them must trigger a REBUILD at the next sync, not a silent skip
    — a forward-fill carry that jumped the gap would serve wrong values
    flagged valid until the next periodic reconcile."""
    A, cap = 3, 8
    ring = LiveRing([f"a{i}" for i in range(A)], capacity=cap,
                    fields=("price",), dtype=np.float64)
    mom = IncrementalMomentum(A, lookback=2, skip=0, dtype=np.float64)
    r = np.random.default_rng(5)

    def _bar_full(b):
        i = ring.append_bar(_bar(b))
        for a in range(A):
            ring.write("price", a, i, float(100 + r.normal()))

    for b in range(4):
        _bar_full(b)
    mom.sync(ring.snapshot())
    assert mom.consumed == 4 and mom.rebuilds == 0
    # 10 more bars land unseen: the window [6, 14) no longer contains
    # the consumed frontier (4) — sync must rebuild, and the rebuilt
    # state must equal the mirror on the surviving window
    for b in range(4, 14):
        _bar_full(b)
    snap = ring.snapshot()
    assert snap.first_bar_index > mom.consumed
    mom.sync(snap)
    assert mom.rebuilds == 1
    ref_m, ref_ok = full_momentum_np(
        np.asarray(snap.values["price"]), snap.mask["price"], 2, 0)
    cur_m, cur_ok = mom.current()
    assert nan_equal(cur_m, ref_m[:, -1])
    assert np.array_equal(cur_ok, ref_ok[:, -1])


def test_reconcile_detects_seeded_drift_and_rebuilds():
    """Corrupt the running state deliberately: reconcile must DETECT the
    drift (count it) and rebuild back to exact equality — the safety
    net is real, not decorative."""
    ring, ing = _mk(A=4, capacity=32, lateness=2)
    mom = IncrementalMomentum(4, lookback=5, skip=1, dtype=np.float64)
    r = np.random.default_rng(3)
    for b in range(20):
        for a in range(4):
            ing.offer(Tick(f"a{a}", _bar(b), float(100 + r.normal()),
                           float(1000)))
    snap = ring.snapshot()
    mom.sync(snap)
    assert mom.reconcile(snap)["drift"] is False
    mom._mom = mom._mom + 1.0  # sabotage the running output state
    verdict = mom.reconcile(snap)
    assert verdict["drift"] is True
    assert mom.drift_events == 1
    assert mom.rebuilds == 1
    # after the rebuild the state is exact again
    assert mom.reconcile(snap)["drift"] is False


# ------------------------------------------------- mirror vs jax engines --

def _gappy_panel(seed, A, T, dtype):
    r = np.random.default_rng(seed)
    steps = r.normal(0, 0.03, (A, T))
    prices = (100.0 * np.exp(np.cumsum(steps, axis=1))).astype(dtype)
    mask = r.random((A, T)) > 0.12
    mask[:, 0] = True
    # one asset delists mid-panel, one lists late
    mask[0, T // 2:] = False
    mask[1, :T // 3] = False
    values = np.where(mask, prices, np.nan).astype(dtype)
    return values, mask


@pytest.mark.parametrize("dtype", [np.float32, np.float64],
                         ids=["f32", "f64"])
def test_momentum_mirror_matches_jax_engine_exactly(dtype):
    """The reconciliation reference must BE the signals engine: the
    momentum mirror and the jitted engine share every elementwise IEEE
    op, so their outputs are bitwise identical."""
    from csmom_tpu.signals.momentum import momentum

    values, mask = _gappy_panel(11, 6, 48, dtype)
    ref_m, ref_ok = full_momentum_np(values, mask, 6, 1)
    jm, jok = momentum(values, mask, lookback=6, skip=1)
    assert np.array_equal(np.asarray(jok), ref_ok)
    assert nan_equal(np.asarray(jm), ref_m)


@pytest.mark.parametrize("dtype,rtol", [(np.float32, 1e-5),
                                        (np.float64, 1e-12)],
                         ids=["f32", "f64"])
def test_turnover_mirror_matches_jax_engine_to_association(dtype, rtol):
    """Turnover parity is a tolerance check by design: the mirror (and
    the incremental updater) accumulate sequentially; XLA's cumsum may
    associate differently.  Validity planes still match exactly."""
    from csmom_tpu.signals.turnover import turnover_features

    values, mask = _gappy_panel(13, 6, 48, dtype)
    vols = np.where(mask, np.abs(values) * 37.0, np.nan).astype(dtype)
    shares = np.ones(6)
    ref_t, ref_ok = full_turnover_np(vols, mask, shares, 3)
    jt, jok = turnover_features(vols, mask, shares.astype(dtype),
                                lookback=3)["turn_avg"]
    assert np.array_equal(np.asarray(jok), ref_ok)
    both = ref_ok
    np.testing.assert_allclose(np.asarray(jt)[both], ref_t[both],
                               rtol=rtol)


# ----------------------- ROADMAP item 4 defect (a): wrap-around reconcile --
#
# Once bar count exceeds ring capacity, the updater's prefix state is
# anchored at global bar 0 while a snapshot-window recompute anchors at
# the window start.  The r12 reconcile compared them bitwise anyway and
# reported spurious drift (masked by run_replay pinning capacity ==
# bars).  The fix re-anchors (counted) and keeps real-drift detection.

def _drive_bars(ring, upd, field, values):
    """Feed every appended-but-unconsumed bar column into the updater,
    the way run_replay's per-bar loop does."""
    for g in range(upd.consumed, ring.next_bar_index):
        upd.update(*ring.column(field, g))


def test_momentum_reconcile_does_not_false_drift_after_ring_wrap():
    A, cap, total = 3, 8, 20
    ring = LiveRing([f"a{i}" for i in range(A)], capacity=cap,
                    fields=("price",), dtype=np.float64)
    mom = IncrementalMomentum(A, lookback=2, skip=0, dtype=np.float64)
    for b in range(total):
        i = ring.append_bar(_bar(b))
        for a in range(A):
            if a == 2 and b > 2:
                continue  # asset 2 goes dark after bar 2: carry-only
            ring.write("price", a, i, float(100 + a + 0.5 * b))
        _drive_bars(ring, mom, "price", None)
    snap = ring.snapshot()
    assert snap.first_bar_index > 0  # the ring wrapped

    # the pre-fix comparison: live (global-anchored) state vs the
    # window recompute — these legitimately DISAGREE (asset 2 is valid
    # under the global forward-fill carry, invalid to a window that
    # never saw it), which the old reconcile misread as drift
    live_val, live_ok = mom.current()
    ref_val, ref_ok = full_momentum_np(
        np.asarray(snap.values["price"]), snap.mask["price"], 2, 0)
    assert not (nan_equal(live_val, ref_val[:, -1])
                and bool(np.array_equal(live_ok, ref_ok[:, -1]))), (
        "precondition lost: the window recompute agreed with the live "
        "state, so this test no longer reproduces the defect")

    verdict = mom.reconcile(snap)
    assert verdict["drift"] is False, (
        "reconcile reported drift with no real error — the wrap-around "
        "false-drift defect is back")
    assert verdict["reanchored"] is True
    assert mom.reanchors == 1
    assert mom.drift_events == 0
    # and the re-anchored state equals the window mirror exactly
    cur_val, cur_ok = mom.current()
    assert nan_equal(cur_val, ref_val[:, -1])
    assert np.array_equal(cur_ok, ref_ok[:, -1])


def test_turnover_reconcile_does_not_false_drift_after_ring_wrap():
    """The turnover state is float prefix sums from global bar 0; after
    the wrap, a window-anchored recompute differs by the cancellation
    residue of the common prefix (f32 makes it visible), which must be
    re-anchored around, not reported as drift."""
    A, cap, total = 4, 16, 60
    ring = LiveRing([f"a{i}" for i in range(A)], capacity=cap,
                    fields=("volume",), dtype=np.float32)
    turn = IncrementalTurnover(A, shares=np.ones(A), lookback=3,
                               dtype=np.float32)
    for b in range(total):
        i = ring.append_bar(_bar(b))
        for a in range(A):
            ring.write("volume", a, i,
                       float(1e7 * (1.0 + 0.001 * ((a * 7 + b * 13) % 17))))
        _drive_bars(ring, turn, "volume", None)
    snap = ring.snapshot()
    assert snap.first_bar_index > 0
    live_val, live_ok = turn.current()
    ref_val, ref_ok = full_turnover_np(
        np.asarray(snap.values["volume"]), snap.mask["volume"],
        np.ones(A), 3)
    # the float residue the old bitwise compare tripped over is real...
    assert not nan_equal(live_val, ref_val[:, -1]), (
        "precondition lost: prefix cancellation left no residue; pick "
        "inputs that expose it or the regression is untested")
    # ...and reconcile treats it as a re-anchor, not drift
    verdict = turn.reconcile(snap)
    assert verdict["drift"] is False
    assert verdict["reanchored"] is True
    assert turn.reanchors == 1 and turn.drift_events == 0
    cur_val, cur_ok = turn.current()
    assert nan_equal(cur_val, ref_val[:, -1])


def test_reconcile_still_detects_real_drift_across_a_reanchor():
    """Re-anchoring must not become a blind spot: genuinely corrupted
    live state (O(signal), not O(ulp)) is still counted as drift in the
    slid-window regime."""
    A, cap, total = 3, 8, 20
    ring = LiveRing([f"a{i}" for i in range(A)], capacity=cap,
                    fields=("price",), dtype=np.float64)
    mom = IncrementalMomentum(A, lookback=2, skip=0, dtype=np.float64)
    for b in range(total):
        i = ring.append_bar(_bar(b))
        for a in range(A):
            ring.write("price", a, i, float(100 + a + 0.5 * b))
        _drive_bars(ring, mom, "price", None)
    snap = ring.snapshot()
    assert snap.first_bar_index > mom.anchor
    mom._mom = mom._mom + 1.0  # sabotage the live output state
    verdict = mom.reconcile(snap)
    assert verdict["drift"] is True and verdict["reanchored"] is True
    assert mom.drift_events == 1
    # the rebuild healed it: a fresh reconcile is clean
    assert mom.reconcile(ring.snapshot())["drift"] is False


# ------------------- ROADMAP item 4 defect (b): non-finite tick dedupe -----

class TestNonFiniteTicks:
    def test_non_finite_price_does_not_poison_dedupe_state(self):
        """Pre-fix: a NaN-price tick wrote nothing (the ring masks on
        finiteness) but still marked the (asset, bar) cell seen, so the
        later REAL tick was counted `deduped` and the cell stayed
        unfilled forever — with the books balancing the whole time."""
        ring, ing = _mk()
        ing.offer(Tick("a0", _bar(0), 10.0))
        out = ing.offer(Tick("a1", _bar(0), float("nan"), 100.0))
        assert out == "quarantined"
        q = list(ing.quarantine)
        assert q and "non-finite price" in q[-1]["reason"]
        # the real tick for the same cell must land, not dedupe
        assert ing.offer(Tick("a1", _bar(0), 11.0, 100.0)) == "applied"
        assert ring.cell_written("price", "a1", 0)
        snap = ring.snapshot()
        assert snap.values["price"][1, 0] == 11.0
        assert ing.deduped == 0
        assert ing.invariant_violations() == []

    def test_inf_price_quarantined_and_grid_not_advanced(self):
        ring, ing = _mk()
        ing.offer(Tick("a0", _bar(0), 10.0))
        before = ring.next_bar_index
        assert ing.offer(Tick("a0", _bar(5), float("inf"))) == "quarantined"
        # garbage must not materialize bars (no stale holes from junk)
        assert ring.next_bar_index == before
        assert ing.gap_bars == 0
        assert ing.invariant_violations() == []

    def test_real_duplicate_after_fix_still_dedupes(self):
        ring, ing = _mk()
        ing.offer(Tick("a0", _bar(0), 10.0))
        assert ing.offer(Tick("a0", _bar(0), 12.0)) == "deduped"
        assert ing.invariant_violations() == []
