"""Committed synthetic golden: end-to-end parity on a bare checkout.

The reference's de-facto golden is its shipped ``results/trades.csv``
(SURVEY §4) — but asserting against it needs the read-only mount, so on a
bare checkout every golden-parity test used to *skip* (VERDICT r4 missing
#3).  This file is the offline analogue: a seeded synthetic daily panel runs
both pipelines end to end, and the resulting statistics are pinned below
as constants computed once (f64, single CPU device) and committed.
Determinism caveat: PCG64's raw bit stream is version-stable, but numpy
reserves the right (NEP 19) to change Generator *distribution* methods
(standard_normal etc.) between feature releases — if both goldens fail
together right after a numpy upgrade, suspect the stream first: bump
``SYNTH_VERSION`` and re-pin before hunting kernel regressions.

What a failure means: either a kernel changed semantics (momentum window,
decile edges, fill/MTM ordering, CV folds), or the synthetic generator
changed its stream (bump ``SYNTH_VERSION`` and re-pin — the constants are
part of the generator's contract).  Tolerances are loose enough for
XLA-version reassociation of f64 reductions, tight enough that any real
semantic drift (one different trade, one shifted window) fails.
"""

import jax.numpy as jnp
import numpy as np
import pandas as pd

from csmom_tpu.analytics.stats import nw_t_stat
from csmom_tpu.backtest import monthly_spread_backtest
from csmom_tpu.panel.calendar import month_end_aggregate, month_end_segments
from csmom_tpu.panel.synthetic import synthetic_daily_panel

# -- pinned fingerprints (computed 2026-07-30, f64, xla cpu) -----------------
# monthly leg: synthetic_daily_panel(40, 1260, seed=123, listing_gaps=True)
# (re-pinned 2026-08-02 after the pandas-parity semantics fix: pct_change
# pad/forward-fill returns, delisting-aware formation mask, pandas>=2.0
# percent-roundtrip qcut edges — the oracle-suite fix set)
MONTHLY = {
    "n_months": 58,
    "n_valid_spreads": 44,
    "mean_spread": -0.024151046163,
    "ann_sharpe": -0.838545964552,
    "nw_t": -2.001284759867,
    "cum_return": 0.271094424165,
}
# event leg: synthetic_daily_panel(8, 10, seed=77) -> synthetic_minute_frame
# (seed=5, 31,200 rows) -> ridge CV -> event backtest (reference constants)
EVENT = {
    "n_trades": 29_423,
    "total_pnl": 12_246.7590405609,
    "final_cash": 1_469_477.6043309155,
    "cv_mse": [1.111000906788e-06, 1.028217201301e-06, 1.515819594342e-06],
    "n_train": 21_828,
}
# same minute frame through the causal model (--model online_ridge): the
# Sherman-Morrison scan, causal scaler, burn-in gating, and prequential
# blocks all pinned offline (computed 2026-07-31, f64, xla cpu)
ONLINE = {
    "n_trades": 28_545,
    "total_pnl": -12_923.9031903070,
    "final_cash": 1_270_969.0140300414,
    "cv_mse": [1.284104689967e-06, 1.622457984344e-06, 1.779746464592e-06],
    "n_train": 31_184,
}


def _monthly_panel():
    panel = synthetic_daily_panel(40, 1260, seed=123, listing_gaps=True)
    seg, ends = month_end_segments(panel.times)
    pm, mm = month_end_aggregate(
        jnp.asarray(panel.values), jnp.asarray(panel.mask), seg, len(ends)
    )
    return pm, mm, len(ends)


def test_monthly_pipeline_golden():
    pm, mm, n_months = _monthly_panel()
    assert n_months == MONTHLY["n_months"]
    res = monthly_spread_backtest(pm, mm, lookback=12, skip=1)
    sv = np.asarray(res.spread_valid)
    # validity pattern is integer-exact: any warmup/mask drift flips it
    assert int(sv.sum()) == MONTHLY["n_valid_spreads"]
    np.testing.assert_allclose(
        float(res.mean_spread), MONTHLY["mean_spread"], rtol=1e-9
    )
    np.testing.assert_allclose(
        float(res.ann_sharpe), MONTHLY["ann_sharpe"], rtol=1e-9
    )
    np.testing.assert_allclose(
        float(nw_t_stat(res.spread, res.spread_valid)), MONTHLY["nw_t"],
        rtol=1e-9,
    )
    cum = float(np.prod(1 + np.asarray(res.spread)[sv]))
    np.testing.assert_allclose(cum, MONTHLY["cum_return"], rtol=1e-9)


def _synthetic_minutes():
    from csmom_tpu.api import synthetic_minute_frame

    daily = synthetic_daily_panel(8, 10, seed=77)
    a, t = len(daily.tickers), len(daily.times)
    df = pd.DataFrame(
        {
            "date": np.repeat(daily.times, a),
            "ticker": np.tile(daily.tickers, t),
            "open": daily.values.T.ravel(),
            "close": daily.values.T.ravel(),
            "adj_close": daily.values.T.ravel(),
            "volume": 1e6,
        }
    )
    minute_df = synthetic_minute_frame(df, seed=5)
    assert len(minute_df) == a * t * 390
    return minute_df, df


def test_event_pipeline_golden():
    from csmom_tpu.api import intraday_pipeline

    minute_df, df = _synthetic_minutes()
    res, fit, compact, *_ = intraday_pipeline(minute_df, df)

    # the trade count is the fingerprint: every threshold crossing, exactly
    assert int(res.n_trades) == EVENT["n_trades"]
    np.testing.assert_allclose(float(res.total_pnl), EVENT["total_pnl"], rtol=1e-9)
    final_cash = float(np.asarray(res.cash).reshape(-1)[-1])
    np.testing.assert_allclose(final_cash, EVENT["final_cash"], rtol=1e-9)
    # expanding-window CV fold MSEs: pins scaler/fold/refit semantics
    assert int(fit.n_train) == EVENT["n_train"]
    np.testing.assert_allclose(
        np.asarray(fit.cv_mse, dtype=np.float64), EVENT["cv_mse"], rtol=1e-8
    )


def test_csv_universe_golden():
    """The committed synthetic CSV universe (tests/fixtures/universe — 8
    tickers, both cache dialects, listing gaps) through the FULL ingest
    path: load_daily -> month-end panel -> 4-bin quartile backtest, against pinned
    constants.  This is the bare-checkout analogue of SURVEY §2 row 16's
    vendored data assets: the CSV pipeline itself, not just the kernels,
    is exercised with nothing mounted.  Regenerate + re-pin with
    tests/fixtures/make_universe.py if the generator stream changes."""
    import os

    from csmom_tpu.api import monthly_price_panel
    from csmom_tpu.backtest import monthly_spread_backtest

    d = os.path.join(os.path.dirname(__file__), "fixtures", "universe")
    tickers = sorted(t.split("_")[0] for t in os.listdir(d))
    assert len(tickers) == 8
    prices, _ = monthly_price_panel(d, tickers)
    assert (prices.n_assets, prices.n_times) == (8, 23)
    res = monthly_spread_backtest(
        np.asarray(prices.values), np.asarray(prices.mask),
        lookback=6, skip=1, n_bins=4,
    )
    sv = np.asarray(res.spread_valid)
    assert int(sv.sum()) == 15
    np.testing.assert_allclose(float(res.mean_spread), 0.007170869622,
                               rtol=1e-9)
    np.testing.assert_allclose(float(res.ann_sharpe), 0.207281538823,
                               rtol=1e-9)
    np.testing.assert_allclose(
        float(nw_t_stat(res.spread, res.spread_valid)), 0.249081731114,
        rtol=1e-9,
    )


def test_online_ridge_pipeline_golden():
    """The causal model's offline fingerprint: one different trade, one
    shifted burn-in row, or one changed prequential block fails this on a
    bare checkout."""
    from csmom_tpu.api import intraday_pipeline

    minute_df, df = _synthetic_minutes()
    res, fit, compact, *_ = intraday_pipeline(
        minute_df, df, model="online_ridge"
    )
    assert int(res.n_trades) == ONLINE["n_trades"]
    np.testing.assert_allclose(
        float(res.total_pnl), ONLINE["total_pnl"], rtol=1e-9
    )
    final_cash = float(np.asarray(res.cash).reshape(-1)[-1])
    np.testing.assert_allclose(final_cash, ONLINE["final_cash"], rtol=1e-9)
    assert int(fit.n_train) == ONLINE["n_train"]
    np.testing.assert_allclose(
        np.asarray(fit.cv_mse, dtype=np.float64), ONLINE["cv_mse"], rtol=1e-8
    )
