"""Paper-style table builders vs hand-computed stats."""

import numpy as np
import pandas as pd
import pytest

from csmom_tpu.analytics.tables import decile_table, double_sort_table, jk_grid_table


def _stats(x):
    x = x[np.isfinite(x)]
    mean = x.mean()
    sd = x.std(ddof=1)
    return mean, mean * 12 / (sd * np.sqrt(12)), mean / (sd / np.sqrt(len(x)))


def test_decile_table_stats(rng):
    B, M = 5, 60
    means = rng.normal(0.005, 0.03, size=(B, M))
    counts = rng.integers(1, 8, size=(B, M))
    counts[1, :10] = 0  # decile 2 empty for 10 months
    means[counts == 0] = np.nan
    spread = means[B - 1] - means[0]

    df = decile_table(means, counts, spread)
    assert list(df.index) == ["R1", "R2", "R3", "R4", "R5", "R5-R1"]

    m, s, t = _stats(means[1][counts[1] > 0])
    row = df.loc["R2"]
    np.testing.assert_allclose([row.mean_ret, row.ann_sharpe, row.t_stat], [m, s, t])
    assert row.months == M - 10
    np.testing.assert_allclose(df.loc["R5-R1"].mean_ret, _stats(spread)[0])
    assert np.isnan(df.loc["R5-R1"].avg_members)
    np.testing.assert_allclose(df.loc["R1"].avg_members, counts[0].mean())


def test_jk_grid_table(rng):
    Js, Ks, M = [3, 6], [1, 3, 6], 48
    spreads = rng.normal(0.004, 0.02, size=(2, 3, M))
    live = rng.random((2, 3, M)) > 0.1
    spreads[~live] = np.nan

    mean_df, tstat_df, sharpe_df = jk_grid_table(spreads, live, Js, Ks)
    assert list(mean_df.index) == Js and list(mean_df.columns) == Ks
    m, s, _ = _stats(spreads[1, 2][live[1, 2]])
    np.testing.assert_allclose(mean_df.loc[6, 6], m)
    np.testing.assert_allclose(sharpe_df.loc[6, 6], s)
    # the grid's reported t-stat is Newey-West with lag = K (here K=6);
    # oracle = the independent numpy implementation.  The kernel's masked
    # form compacts prefix/suffix-gap series identically; this row has
    # interior gaps too, so compare against the kernel's own convention via
    # the dense compacted series with auto lag replaced by the K lag.
    from csmom_tpu.analytics.stats import nw_t_stat

    np.testing.assert_allclose(
        tstat_df.loc[6, 6],
        float(nw_t_stat(spreads[1, 2], live[1, 2], lags=6)),
    )


def test_jk_grid_ci_table(rng):
    from csmom_tpu.analytics.tables import jk_grid_ci_table

    Js, Ks, M = [3, 6], [1, 3], 60
    spreads = rng.normal(0.004, 0.02, size=(2, 2, M))
    live = np.ones((2, 2, M), bool)
    lo, hi = jk_grid_ci_table(spreads, live, Js, Ks, n_samples=100)
    assert list(lo.index) == Js and list(lo.columns) == Ks
    assert (lo.to_numpy() <= hi.to_numpy()).all()
    # the point estimate sits inside its CI for a well-behaved cell
    m = spreads[1, 1].mean()
    assert lo.loc[6, 3] <= m <= hi.loc[6, 3]


def test_double_sort_table(rng):
    class DS:
        spreads = rng.normal(0.005, 0.02, size=(3, 40))
        spread_valid = np.ones((3, 40), bool)

    DS.spread_valid[0, :5] = False
    df = double_sort_table(DS)
    assert list(df.index) == ["V1 (low)", "V2", "V3 (high)", "V3-V1"]
    m, _, _ = _stats(DS.spreads[2])
    np.testing.assert_allclose(df.loc["V3 (high)"].mean_ret, m)
    both = DS.spread_valid[2] & DS.spread_valid[0]
    md, _, _ = _stats((DS.spreads[2] - DS.spreads[0])[both])
    np.testing.assert_allclose(df.loc["V3-V1"].mean_ret, md)


def test_double_sort_turnover_counts_unwind_months(rng):
    """ADVICE r5 #1: a full-book unwind lands its |dw| on the first month
    the book goes INVALID; the turnover average must include every month
    with activity (valid OR turn > 0), or net_mean/be_bps are overstated."""
    V, M = 3, 10

    class DS:
        spreads = rng.normal(0.005, 0.02, size=(V, M))
        spread_valid = np.ones((V, M), bool)
        book_turnover = np.full((V, M), 0.5)

    # tercile 0: the book dies at month 6 — invalid from there on, but the
    # unwind itself (2.0 = full both-legs exit) is charged at month 6
    DS.spread_valid[0, 6:] = False
    DS.book_turnover[0, 6:] = 0.0
    DS.book_turnover[0, 6] = 2.0

    df = double_sort_table(DS, half_spread_bps=10.0)
    # 6 valid months at 0.5 plus the unwind month at 2.0, over 7 active
    expected = (6 * 0.5 + 2.0) / 7
    np.testing.assert_allclose(df.loc["V1 (low)"].mean_turnover, expected)
    # and the net mean is charged at that heavier turnover
    np.testing.assert_allclose(
        df.loc["V1 (low)"].net_mean,
        df.loc["V1 (low)"].mean_ret - 10.0 / 1e4 * expected,
    )
    # terciles with no invalid-month activity are unchanged by the fix
    np.testing.assert_allclose(df.loc["V2"].mean_turnover, 0.5)


@pytest.mark.reference_data
@pytest.mark.slow
def test_cli_doublesort_and_tables_run():
    """End-to-end CLI smoke on the shipped caches (CPU/pandas-safe paths)."""
    from tests.conftest import REFERENCE_DATA

    from csmom_tpu.cli.main import main

    assert main(["doublesort", "--data-dir", REFERENCE_DATA]) == 0
    assert main(["replicate", "--data-dir", REFERENCE_DATA,
                 "--backend", "pandas", "--tables", "--out", "/tmp/cli_tables"]) == 0
