"""Tearsheet statistics vs independent numpy/scipy/pandas oracles."""

import numpy as np
import pytest

from csmom_tpu.analytics import (
    annual_returns,
    format_tearsheet,
    max_drawdown,
    tearsheet,
)


def _series(rng, T=240, hole_frac=0.1):
    r = rng.normal(0.005, 0.04, size=T)
    valid = rng.random(T) > hole_frac
    r[~valid] = np.nan
    return r, valid


def _mdd_loop(r, valid):
    """Straight-line oracle: compound, track peak, max loss from peak."""
    growth, peak, mdd = 1.0, 1.0, 0.0
    for x, v in zip(r, valid):
        if v:
            growth *= 1.0 + x
            peak = max(peak, growth)
            mdd = max(mdd, 1.0 - growth / peak)
    return mdd


def test_max_drawdown_vs_loop(rng):
    r, valid = _series(rng)
    got = float(max_drawdown(r, valid))
    assert got == pytest.approx(_mdd_loop(r, valid), rel=1e-12)


def test_max_drawdown_declining_from_start():
    """A curve that never exceeds 1.0 draws down against the initial
    capital: r=[-0.10,-0.05,0.02,0.01] troughs at 0.855, mdd=0.145 — not
    the 0.050 a peak that starts at the first point would give."""
    r = np.array([-0.10, -0.05, 0.02, 0.01])
    valid = np.ones(4, bool)
    got = float(max_drawdown(r, valid))
    assert got == pytest.approx(_mdd_loop(r, valid), rel=1e-12)
    assert got == pytest.approx(1.0 - 0.90 * 0.95, rel=1e-12)


def test_moments_vs_scipy(rng):
    from scipy import stats as sps

    r, valid = _series(rng)
    ts = tearsheet(r, valid)
    rv = r[valid]
    assert float(ts.skewness) == pytest.approx(sps.skew(rv), rel=1e-10)
    assert float(ts.excess_kurtosis) == pytest.approx(
        sps.kurtosis(rv), rel=1e-10
    )
    assert float(ts.hit_rate) == pytest.approx((rv > 0).mean(), rel=1e-12)
    assert float(ts.best) == pytest.approx(rv.max(), rel=1e-12)
    assert float(ts.worst) == pytest.approx(rv.min(), rel=1e-12)
    assert int(ts.n_periods) == valid.sum()


def test_annualization_identities(rng):
    r, valid = _series(rng)
    ts = tearsheet(r, valid, freq_per_year=12)
    rv = r[valid]
    n = len(rv)
    want_ann = np.prod(1 + rv) ** (12.0 / n) - 1
    assert float(ts.ann_return) == pytest.approx(want_ann, rel=1e-10)
    assert float(ts.ann_vol) == pytest.approx(rv.std(ddof=1) * np.sqrt(12), rel=1e-10)
    if ts.max_drawdown > 0:
        assert float(ts.calmar) == pytest.approx(
            float(ts.ann_return) / float(ts.max_drawdown), rel=1e-10
        )


def test_tail_stats_vs_sorted_tail(rng):
    r, valid = _series(rng, T=400)
    ts = tearsheet(r, valid)
    rv = np.sort(r[valid])
    k = max(int(np.ceil(0.05 * len(rv) - 1e-6)), 1)
    assert float(ts.var_95) == pytest.approx(rv[k - 1], rel=1e-12)
    assert float(ts.cvar_95) == pytest.approx(rv[:k].mean(), rel=1e-12)
    assert float(ts.cvar_95) <= float(ts.var_95)


def test_tail_count_integer_boundary():
    """q*n landing on an integer must give exactly that tail count in every
    dtype: n=240, q=0.05 -> k=12, so VaR is the 12th-worst return."""
    n = 240
    r = np.linspace(-0.12, 0.119, n)  # distinct, sorted, 12th worst known
    ts = tearsheet(r, np.ones(n, bool))
    want_var = np.sort(r)[11]
    assert float(ts.var_95) == pytest.approx(want_var, rel=1e-12)
    assert float(ts.cvar_95) == pytest.approx(np.sort(r)[:12].mean(), rel=1e-12)


def test_batched_matches_per_series(rng):
    """[G, T] reduces exactly as G independent [T] calls (the grid use)."""
    G, T = 5, 180
    r = rng.normal(0.003, 0.05, size=(G, T))
    valid = rng.random((G, T)) > 0.15
    batch = tearsheet(r, valid)
    for g in range(G):
        one = tearsheet(r[g], valid[g])
        for f in ("ann_return", "max_drawdown", "cvar_95", "skewness"):
            a, b = np.asarray(getattr(batch, f))[g], np.asarray(getattr(one, f))
            np.testing.assert_allclose(a, b, rtol=1e-12)


def test_degenerate_inputs():
    T = 24
    empty = tearsheet(np.zeros(T), np.zeros(T, bool))
    assert np.isnan(float(empty.ann_return))
    assert np.isnan(float(empty.max_drawdown))
    assert int(empty.n_periods) == 0

    allpos = tearsheet(np.full(T, 0.01), np.ones(T, bool))
    assert float(allpos.max_drawdown) == 0.0
    assert np.isnan(float(allpos.calmar))  # no drawdown -> undefined
    assert float(allpos.hit_rate) == 1.0
    assert np.isnan(float(allpos.sortino))  # no down periods

    txt = format_tearsheet(allpos, "x")
    assert "Max drawdown" in txt and "n/a" in txt


def test_annual_returns_vs_pandas(rng):
    import pandas as pd

    T = 60
    dates = pd.date_range("2018-01-31", periods=T, freq="ME")
    r, valid = _series(rng, T=T)
    years = dates.year.values.astype(np.int32)

    uniq, ann, any_valid = annual_returns(r, valid, years)
    s = pd.Series(np.where(valid, r, 0.0), index=dates)
    want = (1 + s).groupby(s.index.year).prod() - 1
    np.testing.assert_array_equal(np.asarray(uniq), want.index.values)
    has = pd.Series(valid, index=dates).groupby(dates.year).any()
    np.testing.assert_allclose(
        np.asarray(ann)[np.asarray(any_valid)],
        want.values[has.values],
        rtol=1e-10,
    )
    assert np.isnan(np.asarray(ann)[~np.asarray(any_valid)]).all()
