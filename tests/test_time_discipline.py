"""Tier-1 time-discipline lint + telemetry artifact validation.

The r7 skew-proofing made ``utils.deadline`` monotonic-only, and the
chaos ``clock_skew`` fault exists to catch wall-clock timing sneaking
back in — but the ban was enforced by review, not by a test, and one
call site (the CLI probe-marker TTL) survived it until this round.  This
lint makes the discipline mechanical: no bare ``time.time()`` and no
argless ``datetime.now()`` anywhere in the package, the bench harness,
or the capture scripts, outside a documented allowlist.

Legitimate wall-clock needs go through the skew-resistant helpers in
``utils.deadline`` (``wall_now_s`` / ``file_age_s`` / ``marker_fresh``)
or take an explicit timezone (identity stamps:
``datetime.now(timezone.utc)`` — argful, so not matched here).
"""

import glob
import os
import re

from csmom_tpu.chaos import invariants as inv

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# a bare wall-clock read; the suffix form also catches aliased imports
# like ``_time.time()``
_WALL_CLOCK = re.compile(r"time\.time\(\)")
_ARGLESS_NOW = re.compile(r"datetime(?:\.datetime)?\.now\(\s*\)")

# path (repo-relative) -> max allowed matches, each one justified.  These
# are MENTIONS in prose, not executed timing calls; anything new must
# either use the deadline helpers or argue its way in here.
_ALLOWLIST = {
    # module docstring explaining why naive wall-clock pairs mis-measure
    # async dispatch — the warning against the pattern, not a use of it
    "csmom_tpu/utils/profiling.py": 1,
    # comment documenting what the clock_skew fault perturbs
    "csmom_tpu/chaos/plan.py": 1,
}


def _timing_sources():
    files = [os.path.join(_REPO, "bench.py")]
    for root in ("csmom_tpu", "benchmarks"):
        for dirpath, _, names in os.walk(os.path.join(_REPO, root)):
            files += [os.path.join(dirpath, n) for n in names
                      if n.endswith(".py")]
    return sorted(files)


def test_no_bare_wall_clock_in_timing_paths():
    offenders = {}
    for path in _timing_sources():
        with open(path, encoding="utf-8") as f:
            src = f.read()
        n = len(_WALL_CLOCK.findall(src)) + len(_ARGLESS_NOW.findall(src))
        rel = os.path.relpath(path, _REPO)
        if n > _ALLOWLIST.get(rel, 0):
            offenders[rel] = n
    assert offenders == {}, (
        f"bare time.time()/argless datetime.now() in timing paths: "
        f"{offenders} — use utils.deadline.wall_now_s/file_age_s/"
        "marker_fresh (or datetime.now(timezone.utc) for identity "
        "stamps), or extend the documented allowlist"
    )


def test_allowlist_entries_are_not_stale():
    """An allowlisted file that no longer contains its mention must lose
    the entry — a stale allowlist is a hole the next regression walks
    through."""
    for rel, allowed in _ALLOWLIST.items():
        path = os.path.join(_REPO, rel)
        assert os.path.exists(path), f"allowlisted file {rel} is gone"
        with open(path, encoding="utf-8") as f:
            src = f.read()
        n = len(_WALL_CLOCK.findall(src)) + len(_ARGLESS_NOW.findall(src))
        assert 0 < n <= allowed, (
            f"{rel}: {n} matches vs allowlisted {allowed} — update or "
            "drop the entry"
        )


def test_deadline_helpers_are_the_documented_wall_clock_home():
    from csmom_tpu.utils import deadline

    for helper in ("wall_now_s", "file_age_s", "marker_fresh"):
        assert hasattr(deadline, helper)


# --------------------------- committed telemetry sidecars (same tier) ----

def test_telemetry_pattern_is_in_the_tier1_artifact_sweep():
    """TELEMETRY_*.json and SERVE_*.json validate in the SAME tree sweep
    as BENCH_*/MULTICHIP_* (test_chaos.test_every_committed_artifact_
    validates runs it); pin that the patterns stay in the default sweep."""
    import inspect

    sig = inspect.signature(inv.validate_tree)
    assert "TELEMETRY_*.json" in sig.parameters["patterns"].default
    assert "SERVE_*.json" in sig.parameters["patterns"].default
    assert "REPLAY_*.json" in sig.parameters["patterns"].default


def test_committed_telemetry_sidecars_validate():
    paths = sorted(glob.glob(os.path.join(_REPO, "TELEMETRY_*.json")))
    for p in paths:
        assert inv.validate_file(p) == [], (os.path.basename(p),
                                            inv.validate_file(p))


def test_only_round_sidecars_are_committed():
    """ISSUE 4 satellite (extended to SERVE by ISSUE 5):
    TELEMETRY_rehearse_*.json once sat at the repo root despite the
    gitignore declaring rehearse sidecars scratch.  The rule is code
    (invariants.committable_sidecar): only round artifacts
    (TELEMETRY_rNN.json / SERVE_rNN.json) may be tracked.  Checked
    against git's own index so an ignored-but-present scratch file
    (tier-1 rehearse/loadgen runs regenerate them in cwd) never
    false-positives."""
    import subprocess
    import sys

    try:
        p = subprocess.run(
            ["git", "ls-files", "TELEMETRY_*.json", "SERVE_*.json",
             "REPLAY_*.json"],
            cwd=_REPO, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired) as e:  # no git in image
        import pytest

        pytest.skip(f"git unavailable: {e}")
    if p.returncode != 0:
        import pytest

        pytest.skip(f"not a git checkout: {p.stderr.strip()[:100]}")
    tracked = [ln.strip() for ln in p.stdout.splitlines() if ln.strip()]
    offenders = [t for t in tracked if not inv.committable_sidecar(t)]
    assert offenders == [], (
        f"non-round telemetry/serve artifacts committed: {offenders} — "
        "rehearse/smoke/scratch files are regenerated per run and must "
        "stay out of the tree (round evidence is TELEMETRY_rNN.json / "
        "SERVE_rNN.json only)"
    )
    # the rule itself stays strict
    assert inv.committable_sidecar("TELEMETRY_r06.json")
    assert not inv.committable_sidecar("TELEMETRY_rehearse_fast.json")
    assert not inv.committable_sidecar("TELEMETRY_r06-1234.json")
    assert inv.committable_sidecar("SERVE_r10.json")
    assert not inv.committable_sidecar("SERVE_smoke.json")
    assert not inv.committable_sidecar("SERVE_rehearse_x.json")
    assert not inv.committable_sidecar("SERVE_r10-999.json")
    # ISSUE 6: the pool family obeys the same rule
    assert inv.committable_sidecar("SERVE_POOL_r11.json")
    assert not inv.committable_sidecar("SERVE_POOL_smoke.json")
    assert not inv.committable_sidecar(
        "SERVE_POOL_rehearse_pool-worker-kill-mid-batch.json")
    assert not inv.committable_sidecar("SERVE_POOL_r11-42.json")
    # ISSUE 7: the replay family obeys the same rule
    assert inv.committable_sidecar("REPLAY_r12.json")
    assert not inv.committable_sidecar("REPLAY_smoke.json")
    assert not inv.committable_sidecar("REPLAY_rehearse_tick-storm.json")
    assert not inv.committable_sidecar("REPLAY_r12-7.json")
    # other families are not this rule's business
    assert inv.committable_sidecar("BENCH_r04.json")


def test_serve_modules_route_all_timing_through_deadline_helpers():
    """ISSUE 5 satellite: the serve layer's deadlines/latencies must be
    monotonic AND single-sourced — no bare wall clock (the global lint
    covers that) and no inline ``time.monotonic()`` either: every clock
    read goes through utils.deadline.mono_now_s, so the clock the queue
    expires on is the clock the artifact's latencies are measured on, by
    construction.  engine.py is exempt from the monotonic pin only where
    it has no timing at all (checked: zero matches required there too)."""
    mono = re.compile(r"time\.monotonic\(\)")
    serve_modules = (
        "csmom_tpu/serve/__init__.py",
        "csmom_tpu/serve/buckets.py",
        "csmom_tpu/serve/queue.py",
        "csmom_tpu/serve/batcher.py",
        "csmom_tpu/serve/engine.py",
        "csmom_tpu/serve/service.py",
        "csmom_tpu/serve/loadgen.py",
        "csmom_tpu/cli/serve.py",
        # the ISSUE 6 pool tier rides under the same pin: deadlines the
        # router hedges on and the walls the artifact records must be
        # the same clock the single-process service uses
        "csmom_tpu/serve/proto.py",
        "csmom_tpu/serve/health.py",
        "csmom_tpu/serve/worker.py",
        "csmom_tpu/serve/router.py",
        "csmom_tpu/serve/supervisor.py",
        # the ISSUE 8 adaptive-dispatch tier rides under the same pin:
        # SLO deadline budgets and token-bucket refills are mono-only
        # (the bucket never even reads a clock — callers pass now_s from
        # mono_now_s), and the result cache reads NO clock at all (LRU
        # order is recency, version floors are counters)
        "csmom_tpu/serve/slo.py",
        "csmom_tpu/serve/cache.py",
    )
    for rel in serve_modules:
        path = os.path.join(_REPO, rel)
        assert os.path.exists(path), rel
        with open(path, encoding="utf-8") as f:
            src = f.read()
        n_wall = len(_WALL_CLOCK.findall(src)) + len(_ARGLESS_NOW.findall(src))
        assert n_wall == 0, f"{rel}: {n_wall} bare wall-clock call(s)"
        assert rel not in _ALLOWLIST, (
            f"{rel} must not be allowlisted: serve deadlines are "
            "monotonic by contract"
        )
        n_mono = len(mono.findall(src))
        assert n_mono == 0, (
            f"{rel}: {n_mono} inline time.monotonic() call(s) — serve "
            "timing goes through utils.deadline.mono_now_s"
        )
    from csmom_tpu.utils.deadline import mono_now_s

    assert mono_now_s() <= mono_now_s()  # monotone, and the helper exists


def test_stream_modules_are_event_time_only():
    """ISSUE 7 satellite: the streaming data plane runs on EVENT TIME —
    bar stamps from the tick log, versions from counters.  The ring,
    ingestor, and incremental updaters may read NO clock of any kind
    (wall, monotonic, or the deadline helpers): a clock read in the
    data plane is a lateness decision smuggled off the event-time axis.
    The replay harness and its CLI may read the wall only through
    ``mono_now_s`` (throughput reporting), never inline."""
    mono = re.compile(r"time\.monotonic\(\)")
    any_time_import = re.compile(r"^\s*import time\b|^\s*from time import",
                                 re.MULTILINE)

    event_time_only = (
        "csmom_tpu/stream/__init__.py",
        "csmom_tpu/stream/ring.py",
        "csmom_tpu/stream/ingest.py",
        "csmom_tpu/stream/incremental.py",
    )
    for rel in event_time_only:
        path = os.path.join(_REPO, rel)
        assert os.path.exists(path), rel
        with open(path, encoding="utf-8") as f:
            src = f.read()
        assert not _WALL_CLOCK.findall(src), f"{rel}: bare wall clock"
        assert not _ARGLESS_NOW.findall(src), f"{rel}: argless now()"
        assert not mono.findall(src), f"{rel}: inline monotonic read"
        assert not any_time_import.findall(src), (
            f"{rel}: imports the time module — the streaming data plane "
            "is event-time only")
        assert "mono_now_s" not in src, (
            f"{rel}: reads the clock via mono_now_s — lateness and "
            "ordering decisions must come from tick stamps")

    wall_via_helper_only = (
        "csmom_tpu/stream/replay.py",
        "csmom_tpu/cli/replay.py",
    )
    for rel in wall_via_helper_only:
        path = os.path.join(_REPO, rel)
        assert os.path.exists(path), rel
        with open(path, encoding="utf-8") as f:
            src = f.read()
        n_wall = len(_WALL_CLOCK.findall(src)) + len(_ARGLESS_NOW.findall(src))
        assert n_wall == 0, f"{rel}: {n_wall} bare wall-clock call(s)"
        assert not mono.findall(src), (
            f"{rel}: inline time.monotonic() — replay timing goes "
            "through utils.deadline.mono_now_s")
        assert rel not in _ALLOWLIST, (
            f"{rel} must not be allowlisted: replay walls are "
            "monotonic-helper-only by contract")


def test_perf_ledger_modules_stay_wall_clock_free():
    """The ledger/regress/memstats layer reads evidence and must never
    read the wall clock (its verdicts have to be reproducible from the
    committed artifacts alone): zero bare wall-clock matches AND no
    allowlist entry pleading one in."""
    new_modules = (
        "csmom_tpu/obs/ledger.py",
        "csmom_tpu/obs/regress.py",
        "csmom_tpu/obs/memstats.py",
        "csmom_tpu/cli/ledger.py",
    )
    for rel in new_modules:
        path = os.path.join(_REPO, rel)
        assert os.path.exists(path), rel
        with open(path, encoding="utf-8") as f:
            src = f.read()
        n = len(_WALL_CLOCK.findall(src)) + len(_ARGLESS_NOW.findall(src))
        assert n == 0, f"{rel}: {n} bare wall-clock call(s) in the ledger"
        assert rel not in _ALLOWLIST, (
            f"{rel} must not be allowlisted: ledger verdicts are "
            "reproducible-from-artifacts by contract"
        )
