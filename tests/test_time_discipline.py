"""Time-discipline regression pins + telemetry artifact validation.

The r3-r7 regex lints that lived here (bare ``time.time()`` bans with a
count-based ``_ALLOWLIST`` dict, per-module monotonic pins, the
event-time-only stream sweep) are now the AST ``clock-discipline`` rule
in :mod:`csmom_tpu.analysis.rules`, run by the tier-1 sweep in
``tests/test_lint.py`` and by ``csmom lint``.  What remains here are the
THIN PINS (ISSUE 11):

- the historical regex really does have the alias hole the issue names
  (``from time import time as _t; _t()`` passes it), and the AST rule
  really does close it — proven on a known-bad fixture;
- the old ``_ALLOWLIST`` sites carry in-file pragmas now, and those
  pragmas are live (suppressing exactly one finding each);
- the per-layer tier lists still cover the historical modules;
- the committed telemetry/serve sidecar rules (unchanged from r4-r7).
"""

import glob
import os
import re

from csmom_tpu.analysis import run_lint
from csmom_tpu.chaos import invariants as inv

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURE = os.path.join(_REPO, "tests", "fixtures", "lint",
                        "clock_discipline_bad.py")

# THE HISTORICAL REGEX, verbatim from the r3 lint: kept only to prove
# what it misses (its successor is the AST rule)
_WALL_CLOCK = re.compile(r"time\.time\(\)")
_ARGLESS_NOW = re.compile(r"datetime(?:\.datetime)?\.now\(\s*\)")


def _clock_rule():
    from csmom_tpu.analysis.rules import ClockDiscipline

    return ClockDiscipline()


def test_regex_alias_hole_is_real_and_the_ast_rule_closes_it():
    """ISSUE 11 satellite: the known-bad fixture holds one bare
    ``time.time()`` (regex-visible) plus four aliased forms — a
    from-import alias, a module alias, a getattr dodge, and an
    attribute-aliased rebind — that the regex is PROVABLY blind to and
    the AST rule catches, plus an argless ``datetime.now()``."""
    with open(_FIXTURE, encoding="utf-8") as f:
        src = f.read()
    lines = src.splitlines()

    def line_of(snippet):
        return next(i for i, ln in enumerate(lines, 1) if snippet in ln)

    # the regex sees exactly the one historical bare form...
    assert len(_WALL_CLOCK.findall(src)) == 1
    assert len(_ARGLESS_NOW.findall(src)) == 1
    aliased = [line_of("_t()"), line_of("tt.time()"),
               line_of('getattr(time, "time")()'), line_of("indirect()")]
    for ln in aliased:  # ...and is blind on every aliased line
        assert not _WALL_CLOCK.search(lines[ln - 1]), (
            f"line {ln} matches the regex — the fixture no longer "
            "demonstrates the hole")

    rep = run_lint(paths=[_FIXTURE], rules=[_clock_rule()])
    flagged = {f.line for f in rep.findings}
    assert set(aliased) <= flagged, (
        f"the AST rule missed aliased wall-clock reads: "
        f"{sorted(set(aliased) - flagged)}")
    assert line_of("time.time()") in flagged  # the bare form too
    assert line_of("datetime.now()") in flagged


def test_allowlist_sites_migrated_to_live_in_file_pragmas():
    """ISSUE 11 satellite: the two prose-mention sites the old
    ``_ALLOWLIST`` dict covered by count now carry scoped pragmas, each
    suppressing exactly one clock-discipline finding — and the sweep
    would fail if the pragma went stale (tests/test_lint.py pins the
    stale-pragma behavior itself)."""
    for rel in ("csmom_tpu/utils/profiling.py", "csmom_tpu/chaos/plan.py"):
        path = os.path.join(_REPO, rel)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        assert "lint: allow[clock-discipline]" in src, (
            f"{rel}: the in-file pragma is gone")
        rep = run_lint(paths=[path], rules=[_clock_rule()])
        assert [f for f in rep.findings
                if f.rule == "clock-discipline"] == [], (
            f"{rel}: unsuppressed clock findings: {rep.findings}")
        assert len([s for s in rep.suppressed
                    if s.rule == "clock-discipline"]) == 1, (
            f"{rel}: the pragma should suppress exactly one mention")


def test_tier_lists_still_cover_the_historical_modules():
    """The per-layer contracts the old per-module tests spelled out,
    now data on the rule: serve+replay mono-only, stream data plane
    clock-free, ledger wall-free."""
    from csmom_tpu.analysis.rules import ClockDiscipline as CD

    for rel in ("csmom_tpu/serve/queue.py", "csmom_tpu/serve/batcher.py",
                "csmom_tpu/serve/slo.py", "csmom_tpu/serve/cache.py",
                "csmom_tpu/serve/router.py", "csmom_tpu/cli/serve.py",
                "csmom_tpu/stream/replay.py", "csmom_tpu/cli/replay.py",
                # the r18 fabric tier: transport receive deadlines and
                # client-side failover time on the serve clock
                "csmom_tpu/serve/proto.py", "csmom_tpu/serve/fabric.py"):
        assert rel in CD.MONO_ONLY_FILES, rel
    for rel in ("csmom_tpu/stream/ring.py", "csmom_tpu/stream/ingest.py",
                "csmom_tpu/stream/incremental.py"):
        assert rel in CD.NO_CLOCK_FILES, rel
    for rel in ("csmom_tpu/obs/ledger.py", "csmom_tpu/obs/regress.py",
                "csmom_tpu/obs/memstats.py", "csmom_tpu/cli/ledger.py"):
        assert rel in CD.WALL_FREE_FILES, rel
    # every tier file still exists (a rename must update the contract)
    for rel in CD.MONO_ONLY_FILES + CD.NO_CLOCK_FILES + CD.WALL_FREE_FILES:
        assert os.path.isfile(os.path.join(_REPO, rel)), rel


def test_deadline_helpers_are_the_documented_wall_clock_home():
    from csmom_tpu.utils import deadline

    for helper in ("wall_now_s", "file_age_s", "marker_fresh",
                   "mono_now_s"):
        assert hasattr(deadline, helper)


# --------------------------- committed telemetry sidecars (same tier) ----

def test_telemetry_pattern_is_in_the_tier1_artifact_sweep():
    """TELEMETRY_*.json and SERVE_*.json validate in the SAME tree sweep
    as BENCH_*/MULTICHIP_* (test_chaos.test_every_committed_artifact_
    validates runs it); pin that the patterns stay in the default sweep."""
    import inspect

    sig = inspect.signature(inv.validate_tree)
    assert "TELEMETRY_*.json" in sig.parameters["patterns"].default
    assert "SERVE_*.json" in sig.parameters["patterns"].default
    assert "REPLAY_*.json" in sig.parameters["patterns"].default


def test_committed_telemetry_sidecars_validate():
    paths = sorted(glob.glob(os.path.join(_REPO, "TELEMETRY_*.json")))
    for p in paths:
        assert inv.validate_file(p) == [], (os.path.basename(p),
                                            inv.validate_file(p))


def test_only_round_sidecars_are_committed():
    """ISSUE 4 satellite (extended to SERVE by ISSUE 5):
    TELEMETRY_rehearse_*.json once sat at the repo root despite the
    gitignore declaring rehearse sidecars scratch.  The rule is code
    (invariants.committable_sidecar): only round artifacts
    (TELEMETRY_rNN.json / SERVE_rNN.json) may be tracked.  Checked
    against git's own index so an ignored-but-present scratch file
    (tier-1 rehearse/loadgen runs regenerate them in cwd) never
    false-positives."""
    import subprocess

    try:
        p = subprocess.run(
            ["git", "ls-files", "TELEMETRY_*.json", "SERVE_*.json",
             "REPLAY_*.json"],
            cwd=_REPO, capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired) as e:  # no git in image
        import pytest

        pytest.skip(f"git unavailable: {e}")
    if p.returncode != 0:
        import pytest

        pytest.skip(f"not a git checkout: {p.stderr.strip()[:100]}")
    tracked = [ln.strip() for ln in p.stdout.splitlines() if ln.strip()]
    offenders = [t for t in tracked if not inv.committable_sidecar(t)]
    assert offenders == [], (
        f"non-round telemetry/serve artifacts committed: {offenders} — "
        "rehearse/smoke/scratch files are regenerated per run and must "
        "stay out of the tree (round evidence is TELEMETRY_rNN.json / "
        "SERVE_rNN.json only)"
    )
    # the rule itself stays strict
    assert inv.committable_sidecar("TELEMETRY_r06.json")
    assert not inv.committable_sidecar("TELEMETRY_rehearse_fast.json")
    assert not inv.committable_sidecar("TELEMETRY_r06-1234.json")
    assert inv.committable_sidecar("SERVE_r10.json")
    assert not inv.committable_sidecar("SERVE_smoke.json")
    assert not inv.committable_sidecar("SERVE_rehearse_x.json")
    assert not inv.committable_sidecar("SERVE_r10-999.json")
    # ISSUE 6: the pool family obeys the same rule
    assert inv.committable_sidecar("SERVE_POOL_r11.json")
    assert not inv.committable_sidecar("SERVE_POOL_smoke.json")
    assert not inv.committable_sidecar(
        "SERVE_POOL_rehearse_pool-worker-kill-mid-batch.json")
    assert not inv.committable_sidecar("SERVE_POOL_r11-42.json")
    # ISSUE 7: the replay family obeys the same rule
    assert inv.committable_sidecar("REPLAY_r12.json")
    assert not inv.committable_sidecar("REPLAY_smoke.json")
    assert not inv.committable_sidecar("REPLAY_rehearse_tick-storm.json")
    assert not inv.committable_sidecar("REPLAY_r12-7.json")
    # other families are not this rule's business
    assert inv.committable_sidecar("BENCH_r04.json")
