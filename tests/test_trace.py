"""Request-path tracing (ISSUE 13): stage clocks, stitched halves,
closed trace books, the TRACE artifact family, and the burn/quantile
satellites.

The contracts pinned here:

- **zero-cost disarmed** (the obs/spans discipline): ``begin()`` returns
  one shared no-op singleton, and the whole mint/mark/close path does no
  allocation-visible work while no book is armed;
- **telescoping stage clocks**: per-stage walls sum to each request wall
  exactly (the artifact epsilon is rounding headroom, not slack);
- **closed trace books**: every admitted request — served, rejected,
  expired, cache-hit, coalesced — ends as exactly one complete trace or
  one reasoned partial, reconciling with the serve request books;
- **cross-process stitching under SIGKILL**: a real pool with a worker
  killed mid-run still closes its books, the dead dispatches appear as
  reason-carrying ORPHAN halves, and surviving traces carry both halves
  (router transport + worker stages);
- the ``trace`` artifact schema, its committable-sidecar naming rule,
  and the ledger rows (per-stage p99s with CI-backing samples,
  per-class budget-burn).
"""

import gc
import json
import os
import signal
import sys
import time

import pytest

from csmom_tpu.chaos import invariants as inv
from csmom_tpu.obs import metrics
from csmom_tpu.obs import trace as obs_trace
from csmom_tpu.serve.loadgen import (
    LoadConfig,
    run_loadgen,
    run_pool_loadgen,
    write_artifact,
)
from csmom_tpu.serve.service import ServeConfig, SignalService

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_book():
    obs_trace.disarm_tracing()
    yield
    obs_trace.disarm_tracing()


def _run_traced_loadgen(**load_kw):
    book = obs_trace.arm_tracing(seed=1)
    svc = SignalService(ServeConfig(profile="serve-smoke",
                                    engine="stub")).start()
    load = LoadConfig(run_id="trace_unit", **load_kw)
    art = run_loadgen(svc, load)
    obs_trace.disarm_tracing()
    return book, art


# ------------------------------------------------- disarmed = zero cost ----

def test_disarmed_begin_is_a_shared_noop_singleton():
    t1 = obs_trace.begin("momentum", "interactive")
    t2 = obs_trace.begin("turnover", "bulk", panel_version=3)
    assert t1 is t2  # no per-call object
    # every method chains and does nothing
    assert t1.mark("admit").set(x=1).close("served") is t1
    assert t1.to_wire() is None
    assert t1.half_record() is None
    assert not obs_trace.tracing_armed()


def test_disarmed_trace_calls_do_no_allocation_visible_work():
    for _ in range(2000):  # warm every code path first
        t = obs_trace.begin("momentum", "interactive")
        t.mark("admit")
        t.close("served")
        obs_trace.note_batch("momentum", 4, 32, 10, 118, "window")
    gc.collect()
    before = sys.getallocatedblocks()
    for _ in range(5000):
        t = obs_trace.begin("momentum", "interactive")
        t.mark("admit")
        t.close("served")
        obs_trace.note_batch("momentum", 4, 32, 10, 118, "window")
    gc.collect()
    grown = sys.getallocatedblocks() - before
    assert grown < 50, (
        f"disarmed trace calls allocated {grown} blocks over 5000 "
        "iterations — the unarmed fast path must be allocation-free")


# -------------------------------------------------- telescoping clocks ----

def test_stage_walls_telescope_to_the_request_wall_exactly():
    book = obs_trace.arm_tracing()
    t = obs_trace.begin("momentum", "interactive", budget_ms=500.0)
    t.mark("admit")
    time.sleep(0.002)
    t.mark("queue_wait")
    t.mark("coalesce")
    t.mark("pad")
    time.sleep(0.001)
    t.mark("dispatch")
    t.close("served")
    assert t.outcome == "served"
    assert abs(sum(t.stage_durs_s.values()) - t.wall_s) < 1e-9, (
        "telescoping marks must sum to the wall exactly — the epsilon "
        "in the artifact is rounding headroom, not slack")
    # the residual after the last mark auto-labels as the NEXT stage
    assert "serialize" in t.stage_durs_s
    assert book.complete == 1 and book.opened == 1
    assert book.invariant_violations() == []


def test_close_is_exactly_once_and_partials_need_reasons():
    book = obs_trace.arm_tracing()
    t = obs_trace.begin("momentum", "bulk")
    t.close("rejected", reason="queue full")
    t.close("served")  # must not move a terminal trace
    assert t.outcome == "rejected"
    assert book.partial == 1 and book.complete == 0
    snap = book.snapshot()
    assert snap["books"]["partial_reasons"] == {"queue full": 1}


# ----------------------------------------------- in-process closed books ----

def test_loadgen_trace_books_close_and_reconcile_with_serve_books():
    """Every admitted request — including cache hits, coalesced
    followers, quota rejections, expiries — yields exactly one closed
    trace, and the trace books reconcile with the serve request books
    (complete == served, partial == rejected + expired)."""
    book, art = _run_traced_loadgen(
        schedule="0.5x150", seed=7, deadline_s=0.05,
        reuse_fraction=0.5, version_bumps=1)
    req = art["requests"]
    assert book.invariant_violations() == []
    assert book.opened == req["admitted"]
    assert book.complete == req["served"]
    assert book.partial == req["rejected"] + req["expired"]
    snap = book.snapshot()
    assert snap["reconcile"]["violations"] == 0
    assert snap["reconcile"]["max_abs_residual_ms"] <= obs_trace.EPSILON_MS
    if book.partial:
        assert sum(snap["books"]["partial_reasons"].values()) == book.partial


def test_trace_artifact_validates_and_renders(tmp_path, capsys):
    book, art = _run_traced_loadgen(schedule="0.4x80", seed=3,
                                    deadline_s=2.0)
    tart = obs_trace.build_artifact(
        book, "trace_unit",
        requests={k: art["requests"][k]
                  for k in ("admitted", "served", "rejected", "expired")},
        fresh_compiles=0, platform="stub", workload="unit")
    assert inv.detect_kind(tart) == "trace"
    assert inv.validate(tart) == []
    path = write_artifact(str(tmp_path), tart, prefix="TRACE")
    assert os.path.basename(path) == "TRACE_trace_unit.json"

    # stage decomposition covers the whole in-process chain
    for stage in ("admit", "queue_wait", "coalesce", "pad", "dispatch",
                  "serialize"):
        assert stage in tart["stages"], f"missing stage {stage}"
    # per-stage CI backing rides in extra.samples, ledger-metric-keyed
    assert tart["extra"]["samples"]["trace_stage_dispatch_p99_ms"]
    # padding goodput is per (endpoint, bucket)
    assert tart["padding"]
    for bucket in tart["padding"].values():
        assert bucket["batches"] >= 1 and bucket["fire_reasons"]

    # the CLI renders it without violations
    from csmom_tpu.cli.main import main

    rc = main(["trace", path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "per-stage decomposition" in out
    assert "critical path" in out
    assert "budget-burn" in out or "budget" in out


def test_trace_validator_rejects_broken_books_and_residuals():
    base = {
        "kind": "trace", "schema_version": 1, "run_id": "x",
        "metric": "trace_complete_traces", "value": 2, "unit": "traces",
        "vs_baseline": 1.0,
        "books": {"opened": 3, "complete": 2, "partial": 0,
                  "partial_reasons": {}},
        "orphans": {"count": 0, "reasons": {}},
        "stages": {"dispatch": {"count": 2, "p50": 1.0, "p95": 2.0,
                                "p99": 2.0, "max_ms": 2.0,
                                "total_s": 0.003}},
        "classes": {}, "slowest": [],
        "reconcile": {"checked": 3, "violations": 0,
                      "max_abs_residual_ms": 0.0, "epsilon_ms": 2.0},
        "requests": {"admitted": 3, "served": 2, "rejected": 1,
                     "expired": 0},
    }
    # books don't close: opened != complete + partial
    viols = inv.validate(base, "trace")
    assert any("books broken" in v for v in viols)
    # fixed books but partial ledger does not cover rejected+expired
    ok = dict(base, books={"opened": 3, "complete": 2, "partial": 1,
                           "partial_reasons": {"queue full": 1}})
    assert inv.validate(ok, "trace") == []
    bad_req = dict(ok, requests={"admitted": 3, "served": 1,
                                 "rejected": 2, "expired": 0})
    assert any("complete" in v for v in inv.validate(bad_req, "trace"))
    # a slowest entry whose stages don't reconcile with its wall
    bad_slow = dict(ok, slowest=[{"trace_id": "t", "wall_ms": 50.0,
                                  "stages": {"dispatch": 1.0}}])
    assert any("critical path does not reconcile" in v
               for v in inv.validate(bad_slow, "trace"))
    # reconcile violations are invalid evidence, full stop
    bad_rec = dict(ok)
    bad_rec["reconcile"] = dict(ok["reconcile"], violations=2)
    assert any("full stop" in v for v in inv.validate(bad_rec, "trace"))


def test_trace_committable_sidecar_naming():
    assert inv.committable_sidecar("TRACE_r17.json")
    assert not inv.committable_sidecar("TRACE_smoke.json")
    assert not inv.committable_sidecar("TRACE_rehearse_x.json")
    assert not inv.committable_sidecar("TRACE_r17-999.json")


# ------------------------------------------- cross-process SIGKILL stitch ----

def test_pool_trace_stitching_under_mid_run_worker_sigkill(tmp_path):
    """ISSUE 13 satellite: a REAL worker process SIGKILLed mid-run.  The
    router closes the dead dispatches as reason-carrying orphan halves,
    the surviving traces carry both stitched halves, the books balance
    against the router's request books, and every stage sum reconciles."""
    from csmom_tpu.serve.router import Router, RouterConfig
    from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor

    book = obs_trace.arm_tracing(seed=2)
    sup = PoolSupervisor(
        PoolConfig(profile="serve-smoke", engine="stub", n_workers=2,
                   backoff_base_s=0.05, ready_timeout_s=30.0),
        str(tmp_path))
    sup.start()
    router = Router(sup.ready_workers,
                    RouterConfig(profile="serve-smoke",
                                 default_deadline_s=3.0))

    def kill_one():
        time.sleep(0.25)
        os.kill(sup.handles[0].proc.pid, signal.SIGKILL)

    try:
        art = run_pool_loadgen(
            router, sup,
            LoadConfig(schedule="1.0x80", seed=5, deadline_s=3.0,
                       run_id="trace_kill"),
            concurrent=kill_one)
    finally:
        sup.stop()
    obs_trace.disarm_tracing()

    req = art["requests"]
    assert book.invariant_violations() == []
    assert book.opened == req["admitted"]
    assert book.complete == req["served"]
    assert book.partial == req["rejected"] + req["expired"]

    snap = book.snapshot()
    # the SIGKILLed worker's in-flight dispatches are orphan halves,
    # closed WITH the connection failure as the reason
    assert snap["orphans"]["count"] > 0, (
        "the kill left no orphan half — nothing was in flight, or the "
        "orphan leaked unclosed")
    assert all(("connection" in r or "closed" in r)
               for r in snap["orphans"]["reasons"]), snap["orphans"]
    # stitched traces carry both halves: router-side transport and the
    # worker-side queue/dispatch stages
    for stage in ("route", "transport", "queue_wait", "dispatch",
                  "serialize", "finalize"):
        assert stage in snap["stages"], f"missing stitched stage {stage}"
    assert snap["reconcile"]["violations"] == 0

    tart = obs_trace.build_artifact(
        book, "trace_kill",
        requests={k: req[k]
                  for k in ("admitted", "served", "rejected", "expired")},
        fresh_compiles=0, platform="stub", workload="unit pool kill")
    assert inv.validate(tart) == []


def test_wire_roundtrip_preserves_identity_and_half_records():
    obs_trace.arm_tracing()
    t = obs_trace.begin("momentum", "interactive", panel_version=4,
                        budget_ms=500.0)
    wire = t.to_wire()
    assert wire["trace_id"] == t.trace_id
    half_ctx = obs_trace.TraceContext.from_wire(wire)
    assert half_ctx.trace_id == t.trace_id
    assert half_ctx.panel_version == 4
    assert half_ctx.half_record() is None  # not closed yet: no half
    half_ctx.mark("admit")
    half_ctx.close("served")
    half = half_ctx.half_record()
    assert half["trace_id"] == t.trace_id
    assert abs(sum(half["stages"].values()) - half["wall_s"]) < 1e-5
    # stitch: the absorbed half + attempt window telescope to the wall
    t0 = t.t0_s
    t.absorb_remote(half, t0 + 0.010, t0 + 0.030, worker_id="w1")
    t.close_routed("served", t0 + 0.040)
    assert abs(sum(t.stage_durs_s.values()) - t.wall_s) < 1e-9
    assert t.stage_durs_s["route"] == pytest.approx(0.010)
    assert t.stage_durs_s["finalize"] == pytest.approx(0.010)
    assert t.attrs["worker"] == "w1"


# ------------------------------------------------------ ledger ingestion ----

def test_ledger_ingests_trace_rows_with_samples_and_burn(tmp_path):
    book, art = _run_traced_loadgen(schedule="0.4x80", seed=3,
                                    deadline_s=2.0)
    tart = obs_trace.build_artifact(
        book, "r90",
        requests={k: art["requests"][k]
                  for k in ("admitted", "served", "rejected", "expired")},
        fresh_compiles=0, platform="stub", workload="unit")
    with open(tmp_path / "TRACE_r90.json", "w") as f:
        json.dump(tart, f)
    from csmom_tpu.obs import ledger as ld

    L = ld.load(str(tmp_path))
    by_metric = {}
    for r in L.rows:
        by_metric.setdefault(r.metric, []).append(r)
    disp = by_metric["trace_stage_dispatch_p99_ms"][0]
    assert disp.direction == "lower" and disp.gate_eligible()
    assert disp.samples, "per-stage rows must carry their CI backing"
    burn_rows = [m for m in by_metric if m.endswith("_budget_burn")]
    assert burn_rows, "per-class budget-burn rows must land"
    for m in burn_rows:
        assert by_metric[m][0].gate_eligible()
    assert "trace_complete_traces" in by_metric
    assert not by_metric["trace_complete_traces"][0].gate_eligible()


def test_ledger_attaches_serve_latency_samples_to_p99_rows(tmp_path):
    _, art = _run_traced_loadgen(schedule="0.4x80", seed=3,
                                 deadline_s=2.0)
    with open(tmp_path / "SERVE_r91.json", "w") as f:
        json.dump(dict(art, run_id="r91"), f)
    from csmom_tpu.obs import ledger as ld

    L = ld.load(str(tmp_path))
    rows = {r.metric: r for r in L.rows}
    assert rows["serve_p99_ms"].samples, (
        "serve p99 rows must carry the persisted per-request samples — "
        "the whole point of the satellite is CI-backed gate verdicts")
    cls_rows = [r for m, r in rows.items()
                if m.endswith("_p99_ms") and m.startswith("serve_")
                and not m.startswith(("serve_p", "serve_ep_"))]
    assert any(r.samples for r in cls_rows), "class p99 rows lost samples"
    ep_rows = [r for m, r in rows.items() if m.startswith("serve_ep_")
               and m.endswith("_p99_ms")]
    assert any(r.samples for r in ep_rows), "endpoint p99 rows lost samples"
    # and the artifact is v4-valid (burn + samples are schema rules)
    assert inv.validate(art, "serve") == []


def test_serve_v4_schema_requires_burn_and_samples():
    _, art = _run_traced_loadgen(schedule="0.3x60", seed=3,
                                 deadline_s=2.0)
    damaged = json.loads(json.dumps(art))
    del damaged["extra"]["samples"]
    assert any("serve_total_ms" in v for v in inv.validate(damaged, "serve"))
    damaged2 = json.loads(json.dumps(art))
    for book in damaged2["classes"].values():
        book.pop("violations", None)
    assert any("violations" in v for v in inv.validate(damaged2, "serve"))


# ------------------------------------------------- histogram quantiles ----

def test_histogram_log_bucket_quantiles_bounded_relative_error():
    from csmom_tpu.obs import spans

    spans.arm(None, run_id="hist-unit", proc="t")
    try:
        metrics.reset()
        h = metrics.histogram("unit.lat")
        import random as _random

        rng = _random.Random(0)
        vals = sorted(rng.lognormvariate(0.0, 1.0) for _ in range(5000))
        for v in vals:
            h.observe(v)
        import math

        for q in (0.50, 0.95, 0.99):
            exact = vals[max(0, math.ceil(q * len(vals)) - 1)]
            est = h.quantile(q)
            assert est is not None
            assert abs(est - exact) / exact < 0.12, (
                f"p{q:.0%} estimate {est} vs exact {exact}: log-bucket "
                "error must stay inside the bucket ratio (~9%)")
        s = h.summary()
        assert s["p50"] <= s["p95"] <= s["p99"] <= s["max"]
        assert s["count"] == 5000
    finally:
        spans.disarm()
        metrics.reset()


def test_histogram_quantiles_none_when_empty_and_clamped_single_sample():
    from csmom_tpu.obs import spans

    spans.arm(None, run_id="hist-unit2", proc="t")
    try:
        metrics.reset()
        h = metrics.histogram("unit.single")
        assert h.quantile(0.99) is None
        assert h.summary()["p99"] is None
        h.observe(0.0371)
        # a one-sample histogram answers that sample, not a bucket edge
        assert h.quantile(0.5) == pytest.approx(0.0371)
        assert h.summary()["p99"] == pytest.approx(0.0371, rel=1e-6)
    finally:
        spans.disarm()
        metrics.reset()


def test_budget_burn_arithmetic():
    assert metrics.budget_burn(0, 0) is None  # no traffic != no burn
    assert metrics.budget_burn(100, 0) == 0.0
    assert metrics.budget_burn(100, 1) == 1.0     # exactly on budget
    assert metrics.budget_burn(100, 3) == 3.0     # burning at 3x
    assert metrics.budget_burn(200, 1, slo_target=0.995) == 1.0
    with pytest.raises(ValueError):
        metrics.budget_burn(10, 1, slo_target=1.0)


# ----------------------------------------------------- repo-level rules ----

def test_committed_trace_artifacts_validate():
    import glob as _glob

    for path in _glob.glob(os.path.join(_REPO, "TRACE_*.json")):
        base = os.path.basename(path)
        if not inv.committable_sidecar(base):
            continue
        assert inv.validate_file(path) == [], f"{base} fails its schema"


def test_no_stray_scratch_sidecars_at_repo_root():
    """The satellite that motivated scratch_dir: regenerated sidecars
    (TELEMETRY_rehearse*, TRACE_smoke*, ...) must not sit at the repo
    root — they land in .csmom_scratch (gitignored as a directory)."""
    import glob as _glob

    strays = []
    for pat in ("TELEMETRY_rehearse*.json", "TRACE_rehearse*.json",
                "TRACE_smoke*.json"):
        strays += _glob.glob(os.path.join(_REPO, pat))
    assert strays == [], (
        f"scratch sidecars at the repo root: {strays} — they belong in "
        ".csmom_scratch/ (obs.timeline.scratch_dir)")


def test_three_tier_trace_books_close_under_router_replica_sigkill(
        tmp_path):
    """ISSUE 14 satellite: the trace books across THREE process tiers
    (loadgen client → supervised router replicas → workers), with one
    replica SIGKILLed mid-dispatch.  The client closes the dead
    replica's unstitchable dispatches as reason-counted orphan halves,
    surviving complete traces carry the stages of ALL three tiers
    (client route/transport + replica route/transport + worker
    queue/dispatch), the client book reconciles with the SERVE_FABRIC
    request books, and each SURVIVING replica's own books — request and
    trace — close too."""
    from csmom_tpu.serve.fabric import (
        FabricClient,
        FabricClientConfig,
        RouterSupervisor,
        RoutesPublisher,
    )
    from csmom_tpu.serve.loadgen import run_fabric_loadgen
    from csmom_tpu.serve.supervisor import PoolConfig, PoolSupervisor

    smoke = dict(profile="serve-smoke", engine="stub",
                 ready_timeout_s=30.0, poll_interval_s=0.05,
                 backoff_base_s=0.05, backoff_cap_s=0.5)
    book = obs_trace.arm_tracing(seed=3)
    wdir = os.path.join(str(tmp_path), "workers")
    os.makedirs(wdir, exist_ok=True)
    wsup = PoolSupervisor(PoolConfig(n_workers=2, **smoke), wdir)
    wsup.start()
    routes = os.path.join(str(tmp_path), "routes.json")
    pub = RoutesPublisher(wsup, routes, interval_s=0.05).start()
    rsup = RouterSupervisor(
        PoolConfig(n_workers=2, expect_cache_version=wsup.expect_cache_version,
                   **smoke),
        os.path.join(str(tmp_path), "routers"), routes,
        deadline_ms=3000.0, trace=True)
    os.makedirs(rsup.run_dir, exist_ok=True)
    rsup.start()
    client = FabricClient(rsup.ready_workers,
                          FabricClientConfig(default_deadline_s=3.0))

    def kill_replica():
        time.sleep(0.3)
        os.kill(rsup.handles[0].proc.pid, signal.SIGKILL)
        give_up = time.monotonic() + 30.0
        while time.monotonic() < give_up:
            if any(h.generation >= 1 and h.state == "ready"
                   for h in rsup.handles):
                return
            time.sleep(0.05)

    try:
        art = run_fabric_loadgen(
            client, rsup, wsup,
            LoadConfig(schedule="1.2x70", seed=7, deadline_s=3.0,
                       run_id="trace_fabric_kill"),
            concurrent=kill_replica)
    finally:
        pub.stop()
        rsup.stop()
        wsup.stop()
    obs_trace.disarm_tracing()

    # the CLIENT book is the outermost trace ledger: closed, balanced
    # against the fabric artifact's request books
    req = art["requests"]
    assert book.invariant_violations() == []
    assert book.opened == req["admitted"]
    assert book.complete == req["served"]
    assert book.partial == req["rejected"] + req["expired"]

    snap = book.snapshot()
    assert snap["orphans"]["count"] > 0, (
        "the replica SIGKILL left no orphan half — nothing was in "
        "flight, or the orphan leaked unclosed")
    assert all(("connection" in r or "closed" in r or "reset" in r)
               for r in snap["orphans"]["reasons"]), snap["orphans"]
    # three-tier stitching: the client's chain carries its own route/
    # transport plus the replica's (merged by name) plus the worker's
    # queue/dispatch stages
    for stage in ("route", "transport", "queue_wait", "dispatch",
                  "finalize"):
        assert stage in snap["stages"], f"missing stitched stage {stage}"
    assert snap["reconcile"]["violations"] == 0

    # every SURVIVING replica's books — request AND trace — close too;
    # the dead replica's are reported lost, never faked
    surviving = [r for r in art["routers"]["replicas"]
                 if r.get("state") == "ready" and "accounting" in r]
    assert surviving, "no surviving replica reported stats"
    for rep in surviving:
        assert rep.get("invariant_violations") == [], rep
        tr = rep.get("trace")
        assert tr is not None, "replica tracing was armed but no book"
        assert tr["invariant_violations"] == []
        books = tr["snapshot"]["books"]
        assert books["opened"] == books["complete"] + books["partial"]

    tart = obs_trace.build_artifact(
        book, "trace_fabric_kill",
        requests={k: req[k]
                  for k in ("admitted", "served", "rejected", "expired")},
        fresh_compiles=0, platform="stub", workload="unit fabric kill")
    assert inv.validate(tart) == []
