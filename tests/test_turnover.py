"""Turnover features vs a pandas oracle of the reference formulas."""

import numpy as np
import pandas as pd

from csmom_tpu.signals.turnover import (
    turnover_features,
    shares_outstanding_vector,
    TRADING_DAYS_PER_MONTH,
)


def test_turnover_matches_reference_formulas(rng):
    A, M = 6, 24
    vol = rng.integers(1e6, 5e8, size=(A, M)).astype(float)
    vmask = np.ones((A, M), bool)
    vmask[0, :5] = False
    so = np.array([1e9, 5e8, np.nan, 2e9, 1e9, 3e8])

    feats = turnover_features(vol, vmask, so, lookback=3)
    adv, _ = feats["adv_est"]
    turn, turn_valid = feats["turnover_monthly"]
    tavg, tavg_valid = feats["turn_avg"]

    np.testing.assert_allclose(np.asarray(adv), vol / TRADING_DAYS_PER_MONTH)
    # asset 2 has unknown shares -> all turnover invalid
    assert not np.asarray(turn_valid)[2].any()
    # oracle: rolling 3-month mean with min_periods=1, NaN-skipping
    for a in (1, 3):
        t_series = pd.Series(np.where(vmask[a], vol[a] / 21.0 / so[a], np.nan))
        want = t_series.rolling(3, min_periods=1).mean().values
        got = np.where(np.asarray(tavg_valid)[a], np.asarray(tavg)[a], np.nan)
        np.testing.assert_allclose(got, want, rtol=1e-12)
    # masked leading months of asset 0 are invalid then recover
    assert not np.asarray(turn_valid)[0, :5].any()
    assert np.asarray(turn_valid)[0, 5:].all()


def test_shares_outstanding_resolution():
    tickers = ["A", "B", "C", "D"]
    info = {
        "A": {"shares_outstanding": 123, "market_cap": 999},
        "B": {"shares_outstanding": None, "market_cap": 1000},
        "C": {},
        # D absent entirely
    }
    last_price = np.array([10.0, 4.0, 1.0, 1.0])
    got = shares_outstanding_vector(tickers, info, last_price)
    assert got[0] == 123
    assert got[1] == int(1000 / 4.0)  # market-cap fallback, int-truncated
    assert np.isnan(got[2]) and np.isnan(got[3])
