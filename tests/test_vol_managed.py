"""Volatility-managed overlay vs a pandas oracle + BSC sanity properties."""

import numpy as np
import pandas as pd

from csmom_tpu.analytics import vol_managed


def test_matches_pandas_oracle(rng):
    T = 120  # canonical stats-family length (shared eager-op cache)
    r = rng.normal(0.004, 0.05, size=T)
    valid = np.ones(T, bool)
    valid[10:14] = False
    managed, ok, scale = vol_managed(np.where(valid, r, np.nan), valid,
                                     window=6, target_ann_vol=0.10,
                                     freq_per_year=12, max_leverage=2.0)

    s = pd.Series(np.where(valid, r, np.nan))
    sd = s.rolling(6, min_periods=6).std(ddof=1).shift(1)
    ann = sd * np.sqrt(12)
    want_scale = (0.10 / ann).clip(upper=2.0)
    want = want_scale * s
    ok = np.asarray(ok)
    np.testing.assert_array_equal(ok, want.notna().values & valid)
    np.testing.assert_allclose(np.asarray(scale)[ok], want_scale[ok],
                               rtol=1e-9)
    np.testing.assert_allclose(np.asarray(managed)[ok], want[ok], rtol=1e-9)


def test_no_lookahead(rng):
    """scale[t] must not depend on returns[t:] — perturbing the future
    leaves every earlier scale unchanged.  Tolerance, not bit-equality:
    the rolling kernels are prefix-sum based, so a tail change perturbs
    the shared cumulative sums by float epsilon (~1e-14) even where the
    window itself is untouched; a real lookahead leak would move scales
    by orders of magnitude more (the perturbation is 10x the vol)."""
    T = 120
    r = rng.normal(0.004, 0.05, size=T)
    valid = np.ones(T, bool)
    _, _, s1 = vol_managed(r, valid, window=6)
    r2 = r.copy()
    r2[80:] += 0.5
    _, _, s2 = vol_managed(r2, valid, window=6)
    np.testing.assert_allclose(np.asarray(s1)[:81], np.asarray(s2)[:81],
                               rtol=1e-9, equal_nan=True)
    # and the first slot that MAY see the change really does move
    assert abs(float(s1[81]) - float(s2[81])) > 1e-3


def test_constant_scaling_preserves_sharpe(rng):
    """On a constant-vol series the scale is ~constant, and a constant
    scale cannot change the Sharpe ratio — the overlay earns its keep only
    when vol varies (BSC's entire point)."""
    from csmom_tpu.analytics.stats import sharpe

    T = 240
    r = rng.normal(0.01, 0.03, size=T)  # one vol regime
    valid = np.ones(T, bool)
    managed, ok, scale = vol_managed(r, valid, window=24, max_leverage=10.0)
    ok = np.asarray(ok)
    sc = np.asarray(scale)[ok]
    assert sc.std() / sc.mean() < 0.25   # near-constant scale
    s_raw = float(sharpe(r[ok], np.ones(ok.sum(), bool), freq_per_year=12))
    s_man = float(sharpe(np.asarray(managed)[ok], np.ones(ok.sum(), bool),
                         freq_per_year=12))
    assert abs(s_raw - s_man) < 0.12 * abs(s_raw) + 0.05


def test_downweights_high_vol_regime(rng):
    """Two-regime series: the scale in the quiet regime must exceed the
    scale in the turbulent regime (the crash-protection mechanism)."""
    T = 240
    r = np.concatenate([
        rng.normal(0.005, 0.02, size=T // 2),   # quiet
        rng.normal(0.005, 0.10, size=T // 2),   # turbulent
    ])
    valid = np.ones(T, bool)
    _, ok, scale = vol_managed(r, valid, window=12, max_leverage=5.0)
    ok = np.asarray(ok)
    sc = np.asarray(scale)
    quiet = sc[30:T // 2][ok[30:T // 2]]
    # skip the transition window: vol estimates straddling the break mix regimes
    turb = sc[T // 2 + 13:][ok[T // 2 + 13:]]
    assert quiet.mean() > 2 * turb.mean()


def test_batched_leading_axes_match_per_series(rng):
    """vol_managed over a [G, T] stack equals per-series calls — the shape
    contract that lets a grid of spread series be managed in one call."""
    G, T = 4, 120
    r = rng.normal(0.004, 0.05, size=(G, T))
    valid = rng.random((G, T)) > 0.1
    managed, ok, scale = vol_managed(np.where(valid, r, np.nan), valid,
                                     window=6)
    for g in range(G):
        m1, o1, s1 = vol_managed(np.where(valid[g], r[g], np.nan), valid[g],
                                 window=6)
        np.testing.assert_array_equal(np.asarray(ok)[g], np.asarray(o1))
        np.testing.assert_allclose(np.asarray(managed)[g], np.asarray(m1),
                                   rtol=1e-12, equal_nan=True)
        np.testing.assert_allclose(np.asarray(scale)[g], np.asarray(s1),
                                   rtol=1e-12, equal_nan=True)
