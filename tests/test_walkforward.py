"""Walk-forward selection vs a per-month numpy loop oracle."""

import pytest

import numpy as np
import jax.numpy as jnp

from csmom_tpu.backtest import walk_forward_select, walk_forward_grid_backtest
from tests.test_bootstrap import np_sharpe


def oracle_select(x, live, min_months, freq=12):
    G, M = x.shape
    choice = np.full(M, -1, dtype=int)
    oos = np.full(M, np.nan)
    for m in range(M):
        best, best_sh = -1, -np.inf
        for g in range(G):
            prior = live[g, :m]
            if prior.sum() < min_months:
                continue
            sh = np_sharpe(x[g, :m], prior, freq)
            if np.isfinite(sh) and sh > best_sh:
                best, best_sh = g, sh
        choice[m] = best
        if best >= 0 and live[best, m]:
            oos[m] = x[best, m]
    return choice, oos


def test_matches_oracle(rng):
    G, M = 6, 80
    x = rng.normal(0.003, 0.04, size=(G, M))
    live = rng.random((G, M)) > 0.1
    x = np.where(live, x, np.nan)
    res = walk_forward_select(jnp.asarray(x), jnp.asarray(live), min_months=12)
    choice, oos = oracle_select(x, live, 12)
    np.testing.assert_array_equal(np.asarray(res.choice), choice)
    np.testing.assert_allclose(np.asarray(res.oos_spread), oos, rtol=1e-9)
    np.testing.assert_array_equal(np.asarray(res.oos_valid), np.isfinite(oos))


def test_warmup_all_invalid(rng):
    x = rng.normal(size=(3, 30))
    live = np.ones((3, 30), dtype=bool)
    res = walk_forward_select(jnp.asarray(x), jnp.asarray(live), min_months=24)
    assert (np.asarray(res.choice)[:24] == -1).all()
    assert not np.asarray(res.oos_valid)[:24].any()
    assert np.asarray(res.oos_valid)[25:].all()


def test_selection_prefers_dominant_cell(rng):
    """A cell with strictly better risk-adjusted returns gets picked once
    eligible."""
    M = 60
    good = np.full(M, 0.02) + rng.normal(0, 0.001, M)
    bad = rng.normal(0.0, 0.05, size=(4, M))
    x = np.vstack([bad, good[None, :]])
    live = np.ones_like(x, dtype=bool)
    res = walk_forward_select(jnp.asarray(x), jnp.asarray(live), min_months=12)
    assert (np.asarray(res.choice)[13:] == 4).all()


@pytest.mark.slow
def test_end_to_end_grid_sweep(rng):
    A, M = 24, 70
    prices = 50 * np.exp(np.cumsum(rng.normal(0.004, 0.06, size=(A, M)), axis=1))
    mask = np.isfinite(prices)
    Js = np.array([3, 6], dtype=np.int32)
    Ks = np.array([1, 3], dtype=np.int32)
    wf, grid = walk_forward_grid_backtest(prices, mask, Js, Ks, min_months=12, n_bins=5)
    assert wf.insample_sharpe.shape == (4, M)
    choice, oos = oracle_select(
        np.asarray(grid.spreads).reshape(4, M),
        np.asarray(grid.spread_valid).reshape(4, M),
        12,
    )
    np.testing.assert_array_equal(np.asarray(wf.choice), choice)
    np.testing.assert_allclose(
        np.asarray(wf.oos_spread)[np.asarray(wf.oos_valid)],
        oos[np.isfinite(oos)],
        rtol=1e-9,
    )
